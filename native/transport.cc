// Gossip transport core — the memberlist-equivalent native engine.
//
// Capability mirror of the reference's external dependency
// NinesStack/memberlist as used by Sidecar (main.go:239-274,
// services_delegate.go): full SWIM failure detection (direct ping/ack,
// indirect probes through k proxies, incarnation numbers with
// refutation, membership dissemination piggybacked on gossip —
// README.md:83-96), piggybacked gossip broadcast every GossipInterval
// packed first-fit into ~1398-byte UDP packets
// (services_delegate.go:182-223), TCP full-state push-pull anti-entropy
// on join and every PushPullInterval (services_delegate.go:146-167),
// and ClusterName isolation (services_delegate.go:29-32).
//
// Design: the engine runs its own threads for network IO and exposes a
// poll-based C API (create/start/join/broadcast/poll_*) consumed from
// Python via ctypes — no callbacks cross the language boundary, so there
// are no GIL-reentrancy hazards.  Inbound user messages, full-state
// payloads, membership events, and engine diagnostics (the logging
// bridge, logging_bridge.go:25-53) are queued until the host drains
// them.
//
// Wire format v2 ("SC02").  Every packet starts with
//   [magic u32][type u8][cluster str8][name str8][ip str8][port u16]
//   [incarnation u32]
// followed by a type-specific body:
//   GOSSIP   frames: ([kind u8][len u16][payload])*   kind 0 = user
//            payload (a service record), kind 1 = membership update
//            [mstate u8][incarnation u32][name str8][ip str8][port u16]
//   PING     [seq u32]
//   ACK      [seq u32]
//   PING_REQ [seq u32][target name str8][target ip str8][target port u16]
//   ACK_FWD  [seq u32][target name str8]

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;
using Millis = std::chrono::milliseconds;

// Wire constants.
constexpr uint32_t kMagic = 0x53433032;  // "SC02"
constexpr size_t kMaxPacket = 1398;      // single-UDP-packet budget
constexpr uint8_t kTypeGossip = 0;
constexpr uint8_t kTypePing = 1;
constexpr uint8_t kTypeAck = 2;
constexpr uint8_t kTypePingReq = 3;
constexpr uint8_t kTypeAckFwd = 4;
// Pseudo packet type for the test drop mask only: bit 5 refuses TCP
// push-pull exchanges with the node, so an injected partition severs
// anti-entropy exactly as it severs UDP gossip.
constexpr uint8_t kTypePushPull = 5;

constexpr uint8_t kFrameUser = 0;
constexpr uint8_t kFrameMembership = 1;

constexpr uint8_t kMemberAlive = 0;
constexpr uint8_t kMemberSuspect = 1;
constexpr uint8_t kMemberDead = 2;

constexpr int kRetransmitMult = 4;       // memberlist RetransmitMult

struct Member {
  std::string name;
  std::string ip;
  uint16_t port = 0;
  uint32_t incarnation = 0;
  bool suspect = false;
  Clock::time_point last_heard = Clock::now();
  Clock::time_point suspect_since;
};

struct Broadcast {
  std::string payload;
  int transmits_left = 0;
};

// Origin-side bookkeeping for an in-flight probe of one member.
struct PendingProbe {
  std::string target;
  Clock::time_point direct_deadline;
  bool indirect_sent = false;
  Clock::time_point indirect_deadline;
};

// Proxy-side bookkeeping for one relayed ping (SWIM ping-req).
struct Forward {
  uint32_t origin_seq = 0;
  std::string origin_ip;
  uint16_t origin_port = 0;
  std::string target_name;
  Clock::time_point expires;
};

void put_u16(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v & 0xff));
}

uint16_t get_u16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}

void put_u32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  out->push_back(static_cast<char>((v >> 16) & 0xff));
  out->push_back(static_cast<char>((v >> 8) & 0xff));
  out->push_back(static_cast<char>(v & 0xff));
}

uint32_t get_u32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

void put_str8(std::string* out, const std::string& s) {
  uint8_t n = static_cast<uint8_t>(std::min<size_t>(s.size(), 255));
  out->push_back(static_cast<char>(n));
  out->append(s.data(), n);
}

bool get_str8(const uint8_t*& p, const uint8_t* end, std::string* out) {
  if (p >= end) return false;
  uint8_t n = *p++;
  if (p + n > end) return false;
  out->assign(reinterpret_cast<const char*>(p), n);
  p += n;
  return true;
}

// Reads with an overall deadline: the 5 s socket timeout is per-recv, so
// a drip-feeding peer could otherwise pin a connection (and stop()'s
// handler join) indefinitely.
bool read_full(int fd, void* buf, size_t len, Clock::time_point deadline) {
  auto* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    if (Clock::now() > deadline) return false;
    ssize_t n = recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t len) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = send(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

struct UdpSend {
  std::string ip;
  uint16_t port;
  std::string pkt;
};

class Transport {
 public:
  Transport(std::string name, std::string cluster, std::string bind_ip,
            uint16_t bind_port, std::string advertise_ip, int gossip_ms,
            int pushpull_ms, int gossip_nodes, int gossip_messages)
      : name_(std::move(name)),
        cluster_(std::move(cluster)),
        bind_ip_(std::move(bind_ip)),
        advertise_ip_(std::move(advertise_ip)),
        bind_port_(bind_port),
        gossip_ms_(gossip_ms),
        pushpull_ms_(pushpull_ms),
        gossip_nodes_(gossip_nodes),
        gossip_messages_(gossip_messages),
        probe_interval_ms_(std::max(gossip_ms * 5, 500)),
        probe_timeout_ms_(1000),
        suspect_timeout_ms_(3000),
        indirect_k_(3),
        rng_(std::random_device{}()) {}

  ~Transport() { stop(); }

  // SWIM probe tuning (memberlist ProbeInterval/ProbeTimeout analogs).
  void configure_probe(int interval_ms, int timeout_ms, int suspect_ms,
                       int indirect_k) {
    if (interval_ms > 0) probe_interval_ms_ = interval_ms;
    if (timeout_ms > 0) probe_timeout_ms_ = timeout_ms;
    if (suspect_ms > 0) suspect_timeout_ms_ = suspect_ms;
    if (indirect_k >= 0) indirect_k_ = indirect_k;
  }

  // Received-record queue bound (memberlist HandoffQueueDepth analog).
  void set_handoff_depth(int depth) {
    std::lock_guard<std::mutex> lk(mu_);
    if (depth > 0) handoff_depth_ = static_cast<size_t>(depth);
  }

  // Test-only fault injection: drop received packets of the given types
  // (bitmask by packet type) when they come from `node` — models a
  // one-way partition without touching the network stack.
  void test_drop_types(const std::string& node, uint32_t type_mask) {
    std::lock_guard<std::mutex> lk(mu_);
    if (type_mask == 0)
      test_drops_.erase(node);
    else
      test_drops_[node] = type_mask;
  }

  // Binds sockets and launches the IO threads.  Returns the actual bound
  // port (0 input picks an ephemeral port) or -1 on failure.
  int start() {
    udp_fd_ = socket(AF_INET, SOCK_DGRAM, 0);
    tcp_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (udp_fd_ < 0 || tcp_fd_ < 0) {
      logf('E', "socket() failed");
      return -1;
    }
    int one = 1;
    setsockopt(tcp_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(bind_port_);
    addr.sin_addr.s_addr =
        bind_ip_.empty() ? INADDR_ANY : inet_addr(bind_ip_.c_str());
    if (bind(udp_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      logf('E', "udp bind failed on " + bind_ip_);
      return -1;
    }

    socklen_t len = sizeof(addr);
    getsockname(udp_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    bind_port_ = ntohs(addr.sin_port);  // both protocols share the port

    sockaddr_in taddr = addr;
    if (bind(tcp_fd_, reinterpret_cast<sockaddr*>(&taddr), sizeof(taddr)) < 0) {
      logf('E', "tcp bind failed");
      return -1;
    }
    if (listen(tcp_fd_, 16) < 0) return -1;

    // 500 ms recv timeout so loops notice quit_.
    timeval tv{0, 500000};
    setsockopt(udp_fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(tcp_fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

    header_overhead_ = packet_header(kTypeGossip).size();
    quit_ = false;
    // Announce ourselves so dissemination introduces us transitively.
    {
      std::lock_guard<std::mutex> lk(mu_);
      queue_membership_locked(kMemberAlive, incarnation_, name_,
                              advertise_ip_, bind_port_);
    }
    // Thread model: ONE poll()-multiplexed IO+timer thread (the "few
    // execution threads" budget the reference advertises, its
    // README:54-56); push-pull exchanges — blocking TCP — run as
    // tracked transient handler threads.
    fcntl(udp_fd_, F_SETFL, O_NONBLOCK);
    fcntl(tcp_fd_, F_SETFL, O_NONBLOCK);
    threads_.emplace_back(&Transport::io_loop, this);
    return bind_port_;
  }

  void stop() {
    if (quit_.exchange(true)) return;
    // Unblock accept() promptly; the loops also poll quit_ on their
    // 500 ms socket timeouts.
    if (tcp_fd_ >= 0) shutdown(tcp_fd_, SHUT_RDWR);
    for (auto& t : threads_)
      if (t.joinable()) t.join();
    threads_.clear();
    // Join in-flight push-pull connection handlers: they reference this
    // object (mutex, queues, local_state_), so the Transport must not be
    // torn down under them.  Shut their sockets down first so a
    // mid-exchange peer can't pin the join (recv returns immediately).
    {
      std::lock_guard<std::mutex> lk(handlers_mu_);
      for (auto& h : handlers_)
        if (!h.done->load() && h.fd >= 0) shutdown(h.fd, SHUT_RDWR);
    }
    reap_handlers(/*join_all=*/true);
    if (udp_fd_ >= 0) close(udp_fd_);
    if (tcp_fd_ >= 0) close(tcp_fd_);
    udp_fd_ = tcp_fd_ = -1;
  }

  // TCP dial a seed and run the join push-pull (README.md:83-87).
  bool join(const std::string& host, uint16_t port) {
    return pushpull_with(host, port);
  }

  void broadcast(const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    // A frame that can never fit in one packet would sit in the queue
    // forever without ever being transmitted (its transmit count never
    // moved) — drop it loudly instead; push-pull still carries it.
    if (header_overhead_ + 3 + len > kMaxPacket) {
      logf('W', "dropping oversized broadcast (" + std::to_string(len) +
                    " bytes > packet budget); push-pull will carry it");
      return;
    }
    queue_.push_back(
        {std::string(reinterpret_cast<const char*>(data), len),
         transmit_limit_locked()});
    // MAX_PENDING-ish bound so a partitioned node doesn't grow forever.
    while (queue_.size() > 4096) queue_.pop_front();
  }

  void set_local_state(const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    local_state_.assign(reinterpret_cast<const char*>(data), len);
  }

  // Poll queues (returns empty string when drained).
  std::string poll(std::deque<std::string>* q) {
    std::lock_guard<std::mutex> lk(mu_);
    if (q->empty()) return {};
    std::string out = std::move(q->front());
    q->pop_front();
    return out;
  }

  std::string poll_msg() { return poll(&inbound_); }
  std::string poll_state() { return poll(&states_); }
  std::string poll_event() { return poll(&events_); }

  std::string poll_log() {
    std::lock_guard<std::mutex> lk(log_mu_);
    if (logs_.empty()) return {};
    std::string out = std::move(logs_.front());
    logs_.pop_front();
    return out;
  }

  // Size of the next queued full-state payload (0 when drained) so the
  // host can size its buffer — a fixed cap would silently truncate a
  // large cluster's push-pull and fail every decode.
  int next_state_len() {
    std::lock_guard<std::mutex> lk(mu_);
    return states_.empty() ? 0 : static_cast<int>(states_.front().size());
  }

  std::string members_list() {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out = name_ + "\n";
    for (auto& kv : members_) out += kv.first + "\n";
    return out;
  }

  uint16_t port() const { return bind_port_; }

 private:
  // -- diagnostics (the logging bridge) -----------------------------------

  void logf(char level, const std::string& msg) {
    std::lock_guard<std::mutex> lk(log_mu_);
    logs_.push_back(std::string(1, level) + "|" + msg);
    while (logs_.size() > 4096) logs_.pop_front();
  }

  int transmit_limit_locked() const {
    int n_members = static_cast<int>(members_.size()) + 1;
    int limit = kRetransmitMult *
                static_cast<int>(std::ceil(std::log10(n_members + 1)));
    return std::max(limit, 1);
  }

  // -- packet building ---------------------------------------------------

  std::string packet_header(uint8_t type) {
    std::string out;
    put_u32(&out, kMagic);
    out.push_back(static_cast<char>(type));
    put_str8(&out, cluster_);
    put_str8(&out, name_);
    put_str8(&out, advertise_ip_);
    put_u16(&out, bind_port_);
    put_u32(&out, incarnation_.load());
    return out;
  }

  // First-fit packing of queued broadcasts into one UDP packet
  // (packPacket, services_delegate.go:182-223).  Membership updates go
  // first — failure information must not queue behind catalog traffic.
  std::string build_gossip_packet() {
    std::string pkt = packet_header(kTypeGossip);
    std::lock_guard<std::mutex> lk(mu_);
    int packed = 0;
    for (std::deque<Broadcast>* q : {&mqueue_, &queue_}) {
      uint8_t kind = (q == &mqueue_) ? kFrameMembership : kFrameUser;
      for (auto it = q->begin();
           it != q->end() && packed < gossip_messages_;) {
        size_t frame = 3 + it->payload.size();
        if (pkt.size() + frame > kMaxPacket) {
          ++it;
          continue;  // first-fit: try a smaller one
        }
        pkt.push_back(static_cast<char>(kind));
        put_u16(&pkt, static_cast<uint16_t>(it->payload.size()));
        pkt += it->payload;
        ++packed;
        if (--it->transmits_left <= 0)
          it = q->erase(it);
        else
          ++it;
      }
    }
    if (packed == 0) return {};
    return pkt;
  }

  void send_to(const std::string& ip, uint16_t port,
               const std::string& pkt) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    addr.sin_addr.s_addr = inet_addr(ip.c_str());
    ssize_t rc = sendto(udp_fd_, pkt.data(), pkt.size(), 0,
                        reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      // udp_fd_ is O_NONBLOCK for the poll()-driven receive path, but
      // sends share the fd: under send-buffer pressure the old blocking
      // behavior becomes a silent drop — and dropped acks under burst
      // inflate false suspicions.  Briefly wait for POLLOUT and retry
      // once; a still-full buffer after that is a genuine (counted)
      // drop, like any congested UDP path.
      pollfd pfd{udp_fd_, POLLOUT, 0};
      if (::poll(&pfd, 1, 10) > 0 && (pfd.revents & POLLOUT)) {
        rc = sendto(udp_fd_, pkt.data(), pkt.size(), 0,
                    reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
      }
    }
    if (rc < 0) {
      udp_send_drops_.fetch_add(1, std::memory_order_relaxed);
      return;
    }
    udp_out_.fetch_add(1, std::memory_order_relaxed);
    udp_bytes_out_.fetch_add(pkt.size(), std::memory_order_relaxed);
  }

 public:
  // Stats snapshot for the host-side metrics poll (the go-metrics
  // analog, main.go:156-166): [udp_out, udp_bytes_out, udp_in,
  // udp_bytes_in, pushpull_out, pushpull_in].
  int stats(unsigned long long* out, int n) {
    const unsigned long long vals[] = {
        udp_out_.load(),      udp_bytes_out_.load(), udp_in_.load(),
        udp_bytes_in_.load(), pushpull_out_.load(),  pushpull_in_.load(),
        udp_send_drops_.load()};
    int count = static_cast<int>(sizeof(vals) / sizeof(vals[0]));
    if (n < count) count = n;
    for (int i = 0; i < count; i++) out[i] = vals[i];
    return count;
  }

 private:

  std::vector<Member> pick_members(int k, const std::string& exclude = "") {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<Member> all;
    all.reserve(members_.size());
    for (auto& kv : members_)
      if (kv.first != exclude) all.push_back(kv.second);
    std::shuffle(all.begin(), all.end(), rng_);
    if (static_cast<int>(all.size()) > k) all.resize(k);
    return all;
  }

  // -- member accounting --------------------------------------------------

  void heard_from(const std::string& node, const std::string& ip,
                  uint16_t port, uint32_t incarnation) {
    if (node == name_) return;
    std::lock_guard<std::mutex> lk(mu_);
    // Direct traffic from a node we declared dead is authoritative (the
    // node itself is provably back — e.g. a restart rejoining via
    // push-pull); only third-party gossip is watermark-gated.
    dead_.erase(node);
    auto it = members_.find(node);
    if (it == members_.end()) {
      Member m{node, ip, port, incarnation, false, Clock::now(), {}};
      members_[node] = m;
      events_.push_back("join " + node + " " + ip);
      // Disseminate the discovery so the rest of the cluster learns the
      // new member transitively (memberlist aliveNode broadcast).
      queue_membership_locked(kMemberAlive, incarnation, node, ip, port);
    } else {
      it->second.last_heard = Clock::now();
      it->second.suspect = false;  // direct traffic: clearly alive
      it->second.ip = ip;
      it->second.port = port;
      if (incarnation > it->second.incarnation)
        it->second.incarnation = incarnation;
    }
  }

  void mark_dead_locked(const std::string& node, uint32_t inc) {
    auto& wm = dead_[node];
    wm = std::max(wm, inc);
    while (dead_.size() > 4096) dead_.erase(dead_.begin());
  }

  static std::string membership_payload(uint8_t mstate, uint32_t inc,
                                        const std::string& node,
                                        const std::string& ip,
                                        uint16_t port) {
    std::string pl;
    pl.push_back(static_cast<char>(mstate));
    put_u32(&pl, inc);
    put_str8(&pl, node);
    put_str8(&pl, ip);
    put_u16(&pl, port);
    return pl;
  }

  void queue_membership_locked(uint8_t mstate, uint32_t inc,
                               const std::string& node,
                               const std::string& ip, uint16_t port) {
    mqueue_.push_back({membership_payload(mstate, inc, node, ip, port),
                       transmit_limit_locked()});
    while (mqueue_.size() > 1024) mqueue_.pop_front();
  }

  // SWIM membership state machine (alive/suspect/dead with incarnation
  // ordering; refutation for claims about ourselves).
  void handle_membership(uint8_t mstate, uint32_t inc,
                         const std::string& node, const std::string& ip,
                         uint16_t port,
                         std::vector<UdpSend>* sends = nullptr) {
    std::lock_guard<std::mutex> lk(mu_);
    if (node == name_) {
      // A claim about US.  Suspect/dead with a current-or-newer
      // incarnation must be refuted: bump our incarnation and broadcast
      // alive (memberlist refutation, README.md:83-96).
      if ((mstate == kMemberSuspect || mstate == kMemberDead) &&
          inc >= incarnation_.load()) {
        incarnation_.store(inc + 1);
        queue_membership_locked(kMemberAlive, inc + 1, name_,
                                advertise_ip_, bind_port_);
        logf('I', "refuting " +
                      std::string(mstate == kMemberDead ? "death"
                                                        : "suspicion") +
                      " with incarnation " + std::to_string(inc + 1));
      }
      return;
    }

    auto it = members_.find(node);
    switch (mstate) {
      case kMemberAlive:
        if (it == members_.end()) {
          // Incarnation watermark: stale alive frames still circulating
          // after a death must not resurrect the member (ghost churn);
          // only an alive NEWER than the death certificate readmits.
          auto dit = dead_.find(node);
          if (dit != dead_.end()) {
            if (inc <= dit->second) {
              // Send the death certificate straight to the claimed
              // address instead of dropping silently: a RESTARTED node
              // rejoins with a fresh low incarnation, and nodes it
              // contacts directly readmit it (heard_from) and
              // re-disseminate that low-inc alive — which third parties
              // holding the certificate would veto forever, and since
              // the veto blocks the membership entry itself, the vetoing
              // node never gossips toward the ghost either.  The unicast
              // carries the death news to the rejoined node itself,
              // whose self-claim handler above then refutes with inc+1 >
              // watermark, and the refutation's higher incarnation
              // readmits it everywhere (memberlist: a rejoining node
              // learns of its own death from cluster state and refutes).
              // Bounded two ways (the claimed address is attacker-
              // forgeable, so echoes are a reflection vector): one echo
              // per ghost per second, AND a global token budget across
              // all ghosts — per-ghost limiting alone would still let a
              // packet stuffed with stale alives for DISTINCT minted
              // ghost names reflect one unicast per frame.  Legitimate
              // rejoins involve a handful of ghosts at a time, so the
              // small global budget never bites in practice.  Delivered
              // via the caller's deferred-send list so no syscall runs
              // under the lock.
              auto now = Clock::now();
              if (now - echo_window_ >= Millis(1000)) {
                echo_window_ = now;
                echo_budget_ = 32;
              }
              auto eit = echo_last_.find(node);
              if (sends != nullptr && echo_budget_ > 0 &&
                  (eit == echo_last_.end() ||
                   now - eit->second >= Millis(1000))) {
                echo_budget_--;
                echo_last_[node] = now;
                if (echo_last_.size() > 4096) {
                  // Evict by AGE, not map order: entries older than the
                  // 1 s per-ghost window no longer constrain anything,
                  // and erasing begin() (the lexicographically-smallest
                  // name) would let a flood of minted ghost names push
                  // out a legitimate ghost's limiter state so it could
                  // echo more than once per second.
                  for (auto it2 = echo_last_.begin();
                       it2 != echo_last_.end();) {
                    if (now - it2->second >= Millis(1000))
                      it2 = echo_last_.erase(it2);
                    else
                      ++it2;
                  }
                  while (echo_last_.size() > 4096) {
                    auto oldest = echo_last_.begin();
                    for (auto it2 = echo_last_.begin();
                         it2 != echo_last_.end(); ++it2)
                      if (it2->second < oldest->second) oldest = it2;
                    echo_last_.erase(oldest);
                  }
                }
                std::string pl = membership_payload(
                    kMemberDead, dit->second, node, ip, port);
                std::string pkt = packet_header(kTypeGossip);
                pkt.push_back(static_cast<char>(kFrameMembership));
                put_u16(&pkt, static_cast<uint16_t>(pl.size()));
                pkt += pl;
                sends->push_back({ip, port, std::move(pkt)});
              }
              break;
            }
            dead_.erase(dit);
          }
          members_[node] = {node, ip, port, inc, false, Clock::now(), {}};
          events_.push_back("join " + node + " " + ip);
          queue_membership_locked(kMemberAlive, inc, node, ip, port);
        } else if (inc > it->second.incarnation) {
          it->second.incarnation = inc;
          it->second.last_heard = Clock::now();
          // A newer incarnation is authoritative for the address too: a
          // member that restarted on a new ip/port (same name, bumped
          // incarnation) must not keep its stale address here, or probes
          // and gossip keep going to the dead port until a full
          // dead-declare/rejoin cycle (memberlist aliveNode updates the
          // address on a newer incarnation).
          it->second.ip = ip;
          it->second.port = port;
          if (it->second.suspect) {
            it->second.suspect = false;
            logf('I', node + " refuted suspicion (incarnation " +
                          std::to_string(inc) + ")");
          }
          queue_membership_locked(kMemberAlive, inc, node, ip, port);
        }
        break;
      case kMemberSuspect:
        if (it != members_.end() && inc >= it->second.incarnation &&
            !it->second.suspect) {
          it->second.suspect = true;
          it->second.suspect_since = Clock::now();
          queue_membership_locked(kMemberSuspect, inc, node,
                                  it->second.ip, it->second.port);
        }
        break;
      case kMemberDead:
        if (it != members_.end() && inc >= it->second.incarnation) {
          members_.erase(it);
          mark_dead_locked(node, inc);
          events_.push_back("leave " + node);
          queue_membership_locked(kMemberDead, inc, node, ip, port);
          logf('I', node + " declared dead via gossip");
        } else if (it == members_.end()) {
          // Unknown member: record the death certificate anyway, so a
          // node that joined after the member (or never learned of it)
          // won't readmit it from stale alive frames still circulating
          // (inc <= death inc) and then have to rediscover the failure
          // through its own probe cycle.
          mark_dead_locked(node, inc);
        }
        break;
      default:
        break;
    }
  }

  // -- IO loops -----------------------------------------------------------

  // Drain every datagram queued on the (non-blocking) UDP socket.
  void handle_udp_ready() {
    std::vector<uint8_t>& buf = udp_buf_;
    for (;;) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      ssize_t n = recvfrom(udp_fd_, buf.data(), buf.size(), 0,
                           reinterpret_cast<sockaddr*>(&src), &slen);
      if (n <= 0) return;
      udp_in_.fetch_add(1, std::memory_order_relaxed);
      udp_bytes_in_.fetch_add(n, std::memory_order_relaxed);
      const uint8_t* p = buf.data();
      const uint8_t* end = p + n;
      if (n < 5 || get_u32(p) != kMagic) continue;
      uint8_t type = p[4];
      p += 5;
      std::string cluster, node, ip;
      if (!get_str8(p, end, &cluster) || !get_str8(p, end, &node) ||
          !get_str8(p, end, &ip) || p + 6 > end)
        continue;
      uint16_t port = get_u16(p);
      p += 2;
      uint32_t inc = get_u32(p);
      p += 4;
      // ClusterName isolation (services_delegate.go:29-32).
      if (cluster != cluster_) continue;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto dit = test_drops_.find(node);
        if (dit != test_drops_.end() && (dit->second >> type) & 1u)
          continue;
      }
      heard_from(node, ip, port, inc);

      std::vector<UdpSend> sends;
      switch (type) {
        case kTypePing: {
          if (p + 4 > end) break;
          uint32_t seq = get_u32(p);
          std::string ack = packet_header(kTypeAck);
          put_u32(&ack, seq);
          sends.push_back({ip, port, std::move(ack)});
          break;
        }
        case kTypeAck: {
          if (p + 4 > end) break;
          uint32_t seq = get_u32(p);
          std::lock_guard<std::mutex> lk(mu_);
          // The ack proves its SENDER is alive: clear every outstanding
          // probe of that node, not just the acked seq — overlapping
          // probes of one target would otherwise fire a stale suspicion
          // after a successful rescue.
          for (auto it = pending_.begin(); it != pending_.end();)
            it = (it->second.target == node || it->first == seq)
                     ? pending_.erase(it)
                     : ++it;
          auto fit = forwards_.find(seq);
          if (fit != forwards_.end()) {
            // We relayed this ping for someone: forward the ack.
            std::string fwd = packet_header(kTypeAckFwd);
            put_u32(&fwd, fit->second.origin_seq);
            put_str8(&fwd, fit->second.target_name);
            sends.push_back({fit->second.origin_ip,
                             fit->second.origin_port, std::move(fwd)});
            forwards_.erase(fit);
          }
          break;
        }
        case kTypePingReq: {
          if (p + 4 > end) break;
          uint32_t origin_seq = get_u32(p);
          p += 4;
          std::string tname, tip;
          if (!get_str8(p, end, &tname) || !get_str8(p, end, &tip) ||
              p + 2 > end)
            break;
          uint16_t tport = get_u16(p);
          uint32_t myseq = next_seq_++;
          {
            std::lock_guard<std::mutex> lk(mu_);
            forwards_[myseq] = {origin_seq, ip, port, tname,
                               Clock::now() + Millis(5000)};
          }
          std::string ping = packet_header(kTypePing);
          put_u32(&ping, myseq);
          sends.push_back({tip, tport, std::move(ping)});
          break;
        }
        case kTypeAckFwd: {
          if (p + 4 > end) break;
          uint32_t seq = get_u32(p);
          p += 4;
          std::string tname;
          if (!get_str8(p, end, &tname)) break;
          std::lock_guard<std::mutex> lk(mu_);
          // The relayed ack proves the TARGET is alive: clear all of its
          // outstanding probes (same reasoning as the direct-ack case).
          for (auto it = pending_.begin(); it != pending_.end();)
            it = (it->second.target == tname || it->first == seq)
                     ? pending_.erase(it)
                     : ++it;
          auto mit = members_.find(tname);
          if (mit != members_.end()) {
            mit->second.last_heard = Clock::now();
            mit->second.suspect = false;
          }
          break;
        }
        case kTypeGossip: {
          while (p + 3 <= end) {
            uint8_t kind = *p++;
            uint16_t flen = get_u16(p);
            p += 2;
            if (p + flen > end) break;
            if (kind == kFrameUser) {
              std::lock_guard<std::mutex> lk(mu_);
              inbound_.emplace_back(reinterpret_cast<const char*>(p), flen);
              while (inbound_.size() > handoff_depth_)
                inbound_.pop_front();
            } else if (kind == kFrameMembership) {
              const uint8_t* fp = p;
              const uint8_t* fend = p + flen;
              if (fp + 5 <= fend) {
                uint8_t mstate = *fp++;
                uint32_t minc = get_u32(fp);
                fp += 4;
                std::string mnode, mip;
                if (get_str8(fp, fend, &mnode) &&
                    get_str8(fp, fend, &mip) && fp + 2 <= fend) {
                  uint16_t mport = get_u16(fp);
                  handle_membership(mstate, minc, mnode, mip, mport,
                                    &sends);
                }
              }
            }
            p += flen;
          }
          break;
        }
        default:
          break;
      }
      for (auto& s : sends) send_to(s.ip, s.port, s.pkt);
    }
  }

  void gossip_once() {
    // Building a packet consumes transmit counts — don't burn queued
    // broadcasts (e.g. our own join announcement) into the void while
    // the member list is still empty.
    auto targets = pick_members(gossip_nodes_);
    if (targets.empty()) return;
    std::string pkt = build_gossip_packet();
    if (pkt.empty()) return;
    for (auto& m : targets) send_to(m.ip, m.port, pkt);
  }

  // ONE thread drives the whole engine: poll() multiplexes the UDP
  // socket and the TCP accept socket, and the poll timeout doubles as
  // the timer tick for every periodic duty (gossip sends, SWIM probe
  // cycle, anti-entropy dispatch).  The tick bounds added timer jitter
  // at +20 ms per cadence (test tunings run 50-100 ms intervals;
  // production runs 200 ms+).  Only push-pull dials and inbound
  // push-pull exchanges — blocking TCP with 5 s timeouts — leave this
  // thread, as tracked transient handlers.
  void io_loop() {
    constexpr int kTick = 20;
    auto last_gossip = Clock::now();
    auto last_probe = last_gossip;
    auto last_pp = last_gossip;
    while (!quit_) {
      pollfd fds[2] = {{udp_fd_, POLLIN, 0}, {tcp_fd_, POLLIN, 0}};
      ::poll(fds, 2, kTick);
      if (quit_) return;
      if (fds[0].revents & POLLIN) handle_udp_ready();
      if (fds[1].revents & POLLIN) handle_tcp_ready();
      auto now = Clock::now();
      if (now - last_gossip >= Millis(gossip_ms_)) {
        last_gossip = now;
        gossip_once();
      }
      if (now - last_probe >= Millis(probe_interval_ms_)) {
        last_probe = now;
        probe_once();
      }
      if (now - last_pp >= Millis(pushpull_ms_)) {
        last_pp = now;
        // Periodic anti-entropy with one random member
        // (PushPullInterval, main.go:252-256), dispatched onto a
        // tracked transient thread: pushpull_with can block up to 5 s
        // on a dead peer and must not stall probes/gossip.  At most
        // ONE periodic exchange in flight (the old loop's serialization
        // — a dead peer at fast test cadences would otherwise pile up
        // a dialer thread per tick).
        auto targets = pick_members(1);
        if (!targets.empty() && !pp_inflight_->load()) {
          pp_inflight_->store(true);
          auto done = std::make_shared<std::atomic<bool>>(false);
          auto inflight = pp_inflight_;
          std::string ip = targets[0].ip;
          uint16_t port = targets[0].port;
          std::thread t([this, ip, port, done, inflight] {
            pushpull_with(ip, port);
            inflight->store(false);
            done->store(true);
          });
          std::lock_guard<std::mutex> lk(handlers_mu_);
          handlers_.push_back({std::move(t), std::move(done), -1});
        }
        reap_handlers(/*join_all=*/false);
      }
    }
  }

  // The SWIM probe cycle: direct ping → (timeout) → indirect ping-req
  // through up to k proxies → (timeout) → suspect + broadcast →
  // (suspect timeout without refutation) → dead + broadcast.
  void probe_once() {
    auto now = Clock::now();
    std::vector<UdpSend> sends;
    std::vector<std::pair<std::string, Member>> need_indirect;

    {
      std::lock_guard<std::mutex> lk(mu_);
      // Expire stale proxy bookkeeping.
      for (auto it = forwards_.begin(); it != forwards_.end();)
        it = (now > it->second.expires) ? forwards_.erase(it) : ++it;

      for (auto it = pending_.begin(); it != pending_.end();) {
        PendingProbe& pr = it->second;
        auto mit = members_.find(pr.target);
        if (mit == members_.end()) {
          it = pending_.erase(it);
          continue;
        }
        if (!pr.indirect_sent && now > pr.direct_deadline) {
          pr.indirect_sent = true;
          pr.indirect_deadline = now + Millis(probe_timeout_ms_);
          need_indirect.push_back({pr.target, mit->second});
          ++it;
        } else if (pr.indirect_sent && now > pr.indirect_deadline) {
          // No direct or relayed ack: suspicion.
          Member& m = mit->second;
          if (!m.suspect) {
            m.suspect = true;
            m.suspect_since = now;
            queue_membership_locked(kMemberSuspect, m.incarnation,
                                    m.name, m.ip, m.port);
            logf('I', "suspecting " + m.name +
                          " (no ack, direct or indirect)");
          }
          it = pending_.erase(it);
        } else {
          ++it;
        }
      }

      // Suspect → dead after the (refutable) suspicion window.
      std::vector<std::string> dead;
      for (auto it = members_.begin(); it != members_.end();) {
        Member& m = it->second;
        if (m.suspect &&
            std::chrono::duration_cast<Millis>(now - m.suspect_since)
                    .count() > suspect_timeout_ms_) {
          dead.push_back(m.name);
          mark_dead_locked(m.name, m.incarnation);
          queue_membership_locked(kMemberDead, m.incarnation, m.name,
                                  m.ip, m.port);
          it = members_.erase(it);
          continue;
        }
        ++it;
      }
      for (auto& d : dead) {
        events_.push_back("leave " + d);
        logf('I', d + " failed (suspect timeout); declared dead");
      }
    }

    // Fire the queued indirect probes (pick proxies outside the probe
    // bookkeeping pass; sends happen outside the lock).
    for (auto& [tname, target] : need_indirect) {
      uint32_t origin_seq = 0;
      {
        std::lock_guard<std::mutex> lk(mu_);
        for (auto& kv : pending_)
          if (kv.second.target == tname) origin_seq = kv.first;
      }
      for (auto& proxy : pick_members(indirect_k_, tname)) {
        std::string req = packet_header(kTypePingReq);
        put_u32(&req, origin_seq);
        put_str8(&req, target.name);
        put_str8(&req, target.ip);
        put_u16(&req, target.port);
        sends.push_back({proxy.ip, proxy.port, std::move(req)});
      }
    }

    // Start a fresh direct probe of one random member — unless that
    // member already has a probe in flight (overlapping probes of one
    // target confuse the rescue bookkeeping and double suspicion).
    auto targets = pick_members(1);
    if (!targets.empty()) {
      bool already = false;
      uint32_t seq = next_seq_++;
      {
        std::lock_guard<std::mutex> lk(mu_);
        for (auto& kv : pending_)
          if (kv.second.target == targets[0].name) already = true;
        if (!already)
          pending_[seq] = {targets[0].name,
                           now + Millis(probe_timeout_ms_), false, {}};
      }
      if (!already) {
        std::string ping = packet_header(kTypePing);
        put_u32(&ping, seq);
        sends.push_back(
            {targets[0].ip, targets[0].port, std::move(ping)});
      }
    }
    for (auto& s : sends) send_to(s.ip, s.port, s.pkt);
  }

  // -- TCP push-pull ------------------------------------------------------

  struct Handler {
    std::thread thread;
    std::shared_ptr<std::atomic<bool>> done;
    int fd = -1;
  };

  void reap_handlers(bool join_all) {
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> lk(handlers_mu_);
      for (auto it = handlers_.begin(); it != handlers_.end();) {
        if (join_all || it->done->load()) {
          to_join.push_back(std::move(it->thread));
          it = handlers_.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (auto& t : to_join)
      if (t.joinable()) t.join();
  }

  // Accept every pending connection on the (non-blocking) TCP socket.
  void handle_tcp_ready() {
    for (;;) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      int fd = accept(tcp_fd_, reinterpret_cast<sockaddr*>(&src), &slen);
      reap_handlers(/*join_all=*/false);
      if (fd < 0) return;
      // Bound the handler's lifetime: a peer that stalls mid-exchange
      // times out instead of pinning the thread (and stop()'s join).
      timeval tv{5, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      auto done = std::make_shared<std::atomic<bool>>(false);
      std::thread t([this, fd, done] {
        handle_pushpull_conn(fd);
        done->store(true);
        // Close under handlers_mu_ so stop()'s shutdown of still-running
        // handlers can never race a reused descriptor.
        std::lock_guard<std::mutex> lk(handlers_mu_);
        close(fd);
      });
      std::lock_guard<std::mutex> lk(handlers_mu_);
      handlers_.push_back({std::move(t), std::move(done), fd});
    }
  }

  // Framed state exchange: both sides send
  //   [header][state_len u32][state bytes]
  void send_state_frame(int fd) {
    std::string hdr = packet_header(kTypeGossip);
    std::string state;
    {
      std::lock_guard<std::mutex> lk(mu_);
      state = local_state_;
    }
    std::string out;
    out.reserve(hdr.size() + 4 + state.size());
    out += hdr;
    put_u32(&out, static_cast<uint32_t>(state.size()));
    out += state;
    write_full(fd, out.data(), out.size());
  }

  bool recv_state_frame(int fd) {
    // Whole-exchange deadline (see read_full).
    auto deadline = Clock::now() + Millis(30000);
    uint8_t fixed[5];
    if (!read_full(fd, fixed, 5, deadline) || get_u32(fixed) != kMagic)
      return false;
    auto read_str8 = [&](std::string* out) {
      uint8_t n;
      if (!read_full(fd, &n, 1, deadline)) return false;
      out->resize(n);
      return n == 0 || read_full(fd, &(*out)[0], n, deadline);
    };
    std::string cluster, node, ip;
    uint8_t pbuf[6];
    if (!read_str8(&cluster) || !read_str8(&node) || !read_str8(&ip) ||
        !read_full(fd, pbuf, 6, deadline))
      return false;
    // Cluster isolation BEFORE the payload: a foreign (or hostile) peer
    // must not get to size our allocation.
    if (cluster != cluster_) return false;
    {
      // Injected-partition gating: refuse the exchange before the
      // payload, so neither side merges (the initiator's recv then
      // fails too — a severed pair exchanges nothing, like a real cut).
      std::lock_guard<std::mutex> lk(mu_);
      auto dit = test_drops_.find(node);
      if (dit != test_drops_.end() && (dit->second >> kTypePushPull) & 1u)
        return false;
    }
    uint16_t port = get_u16(pbuf);
    uint32_t inc = get_u32(pbuf + 2);
    uint8_t lbuf[4];
    if (!read_full(fd, lbuf, 4, deadline)) return false;
    uint32_t slen = get_u32(lbuf);
    if (slen > (64u << 20)) {  // sanity cap: 64 MB
      logf('E', "push-pull state from " + node + " exceeds 64 MB; dropped");
      return false;
    }
    std::string state(slen, '\0');
    if (slen > 0 && !read_full(fd, &state[0], slen, deadline)) return false;
    heard_from(node, ip, port, inc);
    if (!state.empty()) {
      std::lock_guard<std::mutex> lk(mu_);
      states_.push_back(std::move(state));
      if (states_.size() > 64) states_.pop_front();
    }
    return true;
  }

  void handle_pushpull_conn(int fd) {
    // Remote sends first, then we reply (LocalState/MergeRemoteState).
    pushpull_in_.fetch_add(1, std::memory_order_relaxed);
    if (!recv_state_frame(fd)) return;
    send_state_frame(fd);
  }

  // Resolve a seed given by hostname or dotted quad to an IPv4 address.
  // Seeds are normally names under compose/Kubernetes; the reference gets
  // name-based joining for free from memberlist's Join (which resolves
  // each seed, main.go:264) — here getaddrinfo fills the same role.
  static bool resolve_ipv4(const std::string& host, in_addr* out) {
    in_addr direct{};
    if (inet_aton(host.c_str(), &direct)) {  // fast path: already an IP
      *out = direct;
      return true;
    }
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
      return false;
    *out = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
    return true;
  }

  bool pushpull_with(const std::string& host, uint16_t port) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (!resolve_ipv4(host, &addr.sin_addr)) {
      logf('W', "cannot resolve seed host " + host);
      return false;
    }
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    timeval tv{5, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      close(fd);
      logf('W', "push-pull connect to " + host + " failed");
      return false;
    }
    pushpull_out_.fetch_add(1, std::memory_order_relaxed);
    send_state_frame(fd);
    bool ok = recv_state_frame(fd);
    close(fd);
    return ok;
  }

  std::string name_, cluster_, bind_ip_, advertise_ip_;
  uint16_t bind_port_;
  int gossip_ms_, pushpull_ms_, gossip_nodes_, gossip_messages_;
  int probe_interval_ms_, probe_timeout_ms_, suspect_timeout_ms_,
      indirect_k_;
  size_t header_overhead_ = 64;
  int udp_fd_ = -1, tcp_fd_ = -1;
  std::atomic<bool> quit_{true};
  std::atomic<uint32_t> incarnation_{1};
  std::atomic<uint32_t> next_seq_{1};
  std::atomic<unsigned long long> udp_out_{0}, udp_bytes_out_{0},
      udp_in_{0}, udp_bytes_in_{0}, pushpull_out_{0}, pushpull_in_{0},
      udp_send_drops_{0};
  std::vector<std::thread> threads_;
  std::vector<uint8_t> udp_buf_ = std::vector<uint8_t>(65536);
  std::shared_ptr<std::atomic<bool>> pp_inflight_ =
      std::make_shared<std::atomic<bool>>(false);
  std::mutex mu_;
  std::map<std::string, Member> members_;
  std::deque<Broadcast> queue_;    // user payloads
  std::deque<Broadcast> mqueue_;   // membership updates (priority)
  std::deque<std::string> inbound_, states_, events_;
  // Received-record handoff queue bound (memberlist HandoffQueueDepth,
  // config/config.go:48 — reference default 1024): a slow host-side
  // consumer sheds the OLDEST records; anti-entropy re-delivers them.
  size_t handoff_depth_ = 1024;
  std::map<uint32_t, PendingProbe> pending_;
  std::map<uint32_t, Forward> forwards_;
  std::map<std::string, uint32_t> dead_;  // death-cert incarnation marks
  std::map<std::string, Clock::time_point> echo_last_;  // per-ghost limit
  Clock::time_point echo_window_{};  // global echo token window
  int echo_budget_ = 32;             // echoes left in the window
  std::map<std::string, uint32_t> test_drops_;
  std::string local_state_;
  std::mt19937 rng_;
  std::mutex handlers_mu_;
  std::vector<Handler> handlers_;
  std::mutex log_mu_;
  std::deque<std::string> logs_;
};

int copy_out(const std::string& s, uint8_t* buf, int cap) {
  if (s.empty()) return 0;
  int n = static_cast<int>(std::min<size_t>(s.size(), cap));
  memcpy(buf, s.data(), n);
  return n;
}

}  // namespace

extern "C" {

void* st_create(const char* name, const char* cluster, const char* bind_ip,
                int bind_port, const char* advertise_ip, int gossip_ms,
                int pushpull_ms, int gossip_nodes, int gossip_messages) {
  return new Transport(name, cluster, bind_ip, (uint16_t)bind_port,
                       advertise_ip, gossip_ms, pushpull_ms, gossip_nodes,
                       gossip_messages);
}

int st_start(void* h) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->start();
}

int st_join(void* h, const char* host, int port) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->join(host, (uint16_t)port) ? 0 : -1;
}

void st_broadcast(void* h, const uint8_t* data, int len) {
  if (!h) return;
  static_cast<Transport*>(h)->broadcast(data, (size_t)len);
}

void st_set_local_state(void* h, const uint8_t* data, int len) {
  if (!h) return;
  static_cast<Transport*>(h)->set_local_state(data, (size_t)len);
}

void st_set_handoff_depth(void* h, int depth) {
  if (!h) return;
  static_cast<Transport*>(h)->set_handoff_depth(depth);
}

void st_configure_probe(void* h, int interval_ms, int timeout_ms,
                        int suspect_ms, int indirect_k) {
  if (!h) return;
  static_cast<Transport*>(h)->configure_probe(interval_ms, timeout_ms,
                                              suspect_ms, indirect_k);
}

void st_test_drop_types(void* h, const char* node, unsigned type_mask) {
  if (!h) return;
  static_cast<Transport*>(h)->test_drop_types(node, type_mask);
}

int st_poll_msg(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_msg(), buf, cap);
}

int st_next_state_len(void* h) {
  if (!h) return 0;
  return static_cast<Transport*>(h)->next_state_len();
}

int st_poll_state(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_state(), buf, cap);
}

int st_poll_event(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_event(), buf, cap);
}

int st_poll_log(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_log(), buf, cap);
}

int st_members(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->members_list(), buf, cap);
}

int st_stats(void* h, unsigned long long* out, int n) {
  if (!h) return 0;
  return static_cast<Transport*>(h)->stats(out, n);
}

int st_port(void* h) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->port();
}

void st_stop(void* h) {
  if (h) static_cast<Transport*>(h)->stop();
}

void st_destroy(void* h) { delete static_cast<Transport*>(h); }

}  // extern "C"
