// Gossip transport core — the memberlist-equivalent native engine.
//
// Capability mirror of the reference's external dependency
// NinesStack/memberlist as used by Sidecar (main.go:239-274,
// services_delegate.go): SWIM-style UDP failure detection (ping/ack with
// suspicion), piggybacked gossip broadcast every GossipInterval packed
// first-fit into ~1398-byte UDP packets (services_delegate.go:182-223),
// TCP full-state push-pull anti-entropy on join and every
// PushPullInterval (services_delegate.go:146-167), and ClusterName
// isolation (services_delegate.go:29-32).
//
// Design: the engine runs its own threads for network IO and exposes a
// poll-based C API (create/start/join/broadcast/poll_*) consumed from
// Python via ctypes — no callbacks cross the language boundary, so there
// are no GIL-reentrancy hazards.  Inbound user messages, full-state
// payloads, and membership events are queued until the host drains them.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;
using Millis = std::chrono::milliseconds;

// Wire constants.
constexpr uint32_t kMagic = 0x53433031;  // "SC01"
constexpr size_t kMaxPacket = 1398;      // single-UDP-packet budget
constexpr uint8_t kTypeGossip = 0;
constexpr uint8_t kTypePing = 1;
constexpr uint8_t kTypeAck = 2;

constexpr int kProbeTimeoutMs = 1000;    // ack deadline
constexpr int kSuspectTimeoutMs = 3000;  // suspect -> dead
constexpr int kRetransmitMult = 4;       // memberlist RetransmitMult

struct Member {
  std::string name;
  std::string ip;
  uint16_t port = 0;
  bool suspect = false;
  Clock::time_point last_heard = Clock::now();
  Clock::time_point suspect_since;
};

struct Broadcast {
  std::string payload;
  int transmits_left = 0;
};

void put_u16(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v & 0xff));
}

uint16_t get_u16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}

void put_u32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  out->push_back(static_cast<char>((v >> 16) & 0xff));
  out->push_back(static_cast<char>((v >> 8) & 0xff));
  out->push_back(static_cast<char>(v & 0xff));
}

uint32_t get_u32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

void put_str8(std::string* out, const std::string& s) {
  uint8_t n = static_cast<uint8_t>(std::min<size_t>(s.size(), 255));
  out->push_back(static_cast<char>(n));
  out->append(s.data(), n);
}

bool get_str8(const uint8_t*& p, const uint8_t* end, std::string* out) {
  if (p >= end) return false;
  uint8_t n = *p++;
  if (p + n > end) return false;
  out->assign(reinterpret_cast<const char*>(p), n);
  p += n;
  return true;
}

bool read_full(int fd, void* buf, size_t len) {
  auto* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t len) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = send(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

class Transport {
 public:
  Transport(std::string name, std::string cluster, std::string bind_ip,
            uint16_t bind_port, std::string advertise_ip, int gossip_ms,
            int pushpull_ms, int gossip_nodes, int gossip_messages)
      : name_(std::move(name)),
        cluster_(std::move(cluster)),
        bind_ip_(std::move(bind_ip)),
        advertise_ip_(std::move(advertise_ip)),
        bind_port_(bind_port),
        gossip_ms_(gossip_ms),
        pushpull_ms_(pushpull_ms),
        gossip_nodes_(gossip_nodes),
        gossip_messages_(gossip_messages),
        rng_(std::random_device{}()) {}

  ~Transport() { stop(); }

  // Binds sockets and launches the IO threads.  Returns the actual bound
  // port (0 input picks an ephemeral port) or -1 on failure.
  int start() {
    udp_fd_ = socket(AF_INET, SOCK_DGRAM, 0);
    tcp_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (udp_fd_ < 0 || tcp_fd_ < 0) return -1;
    int one = 1;
    setsockopt(tcp_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(bind_port_);
    addr.sin_addr.s_addr =
        bind_ip_.empty() ? INADDR_ANY : inet_addr(bind_ip_.c_str());
    if (bind(udp_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return -1;

    socklen_t len = sizeof(addr);
    getsockname(udp_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    bind_port_ = ntohs(addr.sin_port);  // both protocols share the port

    sockaddr_in taddr = addr;
    if (bind(tcp_fd_, reinterpret_cast<sockaddr*>(&taddr), sizeof(taddr)) < 0)
      return -1;
    if (listen(tcp_fd_, 16) < 0) return -1;

    // 500 ms recv timeout so loops notice quit_.
    timeval tv{0, 500000};
    setsockopt(udp_fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(tcp_fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

    quit_ = false;
    threads_.emplace_back(&Transport::udp_loop, this);
    threads_.emplace_back(&Transport::gossip_loop, this);
    threads_.emplace_back(&Transport::probe_loop, this);
    threads_.emplace_back(&Transport::tcp_accept_loop, this);
    threads_.emplace_back(&Transport::pushpull_loop, this);
    return bind_port_;
  }

  void stop() {
    if (quit_.exchange(true)) return;
    for (auto& t : threads_)
      if (t.joinable()) t.join();
    threads_.clear();
    if (udp_fd_ >= 0) close(udp_fd_);
    if (tcp_fd_ >= 0) close(tcp_fd_);
    udp_fd_ = tcp_fd_ = -1;
  }

  // TCP dial a seed and run the join push-pull (README.md:83-87).
  bool join(const std::string& host, uint16_t port) {
    return pushpull_with(host, port);
  }

  void broadcast(const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    int n_members = static_cast<int>(members_.size()) + 1;
    int limit = kRetransmitMult *
                static_cast<int>(std::ceil(std::log10(n_members + 1)));
    queue_.push_back(
        {std::string(reinterpret_cast<const char*>(data), len),
         std::max(limit, 1)});
    // MAX_PENDING-ish bound so a partitioned node doesn't grow forever.
    while (queue_.size() > 4096) queue_.pop_front();
  }

  void set_local_state(const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    local_state_.assign(reinterpret_cast<const char*>(data), len);
  }

  // Poll queues (returns empty string when drained).
  std::string poll(std::deque<std::string>* q) {
    std::lock_guard<std::mutex> lk(mu_);
    if (q->empty()) return {};
    std::string out = std::move(q->front());
    q->pop_front();
    return out;
  }

  std::string poll_msg() { return poll(&inbound_); }
  std::string poll_state() { return poll(&states_); }
  std::string poll_event() { return poll(&events_); }

  std::string members_list() {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out = name_ + "\n";
    for (auto& kv : members_) out += kv.first + "\n";
    return out;
  }

  uint16_t port() const { return bind_port_; }

 private:
  // -- packet building ---------------------------------------------------

  std::string packet_header(uint8_t type) {
    std::string out;
    put_u32(&out, kMagic);
    out.push_back(static_cast<char>(type));
    put_str8(&out, cluster_);
    put_str8(&out, name_);
    put_str8(&out, advertise_ip_);
    put_u16(&out, bind_port_);
    return out;
  }

  // First-fit packing of queued broadcasts into one UDP packet
  // (packPacket, services_delegate.go:182-223).
  std::string build_gossip_packet() {
    std::string pkt = packet_header(kTypeGossip);
    std::lock_guard<std::mutex> lk(mu_);
    int packed = 0;
    for (auto it = queue_.begin();
         it != queue_.end() && packed < gossip_messages_;) {
      size_t frame = 2 + it->payload.size();
      if (pkt.size() + frame > kMaxPacket) {
        ++it;
        continue;  // first-fit: try a smaller one
      }
      put_u16(&pkt, static_cast<uint16_t>(it->payload.size()));
      pkt += it->payload;
      ++packed;
      if (--it->transmits_left <= 0)
        it = queue_.erase(it);
      else
        ++it;
    }
    if (packed == 0) return {};
    return pkt;
  }

  void send_to(const std::string& ip, uint16_t port,
               const std::string& pkt) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    addr.sin_addr.s_addr = inet_addr(ip.c_str());
    sendto(udp_fd_, pkt.data(), pkt.size(), 0,
           reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  }

  std::vector<Member> pick_members(int k) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<Member> all;
    all.reserve(members_.size());
    for (auto& kv : members_) all.push_back(kv.second);
    std::shuffle(all.begin(), all.end(), rng_);
    if (static_cast<int>(all.size()) > k) all.resize(k);
    return all;
  }

  // -- member accounting -------------------------------------------------

  void heard_from(const std::string& node, const std::string& ip,
                  uint16_t port) {
    if (node == name_) return;
    std::lock_guard<std::mutex> lk(mu_);
    auto it = members_.find(node);
    if (it == members_.end()) {
      members_[node] = {node, ip, port, false, Clock::now(), {}};
      events_.push_back("join " + node + " " + ip);
    } else {
      it->second.last_heard = Clock::now();
      it->second.suspect = false;
      it->second.ip = ip;
      it->second.port = port;
    }
  }

  // -- IO loops ----------------------------------------------------------

  void udp_loop() {
    std::vector<uint8_t> buf(65536);
    while (!quit_) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      ssize_t n = recvfrom(udp_fd_, buf.data(), buf.size(), 0,
                           reinterpret_cast<sockaddr*>(&src), &slen);
      if (n <= 0) continue;
      const uint8_t* p = buf.data();
      const uint8_t* end = p + n;
      if (n < 5 || get_u32(p) != kMagic) continue;
      uint8_t type = p[4];
      p += 5;
      std::string cluster, node, ip;
      if (!get_str8(p, end, &cluster) || !get_str8(p, end, &node) ||
          !get_str8(p, end, &ip) || p + 2 > end)
        continue;
      uint16_t port = get_u16(p);
      p += 2;
      // ClusterName isolation (services_delegate.go:29-32).
      if (cluster != cluster_) continue;
      heard_from(node, ip, port);

      if (type == kTypePing) {
        std::string ack = packet_header(kTypeAck);
        send_to(ip, port, ack);
      } else if (type == kTypeGossip) {
        while (p + 2 <= end) {
          uint16_t flen = get_u16(p);
          p += 2;
          if (p + flen > end) break;
          std::lock_guard<std::mutex> lk(mu_);
          inbound_.emplace_back(reinterpret_cast<const char*>(p), flen);
          if (inbound_.size() > 65536) inbound_.pop_front();
          p += flen;
        }
      }
      // kTypeAck: heard_from already refreshed liveness.
    }
  }

  void gossip_loop() {
    while (!quit_) {
      std::this_thread::sleep_for(Millis(gossip_ms_));
      std::string pkt = build_gossip_packet();
      if (pkt.empty()) continue;
      for (auto& m : pick_members(gossip_nodes_)) send_to(m.ip, m.port, pkt);
    }
  }

  void probe_loop() {
    while (!quit_) {
      std::this_thread::sleep_for(Millis(std::max(gossip_ms_ * 5, 500)));
      auto targets = pick_members(1);
      if (!targets.empty()) {
        std::string ping = packet_header(kTypePing);
        send_to(targets[0].ip, targets[0].port, ping);
      }
      // Sweep: probe timeouts -> suspect -> dead (SWIM-lite; the
      // reference's NotifyLeave -> ExpireServer path).
      std::vector<std::string> dead;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto now = Clock::now();
        for (auto it = members_.begin(); it != members_.end();) {
          auto& m = it->second;
          auto quiet = std::chrono::duration_cast<Millis>(
                           now - m.last_heard).count();
          if (!m.suspect && quiet > kProbeTimeoutMs + gossip_ms_ * 10) {
            m.suspect = true;
            m.suspect_since = now;
          }
          if (m.suspect &&
              std::chrono::duration_cast<Millis>(now - m.suspect_since)
                      .count() > kSuspectTimeoutMs) {
            dead.push_back(it->first);
            it = members_.erase(it);
            continue;
          }
          ++it;
        }
        for (auto& d : dead) events_.push_back("leave " + d);
      }
    }
  }

  void tcp_accept_loop() {
    while (!quit_) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      int fd = accept(tcp_fd_, reinterpret_cast<sockaddr*>(&src), &slen);
      if (fd < 0) continue;
      std::thread([this, fd] {
        handle_pushpull_conn(fd);
        close(fd);
      }).detach();
    }
  }

  // Framed state exchange: both sides send
  //   [magic u32][cluster str8][name str8][ip str8][port u16]
  //   [state_len u32][state bytes]
  void send_state_frame(int fd) {
    std::string hdr = packet_header(kTypeGossip);
    std::string state;
    {
      std::lock_guard<std::mutex> lk(mu_);
      state = local_state_;
    }
    std::string out;
    out.reserve(hdr.size() + 4 + state.size());
    out += hdr;
    put_u32(&out, static_cast<uint32_t>(state.size()));
    out += state;
    write_full(fd, out.data(), out.size());
  }

  bool recv_state_frame(int fd) {
    uint8_t fixed[5];
    if (!read_full(fd, fixed, 5) || get_u32(fixed) != kMagic) return false;
    auto read_str8 = [&](std::string* out) {
      uint8_t n;
      if (!read_full(fd, &n, 1)) return false;
      out->resize(n);
      return n == 0 || read_full(fd, &(*out)[0], n);
    };
    std::string cluster, node, ip;
    uint8_t pbuf[2];
    if (!read_str8(&cluster) || !read_str8(&node) || !read_str8(&ip) ||
        !read_full(fd, pbuf, 2))
      return false;
    uint16_t port = get_u16(pbuf);
    uint8_t lbuf[4];
    if (!read_full(fd, lbuf, 4)) return false;
    uint32_t slen = get_u32(lbuf);
    if (slen > (64u << 20)) return false;  // sanity cap: 64 MB
    std::string state(slen, '\0');
    if (slen > 0 && !read_full(fd, &state[0], slen)) return false;
    if (cluster != cluster_) return false;
    heard_from(node, ip, port);
    if (!state.empty()) {
      std::lock_guard<std::mutex> lk(mu_);
      states_.push_back(std::move(state));
      if (states_.size() > 64) states_.pop_front();
    }
    return true;
  }

  void handle_pushpull_conn(int fd) {
    // Remote sends first, then we reply (LocalState/MergeRemoteState).
    if (!recv_state_frame(fd)) return;
    send_state_frame(fd);
  }

  bool pushpull_with(const std::string& host, uint16_t port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    timeval tv{5, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    addr.sin_addr.s_addr = inet_addr(host.c_str());
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      close(fd);
      return false;
    }
    send_state_frame(fd);
    bool ok = recv_state_frame(fd);
    close(fd);
    return ok;
  }

  void pushpull_loop() {
    // Periodic anti-entropy with one random member
    // (PushPullInterval, main.go:252-256).
    int elapsed = 0;
    while (!quit_) {
      std::this_thread::sleep_for(Millis(250));
      elapsed += 250;
      if (elapsed < pushpull_ms_) continue;
      elapsed = 0;
      auto targets = pick_members(1);
      if (!targets.empty())
        pushpull_with(targets[0].ip, targets[0].port);
    }
  }

  std::string name_, cluster_, bind_ip_, advertise_ip_;
  uint16_t bind_port_;
  int gossip_ms_, pushpull_ms_, gossip_nodes_, gossip_messages_;
  int udp_fd_ = -1, tcp_fd_ = -1;
  std::atomic<bool> quit_{true};
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::map<std::string, Member> members_;
  std::deque<Broadcast> queue_;
  std::deque<std::string> inbound_, states_, events_;
  std::string local_state_;
  std::mt19937 rng_;
};

int copy_out(const std::string& s, uint8_t* buf, int cap) {
  if (s.empty()) return 0;
  int n = static_cast<int>(std::min<size_t>(s.size(), cap));
  memcpy(buf, s.data(), n);
  return n;
}

}  // namespace

extern "C" {

void* st_create(const char* name, const char* cluster, const char* bind_ip,
                int bind_port, const char* advertise_ip, int gossip_ms,
                int pushpull_ms, int gossip_nodes, int gossip_messages) {
  return new Transport(name, cluster, bind_ip, (uint16_t)bind_port,
                       advertise_ip, gossip_ms, pushpull_ms, gossip_nodes,
                       gossip_messages);
}

int st_start(void* h) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->start();
}

int st_join(void* h, const char* host, int port) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->join(host, (uint16_t)port) ? 0 : -1;
}

void st_broadcast(void* h, const uint8_t* data, int len) {
  if (!h) return;
  static_cast<Transport*>(h)->broadcast(data, (size_t)len);
}

void st_set_local_state(void* h, const uint8_t* data, int len) {
  if (!h) return;
  static_cast<Transport*>(h)->set_local_state(data, (size_t)len);
}

int st_poll_msg(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_msg(), buf, cap);
}

int st_poll_state(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_state(), buf, cap);
}

int st_poll_event(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->poll_event(), buf, cap);
}

int st_members(void* h, uint8_t* buf, int cap) {
  if (!h) return 0;
  return copy_out(static_cast<Transport*>(h)->members_list(), buf, cap);
}

int st_port(void* h) {
  if (!h) return -1;
  return static_cast<Transport*>(h)->port();
}

void st_stop(void* h) {
  if (h) static_cast<Transport*>(h)->stop();
}

void st_destroy(void* h) { delete static_cast<Transport*>(h); }

}  // extern "C"
