"""Node bootstrap: wires config → catalog → discovery → health →
broadcast loops → HTTP API → proxies → gossip transport
(reference: main.go:284-414 and its configure* helpers).

``SidecarNode`` owns the whole object graph so tests can assemble nodes
in-process; ``main()`` is the CLI entry point
(``python -m sidecar_tpu.main`` or the ``sidecar-tpu`` alias)."""

from __future__ import annotations

import argparse
import logging
import pathlib
import sys
import threading
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu import service as svc_mod
from sidecar_tpu.addresses import get_published_ip
from sidecar_tpu.catalog import ServicesState
from sidecar_tpu.catalog.url_listener import UrlListener
from sidecar_tpu.config import Config, format_config, parse_config
from sidecar_tpu.discovery import MultiDiscovery, StaticDiscovery
from sidecar_tpu.discovery.base import ChangeListener, Discoverer
from sidecar_tpu.discovery.docker import DockerDiscovery
from sidecar_tpu.discovery.kubernetes import (
    K8sAPIDiscoverer,
    KubeAPIDiscoveryCommand,
)
from sidecar_tpu.discovery.namer import DockerLabelNamer, RegexpNamer
from sidecar_tpu.health import Monitor
from sidecar_tpu.health.monitor import HEALTH_INTERVAL, WATCH_INTERVAL
from sidecar_tpu.proxy.envoy import EnvoyApiV1, XdsServer
from sidecar_tpu.proxy.haproxy import HAProxy
from sidecar_tpu.runtime.looper import TimedLooper
from sidecar_tpu.runtime.scheduler import Scheduler
from sidecar_tpu.web import SidecarApi, serve_http

log = logging.getLogger(__name__)


def configure_logging(level: str, fmt: str = "") -> None:
    """main.go:212-237."""
    levels = {"debug": logging.DEBUG, "info": logging.INFO,
              "warn": logging.WARNING, "error": logging.ERROR}
    logging.basicConfig(
        level=levels.get(level.lower(), logging.INFO),
        format=("%(message)s" if fmt == "json" else
                "%(asctime)s %(levelname)-7s %(name)s: %(message)s"))


def configure_discovery(config: Config, advertise_ip: str,
                        hostname: Optional[str] = None) -> MultiDiscovery:
    """main.go:62-141 — build the discovery stack from config."""
    discoverers: list[Discoverer] = []
    for kind in config.sidecar.discovery:
        if kind == "docker":
            if config.services.service_namer == "regex":
                namer = RegexpNamer(config.services.name_match)
            else:
                namer = DockerLabelNamer(config.services.name_label)
            discoverers.append(DockerDiscovery(
                config.docker_discovery.docker_url, namer, advertise_ip,
                hostname=hostname))
        elif kind == "static":
            discoverers.append(StaticDiscovery(
                config.static_discovery.config_file, advertise_ip,
                hostname=hostname))
        elif kind == "kubernetes_api":
            k8s = config.k8s_api_discovery
            discoverers.append(K8sAPIDiscoverer(
                KubeAPIDiscoveryCommand(
                    k8s.kube_api_ip, k8s.kube_api_port, k8s.namespace,
                    k8s.kube_timeout, k8s.creds_path),
                namespace=k8s.namespace,
                announce_all_nodes=k8s.announce_all_nodes,
                hostname=hostname or ""))
        elif kind == "none":
            continue
        else:
            log.error("Unrecognized discovery method: %s", kind)
    return MultiDiscovery(discoverers)


class SidecarNode:
    """The assembled node (main.go:284-414)."""

    def __init__(self, config: Optional[Config] = None,
                 hostname: Optional[str] = None,
                 transport=None) -> None:
        import socket

        self.config = config if config is not None else parse_config()
        self.hostname = hostname or socket.gethostname()
        # statsd export when SIDECAR_STATS_ADDR is set (main.go:156-166).
        metrics.configure_statsd(self.config.sidecar.stats_addr)
        self.advertise_ip = get_published_ip(
            self.config.sidecar.exclude_ips,
            self.config.sidecar.advertise_ip)
        self.state = ServicesState(
            hostname=self.hostname,
            cluster_name=self.config.sidecar.cluster_name)
        # Future-admission bound (SIDECAR_TPU_FUTURE_FUDGE, docs/env.md):
        # negative leaves the reference-exact writer path untouched.
        self.state.future_fudge_s = self.config.sidecar.future_fudge
        # Flap damping (catalog/damping.py, docs/chaos.md): attached
        # only when SIDECAR_DAMPING_THRESHOLD enables it — the damper
        # then observes every catalog status transition and the proxy
        # resource generators (HAProxy, Envoy ADS) gate admission on
        # it.  The same knobs flow through POST /simulate so the sim
        # predicts exactly this node's damping decisions.
        if self.config.sidecar.damping_threshold > 0:
            from sidecar_tpu.catalog.damping import FlapDamper
            from sidecar_tpu.ops.suspicion import ProtocolParams

            self.state.attach_damper(FlapDamper.from_protocol(
                ProtocolParams.from_config(self.config.sidecar)))
        # Origin-admission gate (ops/suspicion.QuarantineScorer,
        # docs/chaos.md): attached only when both
        # SIDECAR_TPU_ORIGIN_BUDGET and _ORIGIN_QUARANTINE enable it —
        # push-pull bodies are then scored per origin and quarantined
        # origins rejected at the catalog writer, the live rung of the
        # sim's defense ladder.
        if self.config.sidecar.origin_budget >= 0 and \
                self.config.sidecar.origin_quarantine >= 0:
            from sidecar_tpu.ops.suspicion import (ProtocolParams,
                                                   QuarantineScorer)

            self.state.attach_origin_gate(QuarantineScorer(
                ProtocolParams.from_config(self.config.sidecar)))
        self.disco = configure_discovery(self.config, self.advertise_ip,
                                         self.hostname)
        self.monitor = Monitor(self.advertise_ip,
                               self.config.sidecar.default_check_endpoint)
        self.transport = transport
        self.api = SidecarApi(
            self.state,
            members_fn=self._members,
            cluster_name=self.config.sidecar.cluster_name,
            # The deprecated V1 REST SDS/CDS/LDS rides on the main HTTP
            # server, like the reference's mux (envoy_api.go:428-438).
            envoy_v1=EnvoyApiV1(
                self.state, bind_ip=self.config.envoy.bind_ip,
                use_hostnames=self.config.envoy.use_hostnames,
                cluster_name=self.config.sidecar.cluster_name),
            # The UI reads the managed HAProxy's stats CSV through the
            # API (reference UI hits :3212 directly, services.js:21-33);
            # ";norefresh" stops HAProxy's auto-refresh meta tag.
            haproxy_stats_url=(
                None if self.config.haproxy.disable
                else "http://127.0.0.1:3212/;csv;norefresh"))
        self.haproxy: Optional[HAProxy] = None
        if not self.config.haproxy.disable:
            # HAPROXY_TEMPLATE_FILE: resolve against the cwd first (an
            # operator's custom template), then the repo's stock
            # views/haproxy.cfg (the reference's default path).  An
            # EXPLICITLY configured template that's missing must fail
            # LOUDLY at the render (the driver raises; write_and_reload
            # renders before touching the file) — the operator's proxy
            # must not silently run a config shape they didn't write.
            # The unresolvable DEFAULT (e.g. a package-only install
            # without views/) falls back to the embedded renderer,
            # which produces the same config.
            from sidecar_tpu.config import HAproxyConfig

            tf = self.config.haproxy.template_file
            explicit = tf != HAproxyConfig().template_file
            if tf and not pathlib.Path(tf).is_file():
                repo_tf = pathlib.Path(__file__).resolve().parent.parent \
                    / tf
                if repo_tf.is_file():
                    tf = str(repo_tf)
                elif explicit:
                    log.error(
                        "HAPROXY_TEMPLATE_FILE %r not found; config "
                        "writes will fail until it exists", tf)
                else:
                    tf = ""     # default path absent → embedded renderer
            self.haproxy = HAProxy(
                config_file=self.config.haproxy.config_file,
                pid_file=self.config.haproxy.pid_file,
                bind_ip=self.config.haproxy.bind_ip,
                user=self.config.haproxy.user,
                group=self.config.haproxy.group,
                use_hostnames=self.config.haproxy.use_hostnames,
                reload_cmd=self.config.haproxy.reload_cmd,
                verify_cmd=self.config.haproxy.verify_cmd,
                template_file=tf)
        # use_grpc_api selects the transport for the SAME resource set:
        # the gRPC ADS stream (the reference's production path,
        # envoy/server.go:61-124) or REST xDS polling (main.go:397-411).
        self.xds = None
        self.ads = None
        if self.config.envoy.use_grpc_api:
            try:
                from sidecar_tpu.proxy.ads import AdsServer
            except ImportError as exc:
                # Fail fast: an Envoy fleet bootstrapped for a gRPC ADS
                # stream gets nothing from a silent REST fallback.
                raise RuntimeError(
                    "ENVOY_USE_GRPC_API=true but the gRPC stack is "
                    f"unavailable ({exc}); install grpcio/protobuf or "
                    "set ENVOY_USE_GRPC_API=false for REST xDS"
                ) from exc
            self.ads = AdsServer(self.state, self.config.envoy.bind_ip,
                                 self.config.envoy.use_hostnames)
        else:
            self.xds = XdsServer(self.state, self.config.envoy.bind_ip,
                                 self.config.envoy.use_hostnames)
        self._loopers: list[TimedLooper] = []
        self._scheduler = Scheduler(name="node-scheduler")
        self._http_server = None
        self._xds_server = None

    def _members(self) -> list[str]:
        if self.transport is not None:
            return self.transport.members()
        return sorted(self.state.servers)

    def _looper(self, interval: float) -> TimedLooper:
        looper = TimedLooper(interval)
        self._loopers.append(looper)
        return looper

    def start(self, http_port: int = 7777, xds_port: int = 7776,
              serve: bool = True) -> None:
        """Bring the node up (main.go:284-414 order)."""
        cfg = self.config.sidecar
        log.info("%s", format_config(self.config))

        # The query plane (sidecar_tpu/query/): attach the hub BEFORE
        # any traffic so the v1 snapshot is built at boot — every
        # read-path consumer below (UrlListener, /watch, ADS)
        # subscribes to it instead of touching the state lock.
        self.state.query_hub()

        # Single-writer state mutation loop (main.go:296-299).
        threading.Thread(
            target=self.state.process_service_msgs,
            args=(self._looper(0),), name="state-writer",
            daemon=True).start()

        # Static listener URLs from config (main.go:277-282).
        for url in self.config.listeners.urls:
            listener = UrlListener(url, managed=False)
            listener.watch(self.state)

        # Gossip transport (memberlist equivalent; main.go:239-274,308-316).
        if self.transport is not None:
            self.transport.start(self.state, seeds=cfg.seeds)

        # Discovery → health → catalog loops (main.go:318-385), all
        # driven by ONE scheduler thread (the reference multiplexes the
        # same duties over goroutines; a thread per loop measured ~50
        # threads/node in round 4).  Only genuinely blocking work keeps
        # a dedicated thread: the state-writer queue drain above and the
        # health-check tick (it waits up to interval−1 ms on its worker
        # pool, which would starve sibling tasks).
        self.disco.run(self._looper(cfg.discovery_sleep_interval))
        sched = self._scheduler
        watch_looper = self._looper(WATCH_INTERVAL)
        sched.drive(watch_looper, self._watch_once, name="monitor-watch")
        self._monitor_watch_looper = watch_looper
        monitor_run_looper = self._looper(HEALTH_INTERVAL)
        threading.Thread(target=self.monitor.run,
                         args=(monitor_run_looper,),
                         name="monitor-run", daemon=True).start()

        sched.drive(self._looper(1.0),
                    self.state.broadcast_services_step(
                        self.monitor.services),
                    name="broadcast-services")
        sched.drive(self._looper(2.0),
                    self.state.broadcast_tombstones_step(
                        self.monitor.services),
                    name="broadcast-tombstones")
        # Local services flow into the catalog via the single-writer queue
        # (state.TrackNewServices, main.go:382).
        sched.drive(self._looper(1.0),
                    self.state.track_new_services_step(
                        self.monitor.services),
                    name="track-services")
        sched.drive(self._looper(5.0),
                    self.state.track_local_listeners_step(
                        self._discovered_listeners),
                    name="track-listeners")

        # HTTP API (main.go:387-390).  Asset paths resolve against the
        # repo root (the sidecar_tpu package's parent) so the node works
        # from any working directory — cwd-relative paths still win if
        # they exist (an operator's own ui/ override).
        if serve:
            repo_root = pathlib.Path(__file__).resolve().parent.parent

            def _asset(rel: str) -> str:
                return rel if pathlib.Path(rel).is_dir() \
                    else str(repo_root / rel)

            self._http_server = serve_http(
                self.api, port=http_port, ui_dir=_asset("ui/app"),
                static_dir=_asset("views/static"))

        # Initial HAProxy write (main.go:392-395).
        if self.haproxy is not None:
            self.haproxy.watch(self.state)
            try:
                self.haproxy.write_and_reload(self.state)
            except (RuntimeError, OSError, ValueError) as exc:
                log.error("Initial HAProxy write failed: %s", exc)

        # Envoy xDS (main.go:397-411): gRPC ADS when use_grpc_api, else
        # the REST xDS poll transport, both on grpc_port.  A bind
        # failure (port taken — e.g. several nodes on one dev host)
        # must not kill the node: gossip, the catalog, HAProxy, and the
        # HTTP API are all still useful without a control plane.
        if serve:
            try:
                if self.ads is not None:
                    self.ads.serve(port=int(self.config.envoy.grpc_port))
                else:
                    self._xds_server = self.xds.serve(
                        port=int(self.config.envoy.grpc_port))
            except (OSError, RuntimeError) as exc:
                # OSError from the REST ThreadingHTTPServer; RuntimeError
                # from grpc's port-binding validation (ads.py disables
                # so_reuseport precisely so this surfaces).
                log.error(
                    "Envoy xDS server failed to start on port %s: %s — "
                    "continuing without a control plane "
                    "(set ENVOY_GRPC_PORT to a free port)",
                    self.config.envoy.grpc_port, exc)

    # The monitor.watch loop body needs the discoverer; wrap it so the
    # looper drives one sync per tick.
    def _watch_once(self) -> None:
        from sidecar_tpu.runtime.looper import FreeLooper
        self.monitor.watch(self.disco, FreeLooper(1))

    def _discovered_listeners(self):
        out = []
        for cl in self.disco.listeners():
            listener = UrlListener(cl.url, managed=True)
            listener.set_name(cl.name)
            out.append(listener)
        return out

    def stop(self) -> None:
        for looper in self._loopers:
            looper.quit()
        self._scheduler.stop()
        self.state.stop_processing()
        if self.transport is not None:
            self.transport.stop()
        if self._http_server is not None:
            self._http_server.shutdown()
        if self._xds_server is not None:
            self._xds_server.shutdown()
        if self.ads is not None:
            self.ads.shutdown()
        if self.haproxy is not None:
            self.haproxy.stop()


def parse_command_line(argv=None) -> argparse.Namespace:
    """cli.go:25-41."""
    parser = argparse.ArgumentParser("sidecar-tpu")
    parser.add_argument("-a", "--advertise-ip", default=None,
                        help="The address to advertise to the cluster")
    parser.add_argument("-c", "--cluster-ip", action="append", default=[],
                        help="The cluster seed addresses")
    parser.add_argument("-n", "--cluster-name", default=None,
                        help="The cluster we're part of")
    parser.add_argument("-p", "--cpuprofile", action="store_true",
                        help="Enable CPU profiling")
    parser.add_argument("-d", "--discover", action="append", default=[],
                        help="Method of discovery")
    parser.add_argument("-l", "--logging-level", default=None,
                        help="Set the logging level")
    parser.add_argument("--http-port", type=int, default=7777)
    parser.add_argument("--hostname", default=None,
                        help="Override this node's identity (defaults to "
                             "the machine hostname)")
    return parser.parse_args(argv)


def apply_cli_overrides(config: Config,
                        opts: argparse.Namespace) -> None:
    """main.go:44-60."""
    if opts.advertise_ip:
        config.sidecar.advertise_ip = opts.advertise_ip
    if opts.cluster_ip:
        config.sidecar.seeds = opts.cluster_ip
    if opts.cluster_name:
        config.sidecar.cluster_name = opts.cluster_name
    if opts.discover:
        config.sidecar.discovery = opts.discover
    if opts.logging_level:
        config.sidecar.logging_level = opts.logging_level


def main(argv=None) -> int:
    import os

    opts = parse_command_line(argv)
    config = parse_config()
    apply_cli_overrides(config, opts)
    # Node identity defaults to the machine hostname (as the reference
    # does via memberlist); SIDECAR_HOSTNAME or --hostname overrides it so
    # multiple nodes can share a host outside containers.
    hostname = opts.hostname or os.environ.get("SIDECAR_HOSTNAME") or None
    configure_logging(config.sidecar.logging_level,
                      config.sidecar.logging_format)

    profiler = None
    if opts.cpuprofile:
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()

    from sidecar_tpu.transport import GossipTransport

    # Resolve the advertise address before building the transport — the
    # cluster must learn our published IP, never the loopback fallback
    # (the reference wires memberlist.AdvertiseAddr the same way,
    # main.go:267-271).
    published_ip = get_published_ip(config.sidecar.exclude_ips,
                                    config.sidecar.advertise_ip)
    node = SidecarNode(config=config, hostname=hostname,
                       transport=GossipTransport(
                           node_name=hostname,
                           bind_port=config.sidecar.bind_port,
                           advertise_ip=published_ip,
                           cluster_name=config.sidecar.cluster_name,
                           gossip_interval=config.sidecar.gossip_interval,
                           push_pull_interval=config.sidecar
                           .push_pull_interval,
                           gossip_messages=config.sidecar.gossip_messages,
                           handoff_queue_depth=config.sidecar
                           .handoff_queue_depth,
                           # The membership-level SWIM suspicion window
                           # (the native engine's Lifeguard quarantine)
                           # follows the same knob as the catalog-level
                           # record suspicion, so the two layers agree
                           # on how long a silent peer stays suspect.
                           suspect_timeout=config.sidecar
                           .suspicion_window))
    node.start(http_port=opts.http_port)
    log.info("Sidecar node %s up on %s", node.hostname, node.advertise_ip)
    try:
        threading.Event().wait()  # select {} (main.go:413)
    except KeyboardInterrupt:
        node.stop()
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats("sidecar.cpu.prof")
    return 0


if __name__ == "__main__":
    sys.exit(main())
