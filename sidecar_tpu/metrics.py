"""Metrics: counters / gauges / timers with a statsd sink.

The reference instruments its hot paths with armon/go-metrics —
``MeasureSince`` timers on the delegate and catalog merge paths
(services_delegate.go:73,86,154; services_state.go:294), a
``pendingBroadcasts`` gauge (services_delegate.go:87) — and exports to
statsd when ``SIDECAR_STATS_ADDR`` is set (main.go:156-166).  This is
the same shape: a process-global registry that always aggregates
in-memory (so tests and operators can read ``snapshot()``) and
additionally emits standard statsd datagrams (``name:v|c``, ``|g``,
``|ms``) over UDP when a sink address is configured.

Emission is fire-and-forget UDP on the caller's thread — one
``sendto`` per event, no buffering, errors swallowed — the same
trade statsite/statsd clients make on hot paths."""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

PREFIX = "sidecar"


class Metrics:
    def __init__(self, prefix: str = PREFIX) -> None:
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._timers: dict[str, list] = {}  # name → [count, total_ms, last]
        self._sock: Optional[socket.socket] = None
        self._addr: Optional[tuple[str, int]] = None

    # -- configuration ------------------------------------------------------

    def configure_statsd(self, addr: Optional[str]) -> None:
        """``host:port`` enables the statsd sink; None/'' disables it
        (SIDECAR_STATS_ADDR, main.go:156-166).  Ordered so concurrent
        hot-path emitters never observe an address without a socket."""
        if not addr:
            self._addr = None
            self._sock = None
            return
        host, _, port = addr.partition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._addr = (host or "127.0.0.1", int(port or 8125))

    def _emit(self, name: str, value, kind: str) -> None:
        # Snapshot the pair: reconfiguration races must never kill a
        # delegate thread mid-emit.
        addr, sock = self._addr, self._sock
        if addr is None or sock is None:
            return
        try:
            payload = f"{self.prefix}.{name}:{value}|{kind}".encode()
            sock.sendto(payload, addr)
        except OSError:
            pass

    # -- instruments --------------------------------------------------------

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        self._emit(name, n, "c")

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value
        self._emit(name, value, "g")

    def measure_since(self, name: str, t0: float) -> None:
        """Record elapsed time from ``t0`` (a ``time.perf_counter()``
        stamp) — the go-metrics MeasureSince analog."""
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            agg = self._timers.setdefault(name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += ms
            agg[2] = ms
        self._emit(name, round(ms, 3), "ms")

    def counter(self, name: str) -> int:
        """Current value of one counter (0 if never incremented) —
        the chaos/robustness tests and operators poll the injection and
        shed counters (``chaos.*``, ``transport.shed*``) through this
        without snapshotting the whole registry."""
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: {"count": v[0],
                               "total_ms": round(v[1], 3),
                               "last_ms": round(v[2], 3)}
                           for k, v in self._timers.items()},
            }


# The process-global registry (go-metrics' global sink analog).
registry = Metrics()

incr = registry.incr
set_gauge = registry.set_gauge
measure_since = registry.measure_since
counter = registry.counter
snapshot = registry.snapshot
configure_statsd = registry.configure_statsd
