"""Metrics: counters / gauges / timers / histograms with a statsd sink.

The reference instruments its hot paths with armon/go-metrics —
``MeasureSince`` timers on the delegate and catalog merge paths
(services_delegate.go:73,86,154; services_state.go:294), a
``pendingBroadcasts`` gauge (services_delegate.go:87) — and exports to
statsd when ``SIDECAR_STATS_ADDR`` is set (main.go:156-166).  This is
the same shape: a process-global registry that always aggregates
in-memory (so tests and operators can read ``snapshot()``) and
additionally emits standard statsd datagrams (``name:v|c``, ``|g``,
``|ms``) over UDP when a sink address is configured.

Two latency instruments coexist (docs/metrics.md has the migration
story):

* :meth:`Metrics.measure_since` — the original go-metrics analog:
  count / total / last-value only.  Kept for the legacy gossip-path
  timers (``addServiceEntry``, ``notifyMsg``, ...).
* :meth:`Metrics.histogram` — a bounded-reservoir percentile
  instrument (p50/p95/p99 over up to ``HIST_RESERVOIR`` samples,
  Vitter's Algorithm R beyond it).  The bridge dispatch, query-hub
  fan-out, health-check, and chunk-dispatch sites record through this.
  Every histogram ALSO mirrors count/total/last into the ``timers``
  snapshot block, so dashboards reading the pre-histogram shape keep
  working while sites migrate (the back-compat contract pinned by
  tests/test_telemetry.py).

Emission is fire-and-forget UDP on the caller's thread — one
``sendto`` per event, no buffering, errors swallowed — the same
trade statsite/statsd clients make on hot paths."""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import Optional

PREFIX = "sidecar"


def _percentile(sorted_samples: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list."""
    if not sorted_samples:
        return 0.0
    rank = max(1, int(-(-q * len(sorted_samples) // 1)))  # ceil(q·n)
    return sorted_samples[min(rank, len(sorted_samples)) - 1]


class Metrics:
    # Reservoir bound per histogram: large enough that p99 over a
    # steady stream is stable, small enough that a registry with dozens
    # of histograms stays a few hundred KB.
    HIST_RESERVOIR = 512

    def __init__(self, prefix: str = PREFIX) -> None:
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._timers: dict[str, list] = {}  # name → [count, total_ms, last]
        # name → [count, total_ms, last, max, min, samples(list)]
        self._hists: dict[str, list] = {}
        # Deterministic reservoir replacement (tests never depend on it:
        # percentile assertions stay under the reservoir bound).
        self._rand = random.Random(0xC0FFEE)
        # The statsd sink is ONE (addr, sock) pair swapped atomically:
        # hot-path emitters read it in a single reference load, so a
        # concurrent reconfiguration can never expose a half-configured
        # address-without-socket (or vice versa).
        self._sink: Optional[tuple[tuple[str, int], socket.socket]] = None

    # -- configuration ------------------------------------------------------

    def configure_statsd(self, addr: Optional[str]) -> None:
        """``host:port`` enables the statsd sink; None/'' disables it
        (SIDECAR_STATS_ADDR, main.go:156-166).  Reconfiguration is an
        atomic pair swap under the registry lock — concurrent hot-path
        emitters either see the complete old sink or the complete new
        one — and the PREVIOUS socket is closed instead of leaked (the
        pre-round-9 behavior dropped it unclosed, one leaked fd per
        reconfiguration)."""
        new_sink = None
        if addr:
            host, _, port = addr.partition(":")
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            new_sink = ((host or "127.0.0.1", int(port or 8125)), sock)
        with self._lock:
            old_sink = self._sink
            self._sink = new_sink
        if old_sink is not None:
            try:
                old_sink[1].close()
            except OSError:
                pass

    def _emit(self, name: str, value, kind: str) -> None:
        # One reference load snapshots the whole pair: reconfiguration
        # races must never kill a delegate thread mid-emit.
        sink = self._sink
        if sink is None:
            return
        addr, sock = sink
        try:
            payload = f"{self.prefix}.{name}:{value}|{kind}".encode()
            sock.sendto(payload, addr)
        except OSError:
            pass

    # -- instruments --------------------------------------------------------

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        self._emit(name, n, "c")

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value
        self._emit(name, value, "g")

    def measure_since(self, name: str, t0: float) -> None:
        """Record elapsed time from ``t0`` (a ``time.perf_counter()``
        stamp) — the go-metrics MeasureSince analog."""
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            agg = self._timers.setdefault(name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += ms
            agg[2] = ms
        self._emit(name, round(ms, 3), "ms")

    def histogram(self, name: str, ms: float) -> None:
        """Record one latency sample (milliseconds) into the bounded
        reservoir behind ``name`` — p50/p95/p99 in ``snapshot()``, a
        standard ``|ms`` statsd datagram on the wire, and a mirrored
        count/total/last entry in the legacy ``timers`` block (the
        migration back-compat contract; see the module docstring)."""
        ms = float(ms)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = [0, 0.0, 0.0, ms, ms, []]
            h[0] += 1
            h[1] += ms
            h[2] = ms
            h[3] = max(h[3], ms)
            h[4] = min(h[4], ms)
            samples = h[5]
            if len(samples) < self.HIST_RESERVOIR:
                samples.append(ms)
            else:
                # Vitter's Algorithm R: uniform over the full stream.
                j = self._rand.randrange(h[0])
                if j < self.HIST_RESERVOIR:
                    samples[j] = ms
            agg = self._timers.setdefault(name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += ms
            agg[2] = ms
        self._emit(name, round(ms, 3), "ms")

    def histogram_since(self, name: str, t0: float) -> None:
        """``histogram(name, elapsed-from-t0)`` — the MeasureSince
        spelling for histogram sites."""
        self.histogram(name, (time.perf_counter() - t0) * 1000.0)

    def counter(self, name: str) -> int:
        """Current value of one counter (0 if never incremented) —
        the chaos/robustness tests and operators poll the injection and
        shed counters (``chaos.*``, ``transport.shed*``) through this
        without snapshotting the whole registry."""
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            hists = {}
            for k, h in self._hists.items():
                s = sorted(h[5])
                hists[k] = {
                    "count": h[0],
                    "total_ms": round(h[1], 3),
                    "last_ms": round(h[2], 3),
                    "max_ms": round(h[3], 3),
                    "min_ms": round(h[4], 3),
                    "p50_ms": round(_percentile(s, 0.50), 3),
                    "p95_ms": round(_percentile(s, 0.95), 3),
                    "p99_ms": round(_percentile(s, 0.99), 3),
                }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: {"count": v[0],
                               "total_ms": round(v[1], 3),
                               "last_ms": round(v[2], 3)}
                           for k, v in self._timers.items()},
                "histograms": hists,
            }


# The process-global registry (go-metrics' global sink analog).
registry = Metrics()

incr = registry.incr
set_gauge = registry.set_gauge
measure_since = registry.measure_since
histogram = registry.histogram
histogram_since = registry.histogram_since
counter = registry.counter
snapshot = registry.snapshot
configure_statsd = registry.configure_statsd
