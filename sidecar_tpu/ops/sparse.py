"""Sparse-frontier round execution — the convergence-tail attack.

The north-star run's cost is dominated by the TAIL, not the round: the
strict-unsettled ε takes ~925 rounds at a flat ~31 ms/round
(benchmarks/RESULTS.md round 6) even though the in-flight census shows
the active set collapsing to a few hundred entries within the first
~200 rounds.  Late rounds do O(N·K) dense work to move an O(C)
frontier — the classic sparse-frontier gap GNN-accelerator work names
between dense message-passing kernels and real propagation workloads
(PAPERS.md: the GNN computer-architecture survey), and the same
observation pipelined-gossip analysis makes at the protocol level
(PAPERS.md: *The Algorithm of Pipelined Gossiping*): after the bulk
wave, only stragglers carry traffic.

This module holds the mode plumbing shared by every model:

* **Resolution** — ``SIDECAR_TPU_SPARSE=auto|0|1`` (or the ``sparse=``
  constructor argument), resolved ONCE at sim construction exactly like
  ``SIDECAR_TPU_KERNELS``:

  - ``0``   — sparse execution disabled; ``run*(..., sparse=True)``
    raises.  The pre-round-8 behavior.
  - ``1``   — drivers default to the sparse step (each round still
    carries the overflow→dense fallback, so a burst mid-chunk is
    handled bit-identically).
  - ``auto`` (default) — drivers default to dense; a host-side
    :class:`SparseArbiter` opts chunks in from the census it already
    pulls (bench.py north-star loop, ``SimBridge.simulate``).

* **Frontier compaction** — :func:`compact_rows`: bounded static-width
  ``nonzero`` over a row mask (the same bounded-nonzero machinery as
  the ``metric_inflight_cap`` census path, models/compressed.py
  ``fast_list``) plus the inverse position map used for the
  scatter-free gather-based write-back.

* **The arbiter** — :class:`SparseArbiter`: picks dense vs sparse for
  the NEXT pipelined chunk from the behind-census the driver already
  reads back, with hysteresis (enter/exit thresholds form a band, so a
  census oscillating around one threshold cannot thrash the mode) and
  a frontier-overflow→dense fallback with cooldown (the same
  overflow→resync shape as ``ops/delta.py``: capacity exhaustion is
  REPORTED and degrades to the dense path, never silently truncated).

What "sparse" means mechanically (docs/sparse.md has the full
contract): per round, three bounded frontiers are compacted out of the
dense state —

* **senders**: rows with any ELIGIBLE cache line (occupied AND
  transmits left — TransmitLimited is what makes the tail sparse:
  exhausted relays hold copies but publish nothing),
* **receivers**: alive rows that sampled at least one active sender
  (every other row's pull folds only empty boards — a no-op),
* **announcers**: rows with any refresh/recovery offer this round —
  which, with the suspicion window active, includes every row whose
  own record is SUSPECT: the Lifeguard self-refutation
  (ops/suspicion.announce_refute) marks it due immediately, so
  quarantined owners join the announcer frontier and their refuting
  version goes out the same round on the compacted path too —

and the publish/deliver/merge/announce-insert work runs on the
``[C]``-shaped views, scattered back through gather+select.  Rows
outside the frontiers are PROVABLY unchanged by the dense round, so
the sparse round is bit-identical (the lockstep suites in
tests/test_sparse.py are the oracle).  TTL decay, push-pull and the
floor census stay dense — they are cadence-amortized and already
elementwise-cheap.

The PRNG streams are mode-independent by construction: peer sampling
is drawn at the full ``[N, F]`` shape in both modes (O(N·F) — cheap)
and the sparse path slices rows of the same draw; the ``drop_prob``
keep mask, when active, is likewise drawn at the dense shape and
sliced, so a sparse round replays the dense round's randomness
exactly.
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp

from sidecar_tpu import metrics

SPARSE_ENV = "SIDECAR_TPU_SPARSE"
SPARSE_MODES = ("auto", "0", "1")

# Stats vector layout: every sparse step/driver reports an int32 [3]
# (rounds executed on the compacted path, rounds that overflowed to the
# dense fallback, frontier high-water mark).  Kept positional so the
# scan carry stays a flat array.
STAT_SPARSE_ROUNDS = 0
STAT_OVERFLOW_ROUNDS = 1
STAT_FRONTIER_HWM = 2


def resolve_sparse(explicit: Optional[str] = None, *,
                   record: bool = True) -> str:
    """Resolve the sparse-execution mode: an explicit constructor
    argument wins, else ``SIDECAR_TPU_SPARSE``, else ``auto``.

    Returns one of ``"auto" | "0" | "1"``.  Resolved at sim
    construction (the choice gates which jitted drivers a sim may
    dispatch), so toggling the env var affects sims built afterwards —
    the ``SIDECAR_TPU_KERNELS`` contract."""
    mode = explicit
    if mode is None:
        mode = os.environ.get(SPARSE_ENV, "auto").strip().lower() or "auto"
    mode = {"on": "1", "off": "0"}.get(mode, mode)
    if mode not in SPARSE_MODES:
        raise ValueError(
            f"sparse mode must be one of {SPARSE_MODES}, got {mode!r} "
            f"(explicit argument or {SPARSE_ENV})")
    if record:
        metrics.incr(f"sparse.mode.{mode}")
    return mode


def resolve_request(mode: str, sparse, supports_sparse: bool = True) -> bool:
    """Per-dispatch sparse resolution, shared by every sim family
    (one definition so the ``supports_sparse`` guard cannot silently
    diverge between models): ``sparse=None`` follows the
    construction-time ``mode`` — and DEGRADES to dense on a sim that
    doesn't implement the path (the chaos wrapper under an env-forced
    ``"1"``); an explicit ``True`` is the arbiter's chunk-level opt-in
    and raises when the mode is ``"0"`` or the sim can't honor it."""
    if sparse is None:
        sparse = mode == "1"
        if sparse and not supports_sparse:
            return False        # env default degrades, never breaks
    if sparse and (mode == "0" or not supports_sparse):
        raise ValueError(
            "sparse execution is disabled or unsupported on this sim "
            f"(mode={mode!r}, supports_sparse={supports_sparse}; "
            f"see {SPARSE_ENV} / docs/sparse.md)")
    return bool(sparse)


def default_frontier_cap(n: int) -> int:
    """Auto frontier width: wide enough that the arbiter's entry
    heuristic has slack, narrow enough that the compacted round is
    decisively cheaper than dense (C ≪ N)."""
    return min(n, max(128, n // 16))


def compact_rows(mask, cap: int):
    """Bounded static-width row compaction.

    ``mask`` is bool [N]; returns ``(idx, row, valid, pos)``:

    * ``idx``  int32 [cap] — the first ``cap`` set rows, padded with
      ``n`` (the bounded-nonzero form of the ``metric_inflight_cap``
      census path);
    * ``row``  int32 [cap] — ``min(idx, n-1)``: always-in-bounds gather
      rows (padding rows duplicate row n-1; their results are masked);
    * ``valid`` bool [cap] — True at real entries;
    * ``pos``  int32 [N] — inverse map: ``pos[g]`` is g's compacted
      index where ``mask[g]``, else an arbitrary value the caller must
      mask with ``mask`` (the gather-based write-back reads
      ``compact[pos]`` under ``where(mask, ...)``).
    """
    n = mask.shape[0]
    idx = jnp.nonzero(mask, size=cap, fill_value=n)[0].astype(jnp.int32)
    row = jnp.minimum(idx, n - 1)
    valid = idx < n
    pos = jnp.zeros((n,), jnp.int32).at[idx].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    return idx, row, valid, pos


def zero_stats():
    return jnp.zeros((3,), jnp.int32)


def accumulate_stats(acc, step_stats):
    """Fold one round's [3] stats into the running accumulator:
    counters add, the frontier high-water mark maxes."""
    return jnp.stack([
        acc[STAT_SPARSE_ROUNDS] + step_stats[STAT_SPARSE_ROUNDS],
        acc[STAT_OVERFLOW_ROUNDS] + step_stats[STAT_OVERFLOW_ROUNDS],
        jnp.maximum(acc[STAT_FRONTIER_HWM],
                    step_stats[STAT_FRONTIER_HWM]),
    ])


class SparseArbiter:
    """Host-side dense/sparse chunk arbiter.

    Lives at the pipelined-chunk boundary (bench.py north-star loop,
    ``SimBridge.simulate``): the driver already pulls a census sample
    per chunk (the behind count / the convergence curve); the arbiter
    turns that into the NEXT chunk's mode without any extra
    device↔host traffic.

    Policy:

    * ``mode="0"``  — always dense; ``mode="1"`` — always sparse (the
      per-round overflow fallback still protects capacity).
    * ``mode="auto"`` — hysteresis band on the census: enter sparse
      when the census drops to ``enter_below``, exit only when it
      rises above ``exit_above`` (> enter_below), so oscillation around
      one threshold cannot thrash the mode.  A chunk that reports
      frontier overflows forces dense for ``cooldown`` decisions — the
      overflow→resync shape of ``ops/delta.py``.

    Counters/gauges (docs/metrics.md): ``sparse.rounds``,
    ``sparse.switches``, ``sparse.overflow`` counters and the
    ``sparse.frontier_size`` gauge.  The process registry accumulates
    across runs; per-run numbers come from the INSTANCE counters
    (:meth:`snapshot`), which :meth:`new_trajectory` zeroes — both
    drivers construct (or reset) an arbiter per run, which is what
    keeps ``POST /simulate`` reports per-run (the PR-4
    ``sync_exchange_metrics`` lesson: never report the accumulating
    registry as if it were per-trajectory).
    """

    @classmethod
    def for_census(cls, mode: str, n: int) -> "SparseArbiter":
        """The shared driver policy (bench north-star loop AND
        ``SimBridge.simulate`` — one definition so the entry heuristic
        cannot silently diverge between them): enter sparse when the
        behind census drops to ``n`` — on average under one behind
        cell per node, the tail regime where the active-sender
        frontier fits its cap; a mispredicted chunk costs only the
        mask passes (the per-round overflow fallback IS the dense
        round)."""
        return cls(mode, enter_below=float(n))

    def __init__(self, mode: str = "auto", *, enter_below: float,
                 exit_above: Optional[float] = None, cooldown: int = 2):
        if mode not in SPARSE_MODES:
            raise ValueError(f"mode must be one of {SPARSE_MODES}")
        if exit_above is None:
            exit_above = 2.0 * enter_below
        if exit_above < enter_below:
            raise ValueError("exit_above must be >= enter_below "
                             "(the hysteresis band)")
        self.mode = mode
        self.enter_below = float(enter_below)
        self.exit_above = float(exit_above)
        self.cooldown = int(cooldown)
        self._sparse = mode == "1"
        self._cooldown_left = 0
        self.new_trajectory()

    # -- per-trajectory counters -------------------------------------------

    def new_trajectory(self) -> None:
        """Reset the per-run view (fresh init_state / new simulate
        request): per-run counters restart at zero; the process
        registry keeps accumulating across runs."""
        self.run_sparse_rounds = 0
        self.run_dense_rounds = 0
        self.run_overflow_rounds = 0
        self.run_switches = 0
        self.run_frontier_hwm = 0
        self._cooldown_left = 0
        self._sparse = self.mode == "1"
        metrics.set_gauge("sparse.frontier_size", 0.0)

    def snapshot(self) -> dict:
        """The per-run record (the bridge report / bench JSON block)."""
        return {
            "sparse_rounds": self.run_sparse_rounds,
            "dense_rounds": self.run_dense_rounds,
            "overflow_rounds": self.run_overflow_rounds,
            "switches": self.run_switches,
            "frontier_hwm": self.run_frontier_hwm,
        }

    # -- the decision -------------------------------------------------------

    @property
    def sparse(self) -> bool:
        """Mode for the chunk about to be dispatched."""
        return self._sparse

    def dispatch_kwargs(self) -> dict:
        """The driver kwargs for the next chunk.  ``sparse`` is passed
        EXPLICITLY either way: a dense decision must say
        ``sparse=False`` — omitting the kwarg would let a sim built
        under ``SIDECAR_TPU_SPARSE=1`` resolve its construction-time
        default and silently run the sparse program on a chunk the
        arbiter pinned dense (the BENCH_SPARSE=0 / ``{"sparse":
        false}`` forcing contracts)."""
        return {"sparse": self._sparse}

    def record_chunk(self, rounds: int, stats=None) -> None:
        """Account a finished chunk.  ``stats`` is the driver's int32
        [3] stats vector for a sparse chunk (None for dense chunks)."""
        if stats is None:
            self.run_dense_rounds += rounds
            return
        sparse_rounds = int(stats[STAT_SPARSE_ROUNDS])
        overflow = int(stats[STAT_OVERFLOW_ROUNDS])
        frontier = int(stats[STAT_FRONTIER_HWM])
        self.run_sparse_rounds += sparse_rounds
        self.run_dense_rounds += rounds - sparse_rounds
        self.run_overflow_rounds += overflow
        self.run_frontier_hwm = max(self.run_frontier_hwm, frontier)
        if sparse_rounds:
            metrics.incr("sparse.rounds", sparse_rounds)
        if overflow:
            metrics.incr("sparse.overflow", overflow)
            if self.mode == "auto":
                # Frontier overflow → dense fallback with cooldown.
                self._cooldown_left = self.cooldown
                self._switch(False)
        metrics.set_gauge("sparse.frontier_size", float(frontier))

    def update_census(self, census: float) -> bool:
        """Feed the latest census sample (the behind count the driver
        already pulled); returns the mode for the next chunk."""
        if self.mode != "auto":
            return self._sparse
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return self._sparse
        if not self._sparse and census <= self.enter_below:
            self._switch(True)
        elif self._sparse and census > self.exit_above:
            self._switch(False)
        return self._sparse

    def _switch(self, to_sparse: bool) -> None:
        if self._sparse != to_sparse:
            self._sparse = to_sparse
            self.run_switches += 1
            metrics.incr("sparse.switches")
