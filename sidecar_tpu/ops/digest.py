"""Order-invariant catalog digests — the coherence plane's ONE
fingerprint definition, shared by the simulator and the live cluster.

A catalog is a multiset of records ``(host, service-id, packed key)``.
Its digest is computed record-by-record: a 32-bit identity ``ident``
names the (host, service-id) pair, the record's packed key (tick or
timestamp, status in the low 3 bits — ops/status.py) is mixed with the
ident into TWO 32-bit hash lanes (a 64-bit record hash), and the lanes
are summed mod 2^32 into one of ``B`` buckets chosen by the ident
alone.  Three properties fall out of that construction:

* **Order-invariant** — per-bucket modular SUM is commutative and
  associative, so any insertion order (gossip arrival order, merge
  order, scan order) yields the identical digest.
* **Incrementally updatable** — modular sum is invertible: removing a
  record is a modular SUBTRACT of its lanes, so the live catalog can
  maintain the digest in O(1) per mutation under its writer lock
  (:class:`IncrementalDigest`), with no rescan.
* **Divergence lower bound** — the bucket index depends only on the
  ident, so two versions of the SAME record land in the same bucket:
  a node that is stale on k distinct records differs from the truth
  digest in at most k buckets, i.e. the count of differing buckets
  between two digests LOWER-BOUNDS the number of diverged records
  (hash collisions can only shrink the count, never inflate it).

Three twins compute the same function and must agree byte-for-byte
(tests/test_digest.py pins all pairs):

* the jnp path (:func:`node_digests`, :func:`state_digest_record`) —
  one elementwise hash over the belief matrix plus a ``segment_sum``
  computes ALL N node digests on-device; it runs inside ``lax.scan``
  (``run_with_digest``) and shards under GSPMD because the reduce is
  over the global tensors (the ops/trace.py contract);
* the pure-NumPy oracle (:func:`node_digests_np`, :func:`digest_np`)
  — the sequential ground truth the sim path is validated against;
* the pure-Python live path (:class:`IncrementalDigest`) — the
  ``catalog/state.py`` writer maintains it under ``_lock`` and
  publishes immutable snapshots for lock-free readers.

Key domain: one 64-bit packed key ``(ts << 3) | status``.  The sim's
int32 packed keys embed verbatim (high half zero); the live catalog
packs its raw ``updated`` nanosecond stamp the same way
(:func:`live_key`), so two peers holding byte-identical records hold
byte-identical digests, and a test that stamps live records with
sim-tick ``updated`` values gets cross-plane byte identity.

Like the flight recorder, digesting is OPT-IN per dispatch
(``run_with_digest``): the plain drivers compile none of this, so
digest-off leaves every existing program untouched.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from sidecar_tpu.ops.status import is_known

# Default bucket count B: 64 buckets x 2 lanes = 512 B per digest —
# small enough to annotate every push-pull exchange, wide enough that
# the differing-bucket lower bound stays tight for the diverged-record
# counts coherence monitoring cares about (ones and tens, not
# thousands).
DEFAULT_BUCKETS = 64

# Merkle-ladder depth: level k has DEFAULT_BUCKETS << k buckets, so the
# default ladder is 64 → 128 → 256 → 512 → 1024.  The bucket index at
# 2B buckets is ONE MORE BIT of the same mixed ident (bucket_ids shifts
# one bit less), so a parent bucket's lane sums are exactly the
# wrapping sum of its two children: every coarser level folds out of
# the leaf level (:func:`fold_digest`), and a reconciliation session
# can narrow disagreement level-by-level, requesting children only for
# differing parents — O(divergence · depth) digest bytes, never
# O(catalog).
DEFAULT_LADDER_DEPTH = 5

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF

# Multiplicative mixing constants — the ops/kernels hash_line idiom:
# Knuth's multiplicative constant plus the murmur3 finalizer pair, and
# the 32-bit golden ratio as the lane separator.
_K1 = 2654435761
_K2 = 0x85EBCA6B
_K3 = 0xC2B2AE35
_GOLD = 0x9E3779B9


def _bucket_shift(buckets: int) -> int:
    """Validate ``buckets`` (power of two) and return the top-bits
    shift selecting a bucket from a mixed 32-bit ident."""
    if buckets < 1 or buckets & (buckets - 1):
        raise ValueError(f"buckets must be a power of two, got {buckets}")
    return 32 - (buckets.bit_length() - 1)


# -- jnp twin ----------------------------------------------------------------

def mix32(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 over a uint32 array (wrapping arithmetic)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_K2)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(_K3)
    return x ^ (x >> 16)


def record_lanes(idents: jax.Array, lo: jax.Array, hi: jax.Array):
    """The 64-bit record hash as two uint32 lanes.  All inputs uint32;
    ``lo``/``hi`` are the halves of the 64-bit packed key (sim int32
    packed keys pass ``hi = 0``).  The formula is the shared
    definition — the NumPy and pure-Python twins repeat it verbatim."""
    k = mix32(lo) ^ (mix32(hi ^ jnp.uint32(_GOLD)) * jnp.uint32(_K1))
    lane0 = mix32(idents ^ k)
    lane1 = mix32((idents + jnp.uint32(_GOLD)) ^ (k * jnp.uint32(_K1)))
    return lane0, lane1


def bucket_ids(idents: jax.Array, buckets: int) -> jax.Array:
    """Per-slot bucket index (int32 [M]) — a function of the ident
    ALONE, so every version of a record lands in the same bucket (the
    lower-bound property) and the index is static across rounds."""
    shift = _bucket_shift(buckets)
    if shift >= 32:
        return jnp.zeros(idents.shape, jnp.int32)
    mixed = mix32(idents.astype(jnp.uint32) * jnp.uint32(_K1))
    return (mixed >> jnp.uint32(shift)).astype(jnp.int32)


def node_digests(packed: jax.Array, idents: jax.Array,
                 buckets: int) -> jax.Array:
    """All node digests from a packed belief matrix: int32 [N, M] ->
    uint32 [N, B, 2].  Unknown cells (tick 0) contribute nothing.  One
    elementwise hash plus a segment-sum — inside a scan this is the
    whole per-round cost, and under GSPMD the reduce runs over the
    global tensors (rows stay on their shards)."""
    mask = is_known(packed)
    lo = packed.astype(jnp.uint32)
    hi = jnp.zeros_like(lo)
    ids = idents.astype(jnp.uint32)[None, :]
    lane0, lane1 = record_lanes(ids, lo, hi)
    zero = jnp.uint32(0)
    lane0 = jnp.where(mask, lane0, zero)
    lane1 = jnp.where(mask, lane1, zero)
    seg = bucket_ids(idents, buckets)
    d0 = jax.ops.segment_sum(lane0.T, seg, num_segments=buckets)
    d1 = jax.ops.segment_sum(lane1.T, seg, num_segments=buckets)
    return jnp.stack([d0.T, d1.T], axis=-1)


def diff_counts(dig: jax.Array, ref: jax.Array) -> jax.Array:
    """Differing-bucket counts vs a reference digest: uint32 [N, B, 2]
    x [B, 2] -> int32 [N].  Each count lower-bounds that node's
    diverged-record count vs the reference catalog."""
    differ = jnp.any(dig != ref[None, :, :], axis=-1)
    return jnp.sum(differ.astype(jnp.int32), axis=-1)


def fold_digest_jnp(dig: jax.Array) -> jax.Array:
    """One ladder fold on-device: uint32 [..., 2B, 2] -> [..., B, 2].
    Children (2b, 2b+1) sum (mod 2^32) into parent b — byte-identical
    to digesting at B buckets directly (the prefix property; pinned in
    tests/test_antientropy.py)."""
    b2 = dig.shape[-2]
    if b2 < 2 or b2 % 2:
        raise ValueError(f"cannot fold {b2} buckets")
    folded = dig.reshape(dig.shape[:-2] + (b2 // 2, 2, 2)).sum(axis=-2)
    return folded.astype(jnp.uint32)


def ladder_digests(packed: jax.Array, idents: jax.Array,
                   base: int = DEFAULT_BUCKETS,
                   depth: int = DEFAULT_LADDER_DEPTH) -> list:
    """All node digests at every ladder level, coarse → fine: int32
    [N, M] -> ``depth`` arrays uint32 [N, base << k, 2].  ONE
    elementwise hash + segment-sum at the leaf level; coarser levels
    are folds (no rehash)."""
    if depth < 1:
        raise ValueError(f"ladder depth must be >= 1, got {depth}")
    levels = [node_digests(packed, idents, base << (depth - 1))]
    for _ in range(depth - 1):
        levels.append(fold_digest_jnp(levels[-1]))
    return levels[::-1]


# Digest-record layout — flat int32 [DIGEST_WIDTH], the trace-record
# idiom: positional columns so the scan carry stays one array.
DIG_ROUND = 0
DIG_ALIVE = 1
DIG_AGREE = 2
DIG_DIFF_TOTAL = 3
DIG_DIFF_MAX = 4
DIGEST_WIDTH = 5
DIGEST_FIELDS = ("round", "alive", "agree", "diff_total", "diff_max")


def state_digest_record(round_idx, packed, node_alive, idents,
                        buckets: int) -> jax.Array:
    """One round's coherence record from a packed belief matrix:

    * ``alive``      — live cluster members this round;
    * ``agree``      — alive nodes whose digest equals the truth
      digest (the alive-max catalog — the convergence metric's truth);
    * ``diff_total`` — differing buckets summed over alive nodes: the
      fleet-wide diverged-record lower bound;
    * ``diff_max``   — the worst single node's differing buckets.
    """
    dig = node_digests(packed, idents, buckets)
    truth = jnp.max(jnp.where(node_alive[:, None], packed, 0), axis=0)
    ref = node_digests(truth[None, :], idents, buckets)[0]
    diffs = diff_counts(dig, ref)
    alive_i = node_alive.astype(jnp.int32)
    agree = jnp.sum(alive_i * (diffs == 0).astype(jnp.int32))
    masked = jnp.where(node_alive, diffs, 0)
    return jnp.stack([
        jnp.asarray(round_idx, jnp.int32),
        jnp.sum(alive_i),
        agree,
        jnp.sum(masked),
        jnp.max(masked),
    ])


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DigestTrace:
    """A bounded stream of per-round coherence records — the
    RoundTrace contract: ``count`` is the TRUE number of rounds
    digested, rows past ``min(count, cap)`` are zero padding, and
    ``overflow`` reports truncation (never silent)."""

    count: jax.Array     # int32 scalar — rounds digested (exact)
    rec: jax.Array       # int32 [cap, DIGEST_WIDTH]
    overflow: jax.Array  # bool scalar — count exceeded cap


def zero_digest(cap: int) -> DigestTrace:
    return DigestTrace(count=jnp.zeros((), jnp.int32),
                       rec=jnp.zeros((cap, DIGEST_WIDTH), jnp.int32),
                       overflow=jnp.zeros((), bool))


def append_digest(buf: DigestTrace, rec: jax.Array) -> DigestTrace:
    """Append one [DIGEST_WIDTH] record; past the capacity the write
    drops (truncation) while ``count`` keeps the exact total."""
    cap = buf.rec.shape[0]
    out = buf.rec.at[buf.count].set(rec, mode="drop")
    count = buf.count + 1
    return DigestTrace(count=count, rec=out, overflow=count > cap)


# -- NumPy oracle ------------------------------------------------------------

def mix32_np(x: np.ndarray) -> np.ndarray:
    """fmix32 over a uint32 ndarray — the oracle's mixer."""
    x = np.asarray(x, np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(_K2)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(_K3)
    return x ^ (x >> np.uint32(16))


def record_lanes_np(idents, lo, hi):
    idents = np.asarray(idents, np.uint32)
    lo = np.asarray(lo, np.uint32)
    hi = np.asarray(hi, np.uint32)
    k = mix32_np(lo) ^ (mix32_np(hi ^ np.uint32(_GOLD)) * np.uint32(_K1))
    lane0 = mix32_np(idents ^ k)
    lane1 = mix32_np((idents + np.uint32(_GOLD)) ^ (k * np.uint32(_K1)))
    return lane0, lane1


def bucket_ids_np(idents, buckets: int) -> np.ndarray:
    shift = _bucket_shift(buckets)
    idents = np.asarray(idents, np.uint32)
    if shift >= 32:
        return np.zeros(idents.shape, np.int64)
    mixed = mix32_np(idents * np.uint32(_K1))
    return (mixed >> np.uint32(shift)).astype(np.int64)


def digest_np(idents, keys, buckets: int = DEFAULT_BUCKETS) -> np.ndarray:
    """Oracle digest of one catalog given parallel arrays of idents
    (uint32) and 64-bit packed keys (uint64): -> uint32 [B, 2]."""
    idents = np.asarray(idents, np.uint32)
    keys = np.asarray(keys, np.uint64)
    lo = (keys & np.uint64(_M32)).astype(np.uint32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lane0, lane1 = record_lanes_np(idents, lo, hi)
    seg = bucket_ids_np(idents, buckets)
    dig = np.zeros((buckets, 2), np.uint32)
    np.add.at(dig[:, 0], seg, lane0)
    np.add.at(dig[:, 1], seg, lane1)
    return dig


def node_digests_np(packed, idents, buckets: int = DEFAULT_BUCKETS
                    ) -> np.ndarray:
    """Oracle twin of :func:`node_digests`: int32 [N, M] packed belief
    matrix -> uint32 [N, B, 2], unknown cells skipped."""
    packed = np.asarray(packed, np.int64)
    idents = np.asarray(idents, np.uint32)
    n = packed.shape[0]
    out = np.zeros((n, buckets, 2), np.uint32)
    for i in range(n):
        row = packed[i]
        known = (row >> 3) > 0
        out[i] = digest_np(idents[known], row[known].astype(np.uint64),
                           buckets)
    return out


def diff_counts_np(dig, ref) -> np.ndarray:
    dig = np.asarray(dig)
    ref = np.asarray(ref)
    return np.any(dig != ref[None, :, :], axis=-1).sum(axis=-1)


def fold_digest_np(dig) -> np.ndarray:
    """Oracle twin of :func:`fold_digest_jnp`: uint32 [..., 2B, 2] ->
    [..., B, 2] by pairwise child sum (uint32 wrap)."""
    dig = np.asarray(dig, np.uint32)
    b2 = dig.shape[-2]
    if b2 < 2 or b2 % 2:
        raise ValueError(f"cannot fold {b2} buckets")
    return dig.reshape(dig.shape[:-2] + (b2 // 2, 2, 2)).sum(
        axis=-2, dtype=np.uint32)


def ladder_digests_np(packed, idents, base: int = DEFAULT_BUCKETS,
                      depth: int = DEFAULT_LADDER_DEPTH) -> list:
    """Oracle twin of :func:`ladder_digests` (coarse → fine)."""
    if depth < 1:
        raise ValueError(f"ladder depth must be >= 1, got {depth}")
    levels = [node_digests_np(packed, idents, base << (depth - 1))]
    for _ in range(depth - 1):
        levels.append(fold_digest_np(levels[-1]))
    return levels[::-1]


def default_idents(m: int) -> np.ndarray:
    """The pure-sim slot identity table (uint32 [M]): slot j's ident is
    a mixed function of j.  Bridge-backed runs replace this with
    :func:`catalog_idents` over the snapshot's canonical (host, sid)
    mapping so sim digests are comparable with live ones."""
    slots = np.arange(1, m + 1, dtype=np.uint32)
    return mix32_np(slots * np.uint32(_K1))


def catalog_idents(slot_names) -> np.ndarray:
    """Identity table from the bridge's canonical slot mapping: an
    iterable of ``(hostname, service_id)`` per slot -> uint32 [M] of
    :func:`ident_of` values (the live path's identity function)."""
    return np.asarray([ident_of(h, s) for h, s in slot_names], np.uint32)


# -- pure-Python live twin ---------------------------------------------------

def fmix32_py(x: int) -> int:
    x &= _M32
    x ^= x >> 16
    x = (x * _K2) & _M32
    x ^= x >> 13
    x = (x * _K3) & _M32
    return x ^ (x >> 16)


def ident_of(hostname: str, service_id: str) -> int:
    """The live identity function: FNV-1a 32 over the canonical
    ``host\\x1fservice-id`` byte string.  This is the ONE mapping from
    catalog names to digest identities — the bridge's
    :func:`catalog_idents` reuses it so sim and live bucket the same
    records identically."""
    h = 2166136261
    for b in f"{hostname}\x1f{service_id}".encode("utf-8"):
        h = ((h ^ b) * 16777619) & _M32
    return h


def live_key(updated: int, status: int) -> int:
    """The live record's 64-bit packed key: ``(updated << 3) | status``
    mod 2^64 — the ops/status.py pack formula over the raw nanosecond
    stamp.  A sim packed int32 IS already in this domain (its tick in
    the ts field), so ``live_key(tick, status) == pack(tick, status)``
    whenever the live stamp numerically equals the sim tick."""
    return ((int(updated) << 3) | (int(status) & 7)) & _M64


def bucket_of(ident: int, buckets: int) -> int:
    """Bucket index of an ident at any power-of-two bucket count — the
    pure-Python twin of :func:`bucket_ids`.  The index at 2B buckets is
    ``(index at B) << 1 | next-bit``: deeper ladder levels refine, never
    reshuffle (the prefix property)."""
    shift = _bucket_shift(buckets)
    if shift >= 32:
        return 0
    return fmix32_py(((ident & _M32) * _K1) & _M32) >> shift


def record_hash(ident: int, key: int, buckets: int = DEFAULT_BUCKETS):
    """(bucket, lane0, lane1) of one record — the shared definition in
    pure Python (the reference implementation the array twins are
    pinned against)."""
    ident &= _M32
    key &= _M64
    lo = key & _M32
    hi = key >> 32
    k = fmix32_py(lo) ^ ((fmix32_py(hi ^ _GOLD) * _K1) & _M32)
    lane0 = fmix32_py(ident ^ k)
    lane1 = fmix32_py(((ident + _GOLD) & _M32) ^ ((k * _K1) & _M32))
    return bucket_of(ident, buckets), lane0, lane1


class IncrementalDigest:
    """The live catalog's digest: O(1) add/remove per record mutation
    (modular lane sums are invertible), maintained by the
    ``catalog/state.py`` writer under its lock.  :meth:`value` returns
    the canonical immutable form — a flat tuple of ``2 * B`` uint32
    ints, lane-interleaved per bucket, equal across all three twins
    for the same record multiset."""

    __slots__ = ("buckets", "count", "_lanes")

    def __init__(self, buckets: int = DEFAULT_BUCKETS):
        _bucket_shift(buckets)
        self.buckets = buckets
        self.count = 0
        self._lanes = [0] * (2 * buckets)

    def add(self, ident: int, key: int) -> None:
        b, l0, l1 = record_hash(ident, key, self.buckets)
        i = 2 * b
        self._lanes[i] = (self._lanes[i] + l0) & _M32
        self._lanes[i + 1] = (self._lanes[i + 1] + l1) & _M32
        self.count += 1

    def remove(self, ident: int, key: int) -> None:
        b, l0, l1 = record_hash(ident, key, self.buckets)
        i = 2 * b
        self._lanes[i] = (self._lanes[i] - l0) & _M32
        self._lanes[i + 1] = (self._lanes[i + 1] - l1) & _M32
        self.count -= 1

    def value(self) -> tuple:
        return tuple(self._lanes)

    def hex(self) -> str:
        return digest_to_hex(self._lanes)

    @classmethod
    def of(cls, records, buckets: int = DEFAULT_BUCKETS
           ) -> "IncrementalDigest":
        """Build from an iterable of ``(ident, key)`` pairs — the
        recompute-from-scratch path the churn tests pin the
        incremental path against."""
        dig = cls(buckets)
        for ident, key in records:
            dig.add(ident, key)
        return dig


class LadderDigest:
    """The live catalog's Merkle ladder: one lane table per level
    (level k has ``base << k`` buckets), all maintained incrementally —
    one :func:`record_hash` per mutation (lanes are level-independent;
    only the bucket index deepens), then ``depth`` O(1) lane updates.
    ``level(0)`` is byte-identical to ``IncrementalDigest(base)`` over
    the same records, so the coarse digest every existing surface pins
    (push-pull annotation, /api/digest.json, CoherenceMonitor) is
    unchanged; the deeper levels exist for reconciliation narrowing."""

    __slots__ = ("base", "depth", "count", "_shifts", "_lanes")

    def __init__(self, base: int = DEFAULT_BUCKETS,
                 depth: int = DEFAULT_LADDER_DEPTH):
        if depth < 1:
            raise ValueError(f"ladder depth must be >= 1, got {depth}")
        self._shifts = [_bucket_shift(base << k) for k in range(depth)]
        self.base = base
        self.depth = depth
        self.count = 0
        self._lanes = [[0] * (2 * (base << k)) for k in range(depth)]

    def _apply(self, ident: int, key: int, sign: int) -> None:
        ident &= _M32
        _, l0, l1 = record_hash(ident, key, 1)
        mixed = fmix32_py((ident * _K1) & _M32)
        for lanes, shift in zip(self._lanes, self._shifts):
            i = 2 * (0 if shift >= 32 else mixed >> shift)
            lanes[i] = (lanes[i] + sign * l0) & _M32
            lanes[i + 1] = (lanes[i + 1] + sign * l1) & _M32

    def add(self, ident: int, key: int) -> None:
        self._apply(ident, key, 1)
        self.count += 1

    def remove(self, ident: int, key: int) -> None:
        self._apply(ident, key, -1)
        self.count -= 1

    def buckets_at(self, level: int) -> int:
        return self.base << level

    @property
    def buckets(self) -> int:
        """Coarse (level-0) bucket count — the IncrementalDigest
        drop-in attribute (``digest_doc`` reads it)."""
        return self.base

    @property
    def leaf_level(self) -> int:
        return self.depth - 1

    @property
    def leaf_buckets(self) -> int:
        return self.base << (self.depth - 1)

    def level(self, k: int) -> tuple:
        """Canonical flat-tuple digest of ladder level ``k``."""
        return tuple(self._lanes[k])

    def hex(self, k: int = 0) -> str:
        return digest_to_hex(self._lanes[k])

    def value(self) -> tuple:
        """The coarse (level-0) digest — the IncrementalDigest drop-in
        read every existing consumer keeps using."""
        return tuple(self._lanes[0])

    def leaf_bucket(self, ident: int) -> int:
        """Which leaf bucket this ident's records live in — the
        session's record-selection key."""
        return bucket_of(ident, self.leaf_buckets)

    @classmethod
    def of(cls, records, base: int = DEFAULT_BUCKETS,
           depth: int = DEFAULT_LADDER_DEPTH) -> "LadderDigest":
        """Build from an iterable of ``(ident, key)`` pairs."""
        dig = cls(base, depth)
        for ident, key in records:
            dig.add(ident, key)
        return dig


def fold_digest(value) -> tuple:
    """Pure-Python ladder fold: canonical flat tuple at 2B buckets ->
    B buckets (children ``2b``/``2b+1`` lane-sum into parent ``b``)."""
    v = digest_value(value)
    if len(v) < 4 or len(v) % 4:
        raise ValueError(f"cannot fold digest of {len(v) // 2} buckets")
    out = []
    for i in range(0, len(v), 4):
        out.append((v[i] + v[i + 2]) & _M32)
        out.append((v[i + 1] + v[i + 3]) & _M32)
    return tuple(out)


def diff_bucket_ids(a, b) -> list:
    """Indices of differing buckets between two same-size canonical
    digests — the narrowing step's parent set."""
    a = digest_value(a)
    b = digest_value(b)
    if len(a) != len(b):
        raise ValueError(f"digest sizes differ: {len(a)} vs {len(b)}")
    return [i // 2 for i in range(0, len(a), 2)
            if a[i] != b[i] or a[i + 1] != b[i + 1]]


def digest_value(dig) -> tuple:
    """Canonical flat tuple from any digest form: a uint32 [B, 2]
    array (jnp/NumPy twins) or an already-flat sequence."""
    arr = np.asarray(dig)
    if arr.ndim == 2:
        arr = arr.reshape(-1)
    return tuple(int(v) & _M32 for v in arr)


def digest_to_hex(dig) -> str:
    """Serialize a digest to hex: 16 chars per bucket
    (``lane0 lane1``, 8 hex chars each) — the push-pull annotation and
    ``/api/digest.json`` wire form."""
    return "".join(f"{v:08x}" for v in digest_value(dig))


def digest_from_hex(text: str) -> tuple:
    """Parse :func:`digest_to_hex` output back to the canonical flat
    tuple; raises ``ValueError`` on malformed input."""
    if len(text) % 16 or not text:
        raise ValueError(f"digest hex length {len(text)} not a "
                         "multiple of 16")
    return tuple(int(text[i:i + 8], 16) for i in range(0, len(text), 8))


def diff_buckets_py(a, b) -> int:
    """Differing-bucket count between two canonical digests — the live
    divergence lower bound (CoherenceMonitor's estimator)."""
    a = digest_value(a)
    b = digest_value(b)
    if len(a) != len(b):
        raise ValueError(f"digest sizes differ: {len(a)} vs {len(b)}")
    return sum(1 for i in range(0, len(a), 2)
               if a[i] != b[i] or a[i + 1] != b[i + 1])


# -- host-side views ---------------------------------------------------------

def digest_to_dicts(dt: DigestTrace) -> list:
    """One dict per RECORDED round (padding dropped), with the derived
    ``agreement`` fraction (agree / alive) alongside the raw columns —
    the bridge's ``digest.rounds`` stream."""
    count = int(np.asarray(dt.count))
    rec = np.asarray(dt.rec)
    out = []
    for row in rec[:min(count, rec.shape[0])]:
        doc = {name: int(row[i]) for i, name in enumerate(DIGEST_FIELDS)}
        doc["agreement"] = (doc["agree"] / doc["alive"]
                            if doc["alive"] else 1.0)
        out.append(doc)
    return out


def summarize_digest(dt: DigestTrace) -> dict:
    """Compact tail summary (the bench block / report ``final``): last
    and worst agreement, peak divergence, and the first fully-coherent
    round (-1 when never reached in the recorded window)."""
    count = int(np.asarray(dt.count))
    rec = np.asarray(dt.rec)
    recorded = rec[:min(count, rec.shape[0])]
    if recorded.shape[0] == 0:
        return {"rounds": 0, "truncated": bool(np.asarray(dt.overflow))}
    alive = np.maximum(recorded[:, DIG_ALIVE], 1)
    agreement = recorded[:, DIG_AGREE] / alive
    coherent = np.flatnonzero(recorded[:, DIG_AGREE]
                              == recorded[:, DIG_ALIVE])
    return {
        "rounds": count,
        "truncated": bool(np.asarray(dt.overflow)),
        "agreement_last": float(agreement[-1]),
        "agreement_min": float(agreement.min()),
        "diff_total_last": int(recorded[-1, DIG_DIFF_TOTAL]),
        "diff_max_peak": int(recorded[:, DIG_DIFF_MAX].max()),
        "round_coherent": int(recorded[coherent[0], DIG_ROUND])
        if coherent.size else -1,
    }
