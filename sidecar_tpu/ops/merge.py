"""The LWW merge kernel — the TPU recast of ``ServicesState.AddServiceEntry``.

Reference semantics (catalog/services_state.go:293-347):

1. *Staleness gate*: drop records older than the tombstone window plus a
   1-minute clock-drift fudge (services_state.go:302-308 via
   ``Service.IsStale``, service/service.go:68-72).
2. *Strictly newer wins*: an incoming record replaces a known one only if
   its timestamp is strictly greater (``Invalidates``,
   service/service.go:64-66); unknown cells accept anything non-stale.
3. *DRAINING stickiness*: when a newer ALIVE record lands on a cell
   currently DRAINING, the timestamp advances but the status stays
   DRAINING (services_state.go:329-331).

Here the rule is applied to whole tensors of packed (ts<<3|status) keys at
once: rule 2 is integer ``max`` (see ops/status.py for why), rules 1 and 3
are masks.  ``merge_packed`` merges two aligned views (the anti-entropy
push-pull path, services_delegate.go:146-167); the scatter-based delivery
for fan-out gossip lives in ops/gossip.py and reuses ``apply_stickiness``.

Known divergence from the Go loop: within a single batched delivery the
reference processes messages sequentially, so a DRAINING record followed
by a newer ALIVE record in the *same* batch sticks, while the reverse
order does not — the outcome is order-dependent in the reference itself.
The batched kernel resolves such races one consistent way (highest packed
key wins, then stickiness vs. the pre-batch state).

SUSPECT (ops/suspicion.py) needs NO case here by construction: a
suspicion re-packs at the record's ORIGINAL timestamp with a status
code above every reference status, so the same max both GOSSIPS it (it
wins ties against same-version copies) and REFUTES it (any strictly
newer ALIVE outranks it).  Stickiness stays DRAINING-only — draining
records never enter quarantine (ops/ttl.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from sidecar_tpu.ops.status import (
    ALIVE,
    DRAINING,
    STATUS_BITS,
    TOMBSTONE,
    is_known,
    pack,
    unpack_status,
    unpack_ts,
)

# Traced-sentinel for a disabled origin budget (ops/knobs.budget_arg):
# per-packet suspicious ranks are bounded by the message budget (≤ a few
# hundred), so ``rank > BUDGET_OFF`` is never true.
BUDGET_OFF = 1 << 28


def staleness_mask(packed, now_tick, stale_ticks):
    """True where a packed record is too old to merge.

    ``stale_ticks`` should already include the reference's 1-minute fudge
    (TOMBSTONE_LIFESPAN + 1 min, services_state.go:302 +
    service/service.go:68-72).
    """
    ts = unpack_ts(packed)
    return (ts > 0) & (ts < jnp.asarray(now_tick, jnp.int32) - jnp.asarray(stale_ticks, jnp.int32))


def future_mask(packed, now_tick, future_ticks):
    """True where a packed record is stamped too far in the FUTURE to
    merge — the symmetric twin of :func:`staleness_mask`.

    The reference only defends the past side of clock error (the
    1-minute staleness fudge); a node with a rushing clock therefore
    mints records that win every max-merge, can never be refuted by
    honest refreshes, and never expire at their receivers — the classic
    LWW poison.  This bound REJECTS (never clamps — a clamped stamp
    would silently rewrite the sender's claim and still win merges)
    any record stamped beyond ``now + future_ticks`` at the receiver.

    ``future_ticks`` is the admission fudge in ticks
    (``TimeConfig.future_ticks``); callers that carry a "disabled"
    sentinel skip calling this entirely so the disabled program stays
    bit-identical to the pre-bound kernel.  Overflow-safe at the traced
    MAX_TICK sentinel: ``now + MAX_TICK ≤ 2^29 − 2 < 2^31``.
    """
    ts = unpack_ts(packed)
    return ts > jnp.asarray(now_tick, jnp.int32) + jnp.asarray(future_ticks, jnp.int32)


def budget_mask(vals, now_tick, tomb_budget, own=None):
    """True where a packed record exceeds its sender's per-packet
    SUSPICIOUS-record budget — the Byzantine-defense twin of
    :func:`future_mask` (docs/chaos.md, "the defense ladder").

    The LWW merge admits anything with a bigger timestamp, so a single
    compromised peer can poison a whole packet with forged tombstones
    (a tombstone bomb) or plausibly-fresh forged ALIVE records that
    slip UNDER the future-admission fudge (a sybil flood).  Honest
    packets carry mostly ALIVE records stamped at-or-behind the
    receiver's clock; a record is *suspicious* when it is a third-party
    TOMBSTONE or stamped ahead of the receiver (``ts > now``, i.e.
    within the fudge the future bound tolerates).  This mask rejects
    suspicious records beyond the first ``tomb_budget`` per packet
    (cumulative along the last — message — axis), capping any one
    origin's per-exchange blast radius while leaving honest traffic
    (occasional real tombstones, small skew) untouched.

    ``own`` optionally marks records the SENDER originates (its own
    slots): first-party claims are never counted against the budget —
    an owner is entitled to tombstone or refresh its own records.
    Under heavy honest clock skew a skewed-but-honest sender's records
    do look suspicious to unskewed receivers; that conservatism is the
    documented robustness/speed tradeoff ("Robust and Tuneable Family
    of Gossiping Algorithms", PAPERS.md) — tune ``tomb_budget`` up, or
    rely on the future bound alone, for skew-heavy fleets.

    Callers carry the same disabled-sentinel contract as the future
    bound: a static "off" skips this call entirely (bit-identical
    pre-budget program); traced callers map the off sentinel to
    :data:`BUDGET_OFF`, which no real rank exceeds.
    """
    ts = unpack_ts(vals)
    suspicious = (ts > 0) & (
        (unpack_status(vals) == TOMBSTONE)
        | (ts > jnp.asarray(now_tick, jnp.int32)))
    if own is not None:
        suspicious = suspicious & ~own
    rank = jnp.cumsum(suspicious.astype(jnp.int32), axis=-1)
    return suspicious & (rank > jnp.asarray(tomb_budget, jnp.int32))


def admit_gate(vals, now_tick, stale_ticks, future_ticks=None,
               tomb_budget=None, own=None):
    """Zero out packed values outside the admission window: older than
    the staleness bound, or — when the future bound is enabled
    (``future_ticks`` is not None) — stamped beyond ``now +
    future_ticks``, or — when the origin budget is enabled
    (``tomb_budget`` is not None) — suspicious beyond the sender's
    per-packet budget (:func:`budget_mask`; ``own`` exempts the
    sender's first-party records).  With the defenses at None this
    compiles exactly the bare staleness gate, bit for bit."""
    vals = jnp.where(staleness_mask(vals, now_tick, stale_ticks), 0, vals)
    if future_ticks is not None:
        vals = jnp.where(future_mask(vals, now_tick, future_ticks), 0, vals)
    if tomb_budget is not None:
        vals = jnp.where(budget_mask(vals, now_tick, tomb_budget, own),
                         0, vals)
    return vals


def sticky_adjust(vals, pre_vals, advanced):
    """Apply DRAINING stickiness to incoming message values against the
    receiver's pre-batch state (services_state.go:329-331): where an
    advancing value would flip a known DRAINING cell to ALIVE, rewrite
    the value itself to DRAINING at the new timestamp.

    ``advanced`` is the precomputed ``vals > pre_vals`` mask (callers
    usually need it for accept-stamping as well).  Used by every delivery
    path — gossip scatter, push-pull, and their sharded twins — so batch
    races resolve one consistent way everywhere.
    """
    sticky = (
        advanced
        & is_known(pre_vals)
        & (unpack_status(pre_vals) == DRAINING)
        & (unpack_status(vals) == ALIVE)
    )
    return jnp.where(sticky, pack(unpack_ts(vals), DRAINING), vals)


def apply_stickiness(pre, post):
    """Re-apply DRAINING stickiness after a max-merge.

    For every cell where ``post`` advanced past ``pre`` and the transition
    is DRAINING→ALIVE, keep the new timestamp but restore DRAINING
    (services_state.go:329-331).
    """
    advanced = post > pre
    sticky = (
        advanced
        & is_known(pre)
        & (unpack_status(pre) == DRAINING)
        & (unpack_status(post) == ALIVE)
    )
    return jnp.where(sticky, pack(unpack_ts(post), DRAINING), post)


def merge_packed(known, incoming, now_tick, stale_ticks, future_ticks=None,
                 tomb_budget=None, own=None):
    """Merge an aligned tensor of incoming packed records into ``known``.

    This is the full-state anti-entropy merge (``MergeRemoteState`` →
    ``state.Merge`` → per-record ``AddServiceEntry``,
    services_delegate.go:153-167, services_state.go:367-373) vectorized:
    ``incoming`` and ``known`` have the same shape, one cell per
    (node, service) belief.

    Returns the merged tensor.  Cells where ``incoming`` is unknown
    (ts == 0), stale, or — when the future-admission bound is enabled —
    stamped beyond ``now + future_ticks``, or — when the origin budget
    is enabled — suspicious beyond ``tomb_budget`` per exchanged row
    (:func:`budget_mask`; ``own`` marks the sending origin's own
    cells) are left untouched.  The defenses default to None and then
    compile the pre-bound kernel bit for bit.
    """
    # Canonicalize: a ts==0 key is the unknown sentinel regardless of its
    # status bits — never merge it.
    incoming = jnp.where(is_known(incoming), incoming, 0)
    incoming = admit_gate(incoming, now_tick, stale_ticks, future_ticks,
                          tomb_budget, own)
    post = jnp.maximum(known, incoming)
    return apply_stickiness(known, post)


def merge_records(known_ts, known_status, inc_ts, inc_status, now_tick,
                  stale_ticks, future_ticks=None):
    """Unpacked-tensor variant of :func:`merge_packed` for callers that keep
    separate ts/status tensors. Returns (ts, status, accepted-mask)."""
    known = pack(known_ts, known_status)
    incoming = pack(inc_ts, inc_status)
    merged = merge_packed(known, incoming, now_tick, stale_ticks,
                          future_ticks)
    accepted = merged != known
    return unpack_ts(merged), unpack_status(merged), accepted
