"""Cluster topologies for the gossip simulator.

The reference gossips to randomly-selected members of the full cluster
(memberlist SWIM over UDP); the BASELINE.json validation configs also call
for constrained graphs (ring, Erdős–Rényi, Barabási–Albert, partitioned
mesh).  A :class:`Topology` is the peer-adjacency structure the gossip
kernel samples fan-out targets from.

Representation: a padded neighbor list ``nbrs[N, K]`` (int32) plus a
degree vector ``deg[N]`` — sampling peer *i* of node *n* is
``nbrs[n, randint(deg[n])]``, which keeps peer selection uniform over real
neighbors without ragged shapes (static shapes are required under jit).
The fully-connected ("complete") topology used by memberlist-style gossip
is special-cased: peers are sampled directly from ``[0, N)`` with a
self-exclusion shift, so no O(N²) structure is ever materialized.

Builders run host-side in NumPy (topology construction is one-time setup,
not the hot path) and return device-ready arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """Peer-adjacency for N nodes.

    ``nbrs`` is None for the complete graph.  ``cut_mask`` (optional,
    bool[N, K]) marks edges disabled while a network partition is active
    (the split+heal scenario, BASELINE.json config 5); the gossip kernel
    treats a cut edge as a self-loop (no-op delivery).
    """

    n: int
    nbrs: Optional[np.ndarray] = None  # int32 [N, K], padded with self-index
    deg: Optional[np.ndarray] = None   # int32 [N]
    name: str = "complete"

    @property
    def max_degree(self) -> int:
        return 0 if self.nbrs is None else int(self.nbrs.shape[1])


def complete(n: int) -> Topology:
    """Fully-connected cluster — memberlist's random-member gossip."""
    return Topology(n=n, name="complete")


def _pad_neighbor_list(n: int, adj: list[list[int]], name: str) -> Topology:
    deg = np.array([len(a) for a in adj], dtype=np.int32)
    k = max(1, int(deg.max()))
    nbrs = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k))  # self-pad
    for i, a in enumerate(adj):
        if a:
            nbrs[i, : len(a)] = np.asarray(a, dtype=np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=name)


def ring(n: int, hops: int = 1) -> Topology:
    """Ring lattice: each node linked to ``hops`` neighbors on each side
    (BASELINE.json config 2 uses a 32-node ring)."""
    offsets = [d for h in range(1, hops + 1) for d in (h, -h)]
    nbrs = np.stack(
        [(np.arange(n) + d) % n for d in offsets], axis=1
    ).astype(np.int32)
    deg = np.full(n, len(offsets), dtype=np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"ring{hops}")


def erdos_renyi(n: int, avg_degree: float, seed: int = 0) -> Topology:
    """Erdős–Rényi G(n, p) with p = avg_degree/(n-1) (config 3)."""
    rng = np.random.default_rng(seed)
    p = min(1.0, avg_degree / max(1, n - 1))
    adj: list[list[int]] = [[] for _ in range(n)]
    # Sample undirected edges in blocks of rows to bound memory.
    block = max(1, min(n, 4_000_000 // max(n, 1) + 1))
    for start in range(0, n, block):
        stop = min(n, start + block)
        rows = np.arange(start, stop)
        mask = rng.random((stop - start, n)) < p
        # Keep upper triangle only (i < j) to avoid double-sampling.
        mask &= np.arange(n)[None, :] > rows[:, None]
        for r, i in enumerate(rows):
            for j in np.nonzero(mask[r])[0]:
                adj[i].append(int(j))
                adj[j].append(int(i))
    return _pad_neighbor_list(n, adj, f"er{avg_degree:g}")


def barabasi_albert(n: int, m: int, seed: int = 0) -> Topology:
    """Barabási–Albert scale-free graph, m edges per new node (config 4)."""
    rng = np.random.default_rng(seed)
    adj: list[list[int]] = [[] for _ in range(n)]
    # Degree-proportional attachment via the repeated-endpoint list.
    repeated: list[int] = []
    for v in range(m, n):
        chosen = set()
        while len(chosen) < min(m, v):
            if repeated and rng.random() < 0.9:
                cand = repeated[rng.integers(len(repeated))]
            else:
                cand = int(rng.integers(v))
            chosen.add(cand)
        for t in chosen:
            adj[v].append(t)
            adj[t].append(v)
            repeated.extend((v, t))
    return _pad_neighbor_list(n, adj, f"ba{m}")


def mesh2d(rows: int, cols: int) -> Topology:
    """2-D grid mesh with 4-neighbor connectivity (config 5's 1M-node
    partitioned mesh is a split mesh2d)."""
    n = rows * cols
    idx = np.arange(n, dtype=np.int32).reshape(rows, cols)
    # Vectorized neighbor assembly (a Python per-cell loop takes tens of
    # seconds at the 1M-node config-5 scale): candidate neighbors in the
    # four directions, invalid ones (grid edges) padded with self.
    self_col = idx.reshape(n)
    cand = np.tile(self_col[:, None], (1, 4))
    valid = np.zeros((n, 4), dtype=bool)
    up = np.roll(idx, 1, axis=0).reshape(n)
    down = np.roll(idx, -1, axis=0).reshape(n)
    left = np.roll(idx, 1, axis=1).reshape(n)
    right = np.roll(idx, -1, axis=1).reshape(n)
    rr = np.repeat(np.arange(rows), cols)
    cc = np.tile(np.arange(cols), rows)
    for k, (nbr, ok) in enumerate((
            (up, rr > 0), (down, rr < rows - 1),
            (left, cc > 0), (right, cc < cols - 1))):
        cand[:, k] = np.where(ok, nbr, self_col)
        valid[:, k] = ok
    # Compact valid neighbors to the front of each row (stable order:
    # up, down, left, right — matching the original construction).
    order = np.argsort(~valid, axis=1, kind="stable")
    nbrs = np.take_along_axis(cand, order, axis=1)
    deg = valid.sum(axis=1).astype(np.int32)
    pad = np.arange(4)[None, :] >= deg[:, None]
    nbrs = np.where(pad, self_col[:, None], nbrs).astype(np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"mesh{rows}x{cols}")


def partition_mask(topo: Topology, side_of: np.ndarray) -> np.ndarray:
    """Bool[N, K] mask of edges crossing a partition boundary.

    ``side_of[n]`` assigns each node to a side; an edge is cut when its
    endpoints differ.  Feed the result to the gossip kernel while the
    split is active, then drop it to heal (config 5: 2-way split + heal).
    """
    if topo.nbrs is None:
        raise ValueError("partition_mask requires an explicit neighbor list")
    return side_of[topo.nbrs] != side_of[:, None]
