"""Cluster topologies for the gossip simulator.

The reference gossips to randomly-selected members of the full cluster
(memberlist SWIM over UDP); the BASELINE.json validation configs also call
for constrained graphs (ring, Erdős–Rényi, Barabási–Albert, partitioned
mesh).  A :class:`Topology` is the peer-adjacency structure the gossip
kernel samples fan-out targets from.

Representation: a padded neighbor list ``nbrs[N, K]`` (int32) plus a
degree vector ``deg[N]`` — sampling peer *i* of node *n* is
``nbrs[n, randint(deg[n])]``, which keeps peer selection uniform over real
neighbors without ragged shapes (static shapes are required under jit).
The fully-connected ("complete") topology used by memberlist-style gossip
is special-cased: peers are sampled directly from ``[0, N)`` with a
self-exclusion shift, so no O(N²) structure is ever materialized.

Builders run host-side in NumPy (topology construction is one-time setup,
not the hot path) and return device-ready arrays.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """Peer-adjacency for N nodes.

    ``nbrs`` is None for the complete graph.  Partition cuts are NOT a
    ``Topology`` attribute: a ``cut_mask`` (bool[N, K], built by
    :func:`partition_mask`) is passed separately to the gossip kernel /
    sim constructors, because a cut is transient round state (the
    split+heal scenario, BASELINE.json config 5) while the adjacency is
    compile-time structure; the kernel treats a cut edge as a self-loop
    (no-op delivery).

    ``stagger``/``stagger_period`` (optional) carry per-node round-phase
    offsets for pipelined gossiping (docs/topology.md): node ``i``
    gossips only on rounds where ``(round + stagger[i]) % period == 0``
    and self-loops otherwise.  ``None``/period ≤ 1 compiles to the
    unstaggered program bit for bit.  Anti-entropy push-pull is never
    staggered — it is the catch-up channel.
    """

    n: int
    nbrs: Optional[np.ndarray] = None  # int32 [N, K], padded with self-index
    deg: Optional[np.ndarray] = None   # int32 [N]
    name: str = "complete"
    stagger: Optional[np.ndarray] = None  # int32 [N] phase offsets
    stagger_period: int = 1

    @property
    def max_degree(self) -> int:
        return 0 if self.nbrs is None else int(self.nbrs.shape[1])


def complete(n: int) -> Topology:
    """Fully-connected cluster — memberlist's random-member gossip."""
    return Topology(n=n, name="complete")


def _pad_neighbor_list(n: int, adj: list[list[int]], name: str) -> Topology:
    deg = np.array([len(a) for a in adj], dtype=np.int32)
    k = max(1, int(deg.max()))
    nbrs = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k))  # self-pad
    for i, a in enumerate(adj):
        if a:
            nbrs[i, : len(a)] = np.asarray(a, dtype=np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=name)


def ring(n: int, hops: int = 1) -> Topology:
    """Ring lattice: each node linked to ``hops`` neighbors on each side
    (BASELINE.json config 2 uses a 32-node ring)."""
    offsets = [d for h in range(1, hops + 1) for d in (h, -h)]
    nbrs = np.stack(
        [(np.arange(n) + d) % n for d in offsets], axis=1
    ).astype(np.int32)
    deg = np.full(n, len(offsets), dtype=np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"ring{hops}")


def erdos_renyi(n: int, avg_degree: float, seed: int = 0) -> Topology:
    """Erdős–Rényi G(n, p) with p = avg_degree/(n-1) (config 3).

    Fully vectorized (the original per-row Python append loop took tens
    of seconds at 100k+ nodes; builder cost matters once ``/sweep``
    builds per-scenario overlays) and bit-identical to it: the RNG
    draws are the same block-of-rows ``random((rows, n))`` calls, and
    the append order of the loop left every adjacency row ascending —
    node v collected its smaller neighbors while their rows were
    processed (in ascending i) and its larger ones from its own row (in
    ascending j) — so a lexsorted edge list reproduces the exact padded
    rows.
    """
    rng = np.random.default_rng(seed)
    p = min(1.0, avg_degree / max(1, n - 1))
    srcs, dsts = [], []
    # Sample undirected edges in blocks of rows to bound memory.
    block = max(1, min(n, 4_000_000 // max(n, 1) + 1))
    for start in range(0, n, block):
        stop = min(n, start + block)
        rows = np.arange(start, stop)
        mask = rng.random((stop - start, n)) < p
        # Keep upper triangle only (i < j) to avoid double-sampling.
        mask &= np.arange(n)[None, :] > rows[:, None]
        r, c = np.nonzero(mask)
        srcs.append(rows[r])
        dsts.append(c)
    i = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
    j = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
    # Both directions of every undirected edge, ascending per node.
    src = np.concatenate([i, j])
    dst = np.concatenate([j, i])
    order = np.lexsort((dst, src))
    src, dst = src[order], dst[order]
    deg = np.bincount(src, minlength=n).astype(np.int32)
    k = max(1, int(deg.max())) if n else 1
    nbrs = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k))
    starts = np.cumsum(deg, dtype=np.int64) - deg
    col = np.arange(src.shape[0], dtype=np.int64) - starts[src]
    nbrs[src, col] = dst.astype(np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"er{avg_degree:g}")


def barabasi_albert(n: int, m: int, seed: int = 0) -> Topology:
    """Barabási–Albert scale-free graph, m edges per new node (config 4)."""
    rng = np.random.default_rng(seed)
    adj: list[list[int]] = [[] for _ in range(n)]
    # Degree-proportional attachment via the repeated-endpoint list.
    repeated: list[int] = []
    for v in range(m, n):
        chosen = set()
        while len(chosen) < min(m, v):
            if repeated and rng.random() < 0.9:
                cand = repeated[rng.integers(len(repeated))]
            else:
                cand = int(rng.integers(v))
            chosen.add(cand)
        for t in chosen:
            adj[v].append(t)
            adj[t].append(v)
            repeated.extend((v, t))
    return _pad_neighbor_list(n, adj, f"ba{m}")


def mesh2d(rows: int, cols: int) -> Topology:
    """2-D grid mesh with 4-neighbor connectivity (config 5's 1M-node
    partitioned mesh is a split mesh2d)."""
    n = rows * cols
    idx = np.arange(n, dtype=np.int32).reshape(rows, cols)
    # Vectorized neighbor assembly (a Python per-cell loop takes tens of
    # seconds at the 1M-node config-5 scale): candidate neighbors in the
    # four directions, invalid ones (grid edges) padded with self.
    self_col = idx.reshape(n)
    cand = np.tile(self_col[:, None], (1, 4))
    valid = np.zeros((n, 4), dtype=bool)
    up = np.roll(idx, 1, axis=0).reshape(n)
    down = np.roll(idx, -1, axis=0).reshape(n)
    left = np.roll(idx, 1, axis=1).reshape(n)
    right = np.roll(idx, -1, axis=1).reshape(n)
    rr = np.repeat(np.arange(rows), cols)
    cc = np.tile(np.arange(cols), rows)
    for k, (nbr, ok) in enumerate((
            (up, rr > 0), (down, rr < rows - 1),
            (left, cc > 0), (right, cc < cols - 1))):
        cand[:, k] = np.where(ok, nbr, self_col)
        valid[:, k] = ok
    # Compact valid neighbors to the front of each row (stable order:
    # up, down, left, right — matching the original construction).
    order = np.argsort(~valid, axis=1, kind="stable")
    nbrs = np.take_along_axis(cand, order, axis=1)
    deg = valid.sum(axis=1).astype(np.int32)
    pad = np.arange(4)[None, :] >= deg[:, None]
    nbrs = np.where(pad, self_col[:, None], nbrs).astype(np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"mesh{rows}x{cols}")


def ring_chord(n: int) -> Topology:
    """Ring ±1 plus symmetric power-of-two chord fingers (±2, ±4, …):
    the classic O(log n)-diameter structured overlay.  Undirected —
    every finger is added in both directions."""
    offsets = [1, -1]
    f = 2
    while f <= (n - 1) // 2:
        offsets.extend((f, -f))
        f *= 2
    idx = np.arange(n, dtype=np.int32)
    cols, seen = [], set()
    for d in offsets:
        d_mod = d % n
        if d_mod == 0 or d_mod in seen:
            continue
        seen.add(d_mod)
        cols.append((idx + d) % n)
    nbrs = np.stack(cols, axis=1).astype(np.int32)
    deg = np.full(n, nbrs.shape[1], dtype=np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name="chord")


def expander(n: int, k: int = 4, seed: int = 0) -> Topology:
    """Random k-regular-ish expander: the union of ``k // 2`` seeded
    Hamiltonian cycles (each cycle contributes one left and one right
    neighbor per node).  Connected by construction — every cycle visits
    all nodes — and undirected; coincident cycle edges are deduped per
    node, so ``deg`` may dip slightly below k on small n."""
    if k < 2 or k % 2:
        raise ValueError(f"expander degree k must be even and >= 2, got {k}")
    rng = np.random.default_rng(seed)
    adj: list[list[int]] = [[] for _ in range(n)]
    for _ in range(k // 2):
        perm = rng.permutation(n)
        nxt = np.roll(perm, -1)
        for a, b in zip(perm, nxt):
            a, b = int(a), int(b)
            if b not in adj[a]:
                adj[a].append(b)
            if a not in adj[b]:
                adj[b].append(a)
    return _pad_neighbor_list(n, adj, f"expander{k}")


def zoned(n: int, zones: int, *, local_hops: int = 2, remote_deg: int = 2,
          local_bias: float = 0.5, gateways: int = 2,
          seed: int = 0) -> Topology:
    """Zone-aware two-tier sampling table (docs/topology.md).

    Nodes are grouped into ``zones`` contiguous blocks.  The LOCAL tier
    is a within-zone ring lattice (``local_hops`` each side —
    deterministic, symmetric, connected within the zone).  The REMOTE
    tier gives every node ``remote_deg`` directed links into ONE seeded
    target zone (not its own) — concentrating each node's cross-zone
    reach on a single zone is what keeps the zoned board exchange's
    per-shard-pair row blocks narrow (:func:`zoned_exchange_plan`).
    The first ``gateways`` nodes of each zone additionally link (both
    directions) to their positional twin in the next zone, so the zone
    graph contains a deterministic inter-zone ring and the overlay is
    connected by construction.

    ``local_bias`` sets the probability that a uniform neighbor-table
    draw lands in the local tier: local entries are replicated an
    integer number of times so the local fraction of the padded row
    approximates it (quantized — the realized bias is
    ``r·L / (r·L + R)``).

    Shard alignment rule: with ``n % d == 0`` meshes, choosing
    ``zones`` as a multiple of d makes every zone fall entirely inside
    one shard, so sampling locality becomes shard locality and the
    ``board_exchange="zoned"`` mode ships only the narrow cross-shard
    blocks (docs/sharding.md).
    """
    if n % zones:
        raise ValueError(f"zones={zones} must divide n={n}")
    zl = n // zones
    if zl < 2:
        raise ValueError(f"zoned needs >= 2 nodes per zone, got {zl}")
    if not 0.0 < local_bias < 1.0:
        raise ValueError(f"local_bias must be in (0, 1), got {local_bias}")
    rng = np.random.default_rng(seed)
    idx = np.arange(n, dtype=np.int32)
    zone_of = idx // zl
    z0 = zone_of * zl                      # zone block start
    pos = idx - z0                         # position within zone

    # Local tier: within-zone ring lattice, ±1..±local_hops.
    hops = [h for step in range(1, local_hops + 1) for h in (step, -step)]
    # A zone of zl nodes has at most zl-1 distinct others.
    local_cols = []
    seen_off = set()
    for h in hops:
        if h % zl == 0 or (h % zl) in seen_off:
            continue
        seen_off.add(h % zl)
        local_cols.append(z0 + (pos + h) % zl)
    local = np.stack(local_cols, axis=1).astype(np.int32)   # [n, L]

    # Remote tier: remote_deg directed links into one seeded zone.
    if zones < 2:
        raise ValueError("zoned needs >= 2 zones for the remote tier")
    tz = rng.integers(0, zones - 1, size=n)
    tz = tz + (tz >= zone_of)              # exclude own zone
    rr = rng.integers(0, zl, size=(n, remote_deg))
    remote = (tz[:, None] * zl + rr).astype(np.int32)       # [n, R]

    # Gateway ring: node (z, g) <-> node (z+1, g), both directions, so
    # the zone graph is connected independent of the seeded targets.
    gw = min(gateways, zl)
    gcols = np.full((n, 2), -1, dtype=np.int32)
    is_gw = pos < gw
    gcols[is_gw, 0] = (idx[is_gw] + zl) % n                  # next zone
    gcols[is_gw, 1] = (idx[is_gw] - zl) % n                  # prev zone

    # Bias quantization: replicate the local block r times so the
    # local fraction r·L/(r·L + R) lands nearest local_bias.
    L, R = local.shape[1], remote.shape[1]
    best_r, best_err = 1, float("inf")
    for r in range(1, 9):
        err = abs(r * L / (r * L + R) - local_bias)
        if err < best_err - 1e-12:
            best_r, best_err = r, err
    parts = [local] * best_r + [remote]
    row_parts = np.concatenate(parts, axis=1)
    width = row_parts.shape[1] + 2
    nbrs = np.tile(idx[:, None], (1, width))
    nbrs[:, :row_parts.shape[1]] = row_parts
    deg = np.full(n, row_parts.shape[1], dtype=np.int32)
    has_g = gcols >= 0
    for g in range(2):
        sel = has_g[:, g]
        nbrs[sel, deg[sel]] = gcols[sel, g]
        deg[sel] += 1
    # Self-pad strictly past deg (rows differ in width only via gateways).
    pad = np.arange(width)[None, :] >= deg[:, None]
    nbrs = np.where(pad, idx[:, None], nbrs).astype(np.int32)
    return Topology(n=n, nbrs=nbrs, deg=deg, name=f"zoned{zones}")


def components(topo: Topology) -> np.ndarray:
    """Connected-component label per node (int32[N], labels are the
    minimum member id of each component).  Vectorized min-label
    propagation — converges in O(component diameter) sweeps, which for
    the fragmented ER/BA graphs :func:`repair` targets is small."""
    if topo.nbrs is None:
        return np.zeros(topo.n, dtype=np.int32)
    n = topo.n
    K = topo.nbrs.shape[1]
    edge_ok = np.arange(K)[None, :] < topo.deg[:, None]
    src = np.repeat(np.arange(n, dtype=np.int64), K)[edge_ok.ravel()]
    dst = topo.nbrs.ravel().astype(np.int64)[edge_ok.ravel()]
    label = np.arange(n, dtype=np.int64)
    while True:
        new = label.copy()
        if src.size:
            np.minimum.at(new, src, label[dst])
            np.minimum.at(new, dst, label[src])
        # Pointer-jump: chase each label to its current representative,
        # collapsing chains so sweeps count diameters, not path lengths.
        while True:
            hop = new[new]
            if np.array_equal(hop, new):
                break
            new = hop
        if np.array_equal(new, label):
            return label.astype(np.int32)
        label = new


def repair(topo: Topology) -> Topology:
    """Degree-repair a fragmented overlay: chain its connected
    components into one at min-degree representatives.

    Random builders can fragment — a sparse :func:`erdos_renyi` draw
    strands isolated nodes and islands; :func:`barabasi_albert` cannot,
    but its repaired form is still the documented contract for the
    chaos sweep (benchmarks/topology_sweep.py ``--chaos``): a
    fragmented overlay never converges, which reads as an attack
    finding when it is a builder artifact.

    The repair is minimal and deterministic: components are ordered by
    their minimum member id and chained consecutively, each link
    joining the two components' minimum-degree nodes (ties to the
    lowest id) — the nodes that can best absorb an extra edge without
    distorting the degree profile.  Adds exactly ``components - 1``
    undirected edges; a connected topology is returned unchanged.  The
    repaired overlay is renamed ``{name}+r`` so sweep records show the
    builder artifact was patched.
    """
    if topo.nbrs is None:
        return topo  # complete graph: connected by definition
    label = components(topo)
    reps_of = {}
    for comp in np.unique(label):
        members = np.nonzero(label == comp)[0]
        d = topo.deg[members]
        reps_of[int(comp)] = int(members[int(np.argmin(d))])
    if len(reps_of) <= 1:
        return topo
    reps = [reps_of[c] for c in sorted(reps_of)]
    n = topo.n
    deg = topo.deg.astype(np.int32).copy()
    extra = np.zeros(n, dtype=np.int32)
    for a, b in zip(reps, reps[1:]):
        extra[a] += 1
        extra[b] += 1
    width = max(topo.nbrs.shape[1], int((deg + extra).max()))
    nbrs = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, width))
    nbrs[:, : topo.nbrs.shape[1]] = topo.nbrs
    for a, b in zip(reps, reps[1:]):
        nbrs[a, deg[a]] = b
        deg[a] += 1
        nbrs[b, deg[b]] = a
        deg[b] += 1
    # Re-pad strictly past each row's degree (the widened columns).
    pad = np.arange(width)[None, :] >= deg[:, None]
    nbrs = np.where(pad, np.arange(n, dtype=np.int32)[:, None], nbrs)
    return dataclasses.replace(topo, nbrs=nbrs.astype(np.int32), deg=deg,
                               name=f"{topo.name}+r")


def with_stagger(topo: Topology, period: int,
                 offsets: Optional[np.ndarray] = None,
                 seed: int = 0) -> Topology:
    """Attach per-node round-stagger phase offsets (pipelined gossiping,
    docs/topology.md): node i gossips only when ``(round + offsets[i]) %
    period == 0``.  ``offsets`` defaults to a seeded uniform draw over
    ``[0, period)``; period ≤ 1 strips any stagger (the unstaggered
    program, bit for bit)."""
    if period <= 1:
        return dataclasses.replace(topo, stagger=None, stagger_period=1)
    if offsets is None:
        offsets = np.random.default_rng(seed).integers(
            0, period, size=topo.n)
    offsets = np.asarray(offsets, dtype=np.int32)
    if offsets.shape != (topo.n,):
        raise ValueError(
            f"stagger offsets must be shape ({topo.n},), got {offsets.shape}")
    return dataclasses.replace(topo, stagger=offsets,
                               stagger_period=int(period))


def partition_mask(topo: Topology, side_of: np.ndarray) -> np.ndarray:
    """Bool[N, K] mask of edges crossing a partition boundary.

    ``side_of[n]`` assigns each node to a side; an edge is cut when its
    endpoints differ.  Feed the result to the gossip kernel while the
    split is active, then drop it to heal (config 5: 2-way split + heal).
    """
    if topo.nbrs is None:
        raise ValueError("partition_mask requires an explicit neighbor list")
    return side_of[topo.nbrs] != side_of[:, None]


# -- the overlay registry (name → builder; the /sweep + bench axis) --------


def topology_names() -> tuple[str, ...]:
    """The name families :func:`from_name` resolves — ``{x}`` marks an
    integer parameter baked into the name (``ring2``, ``zoned64``, …)."""
    return ("complete", "ring{h}", "chord", "expander{k}", "er{deg}",
            "ba{m}", "zoned{z}", "mesh{r}x{c}")


def from_name(name: str, n: int, seed: int = 0) -> Topology:
    """Resolve an overlay NAME into a built :class:`Topology` —
    deterministic for a (name, n, seed) triple, so a ``/sweep`` grid
    point and its unbatched rerun build the identical overlay.  Raises
    a named ``ValueError`` for unknown names (the ``POST /sweep`` 400
    contract, docs/sweep.md)."""
    from sidecar_tpu import metrics

    s = str(name).strip().lower()
    m = re.fullmatch(
        r"(complete|chord)"
        r"|ring(\d+)|expander(\d+)|er(\d+(?:\.\d+)?)|ba(\d+)"
        r"|zoned(\d+)|mesh(\d+)x(\d+)", s)
    if m is None:
        raise ValueError(
            f"unknown topology {name!r}: known families are "
            f"{', '.join(topology_names())}")
    try:
        if m.group(1) == "complete":
            family, topo = "complete", complete(n)
        elif m.group(1) == "chord":
            family, topo = "chord", ring_chord(n)
        elif m.group(2):
            family, topo = "ring", ring(n, hops=int(m.group(2)))
        elif m.group(3):
            family, topo = "expander", expander(n, k=int(m.group(3)),
                                                seed=seed)
        elif m.group(4):
            family, topo = "er", erdos_renyi(
                n, avg_degree=float(m.group(4)), seed=seed)
        elif m.group(5):
            family, topo = "ba", barabasi_albert(n, m=int(m.group(5)),
                                                 seed=seed)
        elif m.group(6):
            family, topo = "zoned", zoned(n, zones=int(m.group(6)),
                                          seed=seed)
        else:
            r, c = int(m.group(7)), int(m.group(8))
            if r * c != n:
                raise ValueError(
                    f"mesh{r}x{c} has {r * c} nodes, cluster has {n}")
            family, topo = "mesh", mesh2d(r, c)
    except ValueError as exc:
        raise ValueError(f"topology {name!r} invalid for n={n}: {exc}") \
            from exc
    metrics.incr(f"topology.from_name.{family}")
    return topo


# -- the zoned board-exchange plan (docs/sharding.md) ----------------------


@dataclasses.dataclass(frozen=True)
class ZonedHop:
    """One hop of the zoned exchange: the static per-sender-shard row
    blocks shipped at ring offset h (shard s → shard (s-h) mod d).

    ``rows[d, R]`` are each sender shard's local row ids (0-padded past
    ``valid``); ``pos[d, nl]`` inverts them (local row → block position,
    R for absent rows — the receiver-side lookup of the compressed
    twin's pull fold)."""

    rows: np.ndarray   # int32 [d, R]
    valid: np.ndarray  # bool  [d, R]
    pos: np.ndarray    # int32 [d, nl]

    @property
    def width(self) -> int:
        return int(self.rows.shape[1])


@dataclasses.dataclass(frozen=True)
class ZonedExchangePlan:
    """Static reachability tables for ``board_exchange="zoned"``: which
    of each shard's rows the overlay can actually make another shard
    sample.  ``hops[h-1]`` is the block plan for ring offset h (None
    when no ordered pair needs that offset and the hop is skipped
    entirely); built once host-side at sim construction.

    ``direction="push"`` (dense twin: offers travel to targets) marks
    row r of shard s reachable into shard t when some neighbor of r
    lives on t; ``"pull"`` (compressed twin: boards are pulled by
    samplers) when some node of t has r in its neighbor table.  Either
    way the set is a static superset of every cross-shard (sender,
    receiver) pair a round can sample, which is what makes the mode
    bit-identical to ``all_gather`` for the same sampled peers."""

    d: int
    nl: int
    direction: str
    hops: tuple  # tuple[Optional[ZonedHop]], length d-1

    @property
    def total_rows(self) -> int:
        """Σ hop widths — the per-device per-round row blocks received."""
        return sum(h.width for h in self.hops if h is not None)


def zoned_exchange_plan(topo: Topology, d: int,
                        direction: str = "push") -> ZonedExchangePlan:
    """Build the static per-(sender shard, ring offset) row-block tables
    of the zoned board exchange (see :class:`ZonedExchangePlan`).

    Requires a neighbor-list topology — the complete graph's reach is
    every shard, which is exactly the ``all_gather`` this mode exists to
    shrink."""
    if topo.nbrs is None:
        raise ValueError(
            "zoned exchange requires a neighbor-list topology: the "
            "complete graph reaches every shard (use all_gather there)")
    if direction not in ("push", "pull"):
        raise ValueError(f"direction must be push|pull, got {direction!r}")
    n = topo.n
    if n % d:
        raise ValueError(f"n={n} must divide the {d}-device mesh")
    nl = n // d
    K = topo.nbrs.shape[1]
    edge_ok = np.arange(K)[None, :] < topo.deg[:, None]
    src = np.repeat(np.arange(n, dtype=np.int64), K)[edge_ok.ravel()]
    tgt = topo.nbrs.ravel().astype(np.int64)[edge_ok.ravel()]
    if direction == "push":
        rows_of, needed_by = src, tgt // nl
    else:
        rows_of, needed_by = tgt, src // nl
    reach = np.zeros((n, d), dtype=bool)
    reach[rows_of, needed_by] = True
    reach[np.arange(n), np.arange(n) // nl] = False  # own shard is local
    hops = []
    for h in range(1, d):
        blocks = [np.nonzero(reach[s * nl:(s + 1) * nl, (s - h) % d])[0]
                  for s in range(d)]
        R = max((len(b) for b in blocks), default=0)
        if R == 0:
            hops.append(None)
            continue
        rows = np.zeros((d, R), dtype=np.int32)
        valid = np.zeros((d, R), dtype=bool)
        pos = np.full((d, nl), R, dtype=np.int32)
        for s, b in enumerate(blocks):
            rows[s, :len(b)] = b
            valid[s, :len(b)] = True
            pos[s, b] = np.arange(len(b), dtype=np.int32)
        hops.append(ZonedHop(rows=rows, valid=valid, pos=pos))
    return ZonedExchangePlan(d=d, nl=nl, direction=direction,
                             hops=tuple(hops))
