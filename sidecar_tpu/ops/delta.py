"""TPU-side delta extraction — per-round changed-cell sets.

The query plane's device half: instead of shipping terminal state
tensors to the host and diffing there (two O(N·M) device→host copies
plus a host diff per observation), consecutive belief tensors are
diffed ON DEVICE and only the changed ``(node, slot)`` index sets leave
the chip — the pipelined-gossip shape (PAPERS: *The Algorithm of
Pipelined Gossiping*): rounds stream incremental outputs rather than
terminal snapshots, and per-round change sets are computed where the
state lives (PAPERS: *Tascade*'s on-device reduction argument).

Everything here is shape-static and scan-compatible: a
:class:`DeltaBatch` has a fixed capacity ``cap``, so ``lax.scan`` can
stack one per round and stream them out through the bridge.  A round
that changes more than ``cap`` cells sets ``overflow`` — the consumer's
contract is then *collapse to snapshot-at-latest*, exactly the hub's
backpressure rule (docs/query.md): the capacity bound and the
subscriber queue bound degrade the same way.

Exact model: diff consecutive ``known[N, M]`` tensors directly.
Compressed model: materialize the belief view
``belief(i, m) = max(floor[m], cache hit, own if owner)`` with
:func:`compressed_belief` (row gathers + elementwise, no scatters) and
diff that — O(N·M), which is fine in the bridge/test regime this op
serves; at the 100k-node north star the belief matrix is the thing the
compressed model exists to never materialize, so large-N delta
streaming stays on the exact model's shard sizes.

Validated cell-for-cell against a pure-Python diff oracle
(tests/test_delta.py), tombstone transitions included.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from sidecar_tpu.models.compressed import hash_line


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeltaBatch:
    """One round's changed cells, padded to a static capacity.

    ``count`` is the TRUE number of changed cells (it may exceed the
    padded capacity); entries past ``min(count, cap)`` are padding with
    ``node == slot == -1`` and ``val == 0``.  ``overflow`` is
    ``count > cap`` — the collapse-to-snapshot signal."""

    count: jax.Array     # int32 scalar — true changed-cell count
    node: jax.Array      # int32 [cap] — node index (-1 padding)
    slot: jax.Array      # int32 [cap] — global slot index (-1 padding)
    val: jax.Array       # int32 [cap] — NEW packed key at the cell
    overflow: jax.Array  # bool scalar — count exceeded cap


@functools.partial(jax.jit, static_argnames=("cap",))
def extract_delta(prev, nxt, cap: int) -> DeltaBatch:
    """Changed cells between two aligned packed-belief tensors.

    ``prev``/``nxt`` are same-shape int32 tensors (``[N, M]`` belief
    views; any leading shape works — indices are reported as
    ``(row, col)`` of the 2-D view).  The static-size ``nonzero`` keeps
    the op scan-compatible; capacity overflow is reported, never
    silently truncated away (``count`` stays exact)."""
    prev2 = prev.reshape(prev.shape[0], -1)
    nxt2 = nxt.reshape(nxt.shape[0], -1)
    m = nxt2.shape[1]
    total = nxt2.size
    changed = (prev2 != nxt2).reshape(-1)
    count = jnp.sum(changed.astype(jnp.int32))
    idx = jnp.nonzero(changed, size=cap, fill_value=total)[0]
    valid = idx < total
    safe = jnp.minimum(idx, total - 1)
    node = jnp.where(valid, (safe // m).astype(jnp.int32), -1)
    slot = jnp.where(valid, (safe % m).astype(jnp.int32), -1)
    val = jnp.where(valid, nxt2.reshape(-1)[safe], 0)
    return DeltaBatch(count=count, node=node, slot=slot, val=val,
                      overflow=count > cap)


def compressed_belief(own, cache_slot, cache_val, floor,
                      services_per_node: int):
    """Materialize the compressed model's per-node belief view
    ``[N, M]`` — ``belief(i, m) = max(floor[m], cache line hit,
    own[i] where i owns m)``.

    Scatter-free: the global line hash means slot ``m`` can only live
    at line ``hash_line(m)`` on every node, so the cache contribution
    is one contiguous row gather per node; the owner contribution is a
    masked broadcast of the flattened ``own``.  Node-dead masking is
    deliberately NOT applied here: the belief view reports what each
    node's state tensors hold (the decode the bridge maps back to
    catalogs), and liveness is the consumer's dimension."""
    n, s = own.shape
    m = floor.shape[0]
    slots = jnp.arange(m, dtype=jnp.int32)
    lines = hash_line(slots, cache_slot.shape[1], services_per_node)  # [M]
    hit = cache_slot[:, lines] == slots[None, :]                      # [N, M]
    cached = jnp.where(hit, cache_val[:, lines], 0)
    owner = slots // s                                                # [M]
    own_b = jnp.where(
        owner[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None],
        own.reshape(-1)[None, :], 0)
    return jnp.maximum(jnp.maximum(floor[None, :], cached), own_b)


def oracle_diff(prev, nxt) -> dict:
    """Pure-Python diff oracle: {(node, slot): new_packed} over two 2-D
    numpy belief arrays — the host-side ground truth the jitted op is
    validated against (and the shape the bridge's per-round mapping
    consumes)."""
    import numpy as np

    prev = np.asarray(prev)
    nxt = np.asarray(nxt)
    out = {}
    rows, cols = np.nonzero(prev != nxt)
    for r, c in zip(rows.tolist(), cols.tolist()):
        out[(r, c)] = int(nxt[r, c])
    return out


def batch_to_dict(batch: DeltaBatch) -> dict:
    """Host-side view of one DeltaBatch as {(node, slot): val} —
    drops padding; raises if the batch overflowed (the caller must
    handle overflow by resyncing from a snapshot instead)."""
    import numpy as np

    if bool(np.asarray(batch.overflow)):
        raise OverflowError(
            f"delta batch overflowed: {int(batch.count)} changes > "
            f"capacity {batch.node.shape[0]}")
    node = np.asarray(batch.node)
    slot = np.asarray(batch.slot)
    val = np.asarray(batch.val)
    keep = node >= 0
    return {(int(r), int(c)): int(v)
            for r, c, v in zip(node[keep], slot[keep], val[keep])}
