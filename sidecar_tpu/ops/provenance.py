"""Record-level propagation provenance (docs/telemetry.md).

The flight recorder (ops/trace.py) answers "how is the CLUSTER doing
this round"; this module answers "where is THIS record, how did it get
there, and how long did the tail wait".  A run picks ≤T tracer slots
(service records; the owner is ``slot // services_per_node``) and a
:class:`ProvTrace` rides the scan carry behind a static cap — the
RoundTrace/DeltaBatch contract: fixed shapes, an exact ``count``, and
an ``overflow`` flag instead of silent truncation.

Per tracked record the trace holds, per node:

* ``first_seen`` — the absolute round the node first held the record
  (−1 = never reached);
* ``parent`` — the infection parent: the peer whose sampled channel
  first plausibly delivered it (``PARENT_ORIGIN`` for seeded/minted
  copies, ``PARENT_UNATTRIBUTED`` when no sampled channel from a prior
  holder reached the node that round — e.g. a chaos delay-ring
  arrival, or the compressed model's floor fold);
* ``hops`` — infection-tree depth (0 at the origin and at
  unattributed arrivals, which restart the count conservatively);

plus a per-round ``coverage`` row (holder count per record).

Attribution rule (shared with the pure-NumPy oracle,
sim/oracle.ProvenanceOracle): a node newly holding a record is
attributed to the candidate holder with the minimal ``(hops, node id)``
among every peer channel sampled that round whose sender already held
the record.  The rule is deterministic and channel-exact — the channels
are re-derived from the very PRNG keys the step consumed — but it does
not re-derive per-message budget/loss gates: when several sampled
channels could have delivered, the minimal-(hops, id) one is charged.
Infection DETECTION is exact either way (a state diff), so
``first_seen`` — and every lag statistic — is exact; only the parent
choice among same-round multi-path deliveries is canonicalized.

The update is O(T·N·F) elementwise work plus one scatter-min — it
never touches the round's own tensors, which is what keeps
provenance-enabled runs bit-identical to untraced ones.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

# ``parent`` sentinels (host-side consumers: the bridge, bench).
PARENT_ORIGIN = -1
PARENT_UNATTRIBUTED = -2

_INF = jnp.iinfo(jnp.int32).max

# The smallest packed key with a real tick: pack(tick=1, status=0) =
# 1 << 3.  A ``ref`` below it (an empty slot at seed time) degrades the
# holder test to plain is_known — "the first version to appear".
_MIN_KNOWN = 8


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ProvTrace:
    """The carried provenance stream — static shapes, exact count.

    ``ref`` pins the traced VERSION: the globally-freshest packed key
    each tracer had when observation started (:func:`seed`).  A node
    "holds" the record once its belief reaches ``ref`` — without this,
    any stale copy (the compressed model's floor, a warm bridge
    snapshot) would count as already-infected and every lag would read
    zero.  LWW beliefs are monotone, so holding is monotone too."""

    ref: jax.Array         # int32 [T] traced packed-key threshold
    first_seen: jax.Array  # int32 [T, N] absolute round; -1 unreached
    parent: jax.Array      # int32 [T, N] infector node id / sentinel
    hops: jax.Array        # int32 [T, N] tree depth; -1 unreached
    coverage: jax.Array    # int32 [cap, T] holder count per observed round
    count: jax.Array       # int32 — rounds observed
    overflow: jax.Array    # bool — more rounds than coverage capacity


def zero_prov(tracked: int, n: int, cap: int) -> ProvTrace:
    """An empty trace for ``tracked`` records over ``n`` nodes with a
    ``cap``-round coverage window."""
    return ProvTrace(
        ref=jnp.zeros((tracked,), jnp.int32),
        first_seen=jnp.full((tracked, n), -1, jnp.int32),
        parent=jnp.full((tracked, n), PARENT_ORIGIN, jnp.int32),
        hops=jnp.full((tracked, n), -1, jnp.int32),
        coverage=jnp.zeros((cap, tracked), jnp.int32),
        count=jnp.zeros((), jnp.int32),
        overflow=jnp.zeros((), bool),
    )


def holders(prov: ProvTrace, belief: jax.Array) -> jax.Array:
    """Bool [N, T] holder matrix: which nodes' beliefs (packed [N, T])
    have reached the traced version."""
    return belief >= jnp.maximum(prov.ref, _MIN_KNOWN)[None, :]


def holders_batch(ref: jax.Array, belief: jax.Array) -> jax.Array:
    """Holder test against explicit refs — the fleet engine's batched
    twin of :func:`holders`: ``belief`` [..., N, T] vs ``ref``
    [..., T] → bool [..., N, T]."""
    return belief >= jnp.maximum(ref[..., None, :], _MIN_KNOWN)


def seed(prov: ProvTrace, belief: jax.Array, round_idx) -> ProvTrace:
    """Pin ``ref`` to the freshest current key per tracer and mark the
    nodes already holding it as origin copies: ``first_seen =
    round_idx``, hop 0, ``PARENT_ORIGIN``.  ``belief`` is the packed
    [N, T] belief matrix of the starting state."""
    prov = dataclasses.replace(
        prov, ref=jnp.max(belief, axis=0).astype(jnp.int32))
    hit = holders(prov, belief).T & (prov.first_seen < 0)
    round_idx = jnp.asarray(round_idx, jnp.int32)
    return dataclasses.replace(
        prov,
        first_seen=jnp.where(hit, round_idx, prov.first_seen),
        parent=jnp.where(hit, PARENT_ORIGIN, prov.parent),
        hops=jnp.where(hit, 0, prov.hops),
    )


def observe(prov: ProvTrace, prev_cols: jax.Array, nxt_cols: jax.Array,
            round_idx, pushes=(), pulls=()) -> ProvTrace:
    """Fold one round into the trace.

    ``prev_cols``/``nxt_cols``: bool [N, T] holder matrices before and
    after the step.  ``pushes``: list of ``(dst, mask)`` — sender ``s``
    offered to ``dst[s, k]`` where ``mask`` (broadcastable to ``dst``'s
    shape, or None) holds.  ``pulls``: list of ``(src, mask)`` —
    receiver ``i`` read from ``src[i, k]``.  Masks gate the channel,
    not the infection: a node that newly holds a record with no open
    candidate channel is recorded ``PARENT_UNATTRIBUTED``.
    """
    t_n, n = prov.first_seen.shape
    ids = jnp.arange(n, dtype=jnp.int32)
    # Candidate score: lexicographic (hops, node id) packed into one
    # int32 — valid while hops * n + n < 2^31 (hops is bounded by the
    # round horizon, so even n = 10^6 leaves >2000 hops of headroom).
    hops_c = jnp.maximum(prov.hops, 0)
    score = jnp.where(prev_cols.T, hops_c * n + ids[None, :], _INF)

    best = jnp.full((t_n, n + 1), _INF, jnp.int32)
    for idx, mask in pushes:
        contrib = jnp.broadcast_to(score[:, :, None],
                                   (t_n,) + idx.shape)
        if mask is not None:
            m = jnp.broadcast_to(mask, idx.shape)
            contrib = jnp.where(m[None], contrib, _INF)
        best = best.at[:, idx.reshape(-1)].min(
            contrib.reshape(t_n, -1), mode="drop")
    for idx, mask in pulls:
        cand = score[:, idx]                       # [T, N, K]
        if mask is not None:
            m = jnp.broadcast_to(mask, idx.shape)
            cand = jnp.where(m[None], cand, _INF)
        best = best.at[:, :n].min(jnp.min(cand, axis=2))

    bn = best[:, :n]
    attributed = bn != _INF
    parent_new = jnp.where(attributed, bn % n, PARENT_UNATTRIBUTED)
    hops_new = jnp.where(attributed, bn // n + 1, 0)

    newly = nxt_cols.T & (prov.first_seen < 0)
    round_idx = jnp.asarray(round_idx, jnp.int32)
    cov = jnp.sum(nxt_cols.astype(jnp.int32), axis=0)
    cap = prov.coverage.shape[0]
    coverage = prov.coverage.at[prov.count].set(cov, mode="drop")
    count = prov.count + 1
    return ProvTrace(
        ref=prov.ref,
        first_seen=jnp.where(newly, round_idx, prov.first_seen),
        parent=jnp.where(newly, parent_new, prov.parent),
        hops=jnp.where(newly, hops_new, prov.hops),
        coverage=coverage,
        count=count,
        overflow=prov.overflow | (count > cap),
    )


# -- tracer-key selection ---------------------------------------------------

def default_tracked(m: int, count: int) -> tuple:
    """``count`` tracer slots spread evenly over the slot space (so the
    tracers cover distinct owners on the owner-run layout)."""
    if m < 1 or count < 1:
        return ()
    count = min(count, m)
    return tuple(sorted({int(round(i * (m - 1) / max(count - 1, 1)))
                         for i in range(count)}))


# -- host-side reductions ---------------------------------------------------

def _pctl(vals, q: float):
    """Nearest-rank percentile, matching metrics._percentile so the SLO
    plane and the process histograms quote the same statistic."""
    if len(vals) == 0:
        return None
    vals = sorted(vals)
    k = max(0, min(len(vals) - 1,
                   int(round(q / 100.0 * len(vals) + 0.5)) - 1))
    return vals[k]


def lag_values(first_seen_row: np.ndarray) -> list:
    """Per-node lag samples for one record: rounds from the record's
    origin (its minimum first_seen) to each reached node."""
    fs = np.asarray(first_seen_row)
    seen = fs >= 0
    if not seen.any():
        return []
    origin = int(fs[seen].min())
    return [int(v) - origin for v in fs[seen]]


def pooled_lag(first_seen: np.ndarray) -> dict:
    """Lag CDF summary pooled across every tracked record: the
    per-(record, reached node) lag distribution in rounds."""
    lags: list = []
    for row in np.asarray(first_seen):
        lags.extend(lag_values(row))
    return {
        "samples": len(lags),
        "p50": _pctl(lags, 50.0),
        "p95": _pctl(lags, 95.0),
        "p99": _pctl(lags, 99.0),
        "max": max(lags) if lags else None,
    }


def p99_lag_rounds(first_seen: np.ndarray):
    """The /sweep column: pooled p99 rounds-lag, or None without
    samples (no tracers, or nothing reached)."""
    return pooled_lag(first_seen)["p99"]


def summarize(prov: ProvTrace, tracked, services_per_node: int) -> dict:
    """Host-side reduction of a finished trace: per-record lag CDFs,
    hop histograms, reach accounting, and the pooled lag summary."""
    fs = np.asarray(jax.device_get(prov.first_seen))
    hops = np.asarray(jax.device_get(prov.hops))
    parent = np.asarray(jax.device_get(prov.parent))
    count = int(jax.device_get(prov.count))
    cap = prov.coverage.shape[0]
    n = fs.shape[1]

    records = []
    for ti, slot in enumerate(tracked):
        seen = fs[ti] >= 0
        lags = lag_values(fs[ti])
        hop_vals = hops[ti][seen & (hops[ti] >= 0)]
        hist = np.bincount(hop_vals).tolist() if hop_vals.size else []
        records.append({
            "slot": int(slot),
            "origin_node": int(slot) // services_per_node,
            "origin_round": int(fs[ti][seen].min()) if seen.any()
            else None,
            "reached": int(seen.sum()),
            "rounds_to_reach_all": (max(lags) if seen.all() else None),
            "unattributed": int(np.sum(
                seen & (parent[ti] == PARENT_UNATTRIBUTED))),
            "lag": {"p50": _pctl(lags, 50.0), "p95": _pctl(lags, 95.0),
                    "p99": _pctl(lags, 99.0)},
            "hop_histogram": hist,
        })
    return {
        "tracked": [int(s) for s in tracked],
        "records": records,
        "lag": pooled_lag(fs),
        "rounds_observed": count,
        "overflow": bool(jax.device_get(prov.overflow)),
        "coverage": np.asarray(jax.device_get(
            prov.coverage))[:min(count, cap)].T.tolist(),
        "nodes": n,
    }


def tree_to_dict(prov: ProvTrace, tracked) -> list:
    """The exportable propagation-tree JSON: per record, the per-node
    parent/hop/first-seen arrays (parent sentinels: −1 origin, −2
    unattributed; first_seen −1 = never reached)."""
    fs = np.asarray(jax.device_get(prov.first_seen))
    parent = np.asarray(jax.device_get(prov.parent))
    hops = np.asarray(jax.device_get(prov.hops))
    return [{"slot": int(slot),
             "first_seen": fs[ti].tolist(),
             "parent": parent[ti].tolist(),
             "hops": hops[ti].tolist()}
            for ti, slot in enumerate(tracked)]


def blast_radius(prov: ProvTrace, tracked, services_per_node: int,
                 origin_nodes) -> dict:
    """Chaos/adversary accounting: which tracked records owned by a
    faulted origin set reached how much of the cluster, and via which
    paths (max tree depth + the unattributed count — deliveries the
    sampled channels cannot explain, i.e. delayed/duplicated paths)."""
    origin_nodes = set(int(x) for x in origin_nodes)
    fs = np.asarray(jax.device_get(prov.first_seen))
    hops = np.asarray(jax.device_get(prov.hops))
    parent = np.asarray(jax.device_get(prov.parent))
    n = fs.shape[1]
    out = []
    for ti, slot in enumerate(tracked):
        owner = int(slot) // services_per_node
        if owner not in origin_nodes:
            continue
        seen = fs[ti] >= 0
        hop_vals = hops[ti][seen & (hops[ti] >= 0)]
        out.append({
            "slot": int(slot),
            "origin_node": owner,
            "reached": int(seen.sum()),
            "reach_fraction": float(seen.sum()) / n,
            "max_hops": int(hop_vals.max()) if hop_vals.size else 0,
            "unattributed_paths": int(np.sum(
                seen & (parent[ti] == PARENT_UNATTRIBUTED))),
        })
    return {"origins": sorted(origin_nodes), "records": out}
