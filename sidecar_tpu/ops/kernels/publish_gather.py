"""Fused Pallas TPU kernels for the publish/board phase — the two
measured per-round floors of the compressed round.

`benchmarks/RESULTS.md` (round 5) pins the single-chip compressed round
at ~29.6 ms, dominated by two primitive floors inside the publish +
delivery phase: exact ``lax.top_k`` over ``[N, K]`` at **6.2 ms** and
the board row-gather at **4.1 ms**.  Neither is compute-bound — both
are "stream the cache through the core and do trivial per-element
work", which is exactly the shape operator fusion wins (the
GNN-architecture survey's scatter/gather argument, PAPERS.md): XLA
spells the publish selection as top_k + cumsum + a chain of elementwise
passes, each a full HBM round trip over ``[N, K]``, then materializes
the board and re-reads it for the gather.

The kernels here collapse that:

* :func:`publish_board_pallas` — ONE pass: each ``[T, K]`` cache tile
  is streamed through VMEM once and the entire selection pipeline runs
  on it in-registers — eligibility mask, the budget-th-largest
  threshold (a 31-step bitwise max search replacing ``top_k``; see
  ``_publish_block``), the rotated prefix-sum tie rank (the cumsum
  lowered onto the MXU as a triangular-ones matmul), the admit mask,
  and the transmit-count bump.  The intermediate tensors XLA would
  bounce through HBM never leave VMEM.
* :func:`fused_publish_gather_pallas` — the same pass ALSO serves the
  delivery gather: for each receiver row the kernel DMAs its sampled
  peers' cache rows from HBM (a depth-``_DMA_RING`` ring of async
  copies overlapped with compute), recomputes their publish selection
  in VMEM, applies the board staleness gate, and emits the pulled
  boards ``[N, F, K]`` directly — the ``[N, K]`` message board is
  never materialized in HBM at all on the single-chip path.

Bit-identity contract: both kernels are **bit-identical** to the XLA
reference (:func:`publish_board_xla` — the exact op sequence the model
shipped through round 5), enforced by tests/test_kernels.py across
ragged shapes, tie-heavy bursts, all-ineligible rows and tombstone-only
rows, plus a lockstep ``CompressedSim`` parity run.  On CPU the kernels
run under ``pallas_call(interpret=True)`` so tier-1 exercises the same
kernel logic the TPU compiles.

Why the threshold search is exact: the XLA path's threshold is
``top_k(priority, B)[:, -1]`` — the B-th largest *with multiplicity*.
That value is the maximum ``t`` with ``count(priority >= t) >= B``
(monotone in ``t``), so a greedy bitwise maximization over the 31
value bits finds exactly it: 31 compare+row-sum passes over a VMEM
tile instead of a full sort.  All arithmetic is int32; there is no
tolerance anywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sidecar_tpu.ops.gossip import PHASE_MULT
from sidecar_tpu.ops.merge import staleness_mask

# Depth of the peer-row DMA ring in the fused gather (outstanding async
# copies per buffer); sized so the fetch of row i+_DMA_RING overlaps the
# publish recompute of row i without exhausting DMA slots.
_DMA_RING = 16


def _tile_rows(n: int, k: int) -> int:
    """Row-tile height: scale with 1/K so the working set (own tile +
    gathered peer rows + outputs + the [K, K] prefix matrix) stays a
    few MB of VMEM at any cache width."""
    return max(1, min(n, max(8, 65536 // max(k, 1))))


# -- the shared selection math (one definition, two backends) ---------------

def eligible_lines(cache_slot, cache_sent, limit: int):
    """Publish eligibility of a cache line: occupied AND transmits
    left (memberlist TransmitLimited semantics).  ONE definition — the
    sparse sender frontier (models/compressed.py ``_sparse_frontiers``,
    parallel/sharded_compressed.py) must be exactly this predicate or
    an eligible row could be silently excluded from the frontier with
    no overflow signal, breaking dense==sparse bit-identity."""
    return (cache_slot >= 0) & (cache_sent.astype(jnp.int32) < limit)


def _publish_block(cv, cs, se, gids, *, budget: int, limit: int,
                   fanout: int, k: int):
    """Publish selection on a ``[T, K]`` block — the in-VMEM recast of
    the XLA reference in :func:`publish_board_xla`, bit-identical by
    construction (integer arithmetic only).

    ``gids`` are the rows' GLOBAL node ids (the tie-rotation seed).
    Returns (bval, bslot, sent) for the block.
    """
    t = cv.shape[0]
    eligible = eligible_lines(cs, se, limit)
    priority = jnp.where(eligible, cv, 0)

    # Threshold: budget-th largest with multiplicity, via bitwise max
    # search (see module docstring).  Unrolled 31 compare+sum passes —
    # VPU work on a tile already resident in VMEM.
    thresh = jnp.zeros((t, 1), jnp.int32)
    for b in range(30, -1, -1):
        cand = thresh | (1 << b)
        cnt = jnp.sum((priority >= cand).astype(jnp.int32), axis=1,
                      keepdims=True)
        thresh = jnp.where(cnt >= budget, cand, thresh)

    above = priority > thresh
    tie = (priority == thresh) & (priority > 0)
    n_above = jnp.sum(above.astype(jnp.int32), axis=1, keepdims=True)

    rot = (gids.astype(jnp.uint32) * jnp.uint32(PHASE_MULT)
           & jnp.uint32(k - 1)).astype(jnp.int32)[:, None]
    cols = lax.broadcasted_iota(jnp.int32, (t, k), 1)
    # Inclusive prefix sum of the tie mask as a triangular-ones matmul:
    # counts are <= K <= 2^24, exact in f32 on the MXU.
    tri = (lax.broadcasted_iota(jnp.int32, (k, k), 0)
           <= lax.broadcasted_iota(jnp.int32, (k, k), 1)
           ).astype(jnp.float32)
    s = jnp.dot(tie.astype(jnp.float32), tri,
                preferred_element_type=jnp.float32).astype(jnp.int32)
    total = jnp.sum(tie.astype(jnp.int32), axis=1, keepdims=True)
    # base = s[rot-1] (0 when rot == 0), spelled as a masked sum so no
    # per-row lane gather is needed.
    base = jnp.sum((tie & (cols < rot)).astype(jnp.int32), axis=1,
                   keepdims=True)
    rank = jnp.where(cols >= rot, s - base, s + total - base)
    admit = tie & (rank <= budget - n_above)

    selected = above | admit
    bval = jnp.where(selected, cv, 0)
    bslot = jnp.where(selected, cs, -1)
    sent = jnp.minimum(
        se.astype(jnp.int32) + jnp.where(selected, fanout, 0),
        limit).astype(jnp.int8)
    return bval, bslot, sent


def publish_board_xla(cache_val, cache_slot, cache_sent, *, budget: int,
                      limit: int, fanout: int, cache_lines: int,
                      row_offset=0, row_ids=None):
    """The XLA reference path — the exact op sequence
    ``CompressedSim._publish`` shipped through round 5 (top_k threshold
    + rotated prefix-sum tie admission; see models/compressed.py for
    the protocol rationale).  The Pallas kernels are bit-identical to
    this function.

    ``row_ids`` overrides the contiguous ``row_offset + i`` global ids
    with explicit per-row ones — the sparse-frontier path publishes a
    compacted, non-contiguous row set and must reproduce each row's
    dense tie rotation exactly (ops/sparse.py; the compacted path is
    XLA-only, riding this reference's bit-identity contract).
    """
    k = cache_lines
    eligible = eligible_lines(cache_slot, cache_sent, limit)
    priority = jnp.where(eligible, cache_val, 0)
    budget = min(budget, k)
    top = lax.top_k(priority, budget)[0]
    thresh = top[:, -1:]
    above = priority > thresh
    tie = (priority == thresh) & (priority > 0)
    n_above = jnp.sum(above, axis=1, keepdims=True)

    n = priority.shape[0]
    rows = (row_ids if row_ids is not None
            else jnp.arange(n, dtype=jnp.int32) + row_offset)
    rot = (rows.astype(jnp.uint32) * jnp.uint32(PHASE_MULT)
           & jnp.uint32(k - 1)).astype(jnp.int32)
    s = jnp.cumsum(tie.astype(jnp.int32), axis=1)
    total = s[:, -1:]
    base = jnp.where(
        rot[:, None] > 0,
        jnp.take_along_axis(s, jnp.maximum(rot[:, None] - 1, 0), axis=1),
        0)
    cols = jnp.arange(k, dtype=jnp.int32)[None, :]
    rank = jnp.where(cols >= rot[:, None], s - base, s + total - base)
    admit = tie & (rank <= budget - n_above)

    selected = above | admit
    bval = jnp.where(selected, cache_val, 0)
    bslot = jnp.where(selected, cache_slot, -1)
    sent = jnp.minimum(
        cache_sent.astype(jnp.int32) + jnp.where(selected, fanout, 0),
        limit).astype(jnp.int8)
    return bval, bslot, sent


# -- board-only kernel ------------------------------------------------------

def publish_board_pallas(cache_val, cache_slot, cache_sent, *, budget: int,
                         limit: int, fanout: int, cache_lines: int,
                         row_offset=0, interpret: bool = True):
    """Publish selection as one fused VMEM pass per ``[T, K]`` tile.

    Drop-in for :func:`publish_board_xla`; ``row_offset`` may be traced
    (the sharded twin passes its shard base inside ``shard_map``), so it
    rides in as an SMEM scalar.
    """
    n, k = cache_val.shape
    if k != cache_lines:
        raise ValueError(f"cache width {k} != cache_lines {cache_lines}")
    budget = min(budget, k)
    tile = _tile_rows(n, k)
    block = functools.partial(_publish_block, budget=budget, limit=limit,
                              fanout=fanout, k=k)

    def kernel(off_s, cv_t, cs_t, se_t, bv_o, bs_o, se_o):
        r0 = pl.program_id(0) * tile + off_s[0]
        gids = r0 + lax.broadcasted_iota(jnp.int32, (tile, 1), 0)[:, 0]
        bv, bs, se = block(cv_t[:], cs_t[:], se_t[:], gids)
        bv_o[:] = bv
        bs_o[:] = bs
        se_o[:] = se

    row_block = pl.BlockSpec((tile, k), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, tile),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_block, row_block, row_block,
        ],
        out_specs=[row_block, row_block, row_block],
        out_shape=[
            jax.ShapeDtypeStruct((n, k), jnp.int32),
            jax.ShapeDtypeStruct((n, k), jnp.int32),
            jax.ShapeDtypeStruct((n, k), jnp.int8),
        ],
        interpret=interpret,
        name="sidecar_publish_board",
    )(jnp.asarray(row_offset, jnp.int32).reshape(1),
      cache_val, cache_slot, cache_sent)


# -- sharded board row-gather ------------------------------------------------
#
# The multi-chip twin's delivery gather (docs/sharding.md): each shard
# holds a board BLOCK (its own rows, or — in the all_gather mode — the
# whole gathered board) and must serve the rows its nodes sampled.  The
# kernel DMAs the in-range rows from the block (``base`` is the block's
# global row offset, traced — the shard passes ``r0`` inside shard_map);
# rows outside the block are emitted as (0, -1) — the merge no-op — so
# the caller can fold them from the exchanged buffer (the a2a response /
# the ring hops) instead.  Bit-identical to :func:`board_row_gather_xla`.


def board_row_gather_xla(bval, bslot, src, base=0):
    """XLA reference: ``pv[r, f] = bval[src[r, f] - base]`` where the
    row is in the block, else ``(0, -1)``.  With ``base=0`` and a full
    board this is exactly the round-5 delivery gather
    (``bval[src]``/``bslot[src]`` of ``_pull_merge``)."""
    rows_total = bval.shape[0]
    rel = src - base
    in_block = (rel >= 0) & (rel < rows_total)
    rows = jnp.clip(rel, 0, rows_total - 1)
    pv = jnp.where(in_block[:, :, None], bval[rows], 0)
    ps = jnp.where(in_block[:, :, None], bslot[rows], -1)
    return pv, ps


def board_row_gather_pallas(bval, bslot, src, base=0, *,
                            interpret: bool = True):
    """Board row-gather as a depth-``_DMA_RING`` async-copy ring: the
    sampled block rows stream into VMEM while earlier rows are masked
    and stored — the sharded delivery path's half of the single-chip
    fused gather (no publish recompute: the block rows ARE board rows,
    already selected and staleness-filtered by their home shard).

    ``src`` holds GLOBAL peer ids; ``base`` (traced, SMEM) is the
    block's global row offset.  Out-of-block rows emit (0, -1).
    """
    n, f = src.shape
    rows_total, k = bval.shape
    tile = _tile_rows(n, k)
    rows = tile * f
    ring = min(_DMA_RING, rows)

    def kernel(base_s, src_s, src_v, bv_h, bs_h, pv_o, ps_o, gv, gs, sem):
        base_t = base_s[0]

        def peer_copies(i):
            # Clamp into the block: out-of-block rows still DMA a valid
            # row (their outputs are masked below), rows past N in a
            # ragged last tile carry garbage src values — both stay in
            # bounds.
            rel = jnp.clip(src_s[i // f, i % f] - base_t, 0,
                           rows_total - 1)
            return tuple(
                pltpu.make_async_copy(h.at[rel], g.at[i],
                                      sem.at[i % ring, w])
                for w, (h, g) in enumerate(((bv_h, gv), (bs_h, gs))))

        def fetch(i, _):
            @pl.when(i >= ring)
            def _():
                for c in peer_copies(i - ring):
                    c.wait()
            for c in peer_copies(i):
                c.start()
            return _

        lax.fori_loop(0, rows, fetch, None)

        def drain(i, _):
            for c in peer_copies(i):
                c.wait()
            return _

        lax.fori_loop(max(0, rows - ring), rows, drain, None)

        rel = src_v[:].reshape(rows) - base_t
        in_block = (rel >= 0) & (rel < rows_total)
        pv = jnp.where(in_block[:, None], gv[:], 0)
        ps = jnp.where(in_block[:, None], gs[:], -1)
        pv_o[:] = pv.reshape(tile, f, k)
        ps_o[:] = ps.reshape(tile, f, k)

    fan_block = pl.BlockSpec((tile, f, k), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    src_map = lambda i: (i, 0)  # noqa: E731 — shared by SMEM+VMEM views
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, tile),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            # src twice: SMEM for scalar DMA addressing, VMEM for the
            # vectorized in-block mask.
            pl.BlockSpec((tile, f), src_map, memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, f), src_map, memory_space=pltpu.VMEM),
            # The block stays addressable for the row DMAs.
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[fan_block, fan_block],
        out_shape=[
            jax.ShapeDtypeStruct((n, f, k), jnp.int32),
            jax.ShapeDtypeStruct((n, f, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, k), jnp.int32),
            pltpu.VMEM((rows, k), jnp.int32),
            pltpu.SemaphoreType.DMA((ring, 2)),
        ],
        interpret=interpret,
        name="sidecar_board_row_gather",
    )(jnp.asarray(base, jnp.int32).reshape(1), src, src, bval, bslot)


# -- fused publish + board row-gather ---------------------------------------

def fused_publish_gather_xla(cache_val, cache_slot, cache_sent, src, now,
                             *, stale_ticks: int, budget: int, limit: int,
                             fanout: int, cache_lines: int):
    """XLA spelling of the fused contract: publish, staleness-filter the
    board, gather the sampled rows.  Exactly the round-5 op sequence
    (``_publish`` + the board filter + ``bval[src]`` / ``bslot[src]``
    from ``_pull_merge``), packaged so both backends share one
    signature.  Returns ``(sent, pv, ps)``.
    """
    bval, bslot, sent = publish_board_xla(
        cache_val, cache_slot, cache_sent, budget=budget, limit=limit,
        fanout=fanout, cache_lines=cache_lines)
    bval = jnp.where(staleness_mask(bval, now, stale_ticks), 0, bval)
    return sent, bval[src], bslot[src]


def fused_publish_gather_pallas(cache_val, cache_slot, cache_sent, src,
                                now, *, stale_ticks: int, budget: int,
                                limit: int, fanout: int, cache_lines: int,
                                interpret: bool = True):
    """Publish + board row-gather in ONE kernel: the ``[N, K]`` board is
    never materialized in HBM.

    Per receiver tile the kernel (a) runs the fused publish pass on its
    own cache rows (emitting the transmit-count bump), and (b) streams
    its sampled peers' cache rows in through a depth-``_DMA_RING`` ring
    of async copies, recomputes their publish selection in VMEM, applies
    the board staleness gate, and writes the pulled boards
    ``pv/ps [N, F, K]`` that feed ``_merge_pulled`` directly.

    ``pv[r, f] == stale_filtered(board)[src[r, f]]`` and
    ``ps[r, f] == bslot[src[r, f]]`` bit-for-bit vs the XLA path; the
    recompute is sound because a board row is a pure function of its
    node's pre-round cache row.  Returns ``(sent, pv, ps)``.
    """
    n, k = cache_val.shape
    f = src.shape[1]
    if k != cache_lines:
        raise ValueError(f"cache width {k} != cache_lines {cache_lines}")
    budget = min(budget, k)
    tile = _tile_rows(n, k)
    rows = tile * f
    ring = min(_DMA_RING, rows)
    block = functools.partial(_publish_block, budget=budget, limit=limit,
                              fanout=fanout, k=k)

    def kernel(params_s, src_s, src_v, cv_t, cs_t, se_t,
               cv_h, cs_h, se_h, se_o, pv_o, ps_o, gv, gs, ge, sem):
        now_t = params_s[0]
        stale_t = params_s[1]
        r0 = pl.program_id(0) * tile
        gids = r0 + lax.broadcasted_iota(jnp.int32, (tile, 1), 0)[:, 0]

        def peer_copies(i):
            # Clamp: rows past N in a ragged last tile carry garbage
            # src values; their outputs are dropped by the block store,
            # but the DMA itself must stay in bounds.
            peer = jnp.clip(src_s[i // f, i % f], 0, n - 1)
            return tuple(
                pltpu.make_async_copy(h.at[peer], g.at[i],
                                      sem.at[i % ring, w])
                for w, (h, g) in enumerate(
                    ((cv_h, gv), (cs_h, gs), (se_h, ge))))

        def fetch(i, _):
            # Free the ring slot this copy reuses, then start it —
            # fetches run ahead of the publish compute below.
            @pl.when(i >= ring)
            def _():
                for c in peer_copies(i - ring):
                    c.wait()
            for c in peer_copies(i):
                c.start()
            return _

        lax.fori_loop(0, rows, fetch, None)

        # Own-tile publish overlaps the tail of the peer-row DMAs.
        se_o[:] = block(cv_t[:], cs_t[:], se_t[:], gids)[2]

        def drain(i, _):
            for c in peer_copies(i):
                c.wait()
            return _

        lax.fori_loop(max(0, rows - ring), rows, drain, None)

        peer_ids = src_v[:].reshape(rows)
        pbv, pbs, _ = block(gv[:], gs[:], ge[:], peer_ids)
        ts = pbv >> 3
        pbv = jnp.where((ts > 0) & (ts < now_t - stale_t), 0, pbv)
        pv_o[:] = pbv.reshape(tile, f, k)
        ps_o[:] = pbs.reshape(tile, f, k)

    row_block = pl.BlockSpec((tile, k), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    fan_block = pl.BlockSpec((tile, f, k), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    src_map = lambda i: (i, 0)  # noqa: E731 — shared by SMEM+VMEM views
    params = jnp.stack([jnp.asarray(now, jnp.int32),
                        jnp.asarray(stale_ticks, jnp.int32)])
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, tile),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            # src twice: SMEM for scalar DMA addressing, VMEM for the
            # vectorized tie-rotation seed of the recomputed boards.
            pl.BlockSpec((tile, f), src_map, memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, f), src_map, memory_space=pltpu.VMEM),
            row_block, row_block, row_block,
            # The full cache stays addressable for the peer-row DMAs.
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[row_block, fan_block, fan_block],
        out_shape=[
            jax.ShapeDtypeStruct((n, k), jnp.int8),
            jax.ShapeDtypeStruct((n, f, k), jnp.int32),
            jax.ShapeDtypeStruct((n, f, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, k), jnp.int32),
            pltpu.VMEM((rows, k), jnp.int32),
            pltpu.VMEM((rows, k), jnp.int8),
            pltpu.SemaphoreType.DMA((ring, 3)),
        ],
        interpret=interpret,
        name="sidecar_fused_publish_gather",
    )(params, src, src, cache_val, cache_slot, cache_sent,
      cache_val, cache_slot, cache_sent)
