"""Custom-kernel layer: runtime selection between the fused Pallas
publish/board kernels and the XLA reference path.

Selection contract (docs/kernels.md):

* ``SIDECAR_TPU_KERNELS=pallas`` — force the Pallas kernels.  On a
  non-TPU backend they run under ``pallas_call(interpret=True)`` — the
  same kernel logic the TPU compiles, executed by the Pallas
  interpreter — which is how tier-1 (CPU) exercises them.  On TPU, if
  Mosaic lowering of the probe kernel fails, the layer FALLS BACK to
  XLA instead of crashing the run.
* ``SIDECAR_TPU_KERNELS=xla`` — force the round-5 XLA op sequence.
* unset / ``auto`` — Pallas on TPU (with the same lowering-probe
  fallback), XLA elsewhere: CPU test runs keep the cheap native path
  unless a test opts in explicitly.

``SIDECAR_TPU_FUSED_GATHER=0`` additionally degrades the Pallas path to
publish-kernel + XLA row-gather (the gather half rides XLA's native
gather lowering) — the documented escape hatch if the in-kernel DMA
gather underperforms on some topology of real hardware.

Every resolution is recorded in the metrics registry: the counter
``kernels.path.<pallas|xla|xla_fallback>`` counts sims built on each
path, and the gauge ``kernels.pallas_active`` holds whether the most
recent resolution selected Pallas — the observability hook the bench
and round_phases reports read back.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

from sidecar_tpu import metrics
from sidecar_tpu.ops.kernels.publish_gather import (  # noqa: F401
    board_row_gather_pallas,
    board_row_gather_xla,
    eligible_lines,
    fused_publish_gather_pallas,
    fused_publish_gather_xla,
    publish_board_pallas,
    publish_board_xla,
)

ENV_VAR = "SIDECAR_TPU_KERNELS"
ENV_FUSED = "SIDECAR_TPU_FUSED_GATHER"

# Lowering-probe result, memoized per process: None = not yet probed.
_probe_ok: Optional[bool] = None


def _probe_lowering() -> bool:
    """Can Mosaic actually lower the publish kernel on this backend?
    Compiles a tiny non-interpret instance once per process; any
    failure (old jaxlib, unsupported target, missing Mosaic) selects
    the XLA fallback rather than crashing the first real dispatch."""
    global _probe_ok
    if _probe_ok is None:
        try:
            cv = jnp.zeros((8, 128), jnp.int32)
            cs = jnp.full((8, 128), -1, jnp.int32)
            se = jnp.zeros((8, 128), jnp.int8)
            jax.jit(lambda a, b, c: publish_board_pallas(
                a, b, c, budget=4, limit=4, fanout=2, cache_lines=128,
                interpret=False)).lower(cv, cs, se).compile()
            _probe_ok = True
        except Exception:  # noqa: BLE001 — any lowering failure ⇒ fallback
            _probe_ok = False
    return _probe_ok


def resolve_path(record: bool = True) -> tuple[str, bool]:
    """Resolve the active kernel path → ``(path, interpret)`` where
    ``path`` is ``"pallas"`` or ``"xla"`` and ``interpret`` says the
    Pallas kernels must run under the interpreter (non-TPU backend).

    Called at sim construction (trace-time decision — the choice is
    baked into the jitted round), so toggling the env var affects sims
    built afterwards, not already-compiled ones.
    """
    mode = os.environ.get(ENV_VAR, "auto").strip().lower() or "auto"
    if mode not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"{ENV_VAR}={mode!r}: expected 'pallas', 'xla' or 'auto'")
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu

    if mode == "xla":
        path = "xla"
    elif mode == "pallas":
        path = "pallas" if (interpret or _probe_lowering()) else "xla"
    else:  # auto: Pallas where it compiles natively, XLA elsewhere
        path = "pallas" if (on_tpu and _probe_lowering()) else "xla"

    if record:
        fellback = path == "xla" and mode != "xla" and on_tpu \
            and not _probe_lowering()
        metrics.incr(f"kernels.path.{'xla_fallback' if fellback else path}")
        metrics.set_gauge("kernels.pallas_active",
                          1.0 if path == "pallas" else 0.0)
    return path, interpret


def fused_gather_enabled() -> bool:
    """Whether the Pallas path uses the fully-fused in-kernel DMA gather
    (default) or the publish-kernel + XLA-gather degraded form."""
    return os.environ.get(ENV_FUSED, "1").strip() != "0"
