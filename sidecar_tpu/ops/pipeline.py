"""Software-pipelined round execution — mode plumbing.

Every model family historically advanced one global synchronous round
per ``lax.scan`` tick: publish/select, gather/deliver, fold/apply, the
board exchange, announce, and anti-entropy serialize back-to-back
inside each tick (PR 12's phase attribution shows it on device).
Pipelined gossiping ("The Algorithm of Pipelined Gossiping", PAPERS.md)
overlaps them: round i+1's publish — peer selection plus message
selection, computed from the PRE-fold state — is issued inside the same
scan tick that folds/applies round i, carried as a ``(state, inflight)``
scan carry.  The semantics are *honestly one round stale*: a message
selected for round i+1 reflects the sender's belief before round i's
deliveries landed, exactly the behavior of a real node that serializes
its outgoing packet while its inbox drains.  Convergence pays a bounded
staleness tax (the bench ``pipeline`` block pins the rounds-to-ε ratio
≤ 1.10); device time wins because the publish/gather phase of the next
round overlaps the fold/apply + exchange of the current one
(``pipeline.overlap_ms``, docs/pipeline.md).

This module holds the mode resolution shared by every model:

* ``SIDECAR_TPU_PIPELINE=auto|0|1`` (or the ``pipeline=`` driver
  argument), resolved ONCE at sim construction like
  ``SIDECAR_TPU_SPARSE``:

  - ``0``    — pipelined execution disabled; ``run*(...,
    pipeline=True)`` raises.  The pre-pipeline behavior.
  - ``1``    — drivers default to the pipelined step.
  - ``auto`` (default) — drivers default to the classic lockstep
    round.  UNLIKE sparse, auto never silently opts in: pipelining
    changes round semantics (one-round-stale publish), so it is only
    ever entered by an explicit ``pipeline=True`` / env ``1`` — never
    by a host-side arbiter.

* :func:`resolve_request` — per-dispatch resolution with the same
  ``supports_pipeline`` degrade/raise contract as
  ``ops/sparse.resolve_request`` (env default degrades on an
  unsupporting sim, an explicit ``True`` raises loudly).

The ``pipeline=off`` dispatch calls the UNCHANGED pre-PR jitted
drivers — bit-identity is structural, pinned per family in
tests/test_pipeline.py.
"""

from __future__ import annotations

import os
from typing import Optional

from sidecar_tpu import metrics

PIPELINE_ENV = "SIDECAR_TPU_PIPELINE"
PIPELINE_MODES = ("auto", "0", "1")


def resolve_pipeline(explicit: Optional[str] = None, *,
                     record: bool = True) -> str:
    """Resolve the pipelined-execution mode: an explicit constructor
    argument wins, else ``SIDECAR_TPU_PIPELINE``, else ``auto``.

    Returns one of ``"auto" | "0" | "1"``.  Resolved at sim
    construction (the ``SIDECAR_TPU_KERNELS`` contract: toggling the
    env var affects sims built afterwards)."""
    mode = explicit
    if mode is None:
        mode = os.environ.get(PIPELINE_ENV, "auto").strip().lower() \
            or "auto"
    mode = {"on": "1", "off": "0"}.get(mode, mode)
    if mode not in PIPELINE_MODES:
        raise ValueError(
            f"pipeline mode must be one of {PIPELINE_MODES}, got "
            f"{mode!r} (explicit argument or {PIPELINE_ENV})")
    if record:
        metrics.incr(f"pipeline.mode.{mode}")
    return mode


def resolve_request(mode: str, pipeline,
                    supports_pipeline: bool = True) -> bool:
    """Per-dispatch pipeline resolution, shared by every sim family.

    ``pipeline=None`` follows the construction-time ``mode`` — ``auto``
    means OFF (pipelining changes semantics; it is never a silent
    default) and an env-forced ``"1"`` DEGRADES to lockstep on a sim
    that doesn't implement the path.  An explicit ``True`` raises when
    the mode is ``"0"`` or the sim can't honor it."""
    if pipeline is None:
        pipeline = mode == "1"
        if pipeline and not supports_pipeline:
            return False        # env default degrades, never breaks
    if pipeline and (mode == "0" or not supports_pipeline):
        raise ValueError(
            "pipelined execution is disabled or unsupported on this sim "
            f"(mode={mode!r}, supports_pipeline={supports_pipeline}; "
            f"see {PIPELINE_ENV} / docs/pipeline.md)")
    return bool(pipeline)
