"""Service status codes and the packed (timestamp, status) representation.

Status codes mirror the reference enum (service/service.go:17-23):
ALIVE, TOMBSTONE, UNHEALTHY, UNKNOWN, DRAINING.

The simulator's unit of knowledge — "what does node *n* currently believe
about service *m*" — is a single int32 **packed key**::

    packed = (ts << STATUS_BITS) | status

where ``ts`` is a logical-tick timestamp (the analog of the reference's
nanosecond ``Service.Updated`` wall clock, service/service.go:39) and
``status`` occupies the low 3 bits.  ``ts == 0`` is the *unknown* sentinel:
a cell with ``packed < (1 << STATUS_BITS)`` means the node has never heard
of the service (the reference models this as a missing map key,
catalog/services_state.go:317).

Why packed?  The merge rule is "accept iff strictly newer timestamp"
(``Service.Invalidates``, service/service.go:64-66).  With timestamps in
the high bits, that rule becomes integer ``max`` — so delivering a batch of
gossip messages to their targets is one ``scatter-max``, which XLA lowers
to an efficient combiner on TPU, and the per-cell status rides along for
free.  Ties (equal ts) resolve toward the higher status code; the simulator
gives every announced record version a distinct tick so ties only occur
between copies of the *same* version, where the resolution is either
harmless (identical payload) or actively correct (a DRAINING-stickied copy
beats the plain ALIVE copy, matching catalog/services_state.go:329-331).

Using int32 logical ticks instead of int64 nanoseconds is a deliberate
TPU-first choice: int64 is emulated on TPU and would halve scatter
throughput.  Wall-clock protocol constants (80 s alive lifespan, 3 h
tombstone retention, ...) are expressed in ticks via
``models.timecfg.TimeConfig``.
"""

from __future__ import annotations

import jax.numpy as jnp

# Mirror of service/service.go:17-23.
ALIVE = 0
TOMBSTONE = 1
UNHEALTHY = 2
UNKNOWN = 3
DRAINING = 4
# SUSPECT is a simulator-side extension occupying a spare code of the
# 3-bit status field (the reference enum stops at DRAINING): a record
# whose refresh window lapsed sits in SWIM-style quarantine for a grace
# window before it may be tombstoned (ops/suspicion.py, docs/chaos.md).
# The code is deliberately ABOVE every reference status: suspicion is
# re-packed at the record's ORIGINAL timestamp, so under the max-merge
# it wins ties against same-version ALIVE/DRAINING copies (suspicion
# gossips for free through the existing scatter-max) while ANY strictly
# newer ALIVE record — an owner refresh — refutes it, also for free.
SUSPECT = 5

STATUS_BITS = 3
STATUS_MASK = (1 << STATUS_BITS) - 1

# Highest representable tick in a non-negative int32 packed key.
MAX_TICK = (1 << (31 - STATUS_BITS)) - 1  # 268_435_455

_STATUS_NAMES = {
    ALIVE: "Alive",
    TOMBSTONE: "Tombstone",
    UNHEALTHY: "Unhealthy",
    UNKNOWN: "Unknown",
    DRAINING: "Draining",
    SUSPECT: "Suspect",
}


def status_string(status: int) -> str:
    """Human name for a status code (service/service.go:168-181)."""
    return _STATUS_NAMES.get(int(status), "Tombstone")


def pack(ts, status):
    """Pack (logical tick, status) into an int32 key. ts=0 means unknown."""
    ts = jnp.asarray(ts, jnp.int32)
    status = jnp.asarray(status, jnp.int32)
    return (ts << STATUS_BITS) | status


def unpack_ts(packed):
    """Logical tick of a packed key (0 = unknown sentinel)."""
    return jnp.asarray(packed, jnp.int32) >> STATUS_BITS


def unpack_status(packed):
    """Status code of a packed key (meaningless when ts == 0)."""
    return jnp.asarray(packed, jnp.int32) & STATUS_MASK


def is_known(packed):
    """True where the cell holds a real record (ts > 0)."""
    return unpack_ts(packed) > 0
