"""The gossip-round kernels: peer sampling, message selection, scatter
delivery, and anti-entropy push-pull.

This is the TPU recast of the reference's broadcast loop:

* Peer selection — memberlist gossips each interval to randomly-selected
  members (GossipNodes; configured main.go:239-274).  Here:
  :func:`sample_peers` draws ``fanout`` targets per node, uniformly from
  the full cluster (complete topology) or from a padded neighbor list.
* Message selection — the reference drains a broadcast queue and packs
  messages first-fit into one ~1398 B UDP packet (``GetBroadcasts`` +
  ``packPacket``, services_delegate.go:85-144,182-223), so each round
  carries a bounded number of the *freshest* records.  Here:
  :func:`select_messages` takes the top-``budget`` packed keys per node
  among *eligible* records — those whose int8 transmit count ``sent`` is
  below the TransmitLimited limit (the vectorized broadcast queue; see
  below).  Records a node just accepted have a zero count and the newest
  timestamps, so epidemic relay (``retransmit``,
  services_state.go:342-345,377-392) emerges from the same top-k without
  explicit queues.
* Delivery — ONE scatter-max over (target, service) cells — the batched
  ``AddServiceEntry`` merge — with DRAINING stickiness applied to the
  message values *before* the scatter (against the pre-round state), and
  ONE int8 scatter zeroing ``sent`` at accepted cells.  Scatters on the
  big state tensors dominate the round on TPU (each costs a full buffer
  rewrite), so the round's budget is one scatter per big tensor plus the
  small transmit-count bump; the announce path's updates are folded into
  the same scatters.

Eligibility bookkeeping (the ``sent`` tensor): memberlist's
TransmitLimited queue keeps a record until it has actually been
transmitted ``RetransmitMult × ⌈log10(n+1)⌉`` times, and acceptance of a
newer version re-enqueues it at count zero.  The count-based form is
essential under backlog: when a node holds more fresh records than
``budget`` slots per round, records WAIT in the queue rather than
expiring — a time-window approximation silently drops them, which
stalls recovery in split-heal scenarios where thousands of records
funnel through the partition boundary.  Ties in the freshest-first
top-k saturate their counts after a few rounds and rotate out, so
backlogged records drain in index waves.

* Anti-entropy — every PushPullInterval (20 s) each memberlist node does a
  full two-way state exchange with one random peer
  (services_delegate.go:146-167, main.go:252-256).  Here:
  :func:`push_pull` gathers the partner's whole row (pull) and row-scatters
  ours onto the partner (push), both through the LWW max-merge.

Message loss is first-class fault injection: ``drop_prob`` zeroes a
Bernoulli subset of messages pre-scatter (a zero packed key is a merge
no-op), modeling UDP loss — which the reference's 5×/10× announce repeats
(services_state.go:29,28) exist to beat.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from sidecar_tpu.ops.merge import (
    merge_packed,
    staleness_mask,
    sticky_adjust,
)


def sample_peers(key, n, fanout, *, nbrs=None, deg=None, node_alive=None,
                 cut_mask=None):
    """Sample ``fanout`` gossip targets per node.

    Returns dst[int32 N, fanout].  Dead senders and cut edges resolve to
    the sender's own index (a self-send is a merge no-op).

    nbrs/deg: padded neighbor list (see ops/topology.py); None = complete
    graph, sampled without self via the shift trick.
    cut_mask: bool[N, K] marking partitioned-away edges.
    """
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    if nbrs is None:
        if cut_mask is not None:
            raise ValueError(
                "cut_mask requires an explicit neighbor-list topology; a "
                "complete graph has no edge structure to cut — build the "
                "cluster on a mesh/ring/ER/BA topology to model partitions"
            )
        r = jax.random.randint(key, (n, fanout), 0, n - 1, dtype=jnp.int32)
        dst = r + (r >= self_idx).astype(jnp.int32)
    else:
        slot = jax.random.randint(
            key, (n, fanout), 0, jnp.maximum(deg, 1)[:, None], dtype=jnp.int32
        )
        dst = jnp.take_along_axis(nbrs, slot, axis=1)
        if cut_mask is not None:
            cut = jnp.take_along_axis(cut_mask, slot, axis=1)
            dst = jnp.where(cut, self_idx, dst)
    if node_alive is not None:
        dst = jnp.where(node_alive[:, None], dst, self_idx)
    return dst


def eligible_mask(sent, limit):
    """True where a record still has transmissions left
    (TransmitLimited; see the module docstring)."""
    return sent.astype(jnp.int32) < limit


def select_messages(known, sent, budget, limit):
    """Top-``budget`` freshest *eligible* records per node.

    The reference's broadcast queue (``GetBroadcasts`` draining
    ``state.Broadcasts`` + pending leftovers into a ~1398 B packet,
    services_delegate.go:85-144) holds only records with transmissions
    remaining (count < limit; see module docstring).  Eligible records
    are offered freshest-first (packed keys sort by timestamp), up to
    ``budget`` per round.

    Returns (svc_idx[N, B], msg[N, B]) — ``msg`` is 0 (merge no-op) in
    slots where a node has fewer than ``budget`` eligible records.
    """
    priority = jnp.where(eligible_mask(sent, limit), known, 0)
    n, m = priority.shape
    budget = min(budget, m)  # tiny catalogs: can't offer more than exists

    if m <= 4 * 1024:
        msg, svc_idx = lax.top_k(priority, budget)
        return svc_idx.astype(jnp.int32), msg

    # Two-stage exact top-k for wide rows: a flat top_k over M dominates
    # the whole round on TPU, so split the row into G groups, rank groups
    # by their max (one cheap bandwidth-bound pass), gather the top
    # ``budget`` groups, and run the real top_k over that small slice.
    # Any true top-``budget`` element has at most budget-1 elements above
    # it, hence at most budget-1 groups with a strictly larger max, so its
    # group is always among the gathered ones (ties resolve to an
    # equal-valued — i.e. identical — record).
    sub = max(8, math.isqrt(m // budget) + 1)
    g = -(-m // sub)  # ceil
    pad = g * sub - m
    if pad:
        priority = jnp.pad(priority, ((0, 0), (0, pad)))
    pr = priority.reshape(n, g, sub)
    gmax = jnp.max(pr, axis=2)
    _, top_g = lax.top_k(gmax, budget)                         # [N, budget]
    cand = jnp.take_along_axis(pr, top_g[:, :, None], axis=1)  # [N, budget, sub]
    msg, pos = lax.top_k(cand.reshape(n, budget * sub), budget)
    gsel = pos // sub
    off = pos % sub
    svc_idx = jnp.take_along_axis(top_g, gsel, axis=1) * sub + off
    # Padded slots (priority 0 — merge no-ops) must not alias a real
    # column: clamping them to m-1 would let a padded .set land on the
    # same cell as a genuine selection of column m-1 (duplicate scatter
    # indices resolve nondeterministically), silently losing that cell's
    # transmit-count bump.  Map them PAST the row end instead — scatters
    # drop them (mode="drop") and gathers clamp to a value the 0 msg
    # never beats.  Genuine selections (msg > 0) always index < m.
    svc_idx = jnp.where(msg > 0, svc_idx, m)
    return svc_idx.astype(jnp.int32), msg


def prepare_deliveries(known, dst, svc_idx, msg, *, now_tick, stale_ticks,
                       node_alive=None, drop_prob=0.0, drop_key=None):
    """Expand each sender's message batch into flat (row, col, val) update
    triples with all merge semantics pre-applied.

    Each sender transmits its ``B`` selected records to each of its ``F``
    targets — the batched equivalent of one ``AddServiceEntry`` per
    received gossip message (services_delegate.go:72-83 →
    services_state.go:293-347):

    * staleness gate (services_state.go:302-308) — stale vals become 0;
    * dead senders transmit nothing, dead receivers accept nothing;
    * ``drop_prob`` models UDP loss;
    * DRAINING stickiness (services_state.go:329-331) — where a delivery
      would advance a cell DRAINING→ALIVE, the delivered value itself is
      rewritten to DRAINING at the new timestamp, evaluated against the
      pre-round state.  (The reference applies messages sequentially, so
      same-batch races are order-dependent there; this kernel resolves
      them one consistent way — max over sticky-adjusted values.)

    Returns (rows, cols, vals, advanced): int32 [N·F·B] flat triples plus
    the bool mask of entries that strictly advance their target cell
    (exactly the cells whose merge is an accept — used to stamp ``acc``).
    """
    n, fanout = dst.shape
    budget = svc_idx.shape[1]

    val = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
    tgt = jnp.broadcast_to(dst[:, :, None], (n, fanout, budget))
    svc = jnp.broadcast_to(svc_idx[:, None, :], (n, fanout, budget))

    val = jnp.where(staleness_mask(val, now_tick, stale_ticks), 0, val)

    if node_alive is not None:
        val = jnp.where(node_alive[:, None, None], val, 0)
        val = jnp.where(node_alive[tgt], val, 0)

    if drop_prob > 0.0:
        keep = jax.random.bernoulli(drop_key, 1.0 - drop_prob, val.shape)
        val = jnp.where(keep, val, 0)

    rows = tgt.reshape(-1)
    cols = svc.reshape(-1)
    val = val.reshape(-1)

    pre_vals = known[rows, cols]
    advanced = val > pre_vals
    val = sticky_adjust(val, pre_vals, advanced)
    return rows, cols, val, advanced


def apply_updates(known, sent, rows, cols, vals, advanced,
                  num_rows=None):
    """The two scatters of a gossip round: merge ``vals`` into ``known``
    (scatter-max) and zero ``sent`` at advanced cells (the re-enqueue of
    a freshly accepted/announced record version).

    Callers concatenate ALL of a round's updates (gossip deliveries +
    announce re-stamps) into one call — scatters on the big tensors cost
    a full buffer rewrite each on TPU, so one per tensor per round is the
    budget.  ``num_rows`` overrides the out-of-bounds row used to drop
    non-advancing entries (defaults to known's row count; sharded
    callers pass their local block height).
    """
    oob = known.shape[0] if num_rows is None else num_rows
    known = known.at[rows, cols].max(vals, mode="drop")
    reset_rows = jnp.where(advanced, rows, oob)
    sent = sent.at[reset_rows, cols].set(jnp.int8(0), mode="drop")
    return known, sent


def record_transmissions(sent, svc_idx, msg, fanout, limit):
    """Bump transmit counts for the records offered this round —
    ``fanout`` sends each — saturating at ``limit`` (TransmitLimited's
    per-message accounting)."""
    n = sent.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    bump = jnp.where(msg > 0, fanout, 0).astype(jnp.int32)
    current = sent[rows, svc_idx].astype(jnp.int32)
    capped = jnp.minimum(current + bump, limit).astype(sent.dtype)
    return sent.at[rows, svc_idx].set(capped, mode="drop")


def push_pull(known, partner, *, now_tick, stale_ticks, node_alive=None):
    """Anti-entropy: each node initiates a full two-way state exchange with
    one reachable peer (services_delegate.go:146-167).

    ``partner[n]`` is the peer node *n* initiates with — callers sample it
    with :func:`sample_peers` (fanout=1) so the exchange respects the
    topology, network partitions (a split cuts TCP push-pull exactly as it
    cuts UDP gossip), and dead nodes; ``partner[n] == n`` means no
    exchange (all merges below are self-identities).

    Pull: merge the partner's full row into ours (gather + elementwise
    LWW merge).  Push: row-scatter our state onto the partner with the
    same max combiner.
    """
    self_idx = jnp.arange(known.shape[0], dtype=jnp.int32)
    if node_alive is not None:
        partner = jnp.where(node_alive & node_alive[partner], partner, self_idx)

    # Pull: our row ← partner's row (stickiness inside merge_packed is
    # evaluated against the pre-exchange state).
    pulled = merge_packed(known, known[partner], now_tick, stale_ticks)

    # Push: partner's row ← our (pre-exchange) row.  Stickiness is
    # applied to the offered values against the RECEIVER's pre-exchange
    # row — both phases resolve vs the same snapshot, matching the
    # oracle's batch resolution.
    offered = jnp.where(staleness_mask(known, now_tick, stale_ticks), 0, known)
    pre_tgt = known[partner]
    offered = sticky_adjust(offered, pre_tgt, offered > pre_tgt)
    return pulled.at[partner].max(offered, mode="drop")
