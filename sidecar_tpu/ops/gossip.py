"""The gossip-round kernels: peer sampling, message selection, scatter
delivery, and anti-entropy push-pull.

This is the TPU recast of the reference's broadcast loop:

* Peer selection — memberlist gossips each interval to randomly-selected
  members (GossipNodes; configured main.go:239-274).  Here:
  :func:`sample_peers` draws ``fanout`` targets per node, uniformly from
  the full cluster (complete topology) or from a padded neighbor list.
* Message selection — the reference drains a broadcast queue and packs
  messages first-fit into one ~1398 B UDP packet (``GetBroadcasts`` +
  ``packPacket``, services_delegate.go:85-144,182-223), so each round
  carries a bounded number of the *freshest* records.  Here:
  :func:`select_messages` takes the top-``budget`` packed keys per node
  among *eligible* records — those whose int8 transmit count ``sent`` is
  below the TransmitLimited limit (the vectorized broadcast queue; see
  below).  Records a node just accepted have a zero count and the newest
  timestamps, so epidemic relay (``retransmit``,
  services_state.go:342-345,377-392) emerges from the same top-k without
  explicit queues.
* Delivery — ONE scatter-max over (target, service) cells — the batched
  ``AddServiceEntry`` merge — with DRAINING stickiness applied to the
  message values *before* the scatter (against the pre-round state), and
  ONE int8 scatter zeroing ``sent`` at accepted cells.  Scatters on the
  big state tensors dominate the round on TPU (each costs a full buffer
  rewrite), so the round's budget is one scatter per big tensor plus the
  small transmit-count bump; the announce path's updates are folded into
  the same scatters.

Eligibility bookkeeping (the ``sent`` tensor): memberlist's
TransmitLimited queue keeps a record until it has actually been
transmitted ``RetransmitMult × ⌈log10(n+1)⌉`` times, and acceptance of a
newer version re-enqueues it at count zero.  The count-based form is
essential under backlog: when a node holds more fresh records than
``budget`` slots per round, records WAIT in the queue rather than
expiring — a time-window approximation silently drops them, which
stalls recovery in split-heal scenarios where thousands of records
funnel through the partition boundary.  Ties in the freshest-first
top-k saturate their counts after a few rounds and rotate out, so
backlogged records drain in index waves.

* Anti-entropy — every PushPullInterval (20 s) each memberlist node does a
  full two-way state exchange with one random peer
  (services_delegate.go:146-167, main.go:252-256).  Here:
  :func:`push_pull` gathers the partner's whole row (pull) and row-scatters
  ours onto the partner (push), both through the LWW max-merge.

Message loss is first-class fault injection: ``drop_prob`` zeroes a
Bernoulli subset of messages pre-scatter (a zero packed key is a merge
no-op), modeling UDP loss — which the reference's 5×/10× announce repeats
(services_state.go:29,28) exist to beat.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from sidecar_tpu.ops.merge import (
    admit_gate,
    merge_packed,
    staleness_mask,
    sticky_adjust,
)
from sidecar_tpu.ops.status import unpack_ts
from sidecar_tpu.telemetry import cost

# Knuth's multiplicative constant — the slot-phase spreader for the
# refresh stagger (and the cache-line hash in models/compressed.py).
PHASE_MULT = 2654435761


def refresh_phase(slots, refresh_rounds: int):
    """Deterministic per-slot refresh phase, uniform over the whole
    refresh interval.  Hash-spread (multiplicative) so consecutive slots
    of one owner don't refresh in one burst."""
    u = jnp.asarray(slots).astype(jnp.uint32) * jnp.uint32(PHASE_MULT)
    return (u % jnp.uint32(refresh_rounds)).astype(jnp.int32)


def refresh_due(own, slots, round_idx, *, refresh_rounds: int,
                round_ticks: int, now):
    """True where an owner's record hits its periodic re-announce this
    round (``BroadcastServices``'s 1-minute refresh path).

    The reference re-stamps a service when its *own elapsed time* exceeds
    ALIVE_BROADCAST_INTERVAL, checked on a 1 s loop per service
    (services_state.go:547-549) — staggering follows each record's own
    history, never the node index.  The vectorized form keeps both
    properties:

    * a record is only due on its hash-spread phase round (one slot in
      ``refresh_rounds`` per round — uniform across the interval), and
    * only once ``now - ts`` clears a quarter of the interval, so a
      freshly minted/churned version is never double-announced, and a
      config that pins the interval far out (the cold-start studies,
      sim/scenarios.py) is genuinely quiet — zero re-stamps — for any
      run shorter than interval/4.

    Steady-state period is exactly ``refresh_rounds`` (phase rounds recur
    every interval and the elapsed guard is then always met); a record
    minted mid-interval waits between ¼ and 1¼ intervals — within the
    80 s ALIVE_LIFESPAN for the default 60 s interval, like the
    reference's interval..interval+1s jitter.

    ``own`` is the owner's packed record, ``slots`` its global slot ids.
    Callers AND the result with their own present/non-tombstone masks.
    """
    at_phase = (round_idx % refresh_rounds) == refresh_phase(
        slots, refresh_rounds)
    guard = (refresh_rounds * round_ticks) // 4
    elapsed = jnp.asarray(now, jnp.int32) - unpack_ts(own)
    return at_phase & (elapsed >= guard)


def cadence_gate(dst, round_idx, tick_period, tick_phase, self_idx=None):
    """Heterogeneous tick-cadence gate (docs/pipeline.md): a node ticks
    iff ``(round_idx + phase[i]) % period[i] == 0``; off this round, it
    resolves every sampled target to itself (the merge no-op self-send,
    like dead senders and cut edges).  ``tick_period``/``tick_phase``
    may be Python ints, traced scalars (the fleet data axis), or
    per-node ``[N]`` vectors (mixed-hardware fleets); scalars broadcast.
    Periods are clamped to ≥ 1, so a traced period of 1 is a value
    no-op (``x % 1 == 0`` gates nothing).  Gossip fan-out only;
    anti-entropy push-pull is never gated (it is the catch-up channel).
    The PRNG draw upstream happens unconditionally — cadence gates
    delivery, never the stream — and off nodes still select and charge
    ``sent`` for the round they sat out (the stagger-gate semantics of
    PR 13, inherited unchanged)."""
    n = dst.shape[0]
    if self_idx is None:
        self_idx = jnp.arange(n, dtype=jnp.int32)
    period = jnp.broadcast_to(
        jnp.asarray(tick_period, jnp.int32).reshape(-1), (n,))
    phase = jnp.broadcast_to(
        jnp.asarray(tick_phase, jnp.int32).reshape(-1), (n,))
    period = jnp.maximum(period, 1)
    off = ((round_idx + phase) % period) != 0
    return jnp.where(off[:, None], self_idx.reshape(-1, 1), dst)


def stagger_gate(dst, round_idx, stagger, stagger_period: int,
                 self_idx=None):
    """Round-stagger phase gate (pipelined gossiping, docs/topology.md):
    a node whose phase is off this round — ``(round_idx + stagger[i]) %
    period != 0`` — resolves every sampled target to itself (the merge
    no-op self-send, like dead senders and cut edges).  ``stagger=None``
    or period ≤ 1 returns ``dst`` untouched — the unstaggered program,
    bit for bit.  Gossip fan-out only; anti-entropy push-pull is never
    staggered (it is the catch-up channel).

    This is the uniform-period special case of :func:`cadence_gate`
    (``tick_period = stagger_period`` for every node, ``tick_phase =
    stagger``) and delegates to it."""
    if stagger is None or stagger_period <= 1:
        return dst
    return cadence_gate(dst, round_idx, stagger_period, stagger,
                        self_idx=self_idx)


def sample_peers(key, n, fanout, *, nbrs=None, deg=None, node_alive=None,
                 cut_mask=None, stagger=None, stagger_period=1,
                 round_idx=None, tick_period=None, tick_phase=None):
    """Sample ``fanout`` gossip targets per node.

    Returns dst[int32 N, fanout].  Dead senders and cut edges resolve to
    the sender's own index (a self-send is a merge no-op).

    nbrs/deg: padded neighbor list (see ops/topology.py); None = complete
    graph, sampled without self via the shift trick.
    cut_mask: bool[N, K] marking partitioned-away edges.
    stagger/stagger_period: per-node round-phase offsets
    (:func:`stagger_gate`; needs ``round_idx``).  The PRNG draw happens
    unconditionally — staggering gates delivery, never the stream — so
    staggered and unstaggered runs stay key-comparable.
    tick_period/tick_phase: heterogeneous per-node cadence
    (:func:`cadence_gate`; needs ``round_idx``) — scalar or per-node,
    static or traced; ``None`` compiles the pre-cadence program bit for
    bit.  Composes with stagger (a node sends only when both gates are
    on).
    """
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    if nbrs is None:
        if cut_mask is not None:
            raise ValueError(
                "cut_mask requires an explicit neighbor-list topology; a "
                "complete graph has no edge structure to cut — build the "
                "cluster on a mesh/ring/ER/BA topology to model partitions"
            )
        r = jax.random.randint(key, (n, fanout), 0, n - 1, dtype=jnp.int32)
        dst = r + (r >= self_idx).astype(jnp.int32)
    else:
        slot = jax.random.randint(
            key, (n, fanout), 0, jnp.maximum(deg, 1)[:, None], dtype=jnp.int32
        )
        dst = jnp.take_along_axis(nbrs, slot, axis=1)
        if cut_mask is not None:
            cut = jnp.take_along_axis(cut_mask, slot, axis=1)
            dst = jnp.where(cut, self_idx, dst)
    if node_alive is not None:
        dst = jnp.where(node_alive[:, None], dst, self_idx)
    if stagger is not None and stagger_period > 1:
        if round_idx is None:
            raise ValueError("stagger gating needs the current round_idx")
        dst = stagger_gate(dst, round_idx, stagger, stagger_period,
                           self_idx=self_idx[:, 0])
    if tick_period is not None:
        if round_idx is None:
            raise ValueError("cadence gating needs the current round_idx")
        dst = cadence_gate(dst, round_idx, tick_period,
                           0 if tick_phase is None else tick_phase,
                           self_idx=self_idx[:, 0])
    return dst


def eligible_mask(sent, limit):
    """True where a record still has transmissions left
    (TransmitLimited; see the module docstring)."""
    return sent.astype(jnp.int32) < limit


def eligible_records(known, sent, limit):
    """A record the dense select could actually publish: known
    (packed key > 0) AND transmissions left.  ONE definition — the
    sparse sender frontier (models/exact.py ``_step_sparse``,
    parallel/sharded.py) must be exactly the rows where
    :func:`select_messages` would offer anything (its ``priority``
    zeroes the same cells), or an eligible row could be silently
    excluded from the frontier with no overflow signal, breaking
    dense==sparse bit-identity (the kernels.eligible_lines contract,
    exact-family form)."""
    return eligible_mask(sent, limit) & (known > 0)


@cost.phased("publish")
def select_messages(known, sent, budget, limit, row_offset=0,
                    row_ids=None):
    """Top-``budget`` freshest *eligible* records per node.

    The reference's broadcast queue (``GetBroadcasts`` draining
    ``state.Broadcasts`` + pending leftovers into a ~1398 B packet,
    services_delegate.go:85-144) holds only records with transmissions
    remaining (count < limit; see module docstring).  Eligible records
    are offered freshest-first (packed keys sort by timestamp), up to
    ``budget`` per round.

    **Tie-break decorrelation**: ``top_k`` breaks value ties by column
    index, which on a tie-heavy state (a cold-start catalog where every
    record is ts=1) would make every node offer the SAME lowest-index
    records each round — a cluster-wide herd that drains the catalog in
    serialized index waves.  Real nodes have no such alignment: a
    memberlist broadcast queue is ordered by each node's own
    transmit/arrival history.  So ties are broken through a per-node
    rotation of the column (or group) order — node *i* starts its scan
    at a hashed offset — which spreads cold-start coverage across the
    cluster.  Values are untouched; only equal-value ordering varies by
    node.  ``row_offset`` is the global id of row 0 (sharded callers
    pass their block offset so rotation follows global node identity);
    ``row_ids`` overrides it with EXPLICIT per-row global ids — the
    sparse-frontier path selects over a compacted, non-contiguous row
    set and must reproduce each row's dense rotation exactly
    (ops/sparse.py).

    Returns (svc_idx[N, B], msg[N, B]) — ``msg`` is 0 (merge no-op) in
    slots where a node has fewer than ``budget`` eligible records, and
    ``svc_idx`` is ``m`` (one past the row end) there, so scatters drop
    padded entries and gathers read a value the 0 msg never beats.
    Clamping them to m-1 instead would alias a genuine selection of the
    last column (duplicate scatter indices resolve nondeterministically).
    """
    priority = jnp.where(eligible_mask(sent, limit), known, 0)
    n, m = priority.shape
    budget = min(budget, m)  # tiny catalogs: can't offer more than exists
    rows = (row_ids if row_ids is not None
            else jnp.arange(n, dtype=jnp.int32) + row_offset)
    rot = rows.astype(jnp.uint32) * jnp.uint32(PHASE_MULT)

    if m <= 4 * 1024:
        # Full per-row rotation (cheap at this width).
        r = (rot % jnp.uint32(m)).astype(jnp.int32)
        idx = (jnp.arange(m, dtype=jnp.int32)[None, :] + r[:, None]) % m
        pr = jnp.take_along_axis(priority, idx, axis=1)
        msg, pos = lax.top_k(pr, budget)
        svc_idx = (pos + r[:, None]) % m
        svc_idx = jnp.where(msg > 0, svc_idx, m)
        return svc_idx.astype(jnp.int32), msg

    # Two-stage exact top-k for wide rows: a flat top_k over M dominates
    # the whole round on TPU, so split the row into G groups, rank groups
    # by their max (one cheap bandwidth-bound pass), gather the top
    # ``budget`` groups, and run the real top_k over that small slice.
    # Any true top-``budget`` element has at most budget-1 elements above
    # it, hence at most budget-1 groups with a strictly larger max, so its
    # group is always among the gathered ones (ties resolve to an
    # equal-valued record).  Tie decorrelation here rotates the GROUP
    # order per node before the group ranking.  A per-row index gather
    # would be the obvious spelling, but arbitrary-index take_along_axis
    # on [N, G] measures ~30 ms on TPU v5e (gathers lower badly) — so the
    # per-row circular shift is done as log2(G) conditional jnp.rolls
    # (binary shift decomposition), each a fused bandwidth-bound pass
    # over [N, G] — ~1 ms total.
    # Group width: prefer an exact divisor of M near the ideal √(M/budget)
    # so the reshape needs NO padding — padding materializes a full copy
    # of the [N, M] priority tensor (a ~3 ms barrier at the bench shapes,
    # measured v5e) that XLA otherwise fuses away into the group-max.
    ideal = max(8, math.isqrt(m // budget) + 1)
    sub = next((d for d in range(ideal, min(4 * ideal, m) + 1) if m % d == 0),
               ideal)
    g = -(-m // sub)  # ceil
    pad = g * sub - m
    if pad:
        priority = jnp.pad(priority, ((0, 0), (0, pad)))
    pr = priority.reshape(n, g, sub)
    gmax = jnp.max(pr, axis=2)

    gp = 1 << (g - 1).bit_length()          # pad groups to a power of two
    gmax_p = jnp.pad(gmax, ((0, 0), (0, gp - g)))
    r = (rot & jnp.uint32(gp - 1)).astype(jnp.int32)           # [N]
    rot_view = gmax_p                       # rot_view[i, j] = gmax_p[i, (j+r_i) % gp]
    for k in range(gp.bit_length() - 1):
        bit = ((r >> k) & 1)[:, None] == 1
        rot_view = jnp.where(bit, jnp.roll(rot_view, -(1 << k), axis=1),
                             rot_view)
    gval, top_g_rot = lax.top_k(rot_view, budget)              # [N, budget]
    top_g = (top_g_rot + r[:, None]) % gp
    # A zero group-max never maps to a real record (priority 0 = merge
    # no-op), and under-full rows may rank padded groups (index ≥ g):
    # clamp those to group 0 and zero their candidate values so the
    # padding contract (msg == 0 ⇒ svc_idx == m) holds without aliasing.
    keep = gval > 0
    top_g = jnp.where(keep, top_g, 0)
    cand = jnp.take_along_axis(pr, top_g[:, :, None], axis=1)  # [N, budget, sub]
    cand = jnp.where(keep[:, :, None], cand, 0)
    msg, pos = lax.top_k(cand.reshape(n, budget * sub), budget)
    gsel = pos // sub
    off = pos % sub
    svc_idx = jnp.take_along_axis(top_g, gsel, axis=1) * sub + off
    svc_idx = jnp.where(msg > 0, svc_idx, m)
    return svc_idx.astype(jnp.int32), msg


def expand_deliveries(dst, svc_idx, msg, *, now_tick, stale_ticks,
                      node_alive=None, drop_prob=0.0, drop_key=None,
                      edge_keep=None, sender_alive=None,
                      record_keep=None, future_ticks=None,
                      tomb_budget=None, sender_own=None):
    """Expand each sender's message batch into RAW flat (row, col, val)
    update triples — every gate applied EXCEPT the pre-round stickiness
    resolution (:func:`finalize_deliveries`), which callers that defer
    delivery (the chaos delay rings) must re-evaluate at arrival time.

    Gates, in order: staleness (services_state.go:302-308), dead
    sender/receiver, ``drop_prob`` (uniform UDP loss), and ``edge_keep``
    — an optional bool [N, F] PACKET-level mask from the fault-injection
    layer (a dropped UDP packet loses all ``B`` records it carries,
    unlike the per-record ``drop_prob``; see sidecar_tpu/chaos/).

    ``sender_alive`` overrides the sender-liveness gate for compacted
    sender batches whose rows are NOT node ids (the sparse-frontier
    path — ``node_alive`` keeps gating receivers through ``dst``).
    ``record_keep`` is a pre-drawn bool ``[rows, F, B]`` keep mask
    replacing the in-call ``drop_prob`` draw: the sparse path draws ONE
    dense-shaped mask and slices its frontier rows, so the loss stream
    is mode-independent (pass ``drop_prob=0`` with it).

    ``now_tick`` may be a broadcastable per-RECEIVER tensor (shape
    ``[rows, F, 1]`` against the ``[rows, F, B]`` values) — the chaos
    family's per-node clocks evaluate staleness and the
    future-admission bound (``future_ticks``, ops/merge.future_mask;
    None = bound disabled, the pre-bound program) at each receiver's
    own clock.

    ``tomb_budget`` enables the per-origin suspicious-record budget
    (ops/merge.budget_mask — the Byzantine defense; None = disabled,
    the pre-budget program); ``sender_own`` is its bool ``[rows, B]``
    first-party mask (True where the sender owns the offered slot),
    broadcast across the fanout so each packet copy is budgeted at its
    receiver's clock."""
    n, fanout = dst.shape
    budget = svc_idx.shape[1]

    val = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
    tgt = jnp.broadcast_to(dst[:, :, None], (n, fanout, budget))
    svc = jnp.broadcast_to(svc_idx[:, None, :], (n, fanout, budget))

    own = sender_own[:, None, :] if sender_own is not None else None
    val = admit_gate(val, now_tick, stale_ticks, future_ticks,
                     tomb_budget, own)

    if node_alive is not None:
        snd = sender_alive if sender_alive is not None else node_alive
        val = jnp.where(snd[:, None, None], val, 0)
        val = jnp.where(node_alive[tgt], val, 0)

    if drop_prob > 0.0:
        keep = jax.random.bernoulli(drop_key, 1.0 - drop_prob, val.shape)
        val = jnp.where(keep, val, 0)

    if record_keep is not None:
        val = jnp.where(record_keep, val, 0)

    if edge_keep is not None:
        val = jnp.where(edge_keep[:, :, None], val, 0)

    return tgt.reshape(-1), svc.reshape(-1), val.reshape(-1)


def finalize_deliveries(known, rows, cols, vals):
    """Resolve a raw delivery batch against the CURRENT pre-round state:
    the strict-advance mask (exactly the cells whose merge is an accept)
    and DRAINING stickiness (services_state.go:329-331) — where a
    delivery would advance a cell DRAINING→ALIVE, the delivered value is
    rewritten to DRAINING at the new timestamp.  (The reference applies
    messages sequentially, so same-batch races are order-dependent
    there; this kernel resolves them one consistent way — max over
    sticky-adjusted values.)  Returns (vals, advanced)."""
    pre_vals = known[rows, cols]
    advanced = vals > pre_vals
    vals = sticky_adjust(vals, pre_vals, advanced)
    return vals, advanced


@cost.phased("gather")
def prepare_deliveries(known, dst, svc_idx, msg, *, now_tick, stale_ticks,
                       node_alive=None, drop_prob=0.0, drop_key=None,
                       edge_keep=None, sender_alive=None,
                       record_keep=None, future_ticks=None,
                       tomb_budget=None, sender_own=None):
    """Expand each sender's message batch into flat (row, col, val) update
    triples with all merge semantics pre-applied.

    Each sender transmits its ``B`` selected records to each of its ``F``
    targets — the batched equivalent of one ``AddServiceEntry`` per
    received gossip message (services_delegate.go:72-83 →
    services_state.go:293-347).  The gate pipeline lives in
    :func:`expand_deliveries`; the pre-round stickiness/advance
    resolution in :func:`finalize_deliveries` — split so the chaos
    layer can divert packets into delay buffers between the two.

    Returns (rows, cols, vals, advanced): int32 [N·F·B] flat triples plus
    the bool mask of entries that strictly advance their target cell
    (exactly the cells whose merge is an accept — used to stamp ``acc``).
    """
    rows, cols, vals = expand_deliveries(
        dst, svc_idx, msg, now_tick=now_tick, stale_ticks=stale_ticks,
        node_alive=node_alive, drop_prob=drop_prob, drop_key=drop_key,
        edge_keep=edge_keep, sender_alive=sender_alive,
        record_keep=record_keep, future_ticks=future_ticks,
        tomb_budget=tomb_budget, sender_own=sender_own)
    vals, advanced = finalize_deliveries(known, rows, cols, vals)
    return rows, cols, vals, advanced


@cost.phased("apply_scatter")
def apply_updates(known, sent, rows, cols, vals, advanced,
                  num_rows=None):
    """The two scatters of a gossip round: merge ``vals`` into ``known``
    (scatter-max) and zero ``sent`` at advanced cells (the re-enqueue of
    a freshly accepted/announced record version).

    Callers concatenate ALL of a round's updates (gossip deliveries +
    announce re-stamps) into one call — scatters on the big tensors cost
    a full buffer rewrite each on TPU, so one per tensor per round is the
    budget.  ``num_rows`` overrides the out-of-bounds row used to drop
    non-advancing entries (defaults to known's row count; sharded
    callers pass their local block height).
    """
    oob = known.shape[0] if num_rows is None else num_rows
    known = known.at[rows, cols].max(vals, mode="drop")
    reset_rows = jnp.where(advanced, rows, oob)
    sent = sent.at[reset_rows, cols].set(jnp.int8(0), mode="drop")
    return known, sent


@cost.phased("publish")
def record_transmissions(sent, svc_idx, msg, fanout, limit, row_ids=None):
    """Bump transmit counts for the records offered this round —
    ``fanout`` sends each (TransmitLimited's per-message accounting).

    A pure scatter-add, deliberately unclamped: eligibility tests
    ``sent < limit`` so values at/above ``limit`` behave identically,
    and a record stops being offered (hence bumped) the round it
    crosses the limit — counts are bounded by ``limit + fanout - 1``
    (≈ 19 at the 4,096-node defaults, far under int8).  Dropping the
    clamp removes the read-modify-write gather, leaving one scatter
    (the dense round's budget, see :func:`apply_updates`).

    ``row_ids`` maps a COMPACTED selection batch back to its true rows
    (``svc_idx``/``msg`` row *i* belongs to ``sent`` row
    ``row_ids[i]``; out-of-range ids drop) — the sparse-frontier path,
    where only the active sender rows selected."""
    del limit  # bounded by construction; kept for the call-site contract
    n = sent.shape[0]
    rows = (row_ids[:, None] if row_ids is not None
            else jnp.arange(n, dtype=jnp.int32)[:, None])
    bump = jnp.where(msg > 0, fanout, 0).astype(sent.dtype)
    return sent.at[rows, svc_idx].add(bump, mode="drop")


@cost.phased("exchange", tag="push_pull")
def push_pull(known, partner, *, now_tick, stale_ticks, node_alive=None,
              future_ticks=None, now_push=None, tomb_budget=None,
              owner=None):
    """Anti-entropy: each node initiates a full two-way state exchange with
    one reachable peer (services_delegate.go:146-167).

    ``partner[n]`` is the peer node *n* initiates with — callers sample it
    with :func:`sample_peers` (fanout=1) so the exchange respects the
    topology, network partitions (a split cuts TCP push-pull exactly as it
    cuts UDP gossip), and dead nodes; ``partner[n] == n`` means no
    exchange (all merges below are self-identities).

    Pull: merge the partner's full row into ours (gather + elementwise
    LWW merge).  Push: row-scatter our state onto the partner with the
    same max combiner.

    ``future_ticks`` enables the future-admission bound on both legs
    (None = disabled, the pre-bound program).  ``now_push`` overrides
    the receiver clock for the PUSH leg (the chaos family's per-node
    clocks: the pull leg admits at the initiator's clock ``now_tick``,
    the push leg at the partner's ``now_push`` — both may be
    broadcastable ``[N, 1]`` tensors; a self-exchange is a merge no-op
    under any clock, so remapped dead partners stay inert).

    ``tomb_budget`` enables the per-origin suspicious-record budget on
    both legs (ops/merge.budget_mask; None = disabled, the pre-budget
    program).  ``owner`` is the int32 ``[M]`` slot→owner table: each
    leg exempts the SENDING side's first-party slots (the pull leg's
    sender is the partner, the push leg's the initiator).  The budget
    counts per exchanged row — an anti-entropy exchange is one
    "packet" for budget purposes — so fleets that rely on push-pull to
    spread genuine mass tombstone events should size the budget for it.
    """
    self_idx = jnp.arange(known.shape[0], dtype=jnp.int32)
    if node_alive is not None:
        partner = jnp.where(node_alive & node_alive[partner], partner, self_idx)
    if now_push is None:
        now_push = now_tick
    own_pull = own_push = None
    if tomb_budget is not None and owner is not None:
        own_pull = owner[None, :] == partner[:, None]
        own_push = owner[None, :] == self_idx[:, None]

    # Pull: our row ← partner's row (stickiness inside merge_packed is
    # evaluated against the pre-exchange state).
    pulled = merge_packed(known, known[partner], now_tick, stale_ticks,
                          future_ticks, tomb_budget, own_pull)

    # Push: partner's row ← our (pre-exchange) row.  Stickiness is
    # applied to the offered values against the RECEIVER's pre-exchange
    # row — both phases resolve vs the same snapshot, matching the
    # oracle's batch resolution.
    offered = admit_gate(known, now_push, stale_ticks, future_ticks,
                         tomb_budget, own_push)
    pre_tgt = known[partner]
    offered = sticky_adjust(offered, pre_tgt, offered > pre_tgt)
    return pulled.at[partner].max(offered, mode="drop")
