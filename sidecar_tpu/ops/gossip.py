"""The gossip-round kernels: peer sampling, message selection, scatter
delivery, and anti-entropy push-pull.

This is the TPU recast of the reference's broadcast loop:

* Peer selection — memberlist gossips each interval to randomly-selected
  members (GossipNodes; configured main.go:239-274).  Here:
  :func:`sample_peers` draws ``fanout`` targets per node, uniformly from
  the full cluster (complete topology) or from a padded neighbor list.
* Message selection — the reference drains a broadcast queue and packs
  messages first-fit into one ~1398 B UDP packet (``GetBroadcasts`` +
  ``packPacket``, services_delegate.go:85-144,182-223), so each round
  carries a bounded number of the *freshest* records.  Here:
  :func:`select_messages` takes the top-``budget`` packed keys per node —
  freshest-first, because packed keys order by timestamp.  Records a node
  just accepted have the newest timestamps, so epidemic relay
  (``retransmit``, services_state.go:342-345,377-392) emerges from the
  same top-k without explicit queues.
* Delivery — one scatter-max over (target, service) cells, i.e. the
  batched ``AddServiceEntry`` merge, followed by the DRAINING-stickiness
  fixup (see ops/merge.py).
* Anti-entropy — every PushPullInterval (20 s) each memberlist node does a
  full two-way state exchange with one random peer
  (services_delegate.go:146-167, main.go:252-256).  Here:
  :func:`push_pull` gathers the partner's whole row (pull) and row-scatters
  ours onto the partner (push), both through the LWW max-merge.

Message loss is first-class fault injection: ``drop_prob`` zeroes a
Bernoulli subset of messages pre-scatter (a zero packed key is a merge
no-op), modeling UDP loss — which the reference's 5×/10× announce repeats
(services_state.go:29,28) exist to beat.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from sidecar_tpu.ops.merge import apply_stickiness, merge_packed, staleness_mask


def sample_peers(key, n, fanout, *, nbrs=None, deg=None, node_alive=None,
                 cut_mask=None):
    """Sample ``fanout`` gossip targets per node.

    Returns dst[int32 N, fanout].  Dead senders and cut edges resolve to
    the sender's own index (a self-send is a merge no-op).

    nbrs/deg: padded neighbor list (see ops/topology.py); None = complete
    graph, sampled without self via the shift trick.
    cut_mask: bool[N, K] marking partitioned-away edges.
    """
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    if nbrs is None:
        if cut_mask is not None:
            raise ValueError(
                "cut_mask requires an explicit neighbor-list topology; a "
                "complete graph has no edge structure to cut — build the "
                "cluster on a mesh/ring/ER/BA topology to model partitions"
            )
        r = jax.random.randint(key, (n, fanout), 0, n - 1, dtype=jnp.int32)
        dst = r + (r >= self_idx).astype(jnp.int32)
    else:
        slot = jax.random.randint(
            key, (n, fanout), 0, jnp.maximum(deg, 1)[:, None], dtype=jnp.int32
        )
        dst = jnp.take_along_axis(nbrs, slot, axis=1)
        if cut_mask is not None:
            cut = jnp.take_along_axis(cut_mask, slot, axis=1)
            dst = jnp.where(cut, self_idx, dst)
    if node_alive is not None:
        dst = jnp.where(node_alive[:, None], dst, self_idx)
    return dst


def select_messages(known, sent, budget, retransmit_limit):
    """Top-``budget`` freshest *eligible* records per node.

    The reference's broadcast queue (``GetBroadcasts`` draining
    ``state.Broadcasts`` + pending leftovers into a ~1398 B packet,
    services_delegate.go:85-144) holds only records that were recently
    announced or relayed, and memberlist's TransmitLimited queue drops a
    message after ``RetransmitMult × ⌈log10(n+1)⌉`` transmissions.  The
    vectorized equivalent: a record is *eligible* while its transmit
    count is below the retransmit limit; eligible records are offered
    freshest-first (packed keys sort by timestamp), up to ``budget`` per
    round.  Acceptance of a record resets its count to zero — that is the
    re-enqueue performed by ``retransmit`` (services_state.go:377-392),
    and it is what makes epidemic relay emerge.

    Returns (svc_idx[N, B], msg[N, B]) — ``msg`` is 0 (merge no-op) in
    slots where a node has fewer than ``budget`` eligible records.
    """
    eligible = sent < retransmit_limit
    priority = jnp.where(eligible, known, 0)
    msg, svc_idx = lax.top_k(priority, budget)
    return svc_idx.astype(jnp.int32), msg


def record_transmissions(sent, svc_idx, msg, fanout, retransmit_limit):
    """Bump transmit counts for the records actually offered this round
    (``fanout`` sends each), saturating at the retransmit limit."""
    n = sent.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    bump = jnp.where(msg > 0, fanout, 0).astype(sent.dtype)
    new = sent.at[rows, svc_idx].add(bump, mode="drop")
    return jnp.minimum(new, retransmit_limit)


def deliver(known, dst, svc_idx, msg, *, now_tick, stale_ticks,
            node_alive=None, drop_prob=0.0, drop_key=None):
    """Scatter-merge every sender's message batch into its targets.

    Each sender transmits its ``B`` selected records to each of its ``F``
    targets; delivery is a single scatter-max over (target, service) cells
    followed by the DRAINING-stickiness fixup — the batched equivalent of
    one ``AddServiceEntry`` per received gossip message
    (services_delegate.go:72-83 → services_state.go:293-347).

    Returns the merged ``known``.
    """
    n, fanout = dst.shape
    budget = svc_idx.shape[1]

    val = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
    tgt = jnp.broadcast_to(dst[:, :, None], (n, fanout, budget))
    svc = jnp.broadcast_to(svc_idx[:, None, :], (n, fanout, budget))

    # Staleness gate (services_state.go:302-308).
    val = jnp.where(staleness_mask(val, now_tick, stale_ticks), 0, val)

    if node_alive is not None:
        # Dead senders transmit nothing; dead receivers merge nothing.
        val = jnp.where(node_alive[:, None, None], val, 0)
        val = jnp.where(node_alive[tgt], val, 0)

    if drop_prob > 0.0:
        keep = jax.random.bernoulli(drop_key, 1.0 - drop_prob, val.shape)
        val = jnp.where(keep, val, 0)

    post = known.at[tgt, svc].max(val, mode="drop")
    return apply_stickiness(known, post)


def push_pull(known, partner, *, now_tick, stale_ticks, node_alive=None):
    """Anti-entropy: each node initiates a full two-way state exchange with
    one reachable peer (services_delegate.go:146-167).

    ``partner[n]`` is the peer node *n* initiates with — callers sample it
    with :func:`sample_peers` (fanout=1) so the exchange respects the
    topology, network partitions (a split cuts TCP push-pull exactly as it
    cuts UDP gossip), and dead nodes; ``partner[n] == n`` means no
    exchange (all merges below are self-identities).

    Pull: merge the partner's full row into ours (gather + elementwise
    LWW merge).  Push: row-scatter our state onto the partner with the
    same max combiner.
    """
    self_idx = jnp.arange(known.shape[0], dtype=jnp.int32)
    if node_alive is not None:
        partner = jnp.where(node_alive & node_alive[partner], partner, self_idx)

    # Pull: our row ← partner's row.
    pulled = merge_packed(known, known[partner], now_tick, stale_ticks)

    # Push: partner's row ← our (pre-exchange) row.
    offered = jnp.where(staleness_mask(known, now_tick, stale_ticks), 0, known)
    pushed = pulled.at[partner].max(offered, mode="drop")
    return apply_stickiness(pulled, pushed)
