"""TPU-side round tracing — the flight recorder's jit half.

Per-round convergence telemetry used to exist only as scattered scalar
counters synced after whole trajectories; this op turns it into a
stream: consecutive scan states are summarized ON DEVICE into a fixed
int32 record per round — frontier size, behind census, offers admitted,
analytic exchange bytes, sparse/dense mode, overflow flag, tombstone
count — and a bounded buffer of those records rides the scan carry.
It is the ``ops/delta.py`` pattern applied to telemetry: shape-static,
scan-compatible, and governed by the same static-cap contract —
``count`` stays exact, rounds past the capacity are truncated away and
``overflow`` reports it (the consumer's cue that the tail of the
trajectory is unrecorded), never silently lost.

Tracing is OPT-IN per dispatch (``run_with_trace``): the plain drivers
compile no trace ops at all, so ``trace=0`` leaves every existing
program untouched — the lockstep and ``check_jit_entrypoints``
contracts pin this.

Record semantics (shared by all four model families — exact,
compressed, and both sharded twins; the sharded records are computed at
the jit level over the global tensors, so GSPMD turns the reductions
into all-reduces and the stream is bit-identical to the single-chip
one, which tests/test_telemetry.py pins at d ∈ {1, 2, 4, 8}):

* ``round``     — the absolute round index the record describes.
* ``frontier``  — the PRE-round sender frontier: rows with any
  eligible record/line (``ops/gossip.eligible_records`` /
  ``ops/kernels.eligible_lines`` — the sparse path's own sender
  predicate, so the traced value is exactly the frontier the sparse
  arbiter reasons about).  Computed before the round's perturbation
  hook runs (the trace extractor sits OUTSIDE the step).
* ``behind``    — the POST-round behind census: #(alive node, slot)
  beliefs not at the global freshest version — the settled/behind
  split the north-star ε detector thresholds on.
* ``admitted``  — offers admitted: state cells the round actually
  changed (belief tensors diffed elementwise, the delta plane's
  "changed cells" without materializing their indices).
* ``exchange_bytes`` — analytic wire bytes of the round's offers: per
  node ``min(budget, eligible) × fanout`` records at
  :data:`RECORD_WIRE_BYTES` each (the reference's ~1398 B packet / 15
  records budget model; push and pull move the same offer volume).
* ``sparse``    — 1 when the round executed on the compacted sparse
  path (the step's stats vector), 0 on dense rounds/runs.
* ``overflow``  — 1 when a sparse round's frontier overflowed its cap
  and took the in-scan dense fallback.
* ``tombstones`` — POST-round count of tombstone-status cells across
  the model's belief structures.
* ``suspects`` — POST-round count of SUSPECT-status cells (the SWIM
  quarantine population, ops/suspicion.py); always 0 while the
  suspicion window is disabled.
* ``fp_tombstones`` — cells that ENTERED tombstone status this round
  while the slot's owner node is a live cluster member (the carried
  ``node_alive`` — a fault-plan pause does not clear it): the
  false-positive eviction count the robustness methodology measures
  (docs/chaos.md).  A tombstone of a genuinely departed owner
  (``node_alive`` false) never counts.  On the compressed family the
  columns cover ``own`` + ``floor`` (the authoritative structures);
  transient cache copies of tombstones ride the tombstone census but
  not this transition count.
* ``rejected_future`` — record copies the receiver-side
  future-admission bound rejected this round (ops/merge.future_mask,
  docs/chaos.md).  Only the chaos family under an active ClockFault
  can produce a nonzero value — a global-clock round never stamps
  beyond ``now`` — so the column is truthfully 0 everywhere else.
* ``ticked_nodes`` — alive nodes whose per-node cadence gate fired
  this round (ops/gossip.cadence_gate, docs/pipeline.md).  Under the
  default uniform cadence (period 1) this equals the alive census, so
  the column doubles as per-round cluster size; under a heterogeneous
  cadence it is the round's ACTIVE gossip population — the
  denominator for per-ticking-node byte budgets.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.kernels.publish_gather import eligible_lines
from sidecar_tpu.ops.status import (
    SUSPECT,
    TOMBSTONE,
    is_known,
    unpack_status,
)

# Analytic wire cost of one gossiped record: the reference's ~1398 B
# UDP packet carries the 15-record budget (services_delegate.go:182).
RECORD_WIRE_BYTES = 93

# Record layout — kept positional (a flat int32 [W] vector) so the scan
# carry stays one array; names map through TRACE_FIELDS.
TRACE_ROUND = 0
TRACE_FRONTIER = 1
TRACE_BEHIND = 2
TRACE_ADMITTED = 3
TRACE_EXCHANGE_BYTES = 4
TRACE_SPARSE = 5
TRACE_OVERFLOW = 6
TRACE_TOMBSTONES = 7
TRACE_SUSPECTS = 8
TRACE_FP_TOMBSTONES = 9
TRACE_REJECTED_FUTURE = 10
TRACE_TICKED_NODES = 11
TRACE_WIDTH = 12
TRACE_FIELDS = ("round", "frontier", "behind", "admitted",
                "exchange_bytes", "sparse", "overflow", "tombstones",
                "suspects", "fp_tombstones", "rejected_future",
                "ticked_nodes")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RoundTrace:
    """A bounded stream of per-round records.

    ``count`` is the TRUE number of rounds traced (it may exceed the
    buffer capacity); rows past ``min(count, cap)`` are zero padding.
    ``overflow`` is ``count > cap`` — records beyond the capacity were
    truncated (the DeltaBatch contract: capacity exhaustion is
    reported, never silent)."""

    count: jax.Array     # int32 scalar — rounds traced (exact)
    rec: jax.Array       # int32 [cap, TRACE_WIDTH]
    overflow: jax.Array  # bool scalar — count exceeded cap


def zero_trace(cap: int) -> RoundTrace:
    return RoundTrace(count=jnp.zeros((), jnp.int32),
                      rec=jnp.zeros((cap, TRACE_WIDTH), jnp.int32),
                      overflow=jnp.zeros((), bool))


def append_record(buf: RoundTrace, rec: jax.Array) -> RoundTrace:
    """Append one [TRACE_WIDTH] record; past the capacity the write
    drops (truncation) while ``count`` keeps the exact total."""
    cap = buf.rec.shape[0]
    out = buf.rec.at[buf.count].set(rec, mode="drop")
    count = buf.count + 1
    return RoundTrace(count=count, rec=out, overflow=count > cap)


def offer_census(elig, budget: int, fanout: int):
    """(frontier, exchange_bytes) from a PRE-round eligibility mask
    ``elig`` (bool [N, X] — records/lines a node could publish):
    frontier = rows with any eligible entry; bytes = the analytic offer
    volume ``Σ min(budget, eligible_i) × fanout × RECORD_WIRE_BYTES``."""
    per_row = jnp.sum(elig.astype(jnp.int32), axis=1)
    frontier = jnp.sum((per_row > 0).astype(jnp.int32))
    recs = jnp.sum(jnp.minimum(per_row, budget))
    return frontier, recs * (fanout * RECORD_WIRE_BYTES)


def count_tombstones(*packed) -> jax.Array:
    """Tombstone-status cells across packed-key tensors (unknown cells
    — packed 0 — never count: ``is_known`` gates them)."""
    total = jnp.zeros((), jnp.int32)
    for arr in packed:
        hit = is_known(arr) & (unpack_status(arr) == TOMBSTONE)
        total = total + jnp.sum(hit.astype(jnp.int32))
    return total


def count_suspects(*packed) -> jax.Array:
    """SUSPECT-status cells (the SWIM quarantine population,
    ops/suspicion.py) across packed-key tensors."""
    total = jnp.zeros((), jnp.int32)
    for arr in packed:
        hit = is_known(arr) & (unpack_status(arr) == SUSPECT)
        total = total + jnp.sum(hit.astype(jnp.int32))
    return total


def fp_tombstone_entries(prev, nxt, owner_alive) -> jax.Array:
    """Cells that ENTERED tombstone status between two aligned packed
    tensors while the slot's owner is alive (``owner_alive`` broadcasts
    against the tensors): the false-positive eviction transition count
    — the service is actually up, yet a belief cell now calls it dead.
    A tombstone arriving at a previously-unknown cell counts too (it is
    a new false belief either way)."""
    entered = is_known(nxt) & (unpack_status(nxt) == TOMBSTONE) & \
        ~(is_known(prev) & (unpack_status(prev) == TOMBSTONE))
    return jnp.sum((entered & owner_alive).astype(jnp.int32))


def ticked_census(round_idx, node_alive, tick_period=None,
                  tick_phase=None) -> jax.Array:
    """#alive nodes whose cadence gate fires at ``round_idx`` (the
    ``ticked_nodes`` column).  ``tick_period=None`` (or a provably-1
    static) is the uniform cadence: every alive node ticks."""
    alive = node_alive
    if tick_period is None or (isinstance(tick_period, int)
                               and tick_period <= 1):
        return jnp.sum(alive.astype(jnp.int32))
    n = alive.shape[0]
    per = jnp.broadcast_to(
        jnp.asarray(tick_period, jnp.int32).reshape(-1), (n,))
    pha = jnp.broadcast_to(
        jnp.asarray(0 if tick_phase is None else tick_phase,
                    jnp.int32).reshape(-1), (n,))
    ticked = ((jnp.asarray(round_idx, jnp.int32) + pha)
              % jnp.maximum(per, 1)) == 0
    return jnp.sum((ticked & alive).astype(jnp.int32))


def build_record(round_idx, frontier, behind, admitted, exchange_bytes,
                 tombstones, suspects, fp_tombstones,
                 stats=None, rejected_future=0,
                 ticked_nodes=0) -> jax.Array:
    """Assemble the [TRACE_WIDTH] int32 record; ``stats`` is the sparse
    step's int32 [3] vector (sparse-taken, overflowed, frontier-hwm) or
    None on dense rounds."""
    if stats is None:
        sparse = jnp.zeros((), jnp.int32)
        overflow = jnp.zeros((), jnp.int32)
    else:
        sparse, overflow = stats[0], stats[1]
    return jnp.stack([
        jnp.asarray(round_idx, jnp.int32),
        jnp.asarray(frontier, jnp.int32),
        jnp.asarray(behind, jnp.int32),
        jnp.asarray(admitted, jnp.int32),
        jnp.asarray(exchange_bytes, jnp.int32),
        jnp.asarray(sparse, jnp.int32),
        jnp.asarray(overflow, jnp.int32),
        jnp.asarray(tombstones, jnp.int32),
        jnp.asarray(suspects, jnp.int32),
        jnp.asarray(fp_tombstones, jnp.int32),
        jnp.asarray(rejected_future, jnp.int32),
        jnp.asarray(ticked_nodes, jnp.int32),
    ])


def exact_record(prev, nxt, *, budget: int, fanout: int, limit: int,
                 stats=None, rejected_future=0, tick_period=None,
                 tick_phase=None) -> jax.Array:
    """One round's record for the EXACT family (``SimState`` in, both
    the single-chip model and the sharded twin — the reductions shard
    cleanly under GSPMD)."""
    elig = gossip_ops.eligible_records(prev.known, prev.sent, limit)
    frontier, xbytes = offer_census(elig, budget, fanout)
    alive = nxt.node_alive
    truth = jnp.max(jnp.where(alive[:, None], nxt.known, 0), axis=0)
    behind = jnp.sum((alive[:, None]
                      & (nxt.known < truth[None, :])).astype(jnp.int32))
    admitted = jnp.sum((nxt.known != prev.known).astype(jnp.int32))
    tombs = count_tombstones(nxt.known)
    suspects = count_suspects(nxt.known)
    n, m = nxt.known.shape
    owner = jnp.arange(m, dtype=jnp.int32) // (m // n)
    fp = fp_tombstone_entries(prev.known, nxt.known,
                              alive[owner][None, :])
    return build_record(nxt.round_idx, frontier, behind, admitted,
                        xbytes, tombs, suspects, fp, stats,
                        rejected_future=rejected_future,
                        ticked_nodes=ticked_census(
                            nxt.round_idx, alive, tick_period,
                            tick_phase))


def compressed_record(prev, nxt, behind, *, budget: int, fanout: int,
                      limit: int, stats=None, tick_period=None,
                      tick_phase=None) -> jax.Array:
    """One round's record for the COMPRESSED family
    (``CompressedState`` in; ``behind`` is the model's own census —
    ``CompressedSim.behind(nxt)`` — passed in so the sharded twin's
    census-path restrictions apply automatically)."""
    elig = eligible_lines(prev.cache_slot, prev.cache_sent, limit)
    frontier, xbytes = offer_census(elig, budget, fanout)
    admitted = (
        jnp.sum((nxt.own != prev.own).astype(jnp.int32))
        + jnp.sum((nxt.cache_val != prev.cache_val).astype(jnp.int32))
        + jnp.sum((nxt.cache_slot != prev.cache_slot).astype(jnp.int32))
        + jnp.sum((nxt.floor != prev.floor).astype(jnp.int32)))
    tombs = count_tombstones(nxt.own, nxt.floor, nxt.cache_val)
    suspects = count_suspects(nxt.own, nxt.floor, nxt.cache_val)
    alive = nxt.node_alive
    n, s = nxt.own.shape
    floor_owner = jnp.arange(n * s, dtype=jnp.int32) // s
    fp = fp_tombstone_entries(prev.own, nxt.own, alive[:, None]) + \
        fp_tombstone_entries(prev.floor, nxt.floor, alive[floor_owner])
    behind_i = jnp.minimum(jnp.asarray(behind, jnp.float32),
                           jnp.float32(2**31 - 1)).astype(jnp.int32)
    return build_record(nxt.round_idx, frontier, behind_i, admitted,
                        xbytes, tombs, suspects, fp, stats,
                        ticked_nodes=ticked_census(
                            nxt.round_idx, alive, tick_period,
                            tick_phase))


# -- host-side views ---------------------------------------------------------

def trace_to_dicts(trace: RoundTrace) -> list[dict]:
    """Host-side view: one dict per RECORDED round (padding dropped —
    with overflow, only the first ``cap`` rounds are present; the
    caller reads ``trace.overflow``/``trace.count`` for the
    truncation)."""
    import numpy as np

    count = int(np.asarray(trace.count))
    rec = np.asarray(trace.rec)
    out = []
    for row in rec[:min(count, rec.shape[0])]:
        out.append({name: int(row[i])
                    for i, name in enumerate(TRACE_FIELDS)})
    return out


def summarize(trace: RoundTrace) -> dict:
    """Compact tail summary of a trace (the bench / MULTICHIP JSON
    block): last-record census plus per-round exchange-byte stats over
    the recorded rounds."""
    import numpy as np

    count = int(np.asarray(trace.count))
    rec = np.asarray(trace.rec)
    recorded = rec[:min(count, rec.shape[0])]
    if recorded.shape[0] == 0:
        return {"rounds": 0, "truncated": bool(np.asarray(trace.overflow))}
    xb = recorded[:, TRACE_EXCHANGE_BYTES].astype(np.int64)
    return {
        "rounds": count,
        "truncated": bool(np.asarray(trace.overflow)),
        "frontier_last": int(recorded[-1, TRACE_FRONTIER]),
        "frontier_max": int(recorded[:, TRACE_FRONTIER].max()),
        "behind_last": int(recorded[-1, TRACE_BEHIND]),
        "admitted_total": int(
            recorded[:, TRACE_ADMITTED].astype(np.int64).sum()),
        "exchange_bytes_per_round_mean": int(xb.mean()),
        "exchange_bytes_per_round_max": int(xb.max()),
        "exchange_bytes_total": int(xb.sum()),
        "sparse_rounds": int(recorded[:, TRACE_SPARSE].sum()),
        "overflow_rounds": int(recorded[:, TRACE_OVERFLOW].sum()),
        "tombstones_last": int(recorded[-1, TRACE_TOMBSTONES]),
        "suspects_last": int(recorded[-1, TRACE_SUSPECTS]),
        "suspects_max": int(recorded[:, TRACE_SUSPECTS].max()),
        "fp_tombstones_total": int(
            recorded[:, TRACE_FP_TOMBSTONES].astype(np.int64).sum()),
        "rejected_future_total": int(
            recorded[:, TRACE_REJECTED_FUTURE].astype(np.int64).sum()),
        "ticked_nodes_last": int(recorded[-1, TRACE_TICKED_NODES]),
        "ticked_nodes_min": int(recorded[:, TRACE_TICKED_NODES].min()),
    }
