"""Suspicion & flap-damping subprotocol — shared knobs and kernels.

The sim's liveness model used to be bare LWW + TTL expiry: a record one
refresh window late was tombstoned immediately (ops/ttl.py), so under
chaos (asymmetric loss, pause windows) healthy services flap
alive→tombstone→alive, churning every downstream consumer — snapshots,
watch deltas, ADS pushes, proxy config.  memberlist grew SWIM/Lifeguard
suspicion for exactly this; "Robust and Tuneable Family of Gossiping
Algorithms" (PAPERS.md) frames the robustness-vs-latency knob this
module makes tunable.

The subprotocol has two halves:

* **Suspicion (device side, this module + ops/ttl.py)** — expired
  records enter a ``SUSPECT`` status (a spare code of the 3-bit status
  field, ops/status.py) for ``TimeConfig.suspicion_window_s`` instead
  of tombstoning.  Three properties come FREE from the packed-key LWW
  machinery:

  - *gossip*: SUSPECT re-packs at the record's ORIGINAL timestamp with
    a status code above every reference status, so the packed key
    strictly increases — the existing scatter-max/lex-merge carries the
    suspicion to every copy of that version, and the sweep's
    changed-cell transmit reset re-enqueues it for broadcast;
  - *refutation*: any strictly newer ALIVE record (an owner refresh)
    outranks the suspicion under the same max — no anti-entropy case
    analysis anywhere;
  - *solicitation*: a suspected OWN record joins the announce path
    immediately (:func:`announce_refute` below — the Lifeguard
    self-refutation), so a node returning from a pause re-asserts its
    services the very next round instead of waiting out its refresh
    phase; SUSPECT rows thereby join the announcer frontier on the
    sparse path for free, and the periodic push-pull leg pulls refuting
    versions for records a node does not own.

  Only an UNREFUTED suspicion expiry becomes a tombstone, stamped
  original ts + 1 s exactly as before — the +1 s rule is preserved, so
  an unseen newer record still wins the LWW race.  With
  ``suspicion_window_s == 0`` every round is bit-identical to the
  pre-suspicion protocol (tests/test_suspicion.py pins this across all
  four model families, sparse and dense, trace and delta streams).

* **Flap damping (host side, catalog/damping.py)** — a per-service
  penalty counter with exponential decay (the BGP route-flap /
  Envoy-outlier shape) gates proxy/ADS admission: a service that keeps
  flapping is damped OUT OF ROUTING without being evicted from the
  catalog, and readmits once its penalty decays below the reuse
  threshold.

:class:`ProtocolParams` is the single knob bundle both worlds consume:
``config.py`` reads it from ``SIDECAR_*`` env vars for the live node,
``SimBridge.simulate`` / ``POST /simulate`` accept the same fields per
request, so a what-if simulation runs the exact settings the live
cluster would.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from sidecar_tpu.ops.status import ALIVE, SUSPECT


def announce_refute(due, st, present, suspicion: bool):
    """Fold the Lifeguard self-refutation into an announce site.

    ``due`` is the refresh-stagger mask (ops/gossip.refresh_due already
    ANDed with the caller's present/non-tombstone gates), ``st`` the
    owners' current status codes, ``present`` the owner-alive gate.
    With ``suspicion`` (a static Python bool — the disabled path
    compiles nothing), an owner whose OWN record is SUSPECT announces
    immediately, and the announced status is ALIVE: the owner is alive
    and answering, which is precisely the refutation (SWIM's
    alive-with-higher-incarnation message; here the higher incarnation
    is the fresh timestamp the caller stamps).

    Returns ``(due, st)`` with the refutation folded in.
    """
    if not suspicion:
        return due, st
    refute = present & (st == SUSPECT)
    return due | refute, jnp.where(refute, ALIVE, st)


@dataclasses.dataclass(frozen=True)
class ProtocolParams:
    """The suspicion/damping knob bundle shared by sim and live.

    Defaults are the DISABLED subprotocol: ``suspicion_window_s == 0``
    keeps every simulated round bit-identical to the pre-suspicion
    protocol, and ``damping_threshold == 0`` never suppresses a
    service.
    """

    suspicion_window_s: float = 0.0   # SWIM quarantine window (0 = off)
    damping_half_life_s: float = 60.0  # penalty exponential-decay half-life
    damping_threshold: float = 0.0    # suppress at penalty ≥ this (0 = off)
    damping_reuse_threshold: float = 0.0  # readmit below this
                                      # (0 = auto: threshold / 2)
    damping_flap_penalty: float = 1.0  # penalty added per observed flap
    future_fudge_s: float = -1.0      # future-admission bound
                                      # (negative = off; ops/merge)
    # Defense ladder (ops/merge.budget_mask, docs/chaos.md): cap on
    # third-party suspicious records (tombstones / future stamps) a
    # single packet may carry, and the misbehavior-evidence count at
    # which an origin is quarantined.  Negative = rung off; the sim
    # twins are TimeConfig.origin_budget / origin_quarantine.
    origin_budget: int = -1
    origin_quarantine: int = -1

    def __post_init__(self):
        if self.suspicion_window_s < 0:
            raise ValueError("suspicion_window_s must be >= 0")
        if self.damping_half_life_s <= 0:
            raise ValueError("damping_half_life_s must be > 0")
        if self.damping_threshold < 0:
            raise ValueError("damping_threshold must be >= 0")
        if self.damping_reuse_threshold > self.damping_threshold:
            raise ValueError(
                "damping_reuse_threshold cannot exceed damping_threshold")

    @property
    def resolved_reuse_threshold(self) -> float:
        """Hysteresis floor: explicit, else half the suppress threshold
        (the BGP reuse < suppress convention) so a service hovering at
        the threshold cannot thrash in and out of routing."""
        if self.damping_reuse_threshold > 0:
            return self.damping_reuse_threshold
        return self.damping_threshold / 2.0

    def timecfg(self, base):
        """``base`` TimeConfig with this bundle's suspicion window
        applied — how the bridge/bench thread per-request protocol
        params into the jitted round."""
        return dataclasses.replace(
            base, suspicion_window_s=self.suspicion_window_s,
            future_fudge_s=self.future_fudge_s,
            origin_budget=self.origin_budget,
            origin_quarantine=self.origin_quarantine)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Optional[dict]) -> "ProtocolParams":
        """Build from a request dict (the ``POST /simulate`` surface);
        unknown keys are rejected loudly — a typoed knob silently
        running the defaults would defeat the sim↔live parity story."""
        if not d:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        bad = set(d) - known
        if bad:
            raise ValueError(
                f"unknown protocol param(s): {sorted(bad)} "
                f"(expected a subset of {sorted(known)})")
        ints = {f.name for f in dataclasses.fields(cls) if f.type == "int"}
        return cls(**{k: (int(v) if k in ints else float(v))
                      for k, v in d.items()})

    @classmethod
    def from_config(cls, sidecar_cfg) -> "ProtocolParams":
        """From the live node's ``SidecarConfig`` (config.py) — the
        SIDECAR_SUSPICION_WINDOW / SIDECAR_DAMPING_* env knobs."""
        return cls(
            suspicion_window_s=sidecar_cfg.suspicion_window,
            damping_half_life_s=sidecar_cfg.damping_half_life,
            damping_threshold=sidecar_cfg.damping_threshold,
            future_fudge_s=sidecar_cfg.future_fudge,
            origin_budget=sidecar_cfg.origin_budget,
            origin_quarantine=sidecar_cfg.origin_quarantine,
        )


class QuarantineScorer:
    """Host-side misbehavior score — the live twin of the sim's
    per-origin violation counter (chaos/sim_inject.py, sim/oracle.py).

    One push from one origin is "one packet": the scorer counts the
    FRESH THIRD-PARTY claims it carries — records the sender does not
    own whose timestamp is at or beyond the receiver's clock (a relay
    of honestly-aged state always trails it) — and charges one
    violation per claim beyond ``origin_budget``.  An origin whose
    violation count reaches ``origin_quarantine`` is quarantined: the
    catalog writer (catalog/state.py ``attach_origin_gate``) drops its
    pushes wholesale, exactly as the sim zeroes a quarantined row's
    deliveries and push-pull legs.  Both knobs negative → the scorer
    never quarantines and scores nothing.
    """

    def __init__(self, params: "ProtocolParams"):
        self.budget = int(params.origin_budget)
        self.threshold = int(params.origin_quarantine)
        self.violations: dict = {}

    @property
    def enabled(self) -> bool:
        return self.budget >= 0 and self.threshold >= 0

    def observe(self, origin: str, claims, now) -> int:
        """Score one push.  ``claims`` is an iterable of ``(owned,
        timestamp)`` pairs — one per record in the packet, ``owned``
        true when the ORIGIN (transport sender, not the record's
        hostname — a forger writes any hostname it likes) owns the
        record; timestamps share ``now``'s clock units (the catalog
        passes ns).  Returns the violations charged to ``origin``."""
        if not self.enabled:
            return 0
        suspicious = sum(1 for owned, ts in claims
                         if (not owned) and ts >= now)
        over = max(0, suspicious - self.budget)
        if over:
            self.violations[origin] = self.violations.get(origin, 0) + over
        return over

    def is_quarantined(self, origin: str) -> bool:
        return (self.enabled and
                self.violations.get(origin, 0) >= self.threshold)

    def quarantined(self) -> set:
        """The quarantined origin set — the live half of the sim↔live
        agreement check (tests/test_adversary.py)."""
        if not self.enabled:
            return set()
        return {o for o, v in self.violations.items()
                if v >= self.threshold}
