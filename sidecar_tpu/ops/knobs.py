"""Per-round protocol knobs — the values a round reads that do NOT
shape the program.

Historically every protocol parameter was baked into the jitted round
as a Python constant (``SimParams``/``TimeConfig`` are static w.r.t.
jit), so evaluating a configuration grid meant one trace + compile +
dispatch per point.  The scenario-fleet engine (``sidecar_tpu/fleet``,
docs/sweep.md) batches S *independent* scenarios into ONE compiled
scan by ``jax.vmap``-ing the round over a stacked :class:`RoundKnobs`
pytree — which requires splitting the parameter space in two:

* **Compile-key axes** (stay static): anything that shapes a tensor or
  selects program structure — ``n``, ``services_per_node``, ``fanout``
  (the sampled-peer width), ``budget`` (the message width),
  ``cache_lines``, ``round_ticks`` (the tick resolution every cadence
  is derived from), ``fold_quorum``/``deep_sweep_every`` (static
  Python branches), the topology (a ``ScenarioSpec.topology`` overlay
  name — its neighbor tables are constants baked into the round), and
  the FaultPlan *structure*.  ``fleet/grid.py`` sweeps these ACROSS
  batches, not within one.
* **Data axes** (this bundle): values consumed only by elementwise
  math and ``lax.cond`` predicates — the transmit limit, packet-loss
  keep probability, push-pull/sweep/refresh cadences, suspicion
  window, record lifespans, staleness bound, per-round churn
  probability, and the FaultPlan seed.  These may be Python scalars
  (the classic static path — they const-fold into exactly the
  pre-knob program) or traced jax scalars (the fleet path — one
  program serves every value).

The models build a static bundle once at construction
(``self._knobs``) and every round helper takes an optional ``kn``
override; a caller that passes nothing gets the pre-knob program bit
for bit.  The fleet engine passes a ``[S]``-stacked bundle through
``jax.vmap`` instead (tests/test_fleet.py pins batched == unbatched
per scenario, bit-identically, on every model family).

Float-knob bit-identity rule: traced float knobs must reach the PRNG
*without arithmetic* — ``keep_prob`` is precomputed host-side
(``1 - drop_prob`` in double precision) rather than derived in traced
f32, because ``f32(1) - f32(p)`` can differ from ``f32(1 - p)`` by one
ulp and flip a Bernoulli draw sitting exactly on the boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax


def _static(v) -> bool:
    """True when a knob is a host scalar (const-folds under jit)."""
    return isinstance(v, (int, float))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RoundKnobs:
    """One scenario's data-axis protocol values (see module docstring).

    Every field is either a Python scalar (static path) or a rank-0 —
    under the fleet's ``vmap``, rank-1 stacked — jax array (fleet
    path).  Durations are logical ticks; cadences are gossip rounds.
    """

    limit: Any              # resolved TransmitLimited limit
    keep_prob: Any          # 1 - drop_prob, precomputed host-side
    push_pull_rounds: Any   # anti-entropy cadence (rounds)
    sweep_rounds: Any       # TTL sweep cadence (rounds)
    refresh_rounds: Any     # owner refresh cadence (rounds)
    recover_rounds: Any     # compressed recovery re-offer cadence
    suspicion_window: Any   # SWIM quarantine window (ticks; 0 = off)
    alive_lifespan: Any     # ticks
    draining_lifespan: Any  # ticks
    tombstone_lifespan: Any  # ticks
    stale_ticks: Any        # merge staleness bound (ticks)
    churn_prob: Any = 0.0   # per-round restart-churn probability
                            # (consumed by knob-aware perturb hooks)
    fault_seed: Any = 0     # FaultPlan seed (chaos family)
    future_ticks: Any = -1  # future-admission bound (ticks;
                            # negative = disabled — ops/merge.future_mask)
    tomb_budget: Any = -1   # per-origin suspicious-record budget
                            # (records/packet; negative = disabled —
                            # ops/merge.budget_mask)
    quarantine_threshold: Any = -1  # cumulative budget violations that
                            # quarantine an origin (negative = off —
                            # chaos/sim_inject.py, docs/chaos.md)
    tick_period: Any = 1    # per-node gossip cadence (rounds between
                            # ticks; 1 = every round — the pre-cadence
                            # program).  Scalar, or a per-node [N]
                            # vector (heterogeneous fleets).
    tick_phase: Any = 0     # per-node cadence phase offset (rounds);
                            # a node ticks iff
                            # (round_idx + phase) % period == 0

    @property
    def cadence_enabled(self) -> bool:
        """Static gate for :func:`ops.gossip.cadence_gate`: False only
        when the tick period is PROVABLY 1 (a static 1 compiles the
        gate away — exactly the pre-cadence program); a traced period
        or a per-node vector keeps the gate compiled, value-identical
        at period 1 because ``x % 1 == 0`` gates nothing."""
        return not (_static(self.tick_period) and self.tick_period <= 1)

    @property
    def suspicion_enabled(self) -> bool:
        """Static gate for :func:`ops.suspicion.announce_refute`: False
        only when the window is PROVABLY zero (a static 0 compiles the
        refutation away, exactly the pre-knob program); a traced window
        keeps the refutation compiled — value-identical at window 0
        because no SUSPECT cell can exist then."""
        return not (_static(self.suspicion_window)
                    and self.suspicion_window <= 0)

    @property
    def needs_drop_draw(self) -> bool:
        """Static gate for the packet-loss Bernoulli: skip the draw
        only when the keep probability is PROVABLY 1 (static path —
        the pre-knob program drew nothing either).  A traced keep_prob
        always draws; at keep_prob 1.0 the mask is all-True, a value
        no-op on its own key (per-purpose keys never shift siblings'
        streams)."""
        return not (_static(self.keep_prob) and self.keep_prob >= 1.0)

    def future_arg(self):
        """The ``future_ticks`` argument for the merge gates
        (ops/merge.admit_gate): None when the bound is PROVABLY
        disabled (a static negative compiles the pre-bound program bit
        for bit); a static non-negative passes through as a Python int
        (const-folds); a traced value keeps the gate compiled with the
        disabled sentinel mapped to MAX_TICK — ``ts > now + MAX_TICK``
        is never true on valid ticks, and ``now + MAX_TICK ≤ 2^29 − 2``
        cannot overflow int32."""
        ft = self.future_ticks
        if _static(ft):
            return None if ft < 0 else int(ft)
        import jax.numpy as jnp

        from sidecar_tpu.ops.status import MAX_TICK
        ft = jnp.asarray(ft, jnp.int32)
        return jnp.where(ft < 0, MAX_TICK, ft)

    def budget_arg(self):
        """The ``tomb_budget`` argument for the merge gates
        (ops/merge.admit_gate) — the ``future_arg`` contract applied to
        the per-origin suspicious-record budget: None when PROVABLY
        disabled (a static negative compiles the pre-budget program bit
        for bit); a static non-negative const-folds as a Python int; a
        traced value keeps the gate compiled with the disabled sentinel
        mapped to ``ops/merge.BUDGET_OFF``, which no per-packet
        suspicious rank can exceed."""
        tb = self.tomb_budget
        if _static(tb):
            return None if tb < 0 else int(tb)
        import jax.numpy as jnp

        from sidecar_tpu.ops.merge import BUDGET_OFF
        tb = jnp.asarray(tb, jnp.int32)
        return jnp.where(tb < 0, BUDGET_OFF, tb)

    def quarantine_arg(self):
        """The origin-quarantine violation threshold with the same
        three-state contract (chaos/sim_inject.py): None when PROVABLY
        disabled; a static non-negative const-folds; a traced value
        maps the off sentinel to ``BUDGET_OFF`` — no origin accrues
        2^28 violations, so the quarantine set stays empty."""
        qt = self.quarantine_threshold
        if _static(qt):
            return None if qt < 0 else int(qt)
        import jax.numpy as jnp

        from sidecar_tpu.ops.merge import BUDGET_OFF
        qt = jnp.asarray(qt, jnp.int32)
        return jnp.where(qt < 0, BUDGET_OFF, qt)


def from_protocol(params, timecfg, *, recover_rounds: int = 1,
                  fault_seed: int = 0, churn_prob: float = 0.0,
                  tick_period=1, tick_phase=0) -> RoundKnobs:
    """The static bundle for a classic single-scenario sim: plain
    Python scalars read off ``SimParams``/``CompressedParams`` +
    ``TimeConfig`` — const-folds into the pre-knob program."""
    return RoundKnobs(
        limit=params.resolved_retransmit_limit(),
        keep_prob=1.0 - params.drop_prob,
        push_pull_rounds=timecfg.push_pull_rounds,
        sweep_rounds=timecfg.sweep_rounds,
        refresh_rounds=timecfg.refresh_rounds,
        recover_rounds=recover_rounds,
        suspicion_window=timecfg.suspicion_window,
        alive_lifespan=timecfg.alive_lifespan,
        draining_lifespan=timecfg.draining_lifespan,
        tombstone_lifespan=timecfg.tombstone_lifespan,
        stale_ticks=timecfg.stale_ticks,
        churn_prob=churn_prob,
        fault_seed=fault_seed,
        future_ticks=(-1 if timecfg.future_ticks is None
                      else timecfg.future_ticks),
        tomb_budget=(-1 if timecfg.tomb_budget is None
                     else timecfg.tomb_budget),
        quarantine_threshold=(-1 if timecfg.quarantine_threshold is None
                              else timecfg.quarantine_threshold),
        tick_period=tick_period,
        tick_phase=tick_phase,
    )
