"""Pure JAX kernels for the gossip/merge compute path."""

from sidecar_tpu.ops.status import (  # noqa: F401
    ALIVE,
    TOMBSTONE,
    UNHEALTHY,
    UNKNOWN,
    DRAINING,
    STATUS_BITS,
    STATUS_MASK,
    MAX_TICK,
    pack,
    unpack_ts,
    unpack_status,
    status_string,
)
from sidecar_tpu.ops.merge import merge_packed, merge_records  # noqa: F401
from sidecar_tpu.ops.ttl import ttl_sweep  # noqa: F401
