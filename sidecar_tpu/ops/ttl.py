"""Record-lifespan sweep — the TPU recast of ``TombstoneOthersServices``.

Reference semantics (catalog/services_state.go:635-683), applied by every
node over its *entire* view (its own and everyone else's records):

* Tombstones older than TOMBSTONE_LIFESPAN (3 h) are garbage-collected
  (services_state.go:645-653; empty-server cleanup is implicit here — a
  row of unknown cells simply contributes nothing).
* Any non-tombstone record not refreshed within its lifespan —
  ALIVE_LIFESPAN (80 s) normally, DRAINING_LIFESPAN (10 min) for draining
  records (services_state.go:655-658) — is tombstoned **at its original
  timestamp + 1 s**, not at now, so an unseen newer record still wins the
  LWW race (the "+1 s rule", services_state.go:667-675).

The reference runs this every TOMBSTONE_SLEEP_INTERVAL (2 s); the
simulator invokes it on the equivalent round cadence.  Expired records get
their timestamp bumped, which naturally pushes them into the node's top-k
freshest records for rebroadcast — the vectorized analog of the 10×
tombstone retransmit (services_state.go:620-624).
"""

from __future__ import annotations

import jax.numpy as jnp

from sidecar_tpu.ops.status import (
    DRAINING,
    SUSPECT,
    TOMBSTONE,
    is_known,
    pack,
    unpack_status,
    unpack_ts,
)
from sidecar_tpu.telemetry import cost


@cost.phased("ttl_sweep")
def ttl_sweep(known, now_tick, *, alive_lifespan, draining_lifespan,
              tombstone_lifespan, one_second, suspicion_window=0):
    """Apply the lifespan sweep to a tensor of packed records.

    Args:
      known: int32 packed (ts<<3|status) tensor, any shape.
      now_tick: current logical tick (scalar).
      alive_lifespan / draining_lifespan / tombstone_lifespan / one_second:
        durations in ticks (see models/timecfg.py for the mapping from the
        reference's wall-clock constants).
      suspicion_window: SWIM-style quarantine window in ticks
        (ops/suspicion.py, docs/chaos.md).  0 — the default — compiles
        the pre-suspicion sweep unchanged, bit for bit.  > 0: an expired
        non-DRAINING record is re-packed SUSPECT at its ORIGINAL
        timestamp (a monotone packed increase, so the max-merge gossips
        the suspicion and any strictly newer ALIVE refutes it), and only
        a suspicion that survives unrefuted past ``lifespan + window``
        becomes a tombstone — still stamped original ts + 1 s, so the
        +1 s rule holds identically.  DRAINING records never enter
        quarantine: draining is an ORDERLY shutdown with its own 10 min
        lifespan, not a suspected failure — they tombstone directly, as
        before (the memberlist/Lifeguard analog suspects alive members
        only).

    Returns:
      (swept, expired) — the updated tensor and a bool mask of cells that
      were tombstoned by this sweep (for event accounting / metrics).
      Cells entering SUSPECT are NOT in ``expired`` (nothing was
      tombstoned); they surface through the trace plane's suspect census
      (ops/trace.py) instead.
    """
    now_tick = jnp.asarray(now_tick, jnp.int32)
    ts = unpack_ts(known)
    st = unpack_status(known)
    present = is_known(known)

    is_tomb = present & (st == TOMBSTONE)
    gc = is_tomb & (ts < now_tick - tombstone_lifespan)

    static_window = isinstance(suspicion_window, (int, float))

    def plain():
        lifespan = jnp.where(st == DRAINING, draining_lifespan,
                             alive_lifespan)
        expired = present & ~is_tomb & (ts < now_tick - lifespan)
        swept = jnp.where(expired, pack(ts + one_second, TOMBSTONE),
                          known)
        return swept, expired

    def quarantine():
        # Quarantine-before-tombstone: fresh expiries of suspectable
        # records become SUSPECT at the original ts; a SUSPECT record
        # tombstones only once the grace window has ALSO lapsed.
        is_suspect = present & (st == SUSPECT)
        is_drain = present & (st == DRAINING)
        suspectable = present & ~is_tomb & ~is_suspect & ~is_drain
        to_suspect = suspectable & (ts < now_tick - alive_lifespan)
        expired = (is_drain & (ts < now_tick - draining_lifespan)) | \
            (is_suspect & (ts < now_tick - alive_lifespan
                           - suspicion_window))
        swept = jnp.where(to_suspect, pack(ts, SUSPECT), known)
        swept = jnp.where(expired, pack(ts + one_second, TOMBSTONE),
                          swept)
        return swept, expired

    if static_window and suspicion_window <= 0:
        swept, expired = plain()
    elif static_window:
        swept, expired = quarantine()
    else:
        # Traced window (the fleet's per-scenario knob, ops/knobs.py):
        # BOTH forms are computed elementwise and selected on
        # ``window > 0`` — a plain jnp.where, NOT the quarantine math
        # evaluated at window 0, because the two differ there: the
        # quarantine form parks a fresh expiry in SUSPECT for one sweep
        # even with a zero window, while the static window-0 contract
        # (pinned bit-for-bit since PR 7) tombstones it immediately.
        on = jnp.asarray(suspicion_window) > 0
        swept_q, expired_q = quarantine()
        swept_p, expired_p = plain()
        swept = jnp.where(on, swept_q, swept_p)
        expired = jnp.where(on, expired_q, expired_p)

    swept = jnp.where(gc, 0, swept)
    return swept, expired
