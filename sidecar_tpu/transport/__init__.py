"""Gossip transport: the native (C++) memberlist-equivalent engine plus
the Python delegate bridging it to the catalog (reference:
services_delegate.go + the NinesStack/memberlist dependency)."""

from sidecar_tpu.transport.antientropy import (AntiEntropyResponder,
                                               ReconcileSession,
                                               SessionConfig, reconcile)
from sidecar_tpu.transport.gossip import GossipTransport, load_native

__all__ = ["GossipTransport", "load_native", "AntiEntropyResponder",
           "ReconcileSession", "SessionConfig", "reconcile"]
