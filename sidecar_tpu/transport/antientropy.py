"""Digest-directed anti-entropy — the rsync-style push-pull body.

A full-catalog push-pull body costs O(catalog) bytes no matter how
little actually diverged; after a partition heals or a node rejoins,
that is the dominant byte cost of recovery (ROADMAP north star).  This
module ships divergence instead: two peers that both advertise a
Merkle ladder (the ``"Ladder"`` key inside the ``"Digest"`` annotation
of ``encode_annotated`` — the version gate) walk the ladder level by
level and then exchange ONLY the records hashing into differing leaf
buckets, so a session's body is O(divergence · depth).

Protocol (initiator-driven request/response over a :class:`Channel`):

1. **HELLO** — exchange geometry ``(base, depth, leaf)`` and the
   coarse level-0 digest.  Equal digests end the session with zero
   record bytes; a geometry mismatch aborts to the fallback ladder.
2. **NARROW** — for each deeper level, the initiator sends its child
   digests for the children of currently-differing parents; the
   responder replies with the child ids that differ on its side.  Each
   message is O(differing buckets), never O(buckets).
3. **TRANSFER** — the initiator sends its records in the differing
   leaf buckets; the responder merges them (LWW — the
   ``add_service_entry`` kernel), replies with ITS records in those
   buckets (captured BEFORE merging, so the reply is the peer's
   divergent view, not an echo), and the initiator merges those.
   Tombstones ride along: a reconciling peer must learn of deaths.
4. **VERIFY** — one more level-0 compare seals the verdict.

Session state machine: every request runs under a per-attempt timeout
with bounded retries and exponential backoff + jitter (deterministic
under an injected ``rng``/``sleep`` — the chaos-test convention).  ANY
failure — channel errors, retry exhaustion, ladder mismatch, protocol
surprises — degrades to ONE full-body exchange via the same channel,
counted in ``antientropy.fallbacks`` and logged loudly; if the
fallback itself fails the session reports ``failed`` and counts
``antientropy.failures``.  Nothing is ever silently truncated.

Plain-wire peers (no ``"Ladder"`` advertisement) are version-gated
straight to the full-body exchange — today's wire behavior, counted
in ``antientropy.plainwire`` — so a mixed-version cluster degrades in
cost, never in correctness.

Metrics (docs/metrics.md): ``antientropy.sessions``,
``antientropy.fallbacks``, ``antientropy.plainwire``,
``antientropy.retries``, ``antientropy.failures``,
``antientropy.records``, ``antientropy.backoff_ms``,
``antientropy.bytes``.  Env knobs (docs/env.md):
``SIDECAR_TPU_ANTIENTROPY``, ``SIDECAR_TPU_ANTIENTROPY_RETRIES``,
``SIDECAR_TPU_ANTIENTROPY_TIMEOUT_S``,
``SIDECAR_TPU_ANTIENTROPY_BACKOFF_MS`` (plus
``SIDECAR_TPU_ANTIENTROPY_DEPTH`` read by catalog/state.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import time
from typing import Callable, List, Optional

from sidecar_tpu import metrics
from sidecar_tpu.catalog import state as state_mod
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.service import Service
from sidecar_tpu.telemetry import coherence as _coherence

log = logging.getLogger(__name__)


def _env_int(name: str, default: int, lo: int = 0) -> int:
    raw = os.environ.get(name, "")
    try:
        return max(lo, int(raw)) if raw else default
    except ValueError:
        return default


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    try:
        return max(lo, float(raw)) if raw else default
    except ValueError:
        return default


def env_enabled() -> bool:
    """The master gate: ``SIDECAR_TPU_ANTIENTROPY=0`` routes every
    session straight to the full-body exchange (today's behavior)."""
    return os.environ.get("SIDECAR_TPU_ANTIENTROPY", "1") != "0"


class ChannelError(Exception):
    """A transport-level failure of one request attempt (retryable)."""


class ProtocolError(Exception):
    """The peer answered, but not in the session's language — a ladder
    mismatch, an error document, or a shape surprise (NOT retryable:
    the same request would fail the same way; fall back instead)."""


class SessionError(Exception):
    """A request exhausted its retry budget."""


class Channel:
    """Minimal request/response transport the session drives.  One
    ``send`` is one attempt; raise :class:`ChannelError` (or
    ``TimeoutError``/``OSError``) to signal a retryable failure."""

    def send(self, doc: dict, timeout: float) -> dict:
        raise NotImplementedError


class LoopbackChannel(Channel):
    """In-process channel onto a responder — the test/bench transport.
    ``fail`` is an optional hook called per attempt (raise from it to
    inject channel failures deterministically)."""

    def __init__(self, responder: "AntiEntropyResponder",
                 fail: Optional[Callable[[dict], None]] = None):
        self.responder = responder
        self.fail = fail
        self.requests: List[dict] = []

    def send(self, doc: dict, timeout: float) -> dict:
        self.requests.append(doc)
        if self.fail is not None:
            self.fail(doc)
        return self.responder.handle(doc)


def _doc_bytes(doc: dict) -> int:
    return len(json.dumps(doc, separators=(",", ":")).encode())


def _bucket_hex(value: tuple, bucket: int) -> str:
    return f"{value[2 * bucket]:08x}{value[2 * bucket + 1]:08x}"


def deliver_records(state, docs, origin: str = "") -> int:
    """Apply a list of Service JSON docs through the LWW merge kernel
    (synchronous — the session's VERIFY step must observe the result).
    Malformed records are skipped loudly, never fatally: one bad
    record must not abort the whole reconciliation."""
    n = 0
    for d in docs:
        try:
            svc = Service.from_json(d)
        except (TypeError, ValueError, KeyError, AttributeError) as exc:
            log.warning("anti-entropy: dropping malformed record from "
                        "%s: %s", origin or "peer", exc)
            continue
        state.add_service_entry(svc)
        n += 1
    if n:
        metrics.incr("antientropy.records", n)
    return n


class AntiEntropyResponder:
    """The passive side of a session: answers HELLO / LEVEL / PULL /
    FULL requests against one :class:`ServicesState`.  Stateless
    between requests — every answer is computed from the catalog as it
    is now, so a responder can serve many concurrent initiators."""

    def __init__(self, state):
        self.state = state

    def handle(self, doc: dict) -> dict:
        try:
            kind = doc.get("T")
            if kind == "hello":
                return self._hello()
            if kind == "level":
                return self._level(doc)
            if kind == "pull":
                return self._pull(doc)
            if kind == "full":
                return self._full(doc)
            return {"T": "error", "Reason": f"unknown request {kind!r}"}
        except Exception as exc:  # noqa: BLE001 — answer, don't kill
            log.warning("anti-entropy responder error: %s", exc)
            return {"T": "error", "Reason": str(exc)}

    def _hello(self) -> dict:
        base, depth = self.state.ladder_geometry()
        count, value = self.state.digest_snapshot
        return {"T": "hello", "Base": base, "Depth": depth,
                "Records": count,
                "Hex": digest_ops.digest_to_hex(value)}

    def _level(self, doc: dict) -> dict:
        level = int(doc["Level"])
        _, depth = self.state.ladder_geometry()
        if not 0 < level < depth:
            return {"T": "error", "Reason": f"bad level {level}"}
        mine = self.state.digest_level(level)
        diff = []
        for raw_id, hex16 in doc["Buckets"].items():
            b = int(raw_id)
            if _bucket_hex(mine, b) != hex16:
                diff.append(b)
        return {"T": "level", "Level": level, "Diff": sorted(diff)}

    def _pull(self, doc: dict) -> dict:
        leaf = int(doc["Leaf"])
        buckets = [int(b) for b in doc["Buckets"]]
        # Capture OUR divergent view BEFORE merging the initiator's
        # records — afterwards the buckets would contain their records
        # too and the reply would echo bytes the peer already has.
        mine = self.state.services_in_buckets(buckets, leaf)
        deliver_records(self.state, doc.get("Services") or (),
                        origin=str(doc.get("From") or ""))
        return {"T": "push",
                "Services": [svc.to_json() for svc in mine]}

    def _full(self, doc: dict) -> dict:
        # Capture our body BEFORE merging theirs (the _pull convention).
        body = json.loads(self.state.encode_annotated())
        merge_body(self.state, doc.get("Body"))
        return {"T": "full", "Body": body}


def merge_body(state, body) -> int:
    """Merge a full-state JSON document (the ``encode_annotated`` wire
    form) synchronously: harvest the coherence annotation like
    ``merge()`` does, then run every record through the LWW kernel."""
    if not isinstance(body, dict):
        raise ProtocolError("full-body exchange: body is not an object")
    remote = state_mod.decode(json.dumps(body))
    origin = remote.hostname
    if origin and origin != state.hostname and remote.wire_digest:
        _coherence.observe_doc(origin, remote.wire_digest,
                               now_ns=state._now())
    n = 0
    for _, _, svc in remote.each_service_sorted():
        state.add_service_entry(svc.copy())
        n += 1
    return n


@dataclasses.dataclass
class SessionConfig:
    """Retry/backoff discipline for one session.  Defaults come from
    the ``SIDECAR_TPU_ANTIENTROPY*`` env knobs at construction."""

    retries: int = 3           # extra attempts per request
    timeout_s: float = 2.0     # per-attempt budget handed to the channel
    backoff_ms: float = 50.0   # base delay; attempt k waits base * 2^k
    jitter: float = 0.5        # uniform [0, jitter) multiplier on top
    verify: bool = True        # seal with a second level-0 compare

    @classmethod
    def from_env(cls) -> "SessionConfig":
        return cls(
            retries=_env_int("SIDECAR_TPU_ANTIENTROPY_RETRIES", 3),
            timeout_s=_env_float("SIDECAR_TPU_ANTIENTROPY_TIMEOUT_S",
                                 2.0, lo=0.001),
            backoff_ms=_env_float("SIDECAR_TPU_ANTIENTROPY_BACKOFF_MS",
                                  50.0))


@dataclasses.dataclass
class SessionReport:
    """What one session did — the bench's raw material.  ``mode`` is
    ``digest`` (ladder walk ran), ``full`` (fallback or plain-wire
    full-body exchange), or ``failed`` (even the fallback failed)."""

    mode: str = "digest"
    coherent: Optional[bool] = None
    bytes_sent: int = 0
    bytes_received: int = 0
    digest_bytes: int = 0      # hello/level/verify traffic
    record_bytes: int = 0      # pull/push/full traffic
    records_sent: int = 0
    records_received: int = 0
    levels_walked: int = 0
    retries: int = 0
    fallback_reason: Optional[str] = None
    states: List[str] = dataclasses.field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return self.bytes_sent + self.bytes_received


class ReconcileSession:
    """One initiator-side reconciliation against one peer channel.

    ``peer_doc`` — the peer's ``"Digest"`` annotation when already
    known (harvested from a previous push-pull body): a peer without a
    ``"Ladder"`` advertisement is version-gated straight to the
    full-body exchange without burning a hello round-trip.
    ``rng``/``sleep`` are injectable for deterministic backoff tests.
    """

    def __init__(self, state, channel: Channel,
                 config: Optional[SessionConfig] = None,
                 peer_doc: Optional[dict] = None,
                 enabled: Optional[bool] = None,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.state = state
        self.channel = channel
        self.cfg = config or SessionConfig.from_env()
        self.peer_doc = peer_doc
        self.enabled = env_enabled() if enabled is None else enabled
        self._rng = rng or random.Random()
        self._sleep = sleep
        self.report = SessionReport()

    # -- retry/backoff spine ------------------------------------------------

    def _send(self, doc: dict, kind: str) -> dict:
        """One request with the session's retry discipline.  ``kind``
        routes byte accounting (digest vs record traffic)."""
        last: Optional[BaseException] = None
        for attempt in range(self.cfg.retries + 1):
            if attempt:
                delay_ms = self.cfg.backoff_ms * (2 ** (attempt - 1))
                delay_ms *= 1.0 + self.cfg.jitter * self._rng.random()
                metrics.histogram("antientropy.backoff_ms", delay_ms)
                metrics.incr("antientropy.retries")
                self.report.retries += 1
                self._sleep(delay_ms / 1000.0)
            try:
                resp = self.channel.send(doc, timeout=self.cfg.timeout_s)
            except (ChannelError, TimeoutError, OSError) as exc:
                last = exc
                log.warning("anti-entropy %s attempt %d/%d failed: %s",
                            doc.get("T"), attempt + 1,
                            self.cfg.retries + 1, exc)
                continue
            sent = _doc_bytes(doc)
            got = _doc_bytes(resp) if isinstance(resp, dict) else 0
            self.report.bytes_sent += sent
            self.report.bytes_received += got
            if kind == "digest":
                self.report.digest_bytes += sent + got
            else:
                self.report.record_bytes += sent + got
            metrics.incr("antientropy.bytes", sent + got)
            if not isinstance(resp, dict):
                raise ProtocolError(f"non-object response to "
                                    f"{doc.get('T')!r}")
            if resp.get("T") == "error":
                raise ProtocolError(str(resp.get("Reason")))
            return resp
        raise SessionError(
            f"{doc.get('T')!r} failed after {self.cfg.retries + 1} "
            f"attempts: {last}")

    # -- the state machine --------------------------------------------------

    def run(self) -> SessionReport:
        metrics.incr("antientropy.sessions")
        rep = self.report
        if not self.enabled:
            return self._full_body("disabled")
        if self.peer_doc is not None and \
                not isinstance(self.peer_doc.get("Ladder"), dict):
            # Version gate: the peer never advertised a ladder — it
            # speaks today's full-body wire, so give it exactly that.
            metrics.incr("antientropy.plainwire")
            return self._full_body("plain-wire peer", plain=True)
        try:
            return self._digest_directed()
        except (ProtocolError, SessionError) as exc:
            metrics.incr("antientropy.fallbacks")
            log.warning(
                "anti-entropy: digest-directed session failed (%s) — "
                "falling back to ONE full-body exchange", exc)
            return self._full_body(str(exc))
        finally:
            rep.states.append("DONE" if rep.mode != "failed"
                              else "FAILED")

    def _digest_directed(self) -> SessionReport:
        rep = self.report
        base, depth = self.state.ladder_geometry()
        leaf_buckets = base << (depth - 1)

        rep.states.append("HELLO")
        hello = self._send({"T": "hello", "Base": base, "Depth": depth,
                            "From": self.state.hostname,
                            "Hex": digest_ops.digest_to_hex(
                                self.state.digest_snapshot[1])},
                           "digest")
        try:
            if int(hello["Base"]) != base or int(hello["Depth"]) != depth:
                raise ProtocolError(
                    f"ladder mismatch: peer ({hello.get('Base')}, "
                    f"{hello.get('Depth')}) vs local ({base}, {depth})")
            theirs0 = digest_ops.digest_from_hex(str(hello["Hex"]))
        except (KeyError, TypeError, ValueError) as exc:
            raise ProtocolError(f"malformed hello: {exc}") from exc
        mine0 = self.state.digest_level(0)
        if len(theirs0) != len(mine0):
            raise ProtocolError("ladder mismatch: level-0 width")
        diff = digest_ops.diff_bucket_ids(mine0, theirs0)
        if not diff:
            rep.coherent = True
            return rep

        rep.states.append("NARROW")
        for level in range(1, depth):
            children = sorted(c for b in diff for c in (2 * b, 2 * b + 1))
            mine = self.state.digest_level(level)
            resp = self._send(
                {"T": "level", "Level": level,
                 "Buckets": {str(c): _bucket_hex(mine, c)
                             for c in children}},
                "digest")
            try:
                diff = sorted(int(b) for b in resp["Diff"])
            except (KeyError, TypeError, ValueError) as exc:
                raise ProtocolError(
                    f"malformed level response: {exc}") from exc
            rep.levels_walked += 1
            if not diff:
                break

        if diff:
            rep.states.append("TRANSFER")
            mine_recs = self.state.services_in_buckets(diff, leaf_buckets)
            rep.records_sent = len(mine_recs)
            resp = self._send(
                {"T": "pull", "Leaf": leaf_buckets, "Buckets": diff,
                 "From": self.state.hostname,
                 "Services": [svc.to_json() for svc in mine_recs]},
                "record")
            rep.records_received = deliver_records(
                self.state, resp.get("Services") or (), origin="peer")

        if self.cfg.verify:
            rep.states.append("VERIFY")
            seal = self._send({"T": "hello", "Base": base,
                               "Depth": depth}, "digest")
            rep.coherent = (str(seal.get("Hex")) ==
                            digest_ops.digest_to_hex(
                                self.state.digest_snapshot[1]))
        return rep

    def _full_body(self, reason: str, plain: bool = False
                   ) -> SessionReport:
        rep = self.report
        rep.mode = "full"
        rep.fallback_reason = reason
        rep.states.append("FULL")
        body = json.loads(self.state.encode_annotated()
                          if not plain else self.state.encode())
        try:
            resp = self._send({"T": "full", "Body": body}, "record")
            got = merge_body(self.state, resp.get("Body"))
            rep.records_received = got
            rep.coherent = None   # a one-shot body proves nothing
        except (ProtocolError, SessionError) as exc:
            rep.mode = "failed"
            rep.coherent = False
            metrics.incr("antientropy.failures")
            log.error("anti-entropy: full-body fallback failed: %s", exc)
        return rep


def reconcile(state, channel: Channel, **kw) -> SessionReport:
    """Run one session (the module's one-call surface)."""
    return ReconcileSession(state, channel, **kw).run()
