"""ctypes bridge to the native gossip engine + the delegate loop.

The C++ core (native/transport.cc) owns the sockets and IO threads:
UDP gossip with first-fit ~1398 B packet packing, SWIM-lite ping/ack
failure detection, and TCP full-state push-pull.  This module is the
Python half of the reference's ``servicesDelegate``
(services_delegate.go:16-223):

* outbound — drains ``state.broadcasts`` into the native queue
  (GetBroadcasts feeding the gossip timer) and keeps the engine's
  local-state snapshot fresh for push-pull replies (LocalState);
* inbound — polls received service records into
  ``state.update_service`` (NotifyMsg → the single-writer merge queue),
  full push-pull payloads into ``state.merge`` (MergeRemoteState), and
  membership leave events into ``state.expire_server`` (NotifyLeave).
"""

from __future__ import annotations

import ctypes
import logging
import pathlib
import queue as queue_mod
import random
import subprocess
import threading
import time
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu import service as svc_mod
from sidecar_tpu.catalog import ServicesState, decode
from sidecar_tpu.telemetry.span import span as _span

log = logging.getLogger(__name__)

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_SO_PATH = _NATIVE_DIR / "build" / "libsidecar_transport.so"

_lib = None
_lib_lock = threading.Lock()


def load_native() -> ctypes.CDLL:
    """Load (building or rebuilding if stale) the native transport
    library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = _NATIVE_DIR / "transport.cc"
        if not _SO_PATH.exists() or (
                src.exists()
                and src.stat().st_mtime > _SO_PATH.stat().st_mtime):
            log.info("Building native transport library...")
            result = subprocess.run(["make"], cwd=str(_NATIVE_DIR),
                                    capture_output=True)
            if result.returncode != 0:
                err = result.stderr.decode(errors="replace")
                usable_prebuilt = False
                if _SO_PATH.exists():
                    # Toolchain-less host with a prebuilt (if stale-
                    # looking) library: usable only if it already has the
                    # full current ABI — probe the newest symbol, else the
                    # argtypes setup below would die with a confusing
                    # AttributeError instead of the build error.
                    try:
                        probe = ctypes.CDLL(str(_SO_PATH))
                        usable_prebuilt = \
                            hasattr(probe, "st_next_state_len") \
                            and hasattr(probe, "st_configure_probe") \
                            and hasattr(probe, "st_poll_log") \
                            and hasattr(probe, "st_stats") \
                            and hasattr(probe, "st_set_handoff_depth")
                    except OSError:
                        # Unloadable (corrupt/wrong-arch) prebuilt: fall
                        # through to the RuntimeError that carries the
                        # actionable compiler output.
                        usable_prebuilt = False
                if usable_prebuilt:
                    log.warning("Native transport rebuild failed; using "
                                "existing library. Build output:\n%s", err)
                else:
                    raise RuntimeError(
                        f"native transport build failed:\n{err}")
        lib = ctypes.CDLL(str(_SO_PATH))
        lib.st_create.restype = ctypes.c_void_p
        lib.st_create.argtypes = [ctypes.c_char_p] * 3 + [ctypes.c_int] + \
            [ctypes.c_char_p] + [ctypes.c_int] * 4
        lib.st_start.restype = ctypes.c_int
        lib.st_start.argtypes = [ctypes.c_void_p]
        lib.st_join.restype = ctypes.c_int
        lib.st_join.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int]
        lib.st_broadcast.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.st_set_local_state.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int]
        lib.st_configure_probe.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_int] * 4
        lib.st_set_handoff_depth.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int]
        lib.st_test_drop_types.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_uint]
        for fn in (lib.st_poll_msg, lib.st_poll_state, lib.st_poll_event,
                   lib.st_poll_log, lib.st_members):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.st_next_state_len.restype = ctypes.c_int
        lib.st_next_state_len.argtypes = [ctypes.c_void_p]
        lib.st_stats.restype = ctypes.c_int
        lib.st_stats.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_ulonglong),
                                 ctypes.c_int]
        lib.st_port.restype = ctypes.c_int
        lib.st_port.argtypes = [ctypes.c_void_p]
        lib.st_stop.argtypes = [ctypes.c_void_p]
        lib.st_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


# Packet-type bits for the engine's test-only one-way packet-drop hook
# (st_test_drop_types masks received packets by type).  DROP_PUSH_PULL
# refuses the node's TCP anti-entropy exchanges (native kTypePushPull),
# so an injected partition severs push-pull exactly like UDP gossip.
DROP_GOSSIP = 1 << 0
DROP_PING = 1 << 1
DROP_ACK = 1 << 2
DROP_PING_REQ = 1 << 3
DROP_ACK_FWD = 1 << 4
DROP_PUSH_PULL = 1 << 5
DROP_ALL_UDP = DROP_GOSSIP | DROP_PING | DROP_ACK | DROP_PING_REQ | \
    DROP_ACK_FWD
DROP_ALL = DROP_ALL_UDP | DROP_PUSH_PULL

_LOG_LEVELS = {"E": logging.ERROR, "W": logging.WARNING,
               "I": logging.INFO, "D": logging.DEBUG}


class GossipTransport:
    """The memberlist-equivalent: owns a native engine instance and the
    delegate threads wiring it to a ServicesState."""

    def __init__(self, node_name: Optional[str] = None,
                 cluster_name: str = "default",
                 bind_ip: str = "0.0.0.0", bind_port: int = 7946,
                 advertise_ip: str = "127.0.0.1",
                 gossip_interval: float = 0.2,
                 push_pull_interval: float = 20.0,
                 gossip_nodes: int = 3,
                 gossip_messages: int = 15,
                 probe_interval: float = 0.0,
                 probe_timeout: float = 0.0,
                 suspect_timeout: float = 0.0,
                 indirect_probes: int = -1,
                 handoff_queue_depth: int = 1024,
                 fault_injector=None,
                 max_pending_broadcasts: int = 4096,
                 push_pull_retries: int = 3,
                 push_pull_backoff_ms: float = 100.0,
                 push_pull_jitter: float = 0.5,
                 push_pull_attempt_timeout: float = 5.0) -> None:
        import socket

        self.node_name = node_name or socket.gethostname()
        self.cluster_name = cluster_name
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.advertise_ip = advertise_ip or "127.0.0.1"
        self.gossip_interval = gossip_interval
        self.push_pull_interval = push_pull_interval
        self.gossip_nodes = gossip_nodes
        self.gossip_messages = gossip_messages
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.suspect_timeout = suspect_timeout
        self.indirect_probes = indirect_probes
        # memberlist HandoffQueueDepth (config/config.go:48): bound on
        # the engine's received-record queue; a stalled consumer sheds
        # the oldest records and anti-entropy re-delivers them.  Loud on
        # nonsense: the engine would silently keep its default and an
        # operator expecting "0 = unbounded" would be shedding at a
        # bound they believe they disabled.
        if handoff_queue_depth <= 0:
            raise ValueError(
                f"handoff_queue_depth must be positive, got "
                f"{handoff_queue_depth} (there is no unbounded mode)")
        self.handoff_queue_depth = handoff_queue_depth
        # Chaos injection shim (sidecar_tpu/chaos/live_inject.py): an
        # object with on_recv/due_records/filter_send, consulted at the
        # send/recv boundary.  None = no injection.
        self.fault_injector = fault_injector
        # Outbound backlog bound: state.broadcasts is unbounded at the
        # producer side (the catalog never blocks on a slow transport),
        # so the BRIDGE enforces the bound — a partitioned or paused
        # node sheds its OLDEST pending broadcasts (freshest-wins, like
        # the native queue's own 4096 cap) and counts them.
        if max_pending_broadcasts <= 0:
            raise ValueError("max_pending_broadcasts must be positive")
        self.max_pending_broadcasts = max_pending_broadcasts
        # Push-pull client retry discipline (the anti-entropy session's
        # backoff contract, transport/antientropy.py): a failed seed
        # join/exchange gets push_pull_retries extra attempts, each
        # under push_pull_attempt_timeout, separated by exponential
        # backoff (base push_pull_backoff_ms, doubled per attempt) plus
        # uniform jitter so a partition heal doesn't produce a
        # thundering herd of simultaneous redials.
        if push_pull_retries < 0:
            raise ValueError("push_pull_retries must be >= 0")
        self.push_pull_retries = push_pull_retries
        self.push_pull_backoff_ms = push_pull_backoff_ms
        self.push_pull_jitter = push_pull_jitter
        self.push_pull_attempt_timeout = push_pull_attempt_timeout
        # Injectable for deterministic backoff tests.
        self._retry_rng = random.Random()
        self._lib = load_native()
        self._handle: Optional[int] = None
        self._quit = threading.Event()
        self._threads: list[threading.Thread] = []
        self.state: Optional[ServicesState] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self, state: ServicesState,
              seeds: Optional[list[str]] = None) -> int:
        """Bind sockets, start IO + delegate threads, join seeds.
        Returns the bound port."""
        self.state = state
        self._handle = self._lib.st_create(
            self.node_name.encode(), self.cluster_name.encode(),
            self.bind_ip.encode(), self.bind_port,
            self.advertise_ip.encode(),
            int(self.gossip_interval * 1000),
            int(self.push_pull_interval * 1000),
            self.gossip_nodes, self.gossip_messages)
        self._lib.st_configure_probe(
            self._handle, int(self.probe_interval * 1000),
            int(self.probe_timeout * 1000),
            int(self.suspect_timeout * 1000), self.indirect_probes)
        self._lib.st_set_handoff_depth(self._handle,
                                       self.handoff_queue_depth)
        port = self._lib.st_start(self._handle)
        if port < 0:
            raise OSError(
                f"failed to start gossip transport on "
                f"{self.bind_ip}:{self.bind_port}")
        self.bind_port = port
        self._push_local_state()

        t = threading.Thread(target=self._bridge_loop,
                             name="gossip-bridge", daemon=True)
        t.start()
        self._threads.append(t)

        for seed in seeds or []:
            host, _, port_s = seed.partition(":")
            self.join_with_retry(host, int(port_s) if port_s else 7946)
        return port

    def join(self, host: str, port: int = 7946) -> None:
        """TCP dial + full-state exchange (memberlist.Join) — ONE
        attempt; raises OSError on failure (callers that want the
        retry discipline use :meth:`join_with_retry`)."""
        if self._lib.st_join(self._handle, host.encode(), port) != 0:
            raise OSError(f"join {host}:{port} failed")

    def _join_once(self, host: str, port: int, timeout: float) -> None:
        """One join attempt under a per-attempt timeout.  ``st_join``
        is a blocking native call (TCP dial + full-state exchange), so
        it runs on a worker thread; on timeout the attempt is charged
        as failed while the dial is left to die in the background (a
        blocking C call cannot be cancelled — the engine's own socket
        timeouts reap it)."""
        outcome: list = []

        def work() -> None:
            try:
                self.join(host, port)
                outcome.append(None)
            except OSError as exc:
                outcome.append(exc)

        t = threading.Thread(target=work, name="gossip-join",
                             daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive():
            raise OSError(
                f"join {host}:{port} timed out after {timeout:.1f}s")
        if outcome and outcome[0] is not None:
            raise outcome[0]

    def join_with_retry(self, host: str, port: int = 7946) -> bool:
        """Seed-join with bounded retries, per-attempt timeout, and
        exponential backoff + jitter.  Before this, a failed seed join
        surfaced as ONE log line and the node waited a full
        ``push_pull_interval`` (20 s default) for anti-entropy to
        rescue it — the slowest, most fragile part of partition heal.
        Returns True on success; exhaustion is counted
        (``transport.pushpull.failures``), never silent."""
        last: Optional[OSError] = None
        for attempt in range(self.push_pull_retries + 1):
            if attempt:
                delay_ms = self.push_pull_backoff_ms * (2 ** (attempt - 1))
                delay_ms *= 1.0 + self.push_pull_jitter \
                    * self._retry_rng.random()
                metrics.histogram("transport.pushpull.backoff_ms",
                                  delay_ms)
                metrics.incr("transport.pushpull.retries")
                if self._quit.wait(delay_ms / 1000.0):
                    break   # stopping — don't redial a dead transport
            try:
                self._join_once(host, port,
                                self.push_pull_attempt_timeout)
                return True
            except OSError as exc:
                last = exc
                log.warning("Join %s:%d attempt %d/%d failed: %s",
                            host, port, attempt + 1,
                            self.push_pull_retries + 1, exc)
        metrics.incr("transport.pushpull.failures")
        log.warning("Giving up on seed %s:%d after %d attempts: %s",
                    host, port, self.push_pull_retries + 1, last)
        return False

    def stop(self) -> None:
        self._quit.set()
        # The delegate threads poll the native handle; join them before
        # destroying it or st_poll_* races a freed Transport.
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        if self._handle is not None:
            self._lib.st_stop(self._handle)
            self._lib.st_destroy(self._handle)
            self._handle = None

    def members(self) -> list[str]:
        """memberlist.Members — node names incl. ourselves."""
        if self._handle is None:
            return [self.node_name]
        buf = ctypes.create_string_buffer(1 << 20)
        n = self._lib.st_members(self._handle, buf, len(buf))
        return [m for m in buf.raw[:n].decode().split("\n") if m]

    def test_drop_types(self, node: str, type_mask: int) -> None:
        """Test-only one-way fault injection: drop received packets of
        the masked types (DROP_* bits) coming from ``node``."""
        if self._handle is not None:
            self._lib.st_test_drop_types(self._handle, node.encode(),
                                         type_mask)

    # -- delegate loops ----------------------------------------------------

    def _push_local_state(self) -> None:
        """Refresh the engine's LocalState snapshot
        (services_delegate.go:146-151).  The push-pull body carries the
        coherence-digest annotation (catalog/state.encode_annotated —
        Go peers ignore the extra key); plain encode() is the fallback
        for bare state doubles in tests."""
        if self.state is not None and self._handle is not None:
            enc = getattr(self.state, "encode_annotated", None) \
                or self.state.encode
            data = enc()
            self._lib.st_set_local_state(self._handle, data, len(data))

    # Engine stats order (native/transport.cc Transport::stats).  An
    # older prebuilt library returns fewer values; zip's [:n] clamp
    # keeps the bridge compatible either way.
    _STAT_NAMES = ("engine.udpOut", "engine.udpBytesOut", "engine.udpIn",
                   "engine.udpBytesIn", "engine.pushPullOut",
                   "engine.pushPullIn", "engine.udpSendDrops")

    def _poll_engine_stats(self) -> None:
        vals = (ctypes.c_ulonglong * len(self._STAT_NAMES))()
        n = self._lib.st_stats(self._handle, vals, len(vals))
        for name, val in zip(self._STAT_NAMES[:n], vals[:n]):
            metrics.set_gauge(name, int(val))

    # Inbound shed backoff: how long (and how often) the bridge is
    # willing to wait on a full single-writer queue before shedding the
    # record.  Total worst-case stall per record: retries × timeout —
    # kept far below the gossip interval so backpressure never turns
    # into bridge-loop wedge (anti-entropy re-delivers shed records).
    INBOUND_PUT_RETRIES = 3
    INBOUND_PUT_TIMEOUT = 0.005

    def _deliver_inbound(self, svc) -> None:
        """Hand a record to the single-writer merge queue with bounded
        backoff instead of a blocking put: a stalled writer (the chaos
        scenarios provoke this on purpose) must not wedge the shared
        bridge thread.  After the retries the record is SHED and
        counted — silent degradation is the failure mode this replaces."""
        for _ in range(self.INBOUND_PUT_RETRIES):
            if self.state.offer_service(svc,
                                        timeout=self.INBOUND_PUT_TIMEOUT):
                return
            if self._quit.is_set():
                return
        metrics.incr("transport.shedInbound")
        log.warning("Single-writer queue full; shedding inbound record "
                    "%s (anti-entropy will re-deliver)", svc.id)

    def _shed_broadcast_backlog(self) -> None:
        """Enforce the outbound bound: drop the OLDEST pending
        broadcast batches beyond ``max_pending_broadcasts`` (stalest
        records lose; push-pull still carries them) and count the shed."""
        q = self.state.broadcasts
        shed = 0
        while q.qsize() > self.max_pending_broadcasts:
            try:
                q.get_nowait()
            except queue_mod.Empty:
                break
            shed += 1
        if shed:
            metrics.incr("transport.shedBroadcasts", shed)
            log.warning("Outbound broadcast backlog over %d; shed %d "
                        "oldest batches", self.max_pending_broadcasts, shed)

    def _bridge_loop(self) -> None:
        """ONE delegate thread for both directions ("few execution
        threads", reference README:54-56): outbound drains
        state.broadcasts into the native queue (GetBroadcasts feed,
        timed + gauged like the reference delegate,
        services_delegate.go:86-87); inbound drains the native queues
        into the catalog (NotifyMsg / MergeRemoteState / NotifyLeave)
        plus the engine-diagnostics log bridge
        (logging_bridge.go:25-53).  The outbound queue get doubles as
        the idle sleep — but ONLY when the previous cycle's inbound
        drain went idle: while inbound is backed up the loop spins
        without the 20 ms wait, so a sustained burst drains at full
        rate instead of ~3.2k msgs/s (64 records / 20 ms).  The chaos
        fault injector (when installed) is consulted on every decoded
        inbound record and outbound batch."""
        buf = ctypes.create_string_buffer(1 << 22)
        last_state_push = 0.0
        inbound_backlogged = False
        while not self._quit.is_set():
            # -- outbound ---------------------------------------------------
            try:
                if inbound_backlogged:
                    prepared = self.state.broadcasts.get_nowait()
                else:
                    prepared = self.state.broadcasts.get(timeout=0.02)
            except queue_mod.Empty:
                prepared = None
            if self._quit.is_set():
                return
            if prepared and self.fault_injector is not None:
                prepared = self.fault_injector.filter_send(prepared)
            if prepared:
                t0 = time.perf_counter()
                for payload in prepared:
                    self._lib.st_broadcast(self._handle, payload,
                                           len(payload))
                metrics.measure_since("getBroadcasts", t0)
            self._shed_broadcast_backlog()
            metrics.set_gauge("pendingBroadcasts",
                              self.state.broadcasts.qsize())
            now = time.monotonic()
            if now - last_state_push > 1.0:
                self._push_local_state()
                self._poll_engine_stats()
                last_state_push = now

            # Chaos: release injector-delayed records whose time came.
            if self.fault_injector is not None:
                for svc in self.fault_injector.due_records():
                    self._deliver_inbound(svc)

            # -- inbound — drain, BOUNDED per cycle so sustained inbound
            # traffic cannot starve the outbound half above (fairness on
            # the shared thread; leftovers are picked up next cycle).
            busy = True
            drained = 0
            while busy and drained < 64 and not self._quit.is_set():
                drained += 1
                busy = False

                n = self._lib.st_poll_msg(self._handle, buf, len(buf))
                if n > 0:
                    busy = True
                    t0 = time.perf_counter()
                    # Receive-side span: decode + hand-off to the
                    # single-writer merge queue.  The merge itself runs
                    # on the writer thread, so it starts its OWN trace
                    # (the queue boundary — docs/telemetry.md); the
                    # queue's `transport.shedInbound` accounting covers
                    # the hand-off.
                    with _span("gossip.receive"):
                        try:
                            svc = svc_mod.decode(buf.raw[:n])
                            if self.fault_injector is not None:
                                records = self.fault_injector.on_recv(svc)
                            else:
                                records = (svc,)
                            for record in records:
                                self._deliver_inbound(record)
                        except ValueError as exc:
                            log.warning("Error decoding gossip message: %s",
                                        exc)
                    metrics.measure_since("notifyMsg", t0)

                # Full-state payloads are unbounded (LocalState is the whole
                # catalog) — size the read from the engine's queue so a large
                # cluster's push-pull can't be silently truncated.
                need = self._lib.st_next_state_len(self._handle)
                if need > 0:
                    sbuf = buf if need <= len(buf) else \
                        ctypes.create_string_buffer(need)
                    n = self._lib.st_poll_state(self._handle, sbuf, len(sbuf))
                    if n > 0:
                        busy = True
                        # Chaos: a paused/crashed node merges nothing —
                        # the full-state path bypasses the per-record
                        # shim, so it gets its own gate.
                        if self.fault_injector is not None and \
                                not self.fault_injector.accept_push_pull():
                            continue
                        t0 = time.perf_counter()
                        try:
                            remote = decode(sbuf.raw[:n])
                            self.state.merge(remote)
                        except (ValueError, KeyError) as exc:
                            log.warning("Error merging remote state: %s", exc)
                        metrics.measure_since("mergeRemoteState", t0)

                n = self._lib.st_poll_log(self._handle, buf, len(buf))
                if n > 0:
                    busy = True
                    line = buf.raw[:n].decode(errors="replace")
                    level, _, msg = line.partition("|")
                    log.log(_LOG_LEVELS.get(level, logging.INFO),
                            "engine: %s", msg)

                n = self._lib.st_poll_event(self._handle, buf, len(buf))
                if n > 0:
                    busy = True
                    parts = buf.raw[:n].decode().split()
                    if parts and parts[0] == "leave" and len(parts) > 1:
                        log.info("Member left: %s", parts[1])
                        threading.Thread(
                            target=self.state.expire_server, args=(parts[1],),
                            daemon=True).start()
                    elif parts and parts[0] == "join" and len(parts) > 1:
                        log.info("Member joined: %s", parts[1])

            # Exited the bounded drain with work still pending (the cap
            # tripped while busy): skip the next cycle's outbound idle
            # wait so the backlog keeps draining at full rate.
            inbound_backlogged = busy

