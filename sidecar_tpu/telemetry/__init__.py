"""The host-side telemetry plane (docs/telemetry.md).

Four surfaces, one package:

* :mod:`sidecar_tpu.telemetry.span` — the lightweight span tracer: a
  thread-safe ring buffer of timed, parent/child-linked spans across
  the live propagation path (gossip receive → catalog merge → snapshot
  publish → watcher delivery), served as JSON at ``GET /api/trace``.
* :mod:`sidecar_tpu.telemetry.prometheus` — Prometheus text exposition
  of the metrics registry (``GET /metrics``), histogram quantiles
  included.
* :mod:`sidecar_tpu.telemetry.profiling` — ``jax.profiler`` trace
  hooks behind ``SIDECAR_TPU_PROFILE_DIR`` (bench.py north-star chunks
  and ``SimBridge`` dispatches annotate themselves when it is set).
* :mod:`sidecar_tpu.telemetry.cost` — the kernel-cost observatory
  (docs/perf.md): ``sidecar.phase.*`` scoping, compiled-program
  cost/memory reports, profile-trace reduction, and the registry
  behind ``GET /api/cost.json``.

The jit-side half — the in-scan per-round :class:`RoundTrace` stream —
lives with the other device ops in :mod:`sidecar_tpu.ops.trace`.
"""

from sidecar_tpu.telemetry.prometheus import render_prometheus
from sidecar_tpu.telemetry.span import (span, spans, spans_since,
                                        reset_spans)

__all__ = ["render_prometheus", "span", "spans", "spans_since",
           "reset_spans"]
