"""Lightweight span tracer — end-to-end timing of the live path.

The histograms in :mod:`sidecar_tpu.metrics` answer "how long does ONE
site take"; spans answer "what did this event pass THROUGH": a record
arriving on gossip crosses receive → catalog merge → snapshot publish →
watcher delivery, and each hop records a span.  Spans on the same
thread nest (a span opened while another is active becomes its child
and shares its ``trace_id``), so one /trace read reconstructs the whole
causal chain of a delivery.

Deliberately tiny: a thread-local stack for parentage, one lock-guarded
ring buffer of COMPLETED spans (bounded — a quiet reader never grows
memory, a busy path overwrites oldest-first), plain dicts out.  No
cross-thread context propagation: a hop that crosses a queue starts a
new trace, which is exactly the boundary where the queue's own metrics
(``query.hub.*``, ``web.watch.dropped``) take over the story.

Served at ``GET /api/trace`` (web/api.py) newest-last; ``reset_spans``
exists for tests.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Optional

# Ring bound: ~1k completed spans ≈ a few seconds of a busy live path —
# enough to reconstruct recent deliveries, small enough to never matter.
RING_CAPACITY = 1024

_lock = threading.Lock()
_ring: "collections.deque[dict]" = collections.deque(maxlen=RING_CAPACITY)
_ids = itertools.count(1)
_tls = threading.local()


class span:
    """Context manager: ``with span("catalog.merge"): ...`` times the
    block and records it into the ring on exit.  Nested spans link to
    their parent and inherit its trace id."""

    __slots__ = ("name", "span_id", "parent_id", "trace_id",
                 "_t0", "_wall0")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        with _lock:
            self.span_id = next(_ids)
        parent: Optional[span] = stack[-1] if stack else None
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = parent.trace_id if parent is not None \
            else self.span_id
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        stack = getattr(_tls, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        with _lock:
            _ring.append({
                "name": self.name,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "trace_id": self.trace_id,
                "thread": threading.current_thread().name,
                "start_unix_s": round(self._wall0, 6),
                "duration_ms": round(dur_ms, 3),
                "error": exc_type is not None,
            })
        return False


def spans(limit: Optional[int] = None) -> list[dict]:
    """Completed spans, oldest first (the ring's natural order); with
    ``limit``, only the newest ``limit``."""
    with _lock:
        items = list(_ring)
    if limit is not None and limit >= 0:
        items = items[len(items) - min(limit, len(items)):]
    return items


def reset_spans() -> None:
    """Clear the ring (tests)."""
    with _lock:
        _ring.clear()
