"""Lightweight span tracer — end-to-end timing of the live path.

The histograms in :mod:`sidecar_tpu.metrics` answer "how long does ONE
site take"; spans answer "what did this event pass THROUGH": a record
arriving on gossip crosses receive → catalog merge → snapshot publish →
watcher delivery, and each hop records a span.  Spans on the same
thread nest (a span opened while another is active becomes its child
and shares its ``trace_id``), so one /trace read reconstructs the whole
causal chain of a delivery.

Deliberately tiny: a thread-local stack for parentage, one lock-guarded
ring buffer of COMPLETED spans (bounded — a quiet reader never grows
memory, a busy path overwrites oldest-first), plain dicts out.  No
cross-thread context propagation: a hop that crosses a queue starts a
new trace, which is exactly the boundary where the queue's own metrics
(``query.hub.*``, ``web.watch.dropped``) take over the story.

Served at ``GET /api/trace`` (web/api.py) newest-last; ``reset_spans``
exists for tests.

Every COMPLETED span carries a monotonic ``seq`` (assigned under the
ring lock at completion, so seq order == ring order).  ``spans_since``
is the cursor read behind ``GET /api/trace?since=<seq>``: spans with
``seq > since``, the resume cursor, and — because the ring overwrites
oldest-first — an explicit ``dropped`` count when the cursor fell
behind the ring (truncation is surfaced, never silent: the DeltaBatch
convention).  ``seq`` is a Python int and never wraps; it keeps
counting across ``reset_spans`` so stale cursors stay safe
(docs/telemetry.md).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Optional

# Ring bound: ~1k completed spans ≈ a few seconds of a busy live path —
# enough to reconstruct recent deliveries, small enough to never matter.
RING_CAPACITY = 1024

_lock = threading.Lock()
_ring: "collections.deque[dict]" = collections.deque(maxlen=RING_CAPACITY)
_ids = itertools.count(1)
# Completion-order cursor: assigned under the lock as a span enters the
# ring, so ring order and seq order agree (span_id is ENTRY order and
# can't page the ring — children complete before their parents).
# ``_last_seq`` mirrors the newest assigned value so the cursor survives
# an empty ring (reset, or everything evicted).
_seq = itertools.count(1)
_last_seq = 0
_tls = threading.local()


class span:
    """Context manager: ``with span("catalog.merge"): ...`` times the
    block and records it into the ring on exit.  Nested spans link to
    their parent and inherit its trace id."""

    __slots__ = ("name", "span_id", "parent_id", "trace_id",
                 "_t0", "_wall0")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        with _lock:
            self.span_id = next(_ids)
        parent: Optional[span] = stack[-1] if stack else None
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = parent.trace_id if parent is not None \
            else self.span_id
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        stack = getattr(_tls, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        global _last_seq
        with _lock:
            _last_seq = next(_seq)
            _ring.append({
                "seq": _last_seq,
                "name": self.name,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "trace_id": self.trace_id,
                "thread": threading.current_thread().name,
                "start_unix_s": round(self._wall0, 6),
                "duration_ms": round(dur_ms, 3),
                "error": exc_type is not None,
            })
        return False


def spans(limit: Optional[int] = None) -> list[dict]:
    """Completed spans, oldest first (the ring's natural order); with
    ``limit``, only the newest ``limit``."""
    with _lock:
        items = list(_ring)
    if limit is not None and limit >= 0:
        items = items[len(items) - min(limit, len(items)):]
    return items


def spans_since(since: int, limit: Optional[int] = None) -> dict:
    """Cursor read (``GET /api/trace?since=<seq>``): spans completed
    after cursor ``since``, OLDEST first so ``limit`` pages forward.

    Returns ``{"spans": [...], "next_since": s, "dropped": d}`` —
    resume with ``since=next_since`` to read exactly once.  ``dropped``
    counts spans the ring overwrote before this read (cursor fell more
    than RING_CAPACITY behind); it is never silent truncation.  With
    ``limit``, the FIRST ``limit`` matching spans are returned and
    ``next_since`` points at the last returned one, so a lagging
    reader catches up over successive pages."""
    since = max(0, int(since))
    with _lock:
        items = [s for s in _ring if s["seq"] > since]
        oldest = _ring[0]["seq"] if _ring else _last_seq + 1
        newest = _last_seq
    dropped = max(0, oldest - 1 - since)
    if limit is not None and limit >= 0:
        items = items[:limit]
    next_since = items[-1]["seq"] if items else max(since, newest)
    return {"spans": items, "next_since": next_since,
            "dropped": dropped}


def reset_spans() -> None:
    """Clear the ring (tests).  ``seq`` keeps counting — a cursor from
    before the reset stays valid and simply reads nothing new."""
    with _lock:
        _ring.clear()


def spans_to_chrome(span_dicts: list, pid: int = 1) -> list:
    """Chrome trace-event form of a span list (Perfetto / chrome://
    tracing loadable; ``GET /api/trace?format=chrome``).

    Each span becomes one complete ("X") event — ``ts``/``dur`` in
    microseconds per the trace-event spec — with its linkage ids
    (span/parent/trace, plus the ring ``seq``) riding in ``args``.
    Thread names map to stable small integer ``tid``s, announced via
    ``thread_name`` metadata events so the viewer shows real names."""
    tids: dict = {}
    events = []
    for s in span_dicts:
        thread = str(s.get("thread") or "main")
        tid = tids.setdefault(thread, len(tids) + 1)
        args = {k: s[k] for k in ("span_id", "parent_id", "trace_id",
                                  "seq") if s.get(k) is not None}
        if s.get("error"):
            args["error"] = s["error"]
        events.append({
            "name": s.get("name", ""),
            "cat": "span",
            "ph": "X",
            "ts": round(float(s.get("start_unix_s") or 0.0) * 1e6, 3),
            "dur": round(float(s.get("duration_ms") or 0.0) * 1e3, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": thread}}
            for thread, tid in tids.items()]
    return meta + events
