"""Prometheus text exposition of the metrics registry.

``GET /metrics`` (and ``/api/metrics``) serves the whole in-memory
registry in the Prometheus text format (version 0.0.4) so a standard
scraper sees the same numbers ``metrics.snapshot()`` reports:

* counters  → ``sidecar_<name>_total``  (TYPE counter)
* gauges    → ``sidecar_<name>``        (TYPE gauge)
* histograms → a summary family ``sidecar_<name>_ms`` with
  ``{quantile="0.5|0.95|0.99"}`` sample lines plus ``_sum``/``_count``
  (the reservoir's percentiles — docs/metrics.md)
* legacy timers → a summary with only ``_sum``/``_count`` (last-value
  timers have no distribution).  Timer entries mirrored from a
  histogram of the same name are skipped — the histogram family IS
  that metric, and Prometheus rejects duplicate families.

Metric names are sanitized to the Prometheus charset (dots and any
other invalid characters become underscores), which maps the dotted
registry names onto conventional Prometheus spellings
(``query.hub.published`` → ``sidecar_query_hub_published_total``).
"""

from __future__ import annotations

import re
from typing import Optional

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    clean = _INVALID.sub("_", name)
    if clean and clean[0].isdigit():
        clean = "_" + clean
    return f"sidecar_{clean}"


def _fmt(value) -> str:
    # Integral floats print as integers — scrapers accept both, humans
    # prefer "3" to "3.0".
    f = float(value)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snapshot: Optional[dict] = None) -> str:
    """The registry (or a pre-taken ``metrics.snapshot()``) as
    Prometheus exposition text."""
    if snapshot is None:
        from sidecar_tpu import metrics
        snapshot = metrics.snapshot()
    lines: list[str] = []

    for name in sorted(snapshot.get("counters", {})):
        metric = _sanitize(name) + "_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {_fmt(snapshot['counters'][name])}")

    for name in sorted(snapshot.get("gauges", {})):
        metric = _sanitize(name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_fmt(snapshot['gauges'][name])}")

    hists = snapshot.get("histograms", {})
    for name in sorted(hists):
        h = hists[name]
        metric = _sanitize(name) + "_ms"
        lines.append(f"# TYPE {metric} summary")
        for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                       ("0.99", "p99_ms")):
            lines.append(f'{metric}{{quantile="{q}"}} {_fmt(h[key])}')
        lines.append(f"{metric}_sum {_fmt(h['total_ms'])}")
        lines.append(f"{metric}_count {_fmt(h['count'])}")

    for name in sorted(snapshot.get("timers", {})):
        if name in hists:
            continue  # mirrored back-compat entry; the summary above IS it
        t = snapshot["timers"][name]
        metric = _sanitize(name) + "_ms"
        lines.append(f"# TYPE {metric} summary")
        lines.append(f"{metric}_sum {_fmt(t['total_ms'])}")
        lines.append(f"{metric}_count {_fmt(t['count'])}")

    return "\n".join(lines) + "\n"
