"""Live-path propagation lag — the sim provenance plane's live twin.

The simulator's record-level provenance tracer (ops/provenance.py)
answers "how many rounds until record X reached everyone, and through
whom".  A live node cannot see other nodes' receive times, but it CAN
see its own: every gossiped record carries its origin's wall-clock
``Updated`` stamp, so ``merge time − record stamp`` at this node IS the
propagation lag of that record's path to us — the same quantity the
sim's per-record first_seen lag measures in rounds (docs/telemetry.md).

Two observation sites, mirroring the sim's round/coverage split:

* ``catalog`` — the catalog writer admitted a remote record
  (``ServicesState._add_service_entry``): gossip transport + merge lag.
* ``query``  — the QueryHub published the change to subscribers
  (``QueryHub.publish``): end-to-end lag to the query plane, the stamp
  a /watch consumer's view trails the origin by.

Each observation lands in a pooled ``propagation.<site>.lag``
histogram (Prometheus summary via /metrics) AND a per-origin reservoir
so the /api/propagation endpoint can show which peer's records arrive
slow — the live analog of the sim report's per-record lag CDFs.

Env contract (docs/env.md):

* ``SIDECAR_TPU_PROVENANCE`` — "0" disables the meter entirely
  (default on; the hot-path cost is one histogram insert per admitted
  record).
* ``SIDECAR_TPU_PROVENANCE_ORIGINS`` — max distinct per-origin series
  (default 64).  Beyond the cap, observations still feed the pooled
  histogram; the origin table stops growing and the snapshot reports
  ``overflow_origins`` (truncation is surfaced, never silent — the
  DeltaBatch convention).
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu.metrics import _percentile

DEFAULT_MAX_ORIGINS = 64
# Per-origin reservoir bound: smaller than the registry's (the origin
# table is max_origins × sites wide).
RESERVOIR = 256

SITES = ("catalog", "query")


def _env_enabled() -> bool:
    return os.environ.get("SIDECAR_TPU_PROVENANCE", "1") != "0"


def _env_max_origins() -> int:
    raw = os.environ.get("SIDECAR_TPU_PROVENANCE_ORIGINS", "")
    try:
        return max(1, int(raw)) if raw else DEFAULT_MAX_ORIGINS
    except ValueError:
        return DEFAULT_MAX_ORIGINS


class PropagationMeter:
    """Thread-safe per-(site, origin) lag accounting."""

    def __init__(self, enabled: Optional[bool] = None,
                 max_origins: Optional[int] = None) -> None:
        self.enabled = _env_enabled() if enabled is None else enabled
        self.max_origins = _env_max_origins() if max_origins is None \
            else max_origins
        self._lock = threading.Lock()
        # site → origin → [count, total_ms, last_ms, max_ms, samples]
        self._origins: dict[str, dict[str, list]] = {}
        self._overflow: dict[str, int] = {}
        self._rand = random.Random(0x51DECA)

    def observe(self, site: str, origin: str, lag_ms: float) -> None:
        """Record one admitted record's lag at ``site``.  Negative lags
        (clock skew within the admission fudge) clamp to 0 — the gate
        (docs/chaos.md) already rejected anything further ahead."""
        if not self.enabled:
            return
        lag_ms = max(0.0, float(lag_ms))
        metrics.histogram(f"propagation.{site}.lag", lag_ms)
        with self._lock:
            table = self._origins.setdefault(site, {})
            ent = table.get(origin)
            if ent is None:
                if len(table) >= self.max_origins:
                    self._overflow[site] = \
                        self._overflow.get(site, 0) + 1
                    return
                ent = table[origin] = [0, 0.0, 0.0, 0.0, []]
            ent[0] += 1
            ent[1] += lag_ms
            ent[2] = lag_ms
            ent[3] = max(ent[3], lag_ms)
            samples = ent[4]
            if len(samples) < RESERVOIR:
                samples.append(lag_ms)
            else:
                j = self._rand.randrange(ent[0])
                if j < RESERVOIR:
                    samples[j] = lag_ms

    def snapshot(self) -> dict:
        """The /api/propagation document: per site, the per-origin lag
        percentiles plus the overflow accounting."""
        with self._lock:
            doc: dict = {"enabled": self.enabled,
                         "max_origins": self.max_origins, "sites": {}}
            for site, table in self._origins.items():
                origins = {}
                for origin, ent in table.items():
                    s = sorted(ent[4])
                    origins[origin] = {
                        "count": ent[0],
                        "mean_ms": round(ent[1] / ent[0], 3)
                        if ent[0] else 0.0,
                        "last_ms": round(ent[2], 3),
                        "max_ms": round(ent[3], 3),
                        "p50_ms": round(_percentile(s, 0.50), 3),
                        "p95_ms": round(_percentile(s, 0.95), 3),
                        "p99_ms": round(_percentile(s, 0.99), 3),
                    }
                doc["sites"][site] = {
                    "origins": origins,
                    "overflow_origins": self._overflow.get(site, 0),
                }
            return doc

    def reset(self) -> None:
        """Clear the origin tables (tests)."""
        with self._lock:
            self._origins.clear()
            self._overflow.clear()


# The process-global meter (the metrics-registry convention) — the
# catalog writer and QueryHub record through it, /api/propagation
# reads it.  ``configure`` swaps gates for tests/embedders.
meter = PropagationMeter()


def configure(enabled: Optional[bool] = None,
              max_origins: Optional[int] = None) -> None:
    """Re-read the env gates (or force them) on the global meter."""
    meter.enabled = _env_enabled() if enabled is None else enabled
    if max_origins is not None:
        meter.max_origins = max_origins


def observe(site: str, origin: str, lag_ms: float) -> None:
    meter.observe(site, origin, lag_ms)


def snapshot() -> dict:
    return meter.snapshot()
