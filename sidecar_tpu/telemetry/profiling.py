"""``jax.profiler`` trace hooks behind ``SIDECAR_TPU_PROFILE_DIR``.

When the env var names a directory, the instrumented drivers record a
TensorBoard/xprof device trace there and annotate their dispatch
boundaries, so the per-kernel timeline lines up with the host-side
phases:

* bench.py wraps its measured phases in :func:`maybe_trace` and each
  pipelined north-star chunk in :func:`annotate`;
* ``SimBridge.simulate`` annotates every chunk dispatch (and can host
  the whole-process trace when the bridge runs standalone).

When the env var is unset every helper is a no-op returning a null
context — zero imports of the profiler machinery, zero overhead on the
hot path.  Profiler failures (a second concurrent trace, an
unwritable directory) are logged and swallowed: telemetry must never
take down the run it observes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Optional

log = logging.getLogger(__name__)

PROFILE_ENV = "SIDECAR_TPU_PROFILE_DIR"

# One device trace per process (jax.profiler is a process singleton);
# losers of the race simply run un-traced.
_gate = threading.Semaphore(1)


def profile_dir() -> Optional[str]:
    """The configured profile directory, or None when profiling is off."""
    return os.environ.get(PROFILE_ENV) or None


@contextlib.contextmanager
def maybe_trace(log_dir: Optional[str] = None):
    """Context: a ``jax.profiler.trace`` into ``log_dir`` (default: the
    env directory) when profiling is enabled AND no other trace is
    active in this process; a no-op otherwise.  Yields True when a
    trace actually started."""
    target = log_dir or profile_dir()
    if not target:
        yield False
        return
    if not _gate.acquire(blocking=False):
        yield False
        return
    started = False
    try:
        import jax
        try:
            jax.profiler.start_trace(target)
            started = True
        except Exception as exc:  # profiler state is process-global
            log.warning("telemetry: jax profiler trace failed to start "
                        "(%s) — continuing untraced", exc)
        yield started
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as exc:
                log.warning("telemetry: jax profiler trace failed to "
                            "stop cleanly (%s)", exc)
        _gate.release()


def annotate(name: str):
    """A ``jax.profiler.TraceAnnotation`` labelling the enclosed
    dispatches on the device timeline when profiling is enabled; a null
    context otherwise."""
    if not profile_dir():
        return contextlib.nullcontext()
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover — profiler API drift
        return contextlib.nullcontext()
