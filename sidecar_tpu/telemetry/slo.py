"""Convergence-SLO evaluator (docs/telemetry.md).

The provenance plane measures propagation lag — per-record
rounds-to-reach-all in the simulator (ops/provenance.py), merge-time −
record-stamp milliseconds on the live path
(telemetry/propagation.py).  This module turns those measurements into
VERDICTS: declarative rules of the form "p99 lag ≤ R rounds" or
"p99 lag ≤ S seconds", evaluated against a lag summary and exposed
three ways:

* ``slo.<rule>.observed`` / ``slo.<rule>.ok`` gauges in the metrics
  registry (scrapeable — an alert on ``sidecar_slo_<rule>_ok == 0``
  is the whole integration);
* a ``slo`` verdict block in the bench JSON (bench.py /
  benchmarks/robustness.py) — the regression-gate surface;
* the ``slo`` block of ``GET /api/propagation.json`` when an
  evaluator is attached to the catalog (``state.slo_evaluator``).

Rule syntax (one string per rule): ``"<pctl> <= <threshold> <unit>"``
with pctl ∈ {p50, p95, p99, max, converge} and unit ∈ {rounds, s,
seconds, ms} — e.g. ``"p99 <= 12 rounds"``, ``"p95<=1.5s"``.  The
``converge`` subject bounds whole-cluster ε-convergence rather than a
lag percentile ("converge <= 20 rounds", "converge <= 5 s") and is
checked against sweep/autopilot result rows via
:meth:`SloEvaluator.evaluate_row`.

The coherence plane (telemetry/coherence.py) adds a FLOOR rule form,
``"agreement >= <fraction>"``, and :meth:`SloEvaluator
.evaluate_coherence` checks both: percentile rules against the
``coherence.ttc`` time-to-coherence histogram ("p99 time-to-coherence
≤ 2 s") and floor rules against the live ``coherence.agreement``
gauge ("agreement ≥ 0.99").  Coherence verdict gauges are namespaced
``slo.coherence.<rule>.*`` so a ttc bound never collides with a
same-shaped propagation bound.

Env contract (docs/env.md):

* ``BENCH_SLO`` — "0" skips SLO evaluation entirely (no verdict
  block, no gauges; also gates the coherence rule set).
* ``BENCH_SLO_RULES`` — comma-separated rule strings replacing the
  defaults (``p99 <= 16 rounds, p99 <= 2 s``).
* ``BENCH_SLO_COHERENCE_RULES`` — comma-separated coherence rules
  replacing ``p99 <= 2 s, agreement >= 0.99``.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

from sidecar_tpu import metrics

DEFAULT_RULES = ("p99 <= 16 rounds", "p99 <= 2 s")
DEFAULT_COHERENCE_RULES = ("p99 <= 2 s", "agreement >= 0.99")

_RULE_RE = re.compile(
    r"^\s*(p50|p95|p99|max|converge)\s*<=\s*([0-9.]+)\s*"
    r"(rounds?|seconds?|s|ms)\s*$", re.IGNORECASE)
# Floor form — a LOWER bound on a unitless fraction gauge
# ("agreement >= 0.99"): the coherence plane's quorum-agreement SLO.
_FLOOR_RE = re.compile(
    r"^\s*(agreement)\s*>=\s*([0-9.]+)\s*$", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One declarative bound: a lag-percentile ceiling (``<=``) or a
    fraction floor (``>=``)."""

    percentile: str          # p50 | p95 | p99 | max | converge | agreement
    threshold: float         # in `unit`
    unit: str                # "rounds" | "s" | "ms" | "fraction"
    direction: str = "<="    # "<=" ceiling | ">=" floor

    @classmethod
    def parse(cls, text: str) -> "SloRule":
        m = _RULE_RE.match(text)
        if m:
            pctl, raw, unit = m.group(1).lower(), m.group(2), \
                m.group(3).lower()
            unit = {"round": "rounds", "rounds": "rounds", "s": "s",
                    "second": "s", "seconds": "s", "ms": "ms"}[unit]
            return cls(percentile=pctl, threshold=float(raw), unit=unit)
        m = _FLOOR_RE.match(text)
        if m:
            return cls(percentile=m.group(1).lower(),
                       threshold=float(m.group(2)), unit="fraction",
                       direction=">=")
        raise ValueError(
            f"bad SLO rule {text!r}: expected "
            "'<p50|p95|p99|max|converge> <= <threshold> "
            "<rounds|s|ms>' or 'agreement >= <fraction>'")

    @property
    def key(self) -> str:
        """The metric-name fragment: ``slo.<key>.ok`` /
        ``slo.<key>.observed``."""
        thr = f"{self.threshold:g}".replace(".", "_")
        suffix = "" if self.unit == "fraction" else self.unit
        return f"{self.percentile}_{thr}{suffix}"

    def text(self) -> str:
        if self.direction == ">=":
            return f"{self.percentile} >= {self.threshold:g}"
        if self.percentile == "converge":
            return f"converge <= {self.threshold:g} {self.unit}"
        return (f"{self.percentile} lag <= {self.threshold:g} "
                f"{self.unit}")

    def check(self, observed: float) -> bool:
        return observed >= self.threshold if self.direction == ">=" \
            else observed <= self.threshold


def _threshold_seconds(rule: SloRule) -> float:
    return rule.threshold / 1e3 if rule.unit == "ms" \
        else rule.threshold


class SloEvaluator:
    """Evaluate a rule set against lag summaries and publish the
    verdicts as gauges."""

    def __init__(self, rules) -> None:
        self.rules = tuple(SloRule.parse(r) if isinstance(r, str)
                           else r for r in rules)

    @classmethod
    def from_env(cls) -> Optional["SloEvaluator"]:
        """The ``BENCH_SLO`` contract: None when skipped, otherwise
        the ``BENCH_SLO_RULES`` (or default) rule set."""
        if os.environ.get("BENCH_SLO", "1") == "0":
            return None
        raw = os.environ.get("BENCH_SLO_RULES", "")
        rules = [r for r in (p.strip() for p in raw.split(","))
                 if r] or list(DEFAULT_RULES)
        return cls(rules)

    @classmethod
    def coherence_from_env(cls) -> Optional["SloEvaluator"]:
        """The coherence rule set (``BENCH_SLO`` gate,
        ``BENCH_SLO_COHERENCE_RULES`` override): the evaluator
        :meth:`evaluate_coherence` runs — "p99 time-to-coherence ≤
        2 s" and "agreement ≥ 0.99" by default."""
        if os.environ.get("BENCH_SLO", "1") == "0":
            return None
        raw = os.environ.get("BENCH_SLO_COHERENCE_RULES", "")
        rules = [r for r in (p.strip() for p in raw.split(","))
                 if r] or list(DEFAULT_COHERENCE_RULES)
        return cls(rules)

    # -- evaluation ---------------------------------------------------------

    def evaluate_lag(self, lag: Optional[dict],
                     seconds_per_round: Optional[float] = None,
                     publish: bool = True) -> dict:
        """Verdict block for a sim-side pooled lag summary
        (ops/provenance.pooled_lag: percentiles in ROUNDS).  Rules in
        seconds are checked through ``seconds_per_round`` (the
        protocol clock) and skipped — verdict null — when no clock or
        no samples are available; a rule that cannot be evaluated
        never passes silently."""
        verdicts = []
        for rule in self.rules:
            observed = None
            if rule.direction == "<=" and lag and lag.get("samples"):
                rounds_v = lag.get(rule.percentile)
                if rounds_v is not None:
                    if rule.unit == "rounds":
                        observed, thr = float(rounds_v), rule.threshold
                    elif seconds_per_round is not None:
                        observed = float(rounds_v) * seconds_per_round
                        thr = _threshold_seconds(rule)
            ok = None if observed is None else observed <= thr
            verdicts.append(self._verdict(rule, observed, ok, publish))
        return self._block(verdicts)

    def evaluate_row(self, row: dict, lag: Optional[dict] = None,
                     seconds_per_round: Optional[float] = None,
                     publish: bool = False) -> dict:
        """Verdict block for ONE fleet-sweep result row
        (fleet/engine.FleetRun.table): the contract ``POST /sweep``
        per-config verdicts and the autopilot objective share.

        * ``converge`` rules bound ``rounds_to_eps`` (rounds unit) or
          ``seconds_to_eps`` (s/ms).  A row that RAN but never reached
          ε is an honest FAIL (observed null, pass false) — never a
          null verdict: "never converged" violates every convergence
          ceiling.
        * percentile rules (p50/p95/p99/max) bound the row's pooled
          propagation-lag summary (``lag``), rounds directly, s/ms via
          ``seconds_per_round``; unevaluable → null.
        * ``agreement >= f`` floors bound ``digest_agreement``;
          null when the row carries no digest."""
        verdicts = []
        for rule in self.rules:
            observed, ok = None, None
            if rule.direction == ">=":
                g = row.get("digest_agreement")
                if g is not None:
                    observed = float(g)
                    ok = observed >= rule.threshold
            elif rule.percentile == "converge":
                if rule.unit == "rounds":
                    v = row.get("rounds_to_eps")
                    thr = rule.threshold
                else:
                    v = row.get("seconds_to_eps")
                    thr = _threshold_seconds(rule)
                if v is not None:
                    observed = float(v)
                    ok = observed <= thr
                elif row.get("rounds_run"):
                    ok = False      # ran the horizon, never converged
            elif lag and lag.get("samples"):
                rounds_v = lag.get(rule.percentile)
                if rounds_v is not None:
                    if rule.unit == "rounds":
                        observed = float(rounds_v)
                        ok = observed <= rule.threshold
                    elif seconds_per_round is not None:
                        observed = float(rounds_v) * seconds_per_round
                        ok = observed <= _threshold_seconds(rule)
            verdicts.append(self._verdict(rule, observed, ok, publish))
        return self._block(verdicts)

    def evaluate_live(self, publish: bool = True) -> dict:
        """Verdict block for the LIVE path: seconds/ms rules checked
        against the pooled ``propagation.query.lag`` histogram (the
        end-to-end site); rounds rules are sim-only and report null
        here."""
        hists = metrics.snapshot().get("histograms", {})
        h = hists.get("propagation.query.lag")
        verdicts = []
        for rule in self.rules:
            observed = None
            if rule.direction == "<=" and rule.unit != "rounds" \
                    and h and h.get("count"):
                pct_ms = h.get(f"{rule.percentile}_ms") \
                    if rule.percentile != "max" else h.get("max_ms")
                if pct_ms is not None:
                    observed = float(pct_ms) / 1e3
                    thr = _threshold_seconds(rule)
            ok = None if observed is None else observed <= thr
            verdicts.append(self._verdict(rule, observed, ok, publish))
        return self._block(verdicts)

    def evaluate_coherence(self, publish: bool = True) -> dict:
        """Verdict block for the coherence plane
        (telemetry/coherence.py): percentile rules (s/ms) bound the
        ``coherence.ttc`` time-to-coherence histogram; floor rules
        (``agreement >= f``) bound the live ``coherence.agreement``
        gauge.  Rounds rules are sim-only and report null, as does any
        rule whose signal has no observations yet — an unevaluable
        rule never passes silently.  Gauges land under
        ``slo.coherence.<rule>.*``."""
        snap = metrics.snapshot()
        h = snap.get("histograms", {}).get("coherence.ttc")
        gauges = snap.get("gauges", {})
        verdicts = []
        for rule in self.rules:
            observed = None
            thr = rule.threshold
            if rule.direction == ">=":
                g = gauges.get("coherence.agreement")
                if g is not None:
                    observed = float(g)
            elif rule.unit != "rounds" and h and h.get("count"):
                pct_ms = h.get(f"{rule.percentile}_ms") \
                    if rule.percentile != "max" else h.get("max_ms")
                if pct_ms is not None:
                    observed = float(pct_ms) / 1e3
                    thr = _threshold_seconds(rule)
            ok = None if observed is None else (
                observed >= thr if rule.direction == ">="
                else observed <= thr)
            verdicts.append(self._verdict(rule, observed, ok, publish,
                                          prefix="coherence."))
        return self._block(verdicts)

    def _verdict(self, rule: SloRule, observed, ok,
                 publish: bool, prefix: str = "") -> dict:
        if publish and ok is not None:
            if observed is not None:
                metrics.set_gauge(f"slo.{prefix}{rule.key}.observed",
                                  observed)
            metrics.set_gauge(f"slo.{prefix}{rule.key}.ok",
                              1.0 if ok else 0.0)
        return {"rule": rule.text(),
                "percentile": rule.percentile,
                "threshold": rule.threshold,
                "unit": rule.unit,
                "direction": rule.direction,
                "observed": observed,
                "pass": ok}

    @staticmethod
    def _block(verdicts: list) -> dict:
        evaluated = [v for v in verdicts if v["pass"] is not None]
        return {"rules": verdicts,
                "evaluated": len(evaluated),
                "pass": all(v["pass"] for v in evaluated)
                if evaluated else None}
