"""Kernel-cost observatory — where each round's milliseconds, bytes,
and HBM actually go.

The trace plane (ops/trace.py) and the provenance plane (PR 11) observe
the PROTOCOL; this module observes the PROGRAMS that run it.  Three
instruments, all built on machinery XLA already exposes:

1. **Phase scopes** — :func:`phase` wraps each step-function phase
   (publish / gather / fold / exchange / ttl_sweep / announce /
   apply_scatter) in a ``jax.named_scope`` carrying the
   ``sidecar.phase.<name>`` label, so every compiled op's metadata
   names the protocol phase that produced it and xprof device
   timelines group by phase.  **Default OFF and free**: unless
   ``SIDECAR_TPU_COST_PHASES=1`` (or a profile dir is configured,
   ``SIDECAR_TPU_PROFILE_DIR``) every scope is a ``nullcontext`` and
   the traced program is bit-identical to the un-instrumented one —
   tests/test_cost.py pins that per model family.  In-jit scopes use
   ``named_scope`` (a ``TraceAnnotation`` cannot label device ops from
   inside a traced function — it would time TRACING, not execution);
   the host-side dispatch boundaries keep their ``TraceAnnotation``
   via telemetry/profiling.annotate.

2. **Compiled-program reports** — :func:`program_report` lowers +
   compiles a callable once, timing both stages, and extracts
   ``cost_analysis()`` FLOP/byte estimates, ``memory_analysis()`` HBM
   sizes, the collective ops (kind + payload bytes, parsed from the
   compiled HLO), and the per-phase byte attribution (op metadata →
   ``sidecar.phase.*``).  Reports are cached per label (the jit-cache
   -hit telemetry: ``compile.count`` / ``compile.cache_hit``) and
   published into a process-global registry served at
   ``GET /api/cost.json``.

3. **Profile-trace reduction** — :func:`parse_profile_dir` reduces a
   captured ``SIDECAR_TPU_PROFILE_DIR`` run (TensorBoard/xprof chrome
   trace-event JSON) into per-phase device-time totals and shares,
   and :func:`reconcile` checks them against a measured ms/round
   (docs/perf.md documents the tolerance contract).

Everything here is measurement-side: nothing in this module runs on
the hot path unless explicitly invoked, and a ``program_report`` is a
SEPARATE compile of the same function — production dispatches never
pay for it.
"""

from __future__ import annotations

import contextlib
import functools
import glob
import gzip
import json
import os
import re
import threading
import time
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu.telemetry import profiling

PHASE_ENV = "SIDECAR_TPU_COST_PHASES"
PHASE_PREFIX = "sidecar.phase."

# The canonical phase taxonomy (docs/perf.md).  Single-chip models use
# `exchange` for the anti-entropy push-pull; the sharded twins reuse it
# for the board exchange collectives — the HLO call path
# (`_push_pull_stride` vs the board section) keeps them separable, see
# measured_exchange_bytes.
PHASES = ("publish", "gather", "fold", "exchange", "ttl_sweep",
          "announce", "apply_scatter")

# Reconciliation contract (docs/perf.md): per-phase attributions are
# accepted when they cover at least this fraction of the measured
# ms/round (device attribution on an async pipeline legitimately misses
# host-side time, gaps, and unannotated ops) and at most COVERAGE_MAX
# (above it the attribution double-counted something).
COVERAGE_MIN = 0.2
COVERAGE_MAX = 1.25
# Static byte attribution: minimum fraction of compiled output bytes
# that must carry a phase label for the share table to be meaningful.
MIN_ATTRIBUTED_FRACTION = 0.5


def phases_enabled() -> bool:
    """Phase scopes compile into traced programs only when explicitly
    requested: ``SIDECAR_TPU_COST_PHASES=1`` wins, else a configured
    profile dir enables them (a profiled run wants labelled ops).  The
    check happens at TRACE time — programs already compiled keep
    whatever they were traced with."""
    raw = os.environ.get(PHASE_ENV)
    if raw is not None:
        return raw.strip() not in ("", "0")
    return profiling.profile_dir() is not None


def phase(name: str):
    """A ``jax.named_scope("sidecar.phase.<name>")`` labelling every op
    traced inside the block when cost phases are enabled; a free
    ``nullcontext`` otherwise (the bit-identity contract)."""
    if not phases_enabled():
        return contextlib.nullcontext()
    try:
        import jax
        return jax.named_scope(PHASE_PREFIX + name)
    except Exception:  # pragma: no cover — profiler/jax API drift
        return contextlib.nullcontext()


def phased(name: str, tag: Optional[str] = None):
    """Decorator form of :func:`phase` — the ops-layer spelling.  The
    enablement check runs per CALL (trace), not at decoration, so a
    decorated kernel traced with phases off stays bit-identical.

    ``tag`` nests a second named scope inside the phase, putting an
    extra token on every op's metadata path — how the anti-entropy
    push-pull (phase ``exchange``, tag ``push_pull``) stays separable
    from the sharded BOARD exchange (same phase) when
    :func:`measured_exchange_bytes` filters collectives."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not phases_enabled():
                return fn(*args, **kwargs)
            with phase(name):
                if tag is None:
                    return fn(*args, **kwargs)
                try:
                    import jax
                    scope = jax.named_scope(tag)
                except Exception:  # pragma: no cover
                    scope = contextlib.nullcontext()
                with scope:
                    return fn(*args, **kwargs)
        return wrapper
    return deco


@contextlib.contextmanager
def forced_phases(enabled: bool = True):
    """Pin the phase-scope env knob for the duration (measurement
    probes re-trace a fresh jit wrapper under this so the production
    jit caches stay un-instrumented)."""
    old = os.environ.get(PHASE_ENV)
    os.environ[PHASE_ENV] = "1" if enabled else "0"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(PHASE_ENV, None)
        else:
            os.environ[PHASE_ENV] = old


# -- compiled-HLO parsing ----------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
# `%name = <type> op-kind(` — <type> is a shape (maybe with layout) or
# a tuple of shapes; shapes never contain parentheses.
_OP_RE = re.compile(
    r"=\s+((?:\([^)]*\))|(?:[a-z]+[0-9]*\[[0-9,]*\](?:\{[^}]*\})?))\s+"
    r"([a-z][a-z0-9-]*)\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_PHASE_TOKEN_RE = re.compile(r"sidecar\.phase\.([A-Za-z0-9_]+)")

COLLECTIVE_KINDS = ("all-gather", "all-to-all", "collective-permute",
                    "all-reduce", "reduce-scatter")


def shape_bytes(type_text: str) -> int:
    """Total buffer bytes of an HLO type string (``s32[64,32]{1,0}``
    or a tuple of shapes).  Unknown element types count 0 — the parser
    must never invent bytes."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        nbytes = _DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * nbytes
    return total


def _op_lines(hlo_text: str):
    """Yield ``(output_bytes, op_kind, op_name_metadata_or_"")`` per
    HLO instruction line."""
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        name_m = _OPNAME_RE.search(line)
        yield (shape_bytes(m.group(1)), m.group(2),
               name_m.group(1) if name_m else "")


def collective_ops(hlo_text: str) -> list[dict]:
    """Every collective instruction in a compiled HLO module:
    ``{"kind", "bytes", "op_name"}`` with bytes = the op's output
    buffer size (for a tiled all-gather that is the FULL gathered
    tensor per device).  ``-start`` async forms count once; their
    ``-done`` halves produce no separate payload."""
    out = []
    for nbytes, kind, op_name in _op_lines(hlo_text):
        base = kind[:-6] if kind.endswith("-start") else kind
        if base in COLLECTIVE_KINDS:
            out.append({"kind": base, "bytes": nbytes,
                        "op_name": op_name})
        elif base.endswith("-done") and base[:-5] in COLLECTIVE_KINDS:
            continue
    return out


def collective_summary(hlo_text: str) -> dict:
    """Per-kind op counts + total payload bytes of a compiled module —
    the bench/benchmark exposition row."""
    ops = collective_ops(hlo_text)
    by_kind: dict[str, dict] = {}
    for op in ops:
        ent = by_kind.setdefault(op["kind"], {"ops": 0, "bytes": 0})
        ent["ops"] += 1
        ent["bytes"] += op["bytes"]
    return {"ops": len(ops), "by_kind": by_kind,
            "total_bytes": sum(o["bytes"] for o in ops)}


def measured_exchange_bytes(hlo_text: str, mode: str, d: int,
                            exclude: tuple = ("push_pull",)) -> int:
    """Measured per-round per-device receive bytes of the sharded
    BOARD exchange, from the compiled collective sizes — the number
    the trace plane's analytic 93 B/record column is cross-checked
    against (``exchange_bytes_per_round`` on both sharded twins).

    Selection: the collective kind the mode compiles to (all_gather →
    ``all-gather``, all_to_all → ``all-to-all``, ring →
    ``collective-permute``), AND the op's metadata path must carry the
    ``sidecar.phase.exchange`` scope — stray collectives (e.g. the
    all-reduce/all-gather pairs a sharded ``_roll_dynamic`` lowers to
    inside a cond branch) carry no phase scope and are skipped.  Ops
    whose path contains an ``exclude`` token (default: the anti-entropy
    ``_push_pull_stride``, which also lowers to collective-permutes)
    are left out.  A tiled all-gather's output is the FULL gathered
    tensor, of which ``(d-1)/d`` actually crossed the interconnect.
    Requires the program to have been compiled with phases ON
    (``forced_phases(True)`` / program_report does this)."""
    kind = {"all_gather": "all-gather", "all_to_all": "all-to-all",
            "ring": "collective-permute",
            "zoned": "collective-permute"}[mode]
    scope = PHASE_PREFIX + "exchange"
    total = 0
    for op in collective_ops(hlo_text):
        if op["kind"] != kind:
            continue
        if scope not in op["op_name"]:
            continue
        if any(tok in op["op_name"] for tok in exclude):
            continue
        if mode == "all_gather":
            total += op["bytes"] * (d - 1) // max(d, 1)
        else:
            total += op["bytes"]
    return total


# Buffer plumbing no protocol phase can own — excluded from the
# attribution denominator (docs/perf.md): parameters and tuple shells
# are the calling convention, copies/bitcasts are layout moves, and
# none of them carry op metadata in the first place.
STRUCTURAL_KINDS = frozenset((
    "parameter", "tuple", "get-tuple-element", "constant", "copy",
    "bitcast"))


def hlo_phase_bytes(hlo_text: str) -> dict:
    """Static per-phase attribution of a compiled module: each
    instruction's OUTPUT buffer bytes accrue to the ``sidecar.phase.*``
    token in its metadata (the write-side weight — these models are
    memory-bound, docs/perf.md).  Compute ops without a phase label
    accrue to ``unattributed``; STRUCTURAL_KINDS (calling-convention
    and layout plumbing) are tallied separately and sit outside the
    ``attributed_fraction`` denominator.  All zeros + fraction 0 when
    the program was compiled with phases off."""
    by_phase: dict[str, int] = {}
    unattributed = 0
    structural = 0
    for nbytes, kind, op_name in _op_lines(hlo_text):
        m = _PHASE_TOKEN_RE.search(op_name)
        if m:
            by_phase[m.group(1)] = by_phase.get(m.group(1), 0) + nbytes
        elif kind in STRUCTURAL_KINDS:
            structural += nbytes
        else:
            unattributed += nbytes
    attributed = sum(by_phase.values())
    total = attributed + unattributed
    return {"by_phase": by_phase, "unattributed_bytes": unattributed,
            "structural_bytes": structural,
            "attributed_bytes": attributed,
            "attributed_fraction": round(attributed / total, 4)
            if total else 0.0}


def phase_share_table(phase_bytes: dict,
                      measured_ms_per_round: Optional[float] = None
                      ) -> dict:
    """Byte-weighted phase shares (over ATTRIBUTED bytes) and, given a
    measured ms/round, the per-phase ms estimate ``share × measured``.
    The estimates reconcile to the measurement by construction; the
    meaningful quality gate is ``attributed_fraction`` ≥
    MIN_ATTRIBUTED_FRACTION (docs/perf.md)."""
    by_phase = phase_bytes.get("by_phase", {})
    attributed = sum(by_phase.values())
    table = {}
    for name, nbytes in sorted(by_phase.items(),
                               key=lambda kv: -kv[1]):
        share = nbytes / attributed if attributed else 0.0
        row = {"bytes": nbytes, "share": round(share, 4)}
        if measured_ms_per_round is not None:
            row["est_ms_per_round"] = round(
                share * measured_ms_per_round, 4)
            metrics.set_gauge(f"phase.{name}.share", round(share, 4))
        table[name] = row
    return {"phases": table,
            "attributed_fraction": phase_bytes.get(
                "attributed_fraction", 0.0),
            "attribution": "compiled-output-bytes"}


# -- profile-trace reduction -------------------------------------------------

def parse_profile_dir(path: str) -> dict:
    """Reduce a captured profile directory (``SIDECAR_TPU_PROFILE_DIR``
    — TensorBoard ``plugins/profile/<run>/*.trace.json.gz``, chrome
    trace-event format) into per-phase device-time totals: every
    complete ("X") event whose name or args carry a
    ``sidecar.phase.<p>`` token accrues its duration to phase ``p``.

    Best-effort by design — a trace with no phase events (phases were
    off, or the backend emits no device events) reduces to
    ``{"phases": {}, "attributed_ms": 0.0}``, never an error."""
    phases: dict[str, dict] = {}
    files = sorted(
        glob.glob(os.path.join(path, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(path, "**", "*.trace.json"),
                    recursive=True))
    parsed_files = 0
    for fname in files:
        try:
            if fname.endswith(".gz"):
                with gzip.open(fname, "rb") as fh:
                    doc = json.loads(fh.read())
            else:
                with open(fname, "rb") as fh:
                    doc = json.loads(fh.read())
        except (OSError, ValueError):
            continue
        parsed_files += 1
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            blob = str(ev.get("name", ""))
            args = ev.get("args")
            if isinstance(args, dict):
                blob += " " + " ".join(str(v) for v in args.values())
            m = _PHASE_TOKEN_RE.search(blob)
            if not m:
                continue
            ent = phases.setdefault(
                m.group(1), {"events": 0, "duration_us": 0.0})
            ent["events"] += 1
            ent["duration_us"] += float(ev.get("dur", 0) or 0)
    total_us = sum(e["duration_us"] for e in phases.values())
    out = {}
    for name, ent in sorted(phases.items(),
                            key=lambda kv: -kv[1]["duration_us"]):
        out[name] = {
            "events": ent["events"],
            "ms": round(ent["duration_us"] / 1000.0, 4),
            "share": round(ent["duration_us"] / total_us, 4)
            if total_us else 0.0,
        }
        metrics.histogram(f"phase.{name}.ms",
                          ent["duration_us"] / 1000.0)
    return {"files": parsed_files, "phases": out,
            "attributed_ms": round(total_us / 1000.0, 4)}


def reconcile(attributed_ms: float, measured_ms: float,
              coverage_min: float = COVERAGE_MIN,
              coverage_max: float = COVERAGE_MAX) -> dict:
    """The reconciliation contract (docs/perf.md): per-phase attributed
    time vs the measured ms for the same span of work.  ``coverage`` =
    attributed/measured; within tolerance when it lands inside
    ``[coverage_min, coverage_max]``."""
    coverage = (attributed_ms / measured_ms) if measured_ms else None
    return {
        "attributed_ms": round(attributed_ms, 4),
        "measured_ms": round(measured_ms, 4),
        "coverage": round(coverage, 4) if coverage is not None else None,
        "tolerance": [coverage_min, coverage_max],
        "within_tolerance": (coverage is not None
                             and coverage_min <= coverage
                             <= coverage_max),
    }


# -- compiled-program reports ------------------------------------------------

_lock = threading.Lock()
_REPORTS: dict[str, dict] = {}


@contextlib.contextmanager
def no_persistent_cache():
    """Disable jax's on-disk compilation cache for the duration.  The
    cache keys programs WITHOUT op metadata
    (``jax_compilation_cache_include_metadata_in_key`` defaults False),
    so a cached scope-free executable can be served for a
    phase-instrumented program — ``as_text()`` would then show the
    STALE metadata and every attribution read zero.  Measurement
    compiles must be real compiles.

    Flipping the config flag alone is NOT enough: ``is_cache_used``
    latches its verdict once per process, so the latch has to be
    dropped (``reset_cache``) on both sides of the toggle."""
    import jax
    try:
        from jax._src import compilation_cache as _cc
    except Exception:  # pragma: no cover — jax internals drift
        _cc = None
    try:
        old = jax.config.jax_enable_compilation_cache
    except AttributeError:  # pragma: no cover — config drift
        yield
        return

    def _drop_latch():
        if _cc is not None:
            try:
                _cc.reset_cache()
            except Exception:  # pragma: no cover
                pass

    jax.config.update("jax_enable_compilation_cache", False)
    _drop_latch()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", old)
        _drop_latch()


def compiled_hlo(fn, *args) -> str:
    """Optimized-HLO text of ``fn(*args)`` from a FRESH jit wrapper and
    a REAL compile (persistent cache bypassed) — the input every parser
    in this module expects."""
    import jax
    with no_persistent_cache():
        return jax.jit(fn).lower(*args).compile().as_text()


def _cost_analysis_doc(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out


def _memory_analysis_doc(compiled) -> dict:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    fields = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")
    out = {}
    for f in fields:
        v = getattr(ma, f, None)
        if v is not None:
            out[f.replace("_size_in_bytes", "_bytes")] = int(v)
    if out:
        # Resident-watermark estimate: arguments + outputs + temps,
        # minus donated aliases (an aliased output is not a second
        # buffer).  XLA's own peak accounting is not exposed here.
        out["peak_bytes"] = max(
            0,
            out.get("argument_bytes", 0) + out.get("output_bytes", 0)
            + out.get("temp_bytes", 0) - out.get("alias_bytes", 0))
    return out


def program_report(label: str, fn, *args, donate_argnums=(),
                   static_argnums=(), refresh: bool = False,
                   exchange_mode: Optional[str] = None,
                   num_devices: Optional[int] = None) -> dict:
    """Lower + compile ``fn(*args)`` under a FRESH ``jax.jit`` wrapper
    and report what the compiler says it costs: lower/compile wall
    time, ``cost_analysis`` FLOP/byte estimates, ``memory_analysis``
    HBM sizes (with a peak-watermark estimate), the collective summary,
    and the per-phase byte attribution.  Cached per ``label`` — a
    repeat call is the jit-cache-hit telemetry (``compile.cache_hit``)
    and returns the stored report without recompiling."""
    with _lock:
        cached = _REPORTS.get(label)
    if cached is not None and not refresh:
        metrics.incr("compile.cache_hit")
        return cached
    import jax

    metrics.incr("compile.count")
    with no_persistent_cache():
        t0 = time.perf_counter()
        jitted = jax.jit(fn, donate_argnums=donate_argnums,
                         static_argnums=static_argnums)
        lowered = jitted.lower(*args)
        t_lower = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter()
    report: dict = {
        "program": label,
        "lower_ms": round((t_lower - t0) * 1000.0, 2),
        "compile_ms": round((t_compile - t_lower) * 1000.0, 2),
        "phases_enabled": phases_enabled(),
    }
    metrics.histogram("compile.ms", (t_compile - t_lower) * 1000.0)
    report.update(_cost_analysis_doc(compiled))
    mem = _memory_analysis_doc(compiled)
    if mem:
        report["memory"] = mem
        metrics.set_gauge(f"hbm.{label}.peak_bytes", mem["peak_bytes"])
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    if hlo:
        report["collectives"] = collective_summary(hlo)
        report["phase_bytes"] = hlo_phase_bytes(hlo)
        report["hlo_chars"] = len(hlo)
        if exchange_mode is not None and num_devices is not None:
            report["measured_exchange_bytes"] = measured_exchange_bytes(
                hlo, exchange_mode, num_devices)
    with _lock:
        _REPORTS[label] = report
    return report


def record_report(label: str, doc: dict) -> None:
    """Publish an externally-assembled cost block (e.g. bench.py's
    reconciliation rows) into the registry served at /api/cost.json."""
    with _lock:
        _REPORTS[label] = doc


def snapshot() -> dict:
    """The registry view behind ``GET /api/cost.json``: every program
    report recorded this process, plus the phase-scope state and the
    ``compile.*`` counters."""
    with _lock:
        programs = {k: dict(v) for k, v in _REPORTS.items()}
    return {
        "phases_enabled": phases_enabled(),
        "phase_taxonomy": list(PHASES),
        "programs": programs,
        "compile": {
            "count": metrics.counter("compile.count"),
            "cache_hits": metrics.counter("compile.cache_hit"),
        },
    }


def reset() -> None:
    """Clear the report registry (tests)."""
    with _lock:
        _REPORTS.clear()
