"""Cluster coherence observatory — the live digest aggregation plane.

The sim's digest scan (ops/digest.py, ``run_with_digest``) can compare
every node's catalog fingerprint against ground truth each round.  A
live node has no ground truth, but it DOES have the same order-invariant
digest of its own catalog (``ServicesState`` maintains it incrementally
under the writer lock) and it learns peers' digests from the annotation
on every push-pull body (``catalog.state.encode_annotated`` →
``merge``).  This module turns those observations into the live
coherence verdicts the sim reports offline:

* **coherence matrix** — pairwise differing-bucket counts between every
  pair of known hosts (each count lower-bounds the number of records on
  which the two catalogs diverge — the ops/digest bucket property);
* **quorum agreement** — the modal digest across hosts and the fraction
  of hosts carrying it (1.0 = the cluster is coherent as far as this
  node can see);
* **diverged estimate** — the summed differing-bucket counts of the
  non-quorum hosts: a lower bound on how many records the cluster still
  has to gossip;
* **time-to-coherence** — when the LOCAL digest changes (a write left
  coherence), the change is stamped with the catalog clock and the
  query-plane version; when every known host agrees again the elapsed
  ms lands in the ``coherence.ttc`` histogram.  This is the live twin
  of the sim's rounds-to-ε curve, and the quantity the coherence SLO
  rules bound (telemetry/slo.py: ``p99 <= 2 s``, ``agreement >= 0.99``).

Metrics (docs/metrics.md): ``coherence.observed``,
``coherence.agreement``, ``coherence.peers``,
``coherence.diverged.estimate``, ``coherence.ttc``.  Surfaces:
``GET /api/coherence.json`` (this module's :func:`snapshot`) and the
``GET /api/coherence`` heat table (web/api.py).

Env contract (docs/env.md):

* ``SIDECAR_TPU_COHERENCE`` — "0" disables the monitor entirely
  (default on; the hot-path cost is one dict upsert + modal tally per
  digest publication).
* ``SIDECAR_TPU_COHERENCE_PEERS`` — max distinct peer digests tracked
  (default 64).  Beyond the cap new peers are counted in
  ``overflow_peers``, never silently dropped (the DeltaBatch
  truncation convention); the local host always fits.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu.ops import digest as digest_ops

DEFAULT_MAX_PEERS = 64


def _env_enabled() -> bool:
    return os.environ.get("SIDECAR_TPU_COHERENCE", "1") != "0"


def _env_max_peers() -> int:
    raw = os.environ.get("SIDECAR_TPU_COHERENCE_PEERS", "")
    try:
        return max(1, int(raw)) if raw else DEFAULT_MAX_PEERS
    except ValueError:
        return DEFAULT_MAX_PEERS


class CoherenceMonitor:
    """Thread-safe per-host digest table + coherence verdict plane."""

    def __init__(self, enabled: Optional[bool] = None,
                 max_peers: Optional[int] = None) -> None:
        self.enabled = _env_enabled() if enabled is None else enabled
        self.max_peers = _env_max_peers() if max_peers is None \
            else max_peers
        self._lock = threading.Lock()
        # host → {value, buckets, records, seen_ns, local}
        self._hosts: dict[str, dict] = {}
        self._local: Optional[str] = None
        self._overflow = 0
        # Earliest un-cohered local change: (hub version, clock ns).
        # Held (not replaced) across further changes so ttc measures
        # from the FIRST write that left coherence — the sim's
        # rounds-to-ε convention, not last-write-to-quiet.
        self._mark: Optional[tuple] = None
        self._ttc = {"count": 0, "last_ms": None, "max_ms": 0.0,
                     "version": None}

    # -- observation (writer paths) ----------------------------------------

    def observe(self, host: str, value, *, buckets: int,
                records: int = 0, local: bool = False,
                version: int = 0, now_ns: Optional[int] = None) -> None:
        """Record one host's digest.  ``local=True`` marks this node's
        own catalog (fed on every writer-side publication); peers come
        from push-pull annotations.  ``now_ns`` is the CATALOG clock
        (``ServicesState._now``) so time-to-coherence is deterministic
        under injected test clocks."""
        if not self.enabled or not host:
            return
        value = digest_ops.digest_value(value)
        with self._lock:
            ent = self._hosts.get(host)
            if ent is None and not local \
                    and len(self._hosts) >= self.max_peers:
                self._overflow += 1
                return
            changed = ent is None or ent["value"] != value
            self._hosts[host] = {"value": value, "buckets": int(buckets),
                                 "records": int(records),
                                 "seen_ns": now_ns, "local": local}
            if local:
                self._local = host
                if changed and self._mark is None:
                    self._mark = (int(version), now_ns)
            metrics.incr("coherence.observed")
            self._refresh(now_ns)

    def observe_doc(self, host: str, doc,
                    now_ns: Optional[int] = None) -> bool:
        """Harvest a peer's wire annotation (the ``"Digest"`` key of a
        push-pull body: ``{"Buckets", "Records", "Hex"}``).  Returns
        False — never raises — on a malformed document: annotations
        come from (same-cluster but untrusted) peers and a shape
        surprise must not kill the merge loop."""
        if not self.enabled or not host or not isinstance(doc, dict):
            return False
        try:
            buckets = int(doc["Buckets"])
            value = digest_ops.digest_from_hex(str(doc["Hex"]))
            if len(value) != 2 * buckets:
                return False
            records = int(doc.get("Records", 0))
        except (KeyError, TypeError, ValueError, OverflowError):
            return False
        self.observe(host, value, buckets=buckets, records=records,
                     now_ns=now_ns)
        return True

    # -- verdict plane (under self._lock) ----------------------------------

    def _comparable(self) -> tuple:
        """Hosts whose digest geometry matches the local one (or the
        first-seen geometry when no local digest is known yet)."""
        if not self._hosts:
            return (), 0
        ref = self._hosts.get(self._local) if self._local else None
        buckets = ref["buckets"] if ref else \
            next(iter(self._hosts.values()))["buckets"]
        hosts = tuple(sorted(h for h, e in self._hosts.items()
                             if e["buckets"] == buckets))
        return hosts, buckets

    def _quorum(self, hosts) -> tuple:
        """(modal digest value, modal count) over ``hosts``."""
        tally: dict = {}
        for h in hosts:
            v = self._hosts[h]["value"]
            tally[v] = tally.get(v, 0) + 1
        # Deterministic tie-break: largest count, then smallest value.
        value, count = min(tally.items(), key=lambda kv: (-kv[1], kv[0]))
        return value, count

    def _refresh(self, now_ns: Optional[int]) -> None:
        hosts, _ = self._comparable()
        metrics.set_gauge("coherence.peers", len(self._hosts))
        if not hosts:
            return
        quorum, count = self._quorum(hosts)
        agreement = count / len(hosts)
        diverged = sum(
            digest_ops.diff_buckets_py(self._hosts[h]["value"], quorum)
            for h in hosts if self._hosts[h]["value"] != quorum)
        metrics.set_gauge("coherence.agreement", agreement)
        metrics.set_gauge("coherence.diverged.estimate", diverged)
        if agreement == 1.0 and self._mark is not None:
            if len(hosts) >= 2:
                # Coherence regained across actual peers: close the
                # change window.  A single-host view holds the mark —
                # agreement-with-nobody is not convergence evidence.
                version, t0 = self._mark
                if now_ns is not None and t0 is not None:
                    ttc_ms = max(0.0, (now_ns - t0) / 1e6)
                    metrics.histogram("coherence.ttc", ttc_ms)
                    self._ttc["count"] += 1
                    self._ttc["last_ms"] = round(ttc_ms, 3)
                    self._ttc["max_ms"] = max(self._ttc["max_ms"],
                                              round(ttc_ms, 3))
                    self._ttc["version"] = version
                self._mark = None

    # -- read surface -------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/api/coherence.json`` document."""
        with self._lock:
            doc: dict = {"enabled": self.enabled,
                         "max_peers": self.max_peers,
                         "local": self._local,
                         "overflow_peers": self._overflow}
            if not self.enabled:
                return doc
            hosts, buckets = self._comparable()
            doc["buckets"] = buckets
            doc["hosts"] = {}
            if hosts:
                quorum, count = self._quorum(hosts)
                diffs = {h: digest_ops.diff_buckets_py(
                    self._hosts[h]["value"], quorum) for h in hosts}
                for h in hosts:
                    ent = self._hosts[h]
                    doc["hosts"][h] = {
                        "records": ent["records"],
                        "local": ent["local"],
                        "agree": diffs[h] == 0,
                        "diff_vs_quorum": diffs[h],
                    }
                doc["quorum"] = {
                    "hex": digest_ops.digest_to_hex(quorum),
                    "count": count,
                    "agreement": round(count / len(hosts), 6),
                }
                doc["diverged_estimate"] = sum(diffs.values())
                doc["matrix"] = {
                    "hosts": list(hosts),
                    "diff": [[digest_ops.diff_buckets_py(
                        self._hosts[a]["value"], self._hosts[b]["value"])
                        for b in hosts] for a in hosts],
                }
            doc["ttc"] = dict(self._ttc)
            doc["pending_change"] = self._mark is not None
            return doc

    def reset(self) -> None:
        """Clear the host table and ttc accounting (tests)."""
        with self._lock:
            self._hosts.clear()
            self._local = None
            self._overflow = 0
            self._mark = None
            self._ttc = {"count": 0, "last_ms": None, "max_ms": 0.0,
                         "version": None}


# The process-global monitor (the propagation-meter convention): the
# catalog writer publishes local digests through it, merge() feeds peer
# annotations, /api/coherence reads it.
monitor = CoherenceMonitor()


def configure(enabled: Optional[bool] = None,
              max_peers: Optional[int] = None) -> None:
    """Re-read the env gates (or force them) on the global monitor."""
    monitor.enabled = _env_enabled() if enabled is None else enabled
    if max_peers is not None:
        monitor.max_peers = max_peers


def observe(host: str, value, *, buckets: int, records: int = 0,
            local: bool = False, version: int = 0,
            now_ns: Optional[int] = None) -> None:
    monitor.observe(host, value, buckets=buckets, records=records,
                    local=local, version=version, now_ns=now_ns)


def observe_doc(host: str, doc, now_ns: Optional[int] = None) -> bool:
    return monitor.observe_doc(host, doc, now_ns=now_ns)


def snapshot() -> dict:
    return monitor.snapshot()
