"""The service record — wire format and lifecycle predicates.

Capability mirror of the reference's ``service`` package
(service/service.go:17-210): a compact record describing one service
instance on one host, shipped over gossip as JSON.  Field names and the
RFC3339-nanosecond timestamp encoding match the Go wire format exactly so
a cluster can mix nodes of both implementations and downstream consumers
(receivers, UIs) keep working.

Timestamps are **integer nanoseconds** since the Unix epoch, not
``datetime`` — the protocol's correctness leans on nanosecond resolution
(the +50 ns broadcast skew, services_state.go:597-599) that
``datetime``'s microseconds would silently destroy.
"""

from __future__ import annotations

import dataclasses
import json
import time as _time
from typing import Any, Iterable, Optional

# Status enum — mirror of service/service.go:17-23.
ALIVE = 0
TOMBSTONE = 1
UNHEALTHY = 2
UNKNOWN = 3
DRAINING = 4
# Simulator-side extension (ops/status.py): SWIM-style quarantine
# before tombstone.  The live catalog never produces this code — it
# exists here so simulator projections (bridge reports, delta streams)
# render it by name instead of the unknown-code "Tombstone" fallback.
SUSPECT = 5

NS_PER_SECOND = 1_000_000_000

# Lifecycle constants (catalog/services_state.go:26-37), in seconds.
TOMBSTONE_LIFESPAN = 3 * 3600.0
ALIVE_LIFESPAN = 80.0
DRAINING_LIFESPAN = 600.0
STALENESS_FUDGE = 60.0


def now_ns() -> int:
    return _time.time_ns()


def status_string(status: int) -> str:
    """service/service.go:168-181 — unknown codes render as Tombstone."""
    return {
        ALIVE: "Alive",
        UNHEALTHY: "Unhealthy",
        UNKNOWN: "Unknown",
        DRAINING: "Draining",
        SUSPECT: "Suspect",
    }.get(status, "Tombstone")


# -- RFC3339-nanosecond timestamps (Go time.Time JSON encoding) ------------

def ns_to_rfc3339(ns: int) -> str:
    """Render like Go's time.Time.MarshalJSON: RFC3339, nanosecond
    precision with trailing zeros trimmed, 'Z' zone."""
    secs, nanos = divmod(ns, NS_PER_SECOND)
    base = _time.strftime("%Y-%m-%dT%H:%M:%S", _time.gmtime(secs))
    if nanos:
        frac = f"{nanos:09d}".rstrip("0")
        return f"{base}.{frac}Z"
    return base + "Z"


def rfc3339_to_ns(text: str) -> int:
    """Parse RFC3339 (with optional fractional seconds / numeric zone)."""
    import calendar

    t = text.strip()
    offset = 0
    if t.endswith(("Z", "z")):
        body = t[:-1]
    else:
        body = t
        for i in range(len(t) - 1, 10, -1):
            if t[i] in "+-":
                body = t[:i]
                sign = -1 if t[i] == "-" else 1
                hh, mm = t[i + 1:].split(":")
                offset = sign * (int(hh) * 3600 + int(mm) * 60)
                break
    if "." in body:
        main, frac = body.split(".", 1)
        nanos = int((frac + "000000000")[:9])
    else:
        main, nanos = body, 0
    st = _time.strptime(main, "%Y-%m-%dT%H:%M:%S")
    secs = calendar.timegm(st) - offset
    return secs * NS_PER_SECOND + nanos


@dataclasses.dataclass
class Port:
    """One published port (service/service.go:25-30)."""

    type: str = "tcp"
    port: int = 0
    service_port: int = 0
    ip: str = ""

    def to_json(self) -> dict:
        return {"Type": self.type, "Port": self.port,
                "ServicePort": self.service_port, "IP": self.ip}

    @classmethod
    def from_json(cls, d: dict) -> "Port":
        # Typed like the reference's json.Unmarshal into Port: wrong-typed
        # fields are decode errors, not junk values stored for later
        # (int() before falsy-normalization, so [] can't launder to 0).
        return cls(type=_as_str(d.get("Type", "tcp"), "tcp"),
                   port=_as_int(d.get("Port")),
                   service_port=_as_int(d.get("ServicePort")),
                   ip=_as_str(d.get("IP", ""), ""))


@dataclasses.dataclass
class Service:
    """One service instance record (service/service.go:32-42)."""

    id: str = ""
    name: str = ""
    image: str = ""
    created: int = 0           # ns since epoch
    hostname: str = ""
    ports: list[Port] = dataclasses.field(default_factory=list)
    updated: int = 0           # ns since epoch — the LWW merge key
    proxy_mode: str = "http"
    status: int = UNKNOWN

    # -- predicates (service/service.go:50-72) -----------------------------

    def is_alive(self) -> bool:
        return self.status == ALIVE

    def is_tombstone(self) -> bool:
        return self.status == TOMBSTONE

    def is_draining(self) -> bool:
        return self.status == DRAINING

    def invalidates(self, other: Optional["Service"]) -> bool:
        """True when this record supersedes ``other`` (strictly newer,
        service/service.go:64-66)."""
        return other is not None and self.updated > other.updated

    def is_stale(self, lifespan_s: float = TOMBSTONE_LIFESPAN,
                 now: Optional[int] = None) -> bool:
        """Older than lifespan + 1-minute clock-drift fudge
        (service/service.go:68-72)."""
        now = now_ns() if now is None else now
        oldest = now - int((lifespan_s + STALENESS_FUDGE) * NS_PER_SECOND)
        return self.updated < oldest

    def tombstone(self, now: Optional[int] = None) -> None:
        """service/service.go:91-94."""
        self.status = TOMBSTONE
        self.updated = now_ns() if now is None else now

    # -- accessors ---------------------------------------------------------

    def status_string(self) -> str:
        return status_string(self.status)

    def version(self) -> str:
        """Image tag, or the full image when untagged
        (service/service.go:116-123)."""
        parts = self.image.split(":")
        return parts[1] if len(parts) > 1 else parts[0]

    def port_for_service_port(self, find_port: int, ptype: str = "tcp") -> int:
        """service/service.go:97-106; -1 when unmapped."""
        for p in self.ports:
            if p.service_port == find_port and p.type == ptype:
                return p.port
        return -1

    def listener_name(self) -> str:
        return f"Service({self.name}-{self.id})"

    # -- wire format -------------------------------------------------------

    def to_json(self) -> dict:
        return {
            "ID": self.id,
            "Name": self.name,
            "Image": self.image,
            "Created": ns_to_rfc3339(self.created),
            "Hostname": self.hostname,
            "Ports": [p.to_json() for p in self.ports] or None,
            "Updated": ns_to_rfc3339(self.updated),
            "ProxyMode": self.proxy_mode,
            "Status": self.status,
        }

    def encode(self) -> bytes:
        return json.dumps(self.to_json(), separators=(",", ":")).encode()

    @classmethod
    def from_json(cls, d: dict) -> "Service":
        # Typed like the reference's json.Unmarshal into Service: a
        # wrong-typed field is a decode error (the Go side would reject
        # it too), never a junk value that detonates later in the merge
        # or encode hot paths.
        ports = d.get("Ports") or []
        if not isinstance(ports, list):
            raise TypeError("Ports: not a list")
        return cls(
            id=_as_str(d.get("ID", ""), ""),
            name=_as_str(d.get("Name", ""), ""),
            image=_as_str(d.get("Image", ""), ""),
            created=_parse_ts(d.get("Created")),
            hostname=_as_str(d.get("Hostname", ""), ""),
            ports=[Port.from_json(p) for p in ports],
            updated=_parse_ts(d.get("Updated")),
            proxy_mode=_as_str(d.get("ProxyMode", "http"), "http")
            or "http",
            status=_as_int(d.get("Status"), UNKNOWN),
        )

    def copy(self) -> "Service":
        return dataclasses.replace(self, ports=[dataclasses.replace(p)
                                                for p in self.ports])


def _as_int(v: Any, default: int = 0) -> int:
    if v is None:
        return default
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise TypeError(f"expected number, got {type(v).__name__}")
    return int(v)


def _as_str(v: Any, default: str) -> str:
    if v is None:
        return default
    if not isinstance(v, str):
        raise TypeError(f"expected string, got {type(v).__name__}")
    return v


def _parse_ts(v: Any) -> int:
    if v is None:
        return 0
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    if isinstance(v, str):
        return rfc3339_to_ns(v)
    raise TypeError(f"timestamp: expected int or RFC3339 string, "
                    f"got {type(v).__name__}")


def decode(data: bytes | str) -> Service:
    """service/service.go:127-136.

    Raises ValueError on ANY malformed payload: this is a wire boundary
    fed by untrusted peers, and shape surprises deeper in the walk
    (a list where a dict belongs, a dict where a string belongs) must
    not escape as TypeError/AttributeError — callers catch ValueError
    and a leaked exception kills their receive loop.
    """
    try:
        d = json.loads(data)
    except (json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise ValueError(f"failed to decode service JSON: {exc}") from exc
    if not isinstance(d, dict):
        raise ValueError("failed to decode service JSON: not an object")
    try:
        return Service.from_json(d)
    except (TypeError, AttributeError, KeyError, OverflowError) as exc:
        raise ValueError(
            f"failed to decode service JSON: malformed shape ({exc})"
        ) from exc


def to_service(container: dict, ip: str, hostname: Optional[str] = None,
               now: Optional[int] = None) -> Service:
    """Convert a Docker API container listing into a Service record
    (service/service.go:139-166, 184-210).

    ``container`` is the dict shape of Docker's ``GET /containers/json``
    entries: Id, Names, Image, Created (unix secs), Labels, Ports
    ([{PrivatePort, PublicPort, Type, IP}]).  ``ServicePort_<private>``
    labels map container ports to well-known service ports; a container
    bound to a specific IP overrides the host IP.
    """
    import socket

    labels = container.get("Labels") or {}
    now = now_ns() if now is None else now
    svc = Service(
        id=(container.get("Id") or "")[:12],
        name=(container.get("Names") or [""])[0],
        image=container.get("Image", ""),
        created=int(container.get("Created", 0)) * NS_PER_SECOND,
        hostname=hostname if hostname is not None else socket.gethostname(),
        updated=now,
        proxy_mode=labels.get("ProxyMode", "http"),
        status=ALIVE,
    )
    for port in container.get("Ports") or []:
        if not port.get("PublicPort"):
            continue
        pip = port.get("IP") or ""
        use_ip = pip if pip not in ("", "0.0.0.0") else ip
        p = Port(type=port.get("Type", "tcp"), port=int(port["PublicPort"]),
                 ip=use_ip)
        label = f"ServicePort_{port.get('PrivatePort', 0)}"
        if label in labels:
            try:
                p.service_port = int(labels[label])
            except ValueError:
                pass
        svc.ports.append(p)
    return svc


def format_service(svc: Service, now: Optional[int] = None) -> str:
    """Human one-liner (service/service.go:74-89)."""
    from sidecar_tpu.output import time_ago

    now = now_ns() if now is None else now
    ports = ",".join(f"{p.service_port}->{p.port}" for p in svc.ports)
    return (f"      {svc.id} {svc.name:<30} {ports:<15} {svc.image:<45}  "
            f"{time_ago(svc.updated, now):<15} {svc.status_string():<9}\n")
