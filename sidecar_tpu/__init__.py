"""sidecar-tpu: a TPU-native service-discovery + gossip-simulation framework.

A ground-up rebuild of the capabilities of WCC-Analytics/sidecar (a Go
peer-to-peer service-discovery platform built on SWIM gossip) with a
TPU-first architecture:

* ``sidecar_tpu.ops``      — pure JAX kernels: LWW merge, gossip scatter,
  TTL decay, topology builders. The reference's ``ServicesState.Merge`` /
  ``AddServiceEntry`` (catalog/services_state.go:293-373) become a batched
  scatter/segment-max over a peer-adjacency structure.
* ``sidecar_tpu.models``   — simulation models built from the ops: the exact
  record-level model and the large-scale bitmap model.
* ``sidecar_tpu.parallel`` — device-mesh sharding (``jax.sharding`` +
  ``shard_map``) for multi-chip simulation of 100k+-node clusters.
* ``sidecar_tpu.sim``      — scenario runners (BASELINE.json configs),
  convergence instrumentation, checkpointing, and the NumPy oracle used to
  validate kernels against the Go reference's merge-loop semantics.
* ``sidecar_tpu.catalog``  — the live replicated-state core (the analog of
  the reference's catalog/ServicesState).
* ``sidecar_tpu.discovery`` / ``health`` / ``proxy`` / ``http`` /
  ``receiver`` — the live service-discovery surface: discovery plugins,
  health monitor, HAProxy/Envoy drivers, HTTP API, event receiver library.
* ``sidecar_tpu.transport`` — gossip wire transport (C++ core via ctypes).
* ``sidecar_tpu.bridge``   — the Delegate-shaped simulation bridge
  ("simulate N rounds over M nodes").

The package is built out incrementally; a module listed above that does
not import yet is simply not built yet — check the repo history.
"""

__version__ = "0.1.0"
