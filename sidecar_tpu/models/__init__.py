"""Simulation models assembled from the ops kernels."""

from sidecar_tpu.models.timecfg import TimeConfig  # noqa: F401
from sidecar_tpu.models.exact import ExactSim, SimParams, SimState  # noqa: F401
from sidecar_tpu.models.compressed import (  # noqa: F401
    CompressedParams,
    CompressedSim,
    CompressedState,
)
