"""The exact record-level gossip simulation model.

Cluster state is one packed int32 tensor ``known[N, M]``: node *n*'s
current belief about every service slot *m* (M = N × services_per_node;
slot *m* is owned by node ``m // services_per_node``).  This is the dense
recast of the reference's ``Servers[hostname].Services[id]`` two-level map
(catalog/services_state.go:70-80) — a node's row is its whole replicated
catalog, and the owner's own cells double as its local truth (exactly as
the reference keeps local services in the same state map).

One simulated round = one GossipInterval (200 ms):

1. **select** — sample fan-out peers; take each node's top-``budget``
   freshest *eligible* records (ops/gossip.py; eligibility is the int8
   transmit-count queue ``sent`` — the vectorized TransmitLimited
   broadcast queue, count-based so backlogged records wait instead of
   expiring).
2. **deliver + announce** — expand messages into update triples with the
   merge semantics (staleness gate, DRAINING stickiness vs the pre-round
   state), fold in the announce path's re-stamps (``BroadcastServices``'s
   1-minute refresh, services_state.go:547-549, staggered per node), and
   apply them all in ONE scatter-max on ``known`` plus ONE reset scatter
   on ``sent``.  Scatters on the big tensors each cost a full buffer
   rewrite on TPU — one per tensor per round (plus the small
   transmit-count bump) is the performance budget.  Announce re-stamps
   land at the END of a round and become broadcastable the following
   round (the reference's 5×/10× @ 1 Hz announce repeats are subsumed by
   the transmit-count queue, which keeps a fresh version offered until
   it has had its ~limit transmissions).
3. **push-pull** — every 20 s, full two-way anti-entropy with one random
   peer (services_delegate.go:146-167).
4. **sweep** — every 2 s, the lifespan/tombstone-GC sweep (ops/ttl.py);
   expired cells get their counts reset, the vectorized analog of the
   10× tombstone rebroadcast (services_state.go:620-624).

Everything is shape-static and scan-compatible; ``run`` drives N rounds
under ``jax.lax.scan`` and reports a per-round convergence fraction.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import knobs as knob_ops
from sidecar_tpu.ops import pipeline as pipeline_ops
from sidecar_tpu.ops import provenance as prov_ops
from sidecar_tpu.ops import sparse as sparse_ops
from sidecar_tpu.ops import suspicion as suspicion_ops
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.status import ALIVE, TOMBSTONE, is_known, pack, unpack_status, unpack_ts
from sidecar_tpu.ops.topology import Topology
from sidecar_tpu.telemetry import cost
from sidecar_tpu.ops.ttl import ttl_sweep


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimState:
    """Pytree carried through the round scan."""

    known: jax.Array       # int32 [N, M] packed (ts<<3|status)
    sent: jax.Array        # int8 [N, M] transmit counts (TransmitLimited)
    node_alive: jax.Array  # bool [N] — cluster membership (churn/SWIM)
    round_idx: jax.Array   # int32 scalar — completed rounds


def clone_state(state):
    """Deep-copy a sim state pytree onto fresh device buffers.

    The ``_run*`` drivers DONATE their input state (the ~100 MB belief
    tensors would otherwise be double-buffered across every chunked
    dispatch); a caller that needs the pre-run state afterwards — the
    warm/timed benchmark pattern, replay tests — passes ``donate=False``
    to the driver, which routes through this copy, or clones explicitly.
    """
    return jax.tree_util.tree_map(jnp.copy, state)


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static simulation parameters (hashable; safe to close over jit)."""

    n: int                      # nodes
    services_per_node: int = 10
    fanout: int = 3             # gossip targets per round (memberlist GossipNodes)
    budget: int = 15            # records per message batch (GossipMessages=15, config/config.go:46)
    drop_prob: float = 0.0      # UDP loss fault injection
    retransmit_limit: int = 0   # 0 = auto: RetransmitMult(4) × ⌈log10(n+1)⌉
                                # transmissions per record version (memberlist
                                # TransmitLimited semantics)
    sparse_cap: int = 0         # C — static sender-frontier width of the
                                # sparse round (0 = auto); rounds whose
                                # eligible-sender set exceeds it fall back
                                # to the dense round, bit-identically
                                # (docs/sparse.md)

    def __post_init__(self):
        # The int8 transmit counters are unclamped scatter-adds bounded
        # by limit + fanout - 1 (ops/gossip.record_transmissions) — the
        # limit must leave that bound representable.
        if self.resolved_retransmit_limit() + self.fanout - 1 > 127:
            raise ValueError(
                f"retransmit_limit={self.resolved_retransmit_limit()} + "
                f"fanout={self.fanout} - 1 exceeds the int8 transmit "
                "counter range (127)")

    @property
    def m(self) -> int:
        return self.n * self.services_per_node

    def resolved_retransmit_limit(self) -> int:
        if self.retransmit_limit > 0:
            return self.retransmit_limit
        return 4 * math.ceil(math.log10(self.n + 1))



# A perturbation hook: (state, key, now_tick) -> state, applied before each
# round. Scenario logic (service churn, node kill, partition toggling) goes
# here so the core step stays pure protocol.
PerturbFn = Callable[[SimState, jax.Array, jax.Array], SimState]


def _resolve_cadence(tick_period, tick_phase, n: int):
    """Normalize constructor cadence arguments (shared by every model
    family): ``None``/1 stays the static Python scalar that compiles
    the pre-cadence program; anything else becomes an int32 device
    vector (scalar → length-1, broadcast by the gate).  Period values
    must be ≥ 1 ints, phase any int — the named validation the fleet
    grid mirrors (fleet/grid.py)."""
    if tick_period is None:
        tick_period = 1
    if tick_phase is None:
        tick_phase = 0
    if isinstance(tick_period, int) and isinstance(tick_phase, int):
        if tick_period < 1:
            raise ValueError(
                f"tick_period must be an int ≥ 1, got {tick_period!r}")
        if tick_period == 1:
            return 1, 0
        return tick_period, tick_phase
    period = np.asarray(tick_period, dtype=np.int64).reshape(-1)
    phase = np.asarray(tick_phase, dtype=np.int64).reshape(-1)
    if (period < 1).any():
        raise ValueError(
            f"tick_period entries must be ≥ 1, got min {period.min()}")
    for name, v in (("tick_period", period), ("tick_phase", phase)):
        if v.shape[0] not in (1, n):
            raise ValueError(
                f"{name} must be a scalar or a length-{n} per-node "
                f"vector, got shape {v.shape}")
    return (jnp.asarray(period, jnp.int32),
            jnp.asarray(phase, jnp.int32))


class ExactSim:
    """Single-chip exact simulator (multi-chip: ``sidecar_tpu.parallel``)."""

    # Whether this sim implements the sparse-frontier round; the chaos
    # wrapper overrides to False (its fault-gated round stays dense —
    # the delay rings/packet masks are already bounded structures).
    supports_sparse = True
    # Whether this sim implements the software-pipelined round
    # (ops/pipeline.py, docs/pipeline.md); the chaos wrapper overrides
    # to False (its fault-gated delivery rings assume the lockstep
    # select-deliver ordering).
    supports_pipeline = True

    def __init__(self, params: SimParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 perturb: Optional[PerturbFn] = None,
                 cut_mask: Optional[np.ndarray] = None,
                 sparse: Optional[str] = None,
                 pipeline: Optional[str] = None,
                 tick_period=None, tick_phase=None):
        if topo.n != params.n:
            raise ValueError(f"topology has {topo.n} nodes, params say {params.n}")
        self.p = params
        self.t = timecfg
        self.topo = topo
        self.perturb = perturb
        # Sparse-frontier mode (ops/sparse.py, docs/sparse.md): resolved
        # once at construction, like the compressed model.
        self._sparse_mode = sparse_ops.resolve_sparse(sparse)
        self._sparse_cap = min(
            params.n,
            params.sparse_cap or sparse_ops.default_frontier_cap(params.n))
        self.last_sparse_stats = None
        if cut_mask is not None and topo.nbrs is None:
            raise ValueError(
                "cut_mask requires a neighbor-list topology (mesh/ring/ER/BA);"
                " a complete graph has no edge structure to cut"
            )
        self._nbrs = None if topo.nbrs is None else jnp.asarray(topo.nbrs)
        self._deg = None if topo.deg is None else jnp.asarray(topo.deg)
        self._cut = None if cut_mask is None else jnp.asarray(cut_mask)
        # Round-stagger phase offsets (ops/topology.with_stagger,
        # docs/topology.md): None compiles the unstaggered program bit
        # for bit — the round only passes the gating kwargs when active.
        self._stagger = (None if topo.stagger is None
                         or topo.stagger_period <= 1
                         else jnp.asarray(topo.stagger, jnp.int32))
        self._stagger_period = int(topo.stagger_period)
        # Pipelined-round mode (ops/pipeline.py, docs/pipeline.md):
        # resolved once at construction, like sparse/kernels.
        self._pipeline_mode = pipeline_ops.resolve_pipeline(pipeline)
        # Heterogeneous tick cadence (docs/pipeline.md): None/1 compiles
        # the pre-cadence program bit for bit; a scalar or per-node [N]
        # vector keeps the cadence gate compiled.  Rides the knob
        # bundle, so the fleet can sweep it as a data axis.
        tick_period, tick_phase = _resolve_cadence(
            tick_period, tick_phase, params.n)
        # The static data-axis knob bundle (ops/knobs.py): plain Python
        # scalars that const-fold the round into exactly the pre-knob
        # program; the fleet engine overrides per round with a stacked,
        # traced bundle instead (docs/sweep.md).
        self._knobs = knob_ops.from_protocol(
            params, timecfg, tick_period=tick_period,
            tick_phase=tick_phase)
        # Max positive clock-skew offset any stamping site can add to a
        # tick (0 outside the chaos family) — the horizon guard folds it
        # in so an injected rushing clock cannot silently run the packed
        # key into the sign bit (models/timecfg.validate_horizon).
        self._skew_ticks = 0
        # owner[m] = node that announces slot m.
        self.owner = jnp.arange(params.m, dtype=jnp.int32) // params.services_per_node

    def _stagger_kw(self, round_idx):
        """The ``sample_peers`` stagger kwargs for this round — ``{}``
        when no stagger is attached, so the call (and the compiled
        program) is byte-identical to the pre-stagger form.  Gossip
        fan-out only; the push-pull partner draw never takes these."""
        if self._stagger is None:
            return {}
        return dict(stagger=self._stagger,
                    stagger_period=self._stagger_period,
                    round_idx=round_idx)

    def _gate_kw(self, round_idx, kn=None):
        """All of ``sample_peers``'s delivery-gating kwargs for this
        round: the topology's stagger offsets plus the knob bundle's
        heterogeneous tick cadence (ops/gossip.cadence_gate) — ``{}``
        when neither is active, so the call compiles the pre-gate
        program byte for byte.  Gossip fan-out only; the push-pull
        partner draw never takes these (anti-entropy is the catch-up
        channel)."""
        kn = self._knobs if kn is None else kn
        kw = self._stagger_kw(round_idx)
        if kn.cadence_enabled:
            kw = dict(kw)
            kw.update(tick_period=kn.tick_period,
                      tick_phase=kn.tick_phase, round_idx=round_idx)
        return kw

    # -- state construction ------------------------------------------------

    def init_state(self, live_fraction: float = 1.0, seed: int = 0) -> SimState:
        """Cold start: every owner knows (only) its own services, announced
        at tick 1 — the moment after cluster boot, before any gossip."""
        p = self.p
        known = jnp.zeros((p.n, p.m), dtype=jnp.int32)
        rows = self.owner
        cols = jnp.arange(p.m, dtype=jnp.int32)
        vals = jnp.full((p.m,), pack(1, ALIVE), dtype=jnp.int32)
        if live_fraction < 1.0:
            rng = np.random.default_rng(seed)
            live = jnp.asarray(rng.random(p.m) < live_fraction)
            vals = jnp.where(live, vals, 0)
        known = known.at[rows, cols].set(vals)
        return SimState(
            known=known,
            sent=jnp.zeros((p.n, p.m), dtype=jnp.int8),
            node_alive=jnp.ones((p.n,), dtype=bool),
            round_idx=jnp.zeros((), jnp.int32),
        )

    # -- kernels -----------------------------------------------------------

    @cost.phased("announce")
    def _announce_updates(self, known, node_alive, round_idx, now_tick,
                          kn=None):
        """Update triples for the owners' refresh re-stamps
        (``BroadcastServices``'s 1-minute path, services_state.go:547-549,
        staggered per record — hash-spread phase + elapsed-time guard,
        ops/gossip.refresh_due).  Non-due cells are masked to val 0 / row
        OOB so the combined scatter drops them.  Tombstones are never
        refreshed — they age out via the 3 h GC."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        cols = jnp.arange(p.m, dtype=jnp.int32)
        own = known[self.owner, cols]              # [M] owners' own cells
        st = unpack_status(own)
        present = is_known(own) & node_alive[self.owner]

        due = gossip_ops.refresh_due(
            own, cols, round_idx, refresh_rounds=kn.refresh_rounds,
            round_ticks=t.round_ticks, now=now_tick) & present \
            & (st != TOMBSTONE)
        # Lifeguard self-refutation (ops/suspicion.py): a SUSPECT own
        # record announces a refuting ALIVE immediately; compiles to
        # nothing while the suspicion window is 0.
        due, st = suspicion_ops.announce_refute(
            due, st, present, kn.suspicion_enabled)

        vals = jnp.where(due, pack(now_tick, st), 0)
        rows = jnp.where(due, self.owner, p.n)     # OOB row drops the entry
        return rows, cols, vals, due

    def _round_deliver_announce(self, known, sent, node_alive, dst,
                                k_drop, round_idx, now, kn=None):
        """Phases 1 + 2 of the round (select → deliveries → announce →
        the combined scatter) — the DENSE form, extracted so the sparse
        step's overflow fallback is literally this function.  Returns
        ``(known, sent)``."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        limit = kn.limit

        # 1. select + gossip deliveries (from the pre-round state).
        svc_idx, msg = gossip_ops.select_messages(
            known, sent, p.budget, limit)
        sent = gossip_ops.record_transmissions(
            sent, svc_idx, msg, p.fanout, limit)
        # Packet loss: the keep mask is drawn HERE (same key, prob, and
        # dense shape as the in-call draw the pre-knob program made —
        # bit-identical, and the shape the sparse path slices) so a
        # traced per-scenario keep_prob works; a static keep_prob of 1
        # compiles no draw at all, as before.
        record_keep = None
        if kn.needs_drop_draw:
            record_keep = jax.random.bernoulli(
                k_drop, kn.keep_prob,
                (p.n, p.fanout, svc_idx.shape[1]))
        tb = kn.budget_arg()
        sender_own = None
        if tb is not None:
            # The sender-owned mask for the per-origin budget: a node's
            # own records never count against its suspicious budget
            # (ops/merge.budget_mask) — owners legitimately announce
            # their own tombstones.  OOB svc slots carry msg == 0
            # (ts 0, never suspicious), so the clamp is value-safe.
            sender_own = (self.owner[jnp.minimum(svc_idx, p.m - 1)]
                          == jnp.arange(p.n, dtype=jnp.int32)[:, None])
        d_rows, d_cols, d_vals, d_adv = gossip_ops.prepare_deliveries(
            known, dst, svc_idx, msg,
            now_tick=now, stale_ticks=kn.stale_ticks,
            node_alive=node_alive,
            record_keep=record_keep,
            future_ticks=kn.future_arg(),
            tomb_budget=tb, sender_own=sender_own,
        )

        # 2. announce re-stamps, folded into the same scatter.
        a_rows, a_cols, a_vals, a_due = self._announce_updates(
            known, node_alive, round_idx, now, kn=kn)

        rows = jnp.concatenate([d_rows, a_rows])
        cols = jnp.concatenate([d_cols, a_cols])
        vals = jnp.concatenate([d_vals, a_vals])
        advanced = jnp.concatenate([d_adv, a_due])
        return gossip_ops.apply_updates(
            known, sent, rows, cols, vals, advanced)

    def _round_deliver_announce_sparse(self, known, sent, node_alive,
                                       dst, k_drop, round_idx, now,
                                       sender):
        """Phases 1 + 2 on the compacted sender frontier — bit-identical
        to the dense form when the frontier fits (the caller guards with
        the dense fallback).  Only SENDERS compact on the exact model:
        deliveries are pushes, so the update triples shrink to
        ``C·F·B`` and the select/top-k runs on ``[C, M]``; the combined
        scatter-max is commutative, and every omitted row's triples are
        provable no-ops (no eligible records ⇒ ``msg == 0`` ⇒ val 0 /
        OOB svc).  The scatter itself stays — the measured dense-model
        floor (benchmarks/RESULTS.md), so the exact model's sparse win
        is the select side, not the apply."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        n = p.n

        idx_s, row_s, valid_s, _ = sparse_ops.compact_rows(
            sender, self._sparse_cap)
        kn_s = jnp.where(valid_s[:, None], known[row_s], 0)
        svc_c, msg_c = gossip_ops.select_messages(
            kn_s, sent[row_s], p.budget, limit, row_ids=idx_s)
        sent = gossip_ops.record_transmissions(
            sent, svc_c, msg_c, p.fanout, limit, row_ids=idx_s)

        keep_c = None
        if p.drop_prob > 0.0:
            # The dense-shaped draw, sliced — the loss stream is
            # mode-independent (ops/sparse.py).
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob,
                (n, p.fanout, svc_c.shape[1]))
            keep_c = keep[row_s]
        sender_own_c = None
        if t.tomb_budget is not None:
            # Compacted twin of the dense sender-owned mask: the sender
            # of compacted row c is ``row_s[c]``.
            sender_own_c = (self.owner[jnp.minimum(svc_c, p.m - 1)]
                            == row_s[:, None])
        d_rows, d_cols, d_vals, d_adv = gossip_ops.prepare_deliveries(
            known, dst[row_s], svc_c, msg_c,
            now_tick=now, stale_ticks=t.stale_ticks,
            node_alive=node_alive,
            sender_alive=node_alive[row_s] & valid_s,
            record_keep=keep_c,
            future_ticks=t.future_ticks,
            tomb_budget=t.tomb_budget, sender_own=sender_own_c,
        )

        a_rows, a_cols, a_vals, a_due = self._announce_updates(
            known, node_alive, round_idx, now)

        rows = jnp.concatenate([d_rows, a_rows])
        cols = jnp.concatenate([d_cols, a_cols])
        vals = jnp.concatenate([d_vals, a_vals])
        advanced = jnp.concatenate([d_adv, a_due])
        return gossip_ops.apply_updates(
            known, sent, rows, cols, vals, advanced)

    def _step(self, state: SimState, key: jax.Array,
              kn=None) -> SimState:
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            # Knob-aware perturb hooks (the fleet's per-scenario churn,
            # fleet/batch.py) opt in via a ``wants_knobs`` attribute;
            # the classic 3-arg contract is unchanged.
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)
        known, sent, node_alive = state.known, state.sent, state.node_alive

        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
            **self._gate_kw(round_idx, kn),
        )
        known, sent = self._round_deliver_announce(
            known, sent, node_alive, dst, k_drop, round_idx, now, kn=kn)

        # 3. anti-entropy push-pull (amortized: every push_pull_rounds).
        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
        )[:, 0]

        pp_tb = kn.budget_arg()

        def do_push_pull(kn_se):
            kn_, se = kn_se
            merged = gossip_ops.push_pull(
                kn_, pp_partner, now_tick=now,
                stale_ticks=kn.stale_ticks, node_alive=node_alive,
                future_ticks=kn.future_arg(),
                tomb_budget=pp_tb,
                owner=self.owner if pp_tb is not None else None)
            se = jnp.where(merged != kn_, jnp.int8(0), se)
            return merged, se

        known, sent = lax.cond(
            round_idx % kn.push_pull_rounds == 0,
            do_push_pull, lambda kn_se: kn_se, (known, sent))

        # 4. lifespan sweep (amortized: every sweep_rounds).  Expired
        # cells get their counts reset — the 10× tombstone rebroadcast.
        def do_sweep(kn_se):
            kn_, se = kn_se
            swept, expired = ttl_sweep(
                kn_, now,
                alive_lifespan=kn.alive_lifespan,
                draining_lifespan=kn.draining_lifespan,
                tombstone_lifespan=kn.tombstone_lifespan,
                one_second=t.one_second,
                suspicion_window=kn.suspicion_window)
            se = jnp.where(swept != kn_, jnp.int8(0), se)
            return swept, se

        known, sent = lax.cond(
            round_idx % kn.sweep_rounds == 0,
            do_sweep, lambda kn_se: kn_se, (known, sent))

        return SimState(known=known, sent=sent, node_alive=node_alive,
                        round_idx=round_idx)

    def _step_sparse(self, state: SimState, key: jax.Array):
        """One round on the sparse path (docs/sparse.md): the sender
        frontier — rows with any ELIGIBLE record (TransmitLimited
        budget left on a known cell) — is compacted when it fits the
        static cap, with a ``lax.cond`` dense fallback when it
        overflows; bit-identical either way.  Returns
        ``(state, stats[3])``."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)
        known, sent, node_alive = state.known, state.sent, state.node_alive

        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
            **self._gate_kw(round_idx),
        )
        sender = jnp.any(
            gossip_ops.eligible_records(known, sent, limit), axis=1)
        n_s = jnp.sum(sender.astype(jnp.int32))
        overflow = n_s > self._sparse_cap

        known, sent = lax.cond(
            overflow,
            lambda ks: self._round_deliver_announce(
                ks[0], ks[1], node_alive, dst, k_drop, round_idx, now),
            lambda ks: self._round_deliver_announce_sparse(
                ks[0], ks[1], node_alive, dst, k_drop, round_idx, now,
                sender),
            (known, sent))

        # 3 + 4 — cadence-amortized, dense in both modes (identical to
        # the dense step's tail).
        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
        )[:, 0]

        def do_push_pull(kn_se):
            kn, se = kn_se
            merged = gossip_ops.push_pull(
                kn, pp_partner, now_tick=now, stale_ticks=t.stale_ticks,
                node_alive=node_alive, future_ticks=t.future_ticks,
                tomb_budget=t.tomb_budget,
                owner=(self.owner if t.tomb_budget is not None
                       else None))
            se = jnp.where(merged != kn, jnp.int8(0), se)
            return merged, se

        known, sent = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            do_push_pull, lambda kn_se: kn_se, (known, sent))

        def do_sweep(kn_se):
            kn, se = kn_se
            swept, expired = ttl_sweep(
                kn, now,
                alive_lifespan=t.alive_lifespan,
                draining_lifespan=t.draining_lifespan,
                tombstone_lifespan=t.tombstone_lifespan,
                one_second=t.one_second,
                suspicion_window=t.suspicion_window)
            se = jnp.where(swept != kn, jnp.int8(0), se)
            return swept, se

        known, sent = lax.cond(
            round_idx % t.sweep_rounds == 0,
            do_sweep, lambda kn_se: kn_se, (known, sent))

        ov = overflow.astype(jnp.int32)
        stats = jnp.stack([1 - ov, ov, n_s])
        return SimState(known=known, sent=sent, node_alive=node_alive,
                        round_idx=round_idx), stats

    # -- software-pipelined round (ops/pipeline.py, docs/pipeline.md) ------
    # The (state, inflight) scan carry: inflight is round r's already-
    # selected publish (dst, svc_idx, msg), chosen from the state BEFORE
    # round r-1's deliveries were folded — the honest one-round-stale
    # semantics of pipelined gossiping.  Each tick folds round r's
    # in-flight messages AND selects round r+1's publish from the
    # pre-fold belief, so on device the next round's publish/top-k
    # overlaps the current round's gather/apply (the scheduler is free
    # to interleave them — no data dependence until the combined
    # scatter).  Per-round PRNG streams stay positionally identical to
    # the lockstep round: round r's (perturb, peers, drop, pp) keys are
    # the 4-way split of fold_in(key, r-1); the peers leg is simply
    # consumed one tick early, by the selection.

    def _select_inflight(self, known, sent, node_alive, round_sel,
                         k_round, kn=None):
        """Select round ``round_sel``'s publish from the current belief:
        sampled fan-out targets (gated by stagger/cadence at
        ``round_sel``, with the CURRENT — stale-by-one — liveness), the
        top-budget eligible records, and the transmit-count charge.
        Returns ``(inflight, sent)`` where inflight = (dst, svc_idx,
        msg).  The charge lands on the pre-apply ``sent``, so a version
        advance folding in the same tick resets it — the reset wins on
        overlap, exactly the lockstep bump-then-reset ordering."""
        p = self.p
        kn = self._knobs if kn is None else kn
        _kp, k_peers, _kd, _kpp = jax.random.split(k_round, 4)
        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
            **self._gate_kw(round_sel, kn),
        )
        svc_idx, msg = gossip_ops.select_messages(
            known, sent, p.budget, kn.limit)
        sent = gossip_ops.record_transmissions(
            sent, svc_idx, msg, p.fanout, kn.limit)
        return (dst, svc_idx, msg), sent

    def _step_pipelined(self, state: SimState, inflight, k_now, k_next,
                        kn=None):
        """One pipelined tick: fold round r's carried in-flight publish
        into the state, select round r+1's publish from the PRE-fold
        belief, then run the lockstep anti-entropy/sweep tail.  Returns
        ``(state, inflight')``.  ``k_now = fold_in(key, r-1)`` carries
        round r's perturb/drop/push-pull streams; ``k_next =
        fold_in(key, r)`` is split for round r+1's peer draw.  Announce
        re-stamps are computed against the pre-fold belief (they land
        in the same combined scatter, as in the lockstep round).  The
        in-flight targets were gated with LAST round's liveness (the
        stale-by-one selection), but the fold's sender/receiver
        liveness gates read THIS round's — a packet from a sender that
        died in this tick's perturb is dropped, as in the lockstep
        round."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, _k_peers, k_drop, k_pp = jax.random.split(k_now, 4)

        if self.perturb is not None:
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)
        known, sent, node_alive = state.known, state.sent, state.node_alive
        dst, svc_idx, msg = inflight

        record_keep = None
        if kn.needs_drop_draw:
            record_keep = jax.random.bernoulli(
                k_drop, kn.keep_prob,
                (p.n, p.fanout, svc_idx.shape[1]))
        tb = kn.budget_arg()
        sender_own = None
        if tb is not None:
            sender_own = (self.owner[jnp.minimum(svc_idx, p.m - 1)]
                          == jnp.arange(p.n, dtype=jnp.int32)[:, None])
        d_rows, d_cols, d_vals, d_adv = gossip_ops.prepare_deliveries(
            known, dst, svc_idx, msg,
            now_tick=now, stale_ticks=kn.stale_ticks,
            node_alive=node_alive,
            record_keep=record_keep,
            future_ticks=kn.future_arg(),
            tomb_budget=tb, sender_own=sender_own,
        )
        a_rows, a_cols, a_vals, a_due = self._announce_updates(
            known, node_alive, round_idx, now, kn=kn)

        # Round r+1's publish, from the pre-fold belief — the overlap.
        inflight, sent = self._select_inflight(
            known, sent, node_alive, round_idx + 1, k_next, kn=kn)

        rows = jnp.concatenate([d_rows, a_rows])
        cols = jnp.concatenate([d_cols, a_cols])
        vals = jnp.concatenate([d_vals, a_vals])
        advanced = jnp.concatenate([d_adv, a_due])
        known, sent = gossip_ops.apply_updates(
            known, sent, rows, cols, vals, advanced)

        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
        )[:, 0]
        pp_tb = kn.budget_arg()

        def do_push_pull(kn_se):
            kn_, se = kn_se
            merged = gossip_ops.push_pull(
                kn_, pp_partner, now_tick=now,
                stale_ticks=kn.stale_ticks, node_alive=node_alive,
                future_ticks=kn.future_arg(),
                tomb_budget=pp_tb,
                owner=self.owner if pp_tb is not None else None)
            se = jnp.where(merged != kn_, jnp.int8(0), se)
            return merged, se

        known, sent = lax.cond(
            round_idx % kn.push_pull_rounds == 0,
            do_push_pull, lambda kn_se: kn_se, (known, sent))

        def do_sweep(kn_se):
            kn_, se = kn_se
            swept, expired = ttl_sweep(
                kn_, now,
                alive_lifespan=kn.alive_lifespan,
                draining_lifespan=kn.draining_lifespan,
                tombstone_lifespan=kn.tombstone_lifespan,
                one_second=t.one_second,
                suspicion_window=kn.suspicion_window)
            se = jnp.where(swept != kn_, jnp.int8(0), se)
            return swept, se

        known, sent = lax.cond(
            round_idx % kn.sweep_rounds == 0,
            do_sweep, lambda kn_se: kn_se, (known, sent))

        return SimState(known=known, sent=sent, node_alive=node_alive,
                        round_idx=round_idx), inflight

    def convergence(self, state: SimState) -> jax.Array:
        """Fraction of (alive-node, slot) cells agreeing with the global
        freshest belief — 1.0 means every live node has converged."""
        alive = state.node_alive
        truth = jnp.max(jnp.where(alive[:, None], state.known, 0), axis=0)
        agree = state.known == truth[None, :]
        alive_f = alive.astype(jnp.float32)
        per_node = jnp.mean(agree.astype(jnp.float32), axis=1)
        return jnp.sum(per_node * alive_f) / jnp.maximum(jnp.sum(alive_f), 1.0)

    # -- provenance hooks (ops/provenance.py, docs/telemetry.md) -----------
    # The provenance plane rides BESIDE the round: belief is a pure read
    # of the state, and channels re-derives the round's peer samples from
    # the very key the step consumed (sample_peers is pure) — the step's
    # own tensors are never touched, which keeps provenance-enabled runs
    # bit-identical to untraced ones.

    def _prov_belief(self, state: SimState,
                     tracked: jax.Array) -> jax.Array:
        """Packed [N, T] belief matrix for the tracked slots."""
        return state.known[:, tracked]

    def _prov_channels(self, state: SimState, key: jax.Array, kn=None):
        """Re-derive the round's sampled channels from ``key`` (the same
        key the step folds): gossip pushes ``dst``, plus the two-way
        push-pull edge when the cadence fires.  The perturb hook is
        re-applied first (pure; same key ⇒ same result) because the step
        samples peers with the POST-perturb liveness."""
        p = self.p
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * self.t.round_ticks
        k_perturb, k_peers, _k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)
        node_alive = state.node_alive

        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
            **self._gate_kw(round_idx, kn),
        )
        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1,
            nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut,
        )
        pp_on = jnp.broadcast_to(round_idx % kn.push_pull_rounds == 0,
                                 (p.n, 1))
        # push-pull is two-way: i pulls from its partner AND pushes to it.
        pushes = [(dst, None), (pp_partner, pp_on)]
        pulls = [(pp_partner, pp_on)]
        return pushes, pulls

    # -- drivers -----------------------------------------------------------
    # Public drivers validate the tick horizon against the *starting*
    # round_idx (state is concrete between calls) before dispatching to the
    # jitted implementations — a resumed/chunked simulation must not be
    # able to silently run the int32 packed-key clock into the sign bit.
    #
    # Donation: every _run*_jit entry point DONATES the input state
    # (donate_argnums=1) so the belief tensors are rewritten in place
    # across chunked dispatches instead of double-buffered — ~840 MB of
    # HBM headroom at the dense bench shape, ~100 MB on the compressed
    # north star.  After run*(state, ...) returns, ``state``'s buffers
    # are DELETED (accessing them raises); pass ``donate=False`` to keep
    # the input alive at the cost of one device copy.

    def _check_horizon(self, state: SimState, num_rounds: int,
                       start_round=None) -> None:
        # ``start_round`` lets pipelined callers validate the horizon
        # from a host-side round counter — reading ``state.round_idx``
        # of an in-flight chunk's output would block on that chunk and
        # serialize the dispatch pipeline (see bridge/sim_bridge.py).
        if start_round is None:
            start_round = int(state.round_idx)
        self.t.validate_horizon(start_round + num_rounds,
                                skew_ticks=self._skew_ticks)

    def _resolve_sparse_request(self, sparse):
        return sparse_ops.resolve_request(self._sparse_mode, sparse,
                                          self.supports_sparse)

    def _resolve_pipeline_request(self, pipeline):
        return pipeline_ops.resolve_request(self._pipeline_mode, pipeline,
                                            self.supports_pipeline)

    def _pipeline_dispatch(self, sparse):
        """Guard for a pipelined ``run``/``run_fast`` dispatch: the
        pipelined round has no sparse-frontier form (the selection it
        hoists IS the dense select) — an explicit or env-forced sparse
        request composes with it only by raising loudly."""
        if self._resolve_sparse_request(sparse):
            raise ValueError(
                "pipelined execution does not compose with the "
                "sparse-frontier round (the hoisted publish is the dense "
                "select); dispatch one or the other — docs/pipeline.md")

    def step(self, state: SimState, key: jax.Array) -> SimState:
        self._check_horizon(state, 1)
        return self._step_jit(state, key)

    def step_sparse(self, state: SimState, key: jax.Array):
        """One sparse-path round → ``(state, stats[3])`` — the lockstep
        suites' probe."""
        self._resolve_sparse_request(True)
        self._check_horizon(state, 1)
        return self._step_sparse_jit(state, key)

    def run(self, state: SimState, key: jax.Array, num_rounds: int,
            donate: bool = True, start_round=None, sparse=None,
            pipeline=None):
        """Scan ``num_rounds`` gossip rounds; returns (final state,
        per-round convergence fraction [num_rounds]).  Donates ``state``
        unless ``donate=False`` (see the drivers note above).
        ``sparse`` selects the sparse-frontier round (docs/sparse.md);
        the dispatch's stats land in ``last_sparse_stats``.
        ``pipeline`` selects the software-pipelined round
        (docs/pipeline.md; one-round-stale publish) — ``None`` follows
        ``SIDECAR_TPU_PIPELINE``, and the off path dispatches the
        UNCHANGED lockstep drivers, bit for bit."""
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, conv, _inflight = self.run_pipelined(
                state, key, num_rounds, donate=donate,
                start_round=start_round)
            return final, conv
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, conv, stats = self._run_sparse_jit(state, key,
                                                      num_rounds)
            self.last_sparse_stats = stats
            return final, conv
        self.last_sparse_stats = None
        return self._run_jit(state, key, num_rounds)

    def run_fast(self, state: SimState, key: jax.Array, num_rounds: int,
                 donate: bool = True, sparse=None, pipeline=None):
        """Scan without per-round metrics — the benchmark path."""
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, _inflight = self.run_fast_pipelined(
                state, key, num_rounds, donate=donate)
            return final
        self._check_horizon(state, num_rounds)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, stats = self._run_fast_sparse_jit(state, key,
                                                     num_rounds)
            self.last_sparse_stats = stats
            return final
        self.last_sparse_stats = None
        return self._run_fast_jit(state, key, num_rounds)

    def run_pipelined(self, state: SimState, key: jax.Array,
                      num_rounds: int, *, inflight=None,
                      donate: bool = True, start_round=None):
        """Scan ``num_rounds`` software-pipelined rounds
        (docs/pipeline.md): returns ``(final state, conv[num_rounds],
        inflight)``.  Pass the returned ``inflight`` back to chain
        chunked dispatches bit-identically to a straight run (the
        chunked == straight contract of every driver); ``inflight=None``
        primes the pipeline by selecting round ``round_idx + 1``'s
        publish from ``state`` — positionally the same peer/select keys
        the lockstep round would use.  Composes with ``run``/
        ``run_fast`` only (trace/digest/delta/provenance planes keep
        the lockstep round)."""
        self._resolve_pipeline_request(True)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if inflight is None:
            state, inflight = self._prime_jit(state, key)
        self.last_sparse_stats = None
        return self._run_pipelined_jit(state, key, num_rounds, inflight)

    def run_fast_pipelined(self, state: SimState, key: jax.Array,
                           num_rounds: int, *, inflight=None,
                           donate: bool = True, start_round=None):
        """Pipelined scan without per-round metrics — the benchmark
        path.  Returns ``(final state, inflight)``."""
        self._resolve_pipeline_request(True)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if inflight is None:
            state, inflight = self._prime_jit(state, key)
        self.last_sparse_stats = None
        return self._run_fast_pipelined_jit(state, key, num_rounds,
                                            inflight)

    def _trace_record(self, prev: SimState, nxt: SimState, stats):
        """One round's flight-recorder record (ops/trace.py)."""
        return trace_ops.exact_record(
            prev, nxt, budget=min(self.p.budget, self.p.m),
            fanout=self.p.fanout,
            limit=self.p.resolved_retransmit_limit(), stats=stats,
            tick_period=self._knobs.tick_period,
            tick_phase=self._knobs.tick_phase)

    def run_with_trace(self, state: SimState, key: jax.Array,
                       num_rounds: int, cap: int = 0,
                       donate: bool = True, start_round=None,
                       sparse=None):
        """Scan with the per-round flight recorder (ops/trace.py):
        returns ``(final state, RoundTrace, conv[num_rounds])``.  The
        record stream rides the scan carry behind the static ``cap``
        (0 = trace every round); rounds past the capacity are truncated
        with ``overflow`` set — the DeltaBatch contract.  The plain
        drivers compile none of this: ``trace=0`` dispatches
        (:meth:`run`) are bit-identical to pre-trace programs."""
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, tr, conv, stats = self._run_trace_sparse_jit(
                state, key, num_rounds, cap)
            self.last_sparse_stats = stats
            return final, tr, conv
        self.last_sparse_stats = None
        return self._run_trace_jit(state, key, num_rounds, cap)

    def _digest_record(self, nxt: SimState, idents, buckets: int):
        """One round's coherence record (ops/digest.py) over the
        post-round belief matrix."""
        return digest_ops.state_digest_record(
            nxt.round_idx, nxt.known, nxt.node_alive, idents, buckets)

    def _resolve_digest_idents(self, idents):
        """The digest identity table: caller-supplied (the bridge's
        canonical (host, sid) idents) or the pure-sim slot default."""
        if idents is None:
            idents = digest_ops.default_idents(self.p.m)
        return jnp.asarray(idents, jnp.uint32)

    def run_with_digest(self, state: SimState, key: jax.Array,
                        num_rounds: int, cap: int = 0,
                        buckets: int = digest_ops.DEFAULT_BUCKETS,
                        idents=None, donate: bool = True,
                        start_round=None, sparse=None):
        """Scan with the per-round coherence digest (ops/digest.py):
        returns ``(final state, DigestTrace, conv[num_rounds])``.  The
        record stream rides the scan carry behind the static ``cap``
        (0 = digest every round); rounds past the capacity truncate
        with ``overflow`` set.  The plain drivers compile none of
        this: digest-off dispatches are bit-identical to pre-digest
        programs (tests/test_digest.py pins all four families)."""
        cap = cap or num_rounds
        idents = self._resolve_digest_idents(idents)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, dt, conv, stats = self._run_digest_sparse_jit(
                state, key, num_rounds, cap, idents, buckets)
            self.last_sparse_stats = stats
            return final, dt, conv
        self.last_sparse_stats = None
        return self._run_digest_jit(state, key, num_rounds, cap, idents,
                                    buckets)

    def run_with_deltas(self, state: SimState, key: jax.Array,
                        num_rounds: int, cap: int, donate: bool = True,
                        start_round=None, sparse=None):
        """Scan with per-round changed-cell extraction (ops/delta.py):
        returns ``(final state, DeltaBatch[num_rounds], conv
        [num_rounds])``.  The diff runs inside the scan on consecutive
        ``known`` tensors, so only the capped index sets leave the
        device — the query plane's streaming contract (a round that
        changes more than ``cap`` cells flags ``overflow`` and the
        consumer resyncs from a snapshot)."""
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, deltas, conv, stats = self._run_deltas_sparse_jit(
                state, key, num_rounds, cap)
            self.last_sparse_stats = stats
            return final, deltas, conv
        self.last_sparse_stats = None
        return self._run_deltas_jit(state, key, num_rounds, cap)

    def run_with_provenance(self, state: SimState, key: jax.Array,
                            num_rounds: int, tracked, cap: int = 0,
                            prov=None, donate: bool = True,
                            start_round=None, sparse=None):
        """Scan with the record-level provenance tracer
        (ops/provenance.py, docs/telemetry.md): returns ``(final state,
        ProvTrace, conv[num_rounds])``.  ``tracked`` is a static tuple
        of ≤T service slots; ``cap`` bounds the per-round coverage
        window (0 = ``num_rounds``).  Pass the previous chunk's
        ``ProvTrace`` as ``prov`` to pipeline chunked dispatches — the
        trace carries absolute round numbers, so chunking is free."""
        tracked = tuple(int(s) for s in tracked)
        if not tracked:
            raise ValueError("provenance needs at least one tracked slot")
        for slot in tracked:
            if not 0 <= slot < self.p.m:
                raise ValueError(
                    f"tracked slot {slot} outside [0, {self.p.m})")
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if prov is None:
            prov = prov_ops.zero_prov(len(tracked), self.p.n, cap)
            prov = prov_ops.seed(
                prov,
                self._prov_belief(state, jnp.asarray(tracked, jnp.int32)),
                state.round_idx)
        if self._resolve_sparse_request(sparse):
            final, prov, conv, stats = self._run_prov_sparse_jit(
                state, key, num_rounds, prov, tracked)
            self.last_sparse_stats = stats
            return final, prov, conv
        self.last_sparse_stats = None
        return self._run_prov_jit(state, key, num_rounds, prov, tracked)

    # no-donate: single-round stepping is the oracle/replay path — those
    # callers diff pre- vs post-step states, so the input must survive.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_jit(self, state: SimState, key: jax.Array) -> SimState:
        return self._step(state, key)

    # no-donate: the sparse single-round probe serves the same
    # oracle/replay callers as _step_jit.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_sparse_jit(self, state: SimState, key: jax.Array):
        return self._step_sparse(state, key)

    # no-donate: the pipeline prologue runs once per chain, and the
    # oracle/replay probes diff against its input.
    @functools.partial(jax.jit, static_argnums=0)
    def _prime_jit(self, state: SimState, key: jax.Array):
        inflight, sent = self._select_inflight(
            state.known, state.sent, state.node_alive,
            state.round_idx + 1,
            jax.random.fold_in(key, state.round_idx))
        return dataclasses.replace(state, sent=sent), inflight

    # no-donate: the pipelined single-round probe is the oracle path.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_pipelined_jit(self, state: SimState, inflight, k_now,
                            k_next):
        return self._step_pipelined(state, inflight, k_now, k_next)

    def prime_pipeline(self, state: SimState, key: jax.Array):
        """The pipeline prologue as a public probe: select round
        ``round_idx + 1``'s publish from ``state`` (charging ``sent``).
        Returns ``(state, inflight)`` — what a fresh
        :meth:`run_pipelined` computes before its first tick."""
        return self._prime_jit(state, key)

    def step_pipelined(self, state: SimState, inflight, key: jax.Array):
        """One pipelined tick → ``(state, inflight')`` — the oracle
        lockstep probe (no-donate).  ``key`` is the chain's BASE key;
        the per-round now/next keys are folded in here exactly as the
        scan drivers fold them."""
        self._check_horizon(state, 1)
        return self._step_pipelined_jit(
            state, inflight,
            jax.random.fold_in(key, state.round_idx),
            jax.random.fold_in(key, state.round_idx + 1))

    # Per-round keys are derived by folding the round index into the base
    # key (not by splitting over num_rounds), so a checkpointed run
    # resumed in chunks replays the exact same randomness as a straight
    # run: run(s0, k, a+b) == run(run(s0, k, a), k, b).

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_jit(self, state: SimState, key: jax.Array, num_rounds: int):
        def body(st, _):
            st = self._step(st, jax.random.fold_in(key, st.round_idx))
            return st, self.convergence(st)

        return lax.scan(body, state, None, length=num_rounds)

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_jit(self, state: SimState, key: jax.Array, num_rounds: int):
        def body(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), None

        final, _ = lax.scan(body, state, None, length=num_rounds)
        return final

    # -- pipelined scan drivers (docs/pipeline.md) -------------------------
    # The (state, inflight) carry chains chunk-to-chunk exactly like the
    # state does, so BOTH are donated; per-round keys fold the round
    # index as everywhere else, keeping chunked == straight.

    @functools.partial(jax.jit, static_argnums=(0, 3),
                       donate_argnums=(1, 4))
    def _run_pipelined_jit(self, state: SimState, key: jax.Array,
                           num_rounds: int, inflight):
        def body(carry, _):
            st, infl = carry
            st2, infl2 = self._step_pipelined(
                st, infl,
                jax.random.fold_in(key, st.round_idx),
                jax.random.fold_in(key, st.round_idx + 1))
            return (st2, infl2), self.convergence(st2)

        (final, inflight), conv = lax.scan(
            body, (state, inflight), None, length=num_rounds)
        return final, conv, inflight

    @functools.partial(jax.jit, static_argnums=(0, 3),
                       donate_argnums=(1, 4))
    def _run_fast_pipelined_jit(self, state: SimState, key: jax.Array,
                                num_rounds: int, inflight):
        def body(carry, _):
            st, infl = carry
            return self._step_pipelined(
                st, infl,
                jax.random.fold_in(key, st.round_idx),
                jax.random.fold_in(key, st.round_idx + 1)), None

        (final, inflight), _ = lax.scan(
            body, (state, inflight), None, length=num_rounds)
        return final, inflight

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_deltas_jit(self, state: SimState, key: jax.Array,
                        num_rounds: int, cap: int):
        # Lazy import: ops/delta pulls in the compressed model's line
        # hash, and a module-level import would cycle through models.
        from sidecar_tpu.ops.delta import extract_delta

        def body(st, _):
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            return st2, (extract_delta(st.known, st2.known, cap),
                         self.convergence(st2))

        final, (deltas, conv) = lax.scan(body, state, None,
                                         length=num_rounds)
        return final, deltas, conv

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_jit(self, state: SimState, key: jax.Array,
                       num_rounds: int, cap: int):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, None))
            return (st2, buf), self.convergence(st2)

        (final, buf), conv = lax.scan(
            body, (state, trace_ops.zero_trace(cap)), None,
            length=num_rounds)
        return final, buf, conv

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_jit(self, state: SimState, key: jax.Array,
                        num_rounds: int, cap: int, idents, buckets: int):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf), self.convergence(st2)

        (final, buf), conv = lax.scan(
            body, (state, digest_ops.zero_digest(cap)), None,
            length=num_rounds)
        return final, buf, conv

    # Donates the ProvTrace too (argnum 4): it chains chunk-to-chunk the
    # way the state does.
    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_jit(self, state: SimState, key: jax.Array,
                      num_rounds: int, prov, tracked):
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2 = self._step(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv), self.convergence(st2)

        (final, prov), conv = lax.scan(body, (state, prov), None,
                                       length=num_rounds)
        return final, prov, conv

    # -- sparse-path scan drivers (docs/sparse.md) ---------------------------
    # Mirrors of the dense drivers: same donation, same per-round key
    # folding (sparse chunks pipeline/resume interchangeably with dense
    # ones), plus the int32 [3] stats accumulator surfaced through
    # ``last_sparse_stats``.

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_sparse_jit(self, state: SimState, key: jax.Array,
                        num_rounds: int):
        def body(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st)

        (final, stats), conv = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_sparse_jit(self, state: SimState, key: jax.Array,
                             num_rounds: int):
        def body(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), None

        (final, stats), _ = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_deltas_sparse_jit(self, state: SimState, key: jax.Array,
                               num_rounds: int, cap: int):
        # Lazy import: ops/delta pulls in the compressed model's line
        # hash, and a module-level import would cycle through models.
        from sidecar_tpu.ops.delta import extract_delta

        def body(carry, _):
            st, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st2, sparse_ops.accumulate_stats(acc, s)), \
                (extract_delta(st.known, st2.known, cap),
                 self.convergence(st2))

        (final, stats), (deltas, conv) = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, deltas, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_sparse_jit(self, state: SimState, key: jax.Array,
                              num_rounds: int, cap: int):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, s))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, buf, stats), conv = lax.scan(
            body, (state, trace_ops.zero_trace(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_sparse_jit(self, state: SimState, key: jax.Array,
                               num_rounds: int, cap: int, idents,
                               buckets: int):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, buf, stats), conv = lax.scan(
            body, (state, digest_ops.zero_digest(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_sparse_jit(self, state: SimState, key: jax.Array,
                             num_rounds: int, prov, tracked):
        # The sparse round consumes the same peer/push-pull draws as the
        # dense one (docs/sparse.md bit-identity), so the channel
        # re-derivation is shared.
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv, acc = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2, s = self._step_sparse(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, prov, stats), conv = lax.scan(
            body, (state, prov, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, prov, conv, stats
