"""Mapping of the reference's wall-clock protocol constants onto logical
ticks.

The reference keys every lifecycle decision off nanosecond wall clocks
(service/service.go:39, catalog/services_state.go:26-37).  int64
nanoseconds are hostile to TPU (emulated 64-bit, half scatter throughput),
so the simulator uses an int32 **logical tick** clock: 1 tick = 1 ms by
default, advancing ``round_ticks`` per gossip round.  All protocol
constants are expressed in ticks, derived from the same wall-clock values
the reference uses:

=========================  =======================  =========================
constant                   reference                default here
=========================  =======================  =========================
gossip interval            200 ms (config.go:47)    round_ticks = 200
alive lifespan             80 s  (s_state.go:32)    80_000 ticks
draining lifespan          10 min (s_state.go:33)   600_000 ticks
tombstone retention        3 h   (s_state.go:27)    10_800_000 ticks
staleness fudge            1 min (service.go:68-72) +60_000 ticks
alive refresh broadcast    1 min (s_state.go:35)    every 300 rounds
anti-entropy push-pull     20 s  (config.go:45)     every 100 rounds
lifespan sweep cadence     2 s   (s_state.go:30)    every 10 rounds
=========================  =======================  =========================

The reference's 5×/10× @ 1 Hz announce repeats (ALIVE_COUNT /
TOMBSTONE_COUNT, services_state.go:28-29) have no tick constant here: the
simulator's transmit-count queue keeps a fresh record version eligible for
~retransmit_limit/fanout rounds, which models the same delivery guarantee
(see models/exact.py ``_announce``).

int32 packed keys give 2^28-1 ticks of range (~74 h of simulated time at
1 ms/tick) — enough for every BASELINE.json scenario with wide margin.
"""

from __future__ import annotations

import dataclasses

from sidecar_tpu.ops.status import MAX_TICK


@dataclasses.dataclass(frozen=True)
class TimeConfig:
    ticks_per_second: int = 1000
    round_ticks: int = 200            # GossipInterval 200 ms (config/config.go:47)
    alive_lifespan_s: float = 80.0    # ALIVE_LIFESPAN (services_state.go:32)
    draining_lifespan_s: float = 600.0  # DRAINING_LIFESPAN (:33)
    tombstone_lifespan_s: float = 10800.0  # TOMBSTONE_LIFESPAN (:27)
    staleness_fudge_s: float = 60.0   # clock-drift fudge (service/service.go:70-71)
    refresh_interval_s: float = 60.0  # ALIVE_BROADCAST_INTERVAL (:35)
    push_pull_interval_s: float = 20.0  # PushPullInterval (config/config.go:45)
    sweep_interval_s: float = 2.0     # TOMBSTONE_SLEEP_INTERVAL (:30)
    # SWIM-style suspicion grace window (ops/suspicion.py, docs/chaos.md):
    # 0 (the default) disables the subprotocol — every round is then
    # bit-identical to the pre-suspicion sweep/announce (the lockstep
    # suites pin this).  > 0: an expired non-DRAINING record becomes
    # SUSPECT at its ORIGINAL timestamp for this window and only an
    # unrefuted suspicion tombstones (at original ts + 1 s, preserving
    # the +1 s rule).  The memberlist analog is the Lifeguard suspicion
    # timeout the live engine already carries (transport/gossip.py
    # suspect_timeout).
    suspicion_window_s: float = 0.0
    # Future-admission bound (ops/merge.future_mask, docs/chaos.md): a
    # record stamped beyond ``now + future_fudge_s`` at the receiver is
    # REJECTED at merge — the symmetric twin of the 1-minute staleness
    # fudge, the defense against rushing-clock LWW poison.  Negative
    # (the default) disables the bound; every merge site then compiles
    # the pre-bound program bit for bit (the lockstep suites pin this).
    future_fudge_s: float = -1.0
    # Per-origin suspicious-record budget (ops/merge.budget_mask,
    # docs/chaos.md "the defense ladder"): at most this many
    # third-party TOMBSTONE or ahead-of-clock records are admitted per
    # packet/exchange from one origin — the Byzantine blast-radius cap
    # the future bound alone cannot provide (a sybil flood stamps
    # WITHIN the fudge).  A count, not a duration.  Negative (the
    # default) disables the budget; every merge site then compiles the
    # pre-budget program bit for bit (the lockstep suites pin this).
    origin_budget: int = -1
    # Cumulative budget violations after which an origin is quarantined
    # outright (senders dropped in the chaos sim, origins gated at the
    # live catalog writer — chaos/sim_inject.py, ops/suspicion.py).
    # Negative (the default) disables quarantine.
    origin_quarantine: int = -1

    def ticks(self, seconds: float) -> int:
        return int(round(seconds * self.ticks_per_second))

    @property
    def alive_lifespan(self) -> int:
        return self.ticks(self.alive_lifespan_s)

    @property
    def draining_lifespan(self) -> int:
        return self.ticks(self.draining_lifespan_s)

    @property
    def tombstone_lifespan(self) -> int:
        return self.ticks(self.tombstone_lifespan_s)

    @property
    def stale_ticks(self) -> int:
        """Merge-time staleness bound: tombstone lifespan + fudge
        (services_state.go:302 + service/service.go:68-72)."""
        return self.ticks(self.tombstone_lifespan_s + self.staleness_fudge_s)

    @property
    def one_second(self) -> int:
        return self.ticks_per_second

    @property
    def suspicion_window(self) -> int:
        """Suspicion grace window in ticks (0 = subprotocol disabled)."""
        return self.ticks(self.suspicion_window_s)

    @property
    def future_ticks(self):
        """Future-admission bound in ticks, or None when disabled —
        callers skip the gate entirely on None, so the disabled program
        is the pre-bound program."""
        if self.future_fudge_s < 0:
            return None
        return self.ticks(self.future_fudge_s)

    @property
    def tomb_budget(self):
        """Per-origin suspicious-record budget (a record count), or
        None when disabled — callers skip the gate entirely on None, so
        the disabled program is the pre-budget program."""
        if self.origin_budget < 0:
            return None
        return int(self.origin_budget)

    @property
    def quarantine_threshold(self):
        """Origin-quarantine violation threshold, or None when
        disabled."""
        if self.origin_quarantine < 0:
            return None
        return int(self.origin_quarantine)

    def rounds(self, seconds: float) -> int:
        """Number of gossip rounds in a wall-clock duration."""
        return max(1, self.ticks(seconds) // self.round_ticks)

    @property
    def refresh_rounds(self) -> int:
        return self.rounds(self.refresh_interval_s)

    @property
    def push_pull_rounds(self) -> int:
        return self.rounds(self.push_pull_interval_s)

    @property
    def sweep_rounds(self) -> int:
        return self.rounds(self.sweep_interval_s)

    @property
    def max_safe_rounds(self) -> int:
        """Largest round count whose tick clock stays inside the int32
        packed-key range (no injected skew)."""
        return MAX_TICK // self.round_ticks

    def validate_horizon(self, num_rounds: int, skew_ticks: int = 0) -> None:
        """Raise when ``num_rounds`` rounds of tick advance — plus any
        injected clock-skew offset (``skew_ticks``, the max positive
        ClockFault offset a chaos plan can add to a stamp) — would run
        the int32 packed-key clock into the sign bit."""
        horizon = num_rounds * self.round_ticks + skew_ticks
        if horizon > MAX_TICK:
            skew = (f" + {skew_ticks} skew ticks" if skew_ticks else "")
            raise ValueError(
                f"{num_rounds} rounds x {self.round_ticks} ticks{skew} "
                f"overflows the int32 packed-key tick range ({MAX_TICK}); "
                f"use a coarser tick"
            )
