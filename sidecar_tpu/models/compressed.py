"""The compressed large-cluster gossip model — bounded memory per node.

The exact model's ``known[N, N·spn]`` belief matrix is O(N²·spn): at the
north-star scale (100k nodes × 1M services, BASELINE.md) that is 4×10¹¹
cells — physically impossible on any chip.  This model replaces it with
three structures totalling O(N·K + M) (SURVEY.md §7 "Sparsity +
raggedness" names this the hard part):

* ``own[N, S]`` — owner-authoritative records for each node's own
  service slots (the reference keeps local services authoritative in the
  same state map, catalog/services_state.go:70-80).
* ``cache_{slot,val,sent}[N, K]`` — each node's bounded **in-flight
  belief cache**: a direct-mapped table of the records the node has
  recently learned and is still relaying.  This mirrors reality better
  than the dense matrix does: memberlist's TransmitLimited broadcast
  queue is itself bounded (the native engine caps it at 4096), and a
  real node's "interesting" state at any moment is the small delta
  against the converged catalog.  The line index is a global
  multiplicative hash of the slot id, so one slot occupies the SAME
  line on every node — deliberately: the floor census folds "every
  line's unanimously-held winner" per sweep, and winners are only
  unanimous because freshness order and line assignment are both
  global (see :func:`hash_line` for why the salted alternative was
  measured and rejected).  Colliding live slots drain newest-first,
  losers re-entering via the owners' recovery re-offer.
* ``floor[M]`` — the shared **converged baseline**: the record version
  every alive node is known to hold.  In the real cluster each of N
  hosts stores the full O(M) catalog; simulating N identical copies of
  the converged part is pure waste, so the model stores it once and
  advances it only when a per-slot census proves every alive node has
  caught up.  belief(i, m) = max(floor[m], cache hit, own if owner).

Line competition: the freshest record (largest packed key) wins a cache
line, ties broken by larger slot id; a line's value never regresses.
Displacing an occupied line loses that belief — the model counts those
displacements (``state.evictions``) so an under-provisioned K is
visible — and liveness is restored by the owners' recovery re-offer
plus the anti-entropy cache/own exchange.

Scale regime: this model starts CONVERGED (floor = the boot catalog)
and measures how injected churn — the steady-state workload —
propagates back to full convergence.  Cold-start full-catalog sync is
the push-pull regime the exact model covers at small N; at 65k+ nodes
the physically meaningful question is delta propagation, which is what
bounded caches represent.

Round structure (mirrors models/exact.py):
1. publish + pull — each node publishes its top-``budget`` freshest
   eligible cache lines as a message **board**, and pulls the boards of
   ``fanout`` sampled peers.  Because the line hash is GLOBAL, every
   board is line-ALIGNED with every cache: delivery is a pure
   elementwise lexicographic max over ``[N, fanout, K]`` — no scatters.
   Merge semantics ride along elementwise: staleness gate, acceptance
   against the pre-round line, same-slot DRAINING stickiness.
2. announce — staggered owner re-stamps (the 1-minute refresh,
   services_state.go:547-549) minting a new version, plus **recovery**
   re-offers: own slots still above the floor re-enter the owner's
   cache with a fresh transmit budget WITHOUT a new version (the
   changed-service re-broadcast, services_state.go:538) — this is what
   makes convergence immune to cache evictions.  Owner slots are
   row-aligned with the floor (``floor.reshape(N, S)``), so the
   refresh fold is elementwise; cache inserts are one broadcast-compare
   lex reduction over the service axis, again scatter-free.
3. anti-entropy — every push-pull cadence, a two-way full-cache +
   own-rows exchange with the node ``stride`` positions away.  Caches
   are line-aligned across nodes, so the exchange is ``jnp.roll`` +
   elementwise merge; own rows ride the same broadcast-compare insert.
4. floor advance + sweep — per-LINE census (each line's winning
   (slot, version) and its holder count, a column reduction over the
   node axis — O(N·K) elementwise, no scatters); lines where every
   alive node holds the winner fold it into the floor and free
   elementwise; the TTL sweep (ops/ttl.py) runs over own + cache +
   floor — one shared floor sweep models every node's identical
   deterministic sweep.  (The winner count equals the per-slot census
   hit count exactly — see ``_line_census``; the per-slot scatter
   census ``_census`` remains as the exact convergence-metric
   fallback.)

TPU cost model (measured on v5e; the reason for the board form): XLA
scatters with dynamic duplicate indices cost ~10-130 ms at these shapes
while the equivalent elementwise/row-gather passes cost ~1-15 ms
(benchmarks/scatter_costs.py), so the round keeps ZERO per-round
scatters — the only scattered paths left are the exact convergence
census (the metric fallback; the common fast path is one gather), the
amortized deep below-floor sweep, and the host-side ``mint``.  Two documented
semantic refinements come with the form, both self-consistent across
this model, its oracle uses, and the sharded twin:

* **Pull, not push**: peers pull ``fanout`` boards instead of pushing
  to ``fanout`` targets — the same expected edge set per round on the
  same topology (reversed direction), the same per-packet budget, the
  standard epidemic-dissemination dual (push ≈ pull to first order;
  pull is in fact stronger in the drain tail).
* **Floor-mediated stickiness folds at the census**: a DRAINING belief
  held only in the floor sticks when the census folds a newer ALIVE
  version (``apply_stickiness`` at the fold) rather than per delivery —
  beliefs may transiently read ALIVE in between (the reference applies
  it per message against each host's full catalog,
  services_state.go:329-331; the floor IS that catalog here, and the
  observable outcome — the converged status — is identical).  Same-slot
  stickiness (a cached DRAINING belief) still applies per delivery,
  elementwise.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.models.exact import _resolve_cadence, clone_state
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import kernels as kernel_ops
from sidecar_tpu.ops import knobs as knob_ops
from sidecar_tpu.ops import pipeline as pipeline_ops
from sidecar_tpu.ops import provenance as prov_ops
from sidecar_tpu.ops import sparse as sparse_ops
from sidecar_tpu.ops import suspicion as suspicion_ops
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.merge import (
    admit_gate,
    apply_stickiness,
    budget_mask,
    future_mask,
    sticky_adjust,
)
from sidecar_tpu.ops.status import (
    ALIVE,
    DRAINING,
    TOMBSTONE,
    is_known,
    pack,
    unpack_status,
    unpack_ts,
)
from sidecar_tpu.ops.topology import Topology
from sidecar_tpu.ops.ttl import ttl_sweep
from sidecar_tpu.telemetry import cost

_K1 = np.uint32(2654435761)   # Knuth multiplicative
_K3 = np.uint32(0xC2B2AE35)   # murmur3 finalizer constant



def hash_line(slot, cache_lines: int, services_per_node: int):
    """Global owner-run hash: slot id → cache line, the SAME line on
    every node — ``line = (H(owner) + col) mod K`` with ``H`` a
    multiplicative mix of the OWNER id and ``col`` the slot's position
    within its owner.

    Cross-node alignment is load-bearing for the unanimity census: the
    fold throughput of the floor is "every line's current winner", and a
    winner can only be unanimously held if it wins its line on EVERY
    node — which the global hash guarantees (freshness order is global).
    A per-node-salted hash was measured and rejected: collisions become
    independent across nodes, so under capacity pressure only the
    globally-freshest few records are ever held by all nodes at once and
    fold throughput collapses (convergence wedged at ~0.4 on a 256-node
    default-refresh run).  With the global hash a line with several live
    slots drains newest-first, and evicted losers re-enter through the
    owners' recovery re-offer (``recover_rounds``) once the line frees.

    The owner-RUN structure (one hashed base per owner, its S slots on
    S consecutive lines) is the r5 refinement over hashing each slot
    independently: collisions stay uniform across owners (the base is
    mixed exactly as before), one owner's slots can never self-collide
    (S ≤ K is enforced), and — the perf point — every owner-offer
    insert (announce recovery, push-pull own rows) becomes line
    arithmetic plus a tiny within-row gather instead of an [N, K, S]
    broadcast-compare (benchmarks/round_phases.py)."""
    slot = jnp.asarray(slot)
    owner = slot // services_per_node
    col = slot - owner * services_per_node
    u = owner.astype(jnp.uint32) * _K1
    u = (u ^ (u >> np.uint32(15))) * _K3
    shift = 32 - int(math.log2(cache_lines))
    base = (u >> np.uint32(shift)).astype(jnp.int32)
    return (base + col.astype(jnp.int32)) & (cache_lines - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CompressedState:
    """Pytree carried through the round scan."""

    own: jax.Array         # int32 [N, S] owner-authoritative packed keys
    cache_slot: jax.Array  # int32 [N, K] slot id per line (-1 = empty)
    cache_val: jax.Array   # int32 [N, K] packed belief
    cache_sent: jax.Array  # int8 [N, K] transmit counts
    floor: jax.Array       # int32 [M] shared converged baseline
    node_alive: jax.Array  # bool [N]
    round_idx: jax.Array   # int32 scalar
    evictions: jax.Array   # int32 scalar — live beliefs lost to capacity
    dropped: jax.Array     # int32 scalar — pulls dropped by bounded
                           # exchange capacity (sharded all_to_all
                           # bucket overflow; always 0 single-chip)


@dataclasses.dataclass(frozen=True)
class CompressedParams:
    n: int
    services_per_node: int = 10
    cache_lines: int = 256       # K — must be a power of two
    fanout: int = 3
    budget: int = 15
    drop_prob: float = 0.0
    retransmit_limit: int = 0    # 0 = auto (RetransmitMult semantics)
    recover_rounds: int = 10     # unconverged-own re-offer cadence — the
                                 # drain rate of collision chains (losers
                                 # of a shared line re-enter this often)
    fold_quorum: float = 0.995   # census fold threshold; < 1.0 models the
                                 # anti-entropy delivery guarantee for the
                                 # straggler tail (see
                                 # _floor_advance_and_sweep)
    deep_sweep_every: int = 1    # every k-th sweep also runs the exact
                                 # below-floor line free (an O(N·K) gather
                                 # from floor[M] — the only sweep-path op
                                 # whose cost scales with M).  Its job is
                                 # clearing refresh-fold residue: line
                                 # folds free their copies inline, and
                                 # TTL-driven floor moves trigger the
                                 # exact free automatically regardless of
                                 # this cadence.  North-star-scale configs
                                 # with refresh pinned out raise it or set
                                 # 0 = periodic pass off entirely.
    metric_inflight_cap: int = 1024
                                 # P — static width of the behind metric's
                                 # in-flight slot list (the fastest census
                                 # path, _behind_and_denom).  Purely a
                                 # metric-path knob: when more than P
                                 # slots are in flight the census falls
                                 # back to the gather form, bit-for-bit
                                 # identical.
    sparse_cap: int = 0          # C — static width of the sparse-frontier
                                 # round's sender/announce compaction
                                 # (receivers get C·fanout); 0 = auto
                                 # (ops/sparse.default_frontier_cap).
                                 # Purely an execution-path knob like
                                 # metric_inflight_cap: a round whose
                                 # frontier exceeds C falls back to the
                                 # dense round, bit-for-bit identical
                                 # (docs/sparse.md).

    def __post_init__(self):
        if self.cache_lines & (self.cache_lines - 1):
            raise ValueError("cache_lines must be a power of two")
        if self.budget > self.cache_lines:
            raise ValueError("budget cannot exceed cache_lines")
        if self.services_per_node > self.cache_lines:
            # The owner-run line layout (hash_line) assigns one owner's
            # S slots to S distinct consecutive lines; S > K would wrap
            # and silently alias an owner's own records.
            raise ValueError(
                f"services_per_node={self.services_per_node} cannot "
                f"exceed cache_lines={self.cache_lines}")
        if not 0.0 < self.fold_quorum <= 1.0:
            raise ValueError("fold_quorum must be in (0, 1]")
        if self.deep_sweep_every < 0:
            raise ValueError("deep_sweep_every must be >= 0 (0 = never)")
        # int8 cache_sent counters must hold limit + fanout - 1 (the
        # unclamped-accounting bound, ops/gossip.record_transmissions).
        if self.resolved_retransmit_limit() + self.fanout - 1 > 127:
            raise ValueError(
                f"retransmit_limit={self.resolved_retransmit_limit()} + "
                f"fanout={self.fanout} - 1 exceeds the int8 transmit "
                "counter range (127)")

    @property
    def m(self) -> int:
        return self.n * self.services_per_node

    def resolved_retransmit_limit(self) -> int:
        if self.retransmit_limit > 0:
            return self.retransmit_limit
        return 4 * math.ceil(math.log10(self.n + 1))


PerturbFn = Callable[["CompressedState", jax.Array, jax.Array],
                     "CompressedState"]


class CompressedSim:
    """Single-chip compressed simulator (multi-chip:
    ``sidecar_tpu.parallel.sharded_compressed``)."""

    # Whether _behind_and_denom may compile the in-flight-list census
    # path; the sharded twin overrides this to False (XLA CPU GSPMD
    # segfault — see _behind_and_denom).  A class attribute, not a
    # getattr default, so a subclass typo fails loudly in tests rather
    # than silently re-enabling the path.
    metric_list_ok = True

    # Whether this sim implements the sparse-frontier round
    # (docs/sparse.md); a wrapper that overrides _step without a sparse
    # twin sets this False and the drivers degrade/raise accordingly.
    supports_sparse = True

    # Whether this sim implements the software-pipelined round
    # (docs/pipeline.md); wrappers without a pipelined twin set this
    # False and ``run*(pipeline=...)`` degrades/raises accordingly.
    supports_pipeline = True

    # Pin the pipelined publish to the XLA kernel twin: the sharded
    # subclass runs the pipelined round at the GLOBAL-array jit level
    # (GSPMD partitions it), where the Pallas kernels cannot partition.
    _pipeline_force_xla = False

    def __init__(self, params: CompressedParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 perturb: Optional[PerturbFn] = None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None,
                 sparse: Optional[str] = None,
                 pipeline: Optional[str] = None,
                 tick_period=None, tick_phase=None):
        if topo.n != params.n:
            raise ValueError(f"topology has {topo.n} nodes, params say {params.n}")
        if cut_mask is not None and topo.nbrs is None:
            raise ValueError("cut_mask requires a neighbor-list topology")
        self.p = params
        self.t = timecfg
        self.topo = topo
        self.perturb = perturb
        self._nbrs = None if topo.nbrs is None else jnp.asarray(topo.nbrs)
        self._deg = None if topo.deg is None else jnp.asarray(topo.deg)
        self._cut = None if cut_mask is None else jnp.asarray(cut_mask)
        # Round-stagger phase offsets (ops/topology.with_stagger,
        # docs/topology.md): None compiles the unstaggered program bit
        # for bit — the round only passes the gating kwargs when active.
        self._stagger = (None if topo.stagger is None
                         or topo.stagger_period <= 1
                         else jnp.asarray(topo.stagger, jnp.int32))
        self._stagger_period = int(topo.stagger_period)
        self._side = None if node_side is None else \
            jnp.asarray(node_side, jnp.int32)
        # Kernel path (ops/kernels): resolved ONCE at construction — the
        # choice is baked into this sim's jitted round, so toggling
        # SIDECAR_TPU_KERNELS affects sims built afterwards.
        self._kernels, self._kernels_interpret = kernel_ops.resolve_path()
        self._fused_gather = (self._kernels == "pallas"
                              and kernel_ops.fused_gather_enabled())
        # Sparse-frontier execution mode (ops/sparse.py, docs/sparse.md):
        # resolved once at construction like the kernel path; the caps
        # are static — they shape the compacted program.
        self._sparse_mode = sparse_ops.resolve_sparse(sparse)
        # Software-pipelined round mode (ops/pipeline.py,
        # docs/pipeline.md): resolved once at construction; ``auto``
        # keeps the drivers on the classic lockstep round.
        self._pipeline_mode = pipeline_ops.resolve_pipeline(pipeline)
        # Per-node tick cadence (docs/pipeline.md): scalars or [N]
        # vectors; a (provable) period of 1 strips the gate and
        # compiles the pre-cadence program bit for bit.
        tick_period, tick_phase = _resolve_cadence(
            tick_period, tick_phase, params.n)
        # Static data-axis knob bundle (ops/knobs.py): Python scalars
        # that const-fold the round into the pre-knob program; the
        # fleet engine passes a stacked traced bundle per round instead.
        self._knobs = knob_ops.from_protocol(
            params, timecfg, recover_rounds=params.recover_rounds,
            tick_period=tick_period, tick_phase=tick_phase)
        cap = params.sparse_cap or sparse_ops.default_frontier_cap(params.n)
        self._sparse_caps = (min(params.n, cap),
                             min(params.n, cap * params.fanout),
                             min(params.n, cap))
        # The most recent sparse dispatch's int32 [3] stats vector
        # (sparse rounds, overflow rounds, frontier high-water mark) —
        # a DEVICE array, so grabbing the handle right after a
        # pipelined dispatch never blocks; None after dense dispatches.
        self.last_sparse_stats = None

    def _stagger_kw(self, round_idx):
        """The ``sample_peers`` stagger kwargs for this round — ``{}``
        when no stagger is attached, so the call (and the compiled
        program) is byte-identical to the pre-stagger form.  Gossip
        fan-out only; the stride push-pull draw never takes these."""
        if self._stagger is None:
            return {}
        return dict(stagger=self._stagger,
                    stagger_period=self._stagger_period,
                    round_idx=round_idx)

    def _gate_kw(self, round_idx, kn=None):
        """The full ``sample_peers`` gating kwargs for this round:
        stagger (topology-attached) plus the per-node tick cadence
        (knob-carried — a traced fleet axis).  ``{}`` when neither is
        active, so the ungated program stays byte-identical."""
        kn = self._knobs if kn is None else kn
        kw = self._stagger_kw(round_idx)
        if kn.cadence_enabled:
            kw = dict(kw)
            kw.update(tick_period=kn.tick_period,
                      tick_phase=kn.tick_phase, round_idx=round_idx)
        return kw

    # -- state construction -------------------------------------------------

    def init_state(self) -> CompressedState:
        """Converged boot state: the whole catalog sits in the floor at
        tick 1, owners hold matching authoritative records, caches are
        empty.  Scenario perturbations (mint/churn) create the in-flight
        work this model measures."""
        p = self.p
        boot = jnp.full((p.n, p.services_per_node), pack(1, ALIVE),
                        dtype=jnp.int32)
        return CompressedState(
            own=boot,
            cache_slot=jnp.full((p.n, p.cache_lines), -1, jnp.int32),
            cache_val=jnp.zeros((p.n, p.cache_lines), jnp.int32),
            cache_sent=jnp.zeros((p.n, p.cache_lines), jnp.int8),
            floor=jnp.full((p.m,), pack(1, ALIVE), dtype=jnp.int32),
            node_alive=jnp.ones((p.n,), bool),
            round_idx=jnp.zeros((), jnp.int32),
            evictions=jnp.zeros((), jnp.int32),
            dropped=jnp.zeros((), jnp.int32),
        )

    # -- perturbation helper ------------------------------------------------

    def mint(self, state: CompressedState, slots, now_tick,
             status=ALIVE) -> CompressedState:
        """Inject new record versions at the given global slots: owners
        re-stamp their authoritative copy and seed their cache line (the
        changed-service broadcast, services_state.go:538-549).  The
        scenario-facing churn hook.

        DRAINING stickiness applies here too: the reference's
        AddServiceEntry rewrites an advancing ALIVE on a DRAINING record
        regardless of origin — local updates included
        (services_state.go:329-331), so an owner's re-announce cannot
        resurrect a draining instance.  The owner's belief of its own
        slot is max(own, floor), so stickiness is evaluated against
        both.  (Found by the ExactSim cross-validation suite: without
        it, ``own`` stays ALIVE while the cluster converges to the
        sticky DRAINING, and the fold census — which counts the owner
        through ``own`` — can never reach unanimity.)"""
        p = self.p
        slots = jnp.asarray(slots, jnp.int32)
        owner = slots // p.services_per_node
        col = slots % p.services_per_node
        val = jnp.broadcast_to(
            pack(jnp.asarray(now_tick, jnp.int32), status), slots.shape)
        val = jnp.where(state.node_alive[owner], val, 0)
        cur = jnp.maximum(state.own[owner, col], state.floor[slots])
        val = sticky_adjust(val, cur, val > cur)
        rows = jnp.where(val > 0, owner, p.n)
        own = state.own.at[rows, col].max(val, mode="drop")
        cs, cv, se, ev = _line_compete(
            state.cache_slot, state.cache_val, state.cache_sent,
            owner, slots, val, p.cache_lines, p.services_per_node,
            state.floor)
        return dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            evictions=state.evictions + ev)

    # -- kernels ------------------------------------------------------------

    @cost.phased("publish")
    def _publish(self, state: CompressedState, limit: int,
                 row_offset=0, force_xla=False):
        """The message board: each node's top-``budget`` freshest
        eligible cache lines, in place (``[N, K]``, unselected lines
        zeroed).  Eligible = occupied with transmits left.

        Budget selection is ``top_k``-exact but materialized as an
        elementwise mask: values strictly above the B-th largest are in;
        ties at the threshold fill the remaining slots in a PER-NODE
        rotated line order.  The rotation is load-bearing: a churn
        burst mints many records at one tick — equal packed values on
        every node — and a fixed tie order would make the whole cluster
        publish the SAME ``budget`` lines while the rest never spread
        (the cluster-aligned index herd the dense model's
        select_messages also rotates away).  The rotated rank comes
        from the prefix-sum identity
        ``rank(j) = S[j] − S[rot−1]  (j ≥ rot)``,
        ``S[j] + T − S[rot−1]  (j < rot)`` — one cumsum plus an
        [N]-sized per-row gather, measured bit-identical to and ~3 ms/
        round cheaper than the earlier 2·log2(K) conditional-roll
        materialization (benchmarks/hotpath_variants.py, pub_roll vs
        pub_cumsum; ``top_k`` itself is the remaining floor at ~7 ms).
        Entries at or below the floor are cleared by the census
        line-freeing and the deferred deep sweep (``deep_sweep_every``);
        between deep sweeps a refresh-fold orphan may stay
        publish-eligible for a few sweeps — stale-but-harmless traffic
        that loses every line competition against in-flight records
        (see ``_floor_advance_and_sweep``).

        The selection op sequence itself lives in ops/kernels — the XLA
        reference (``publish_board_xla``, exactly the round-5 spelling)
        and its bit-identical fused Pallas twin, dispatched by the
        ``SIDECAR_TPU_KERNELS`` path resolved at construction."""
        p = self.p
        kw = dict(budget=min(p.budget, p.cache_lines), limit=limit,
                  fanout=p.fanout, cache_lines=p.cache_lines,
                  row_offset=row_offset)
        if self._kernels == "pallas" and not force_xla:
            return kernel_ops.publish_board_pallas(
                state.cache_val, state.cache_slot, state.cache_sent,
                interpret=self._kernels_interpret, **kw)
        return kernel_ops.publish_board_xla(
            state.cache_val, state.cache_slot, state.cache_sent, **kw)

    @staticmethod
    def _lex_max(wv, ws, cv, cs):
        """Line competition, elementwise: largest val wins, value ties
        break to the larger slot id (the _line_compete rule)."""
        adv = (cv > wv) | ((cv == wv) & (cs > ws))
        return jnp.where(adv, cv, wv), jnp.where(adv, cs, ws)

    @cost.phased("gather")
    def _pull_merge(self, state: CompressedState, sent, bval, bslot, src,
                    alive, now, drop_key=None, kn=None):
        """Deliver: each receiver pulls the boards of its ``src`` peers
        and lex-merges them into its cache, entirely elementwise — the
        global line hash aligns every board with every cache, so slot
        competition happens within each line position.  ``state`` may
        be a shard-local view; ``bval``/``bslot`` are the full board,
        ``src`` holds global peer ids.  (The sharded twin's
        ``all_to_all`` exchange gathers the same peer rows without
        materializing the full board and enters at
        :meth:`_merge_pulled`.)

        The staleness gate runs on the BOARD ([N, K]) rather than per
        gathered candidate ([N, F, K]) — candidates are copies of board
        entries evaluated at the same ``now``, so filtering before the
        gather is identical and F× cheaper."""
        kn = self._knobs if kn is None else kn
        tb = kn.budget_arg()
        b_own = None
        if tb is not None:
            # Per-origin budget (ops/merge.budget_mask) on the BOARD:
            # the board row IS the packet one origin publishes, so the
            # suspicious rank over its K lines is the per-packet rank
            # every gathered copy would compute.  Sender-owned records
            # (slot's owner run == publishing row) are exempt — owners
            # legitimately announce their own tombstones.  Empty lines
            # carry val 0 (never suspicious), so the -1-slot owner
            # arithmetic is value-safe.
            b_own = ((bslot // self.p.services_per_node)
                     == jnp.arange(bval.shape[0],
                                   dtype=jnp.int32)[:, None])
        bval = admit_gate(bval, now, kn.stale_ticks, kn.future_arg(),
                          tb, b_own)
        pv = bval[src]    # [nl, F, K] — row gathers, contiguous in K
        ps = bslot[src]
        ok = alive[src] & state.node_alive[:, None]      # [nl, F]
        return self._merge_pulled(state, sent, pv, ps, ok, now,
                                  drop_key=drop_key, stale_filtered=True,
                                  kn=kn)

    @cost.phased("fold")
    def _fold_pulled(self, cv0, cs0, wv, ws, pv, ps, ok, now, keep=None,
                     stale_filtered=False, kn=None):
        """Fold a GROUP of pulled candidates ``pv``/``ps`` ([nl, G, K])
        into the running line winners ``(wv, ws)``.

        Every candidate is resolved against the PRE-round cache
        ``(cv0, cs0)`` — one consistent batch resolution like
        ops/gossip.prepare_deliveries — and the lex-max accumulation is
        a true max over the (val, slot) total order, so candidate
        groups may be folded in ANY order (the split-phase sharded
        round folds own-shard rows while remote rows are still in
        flight; see docs/sharding.md) without changing the result.
        ``keep`` is a pre-drawn ``drop_prob`` keep-mask slice (the
        caller draws ONE mask over the full candidate set so splitting
        groups never changes the PRNG stream)."""
        kn = self._knobs if kn is None else kn
        pv = jnp.where(ok[:, :, None], pv, 0)
        if keep is not None:
            pv = jnp.where(keep, pv, 0)
        if not stale_filtered:
            pv = admit_gate(pv, now, kn.stale_ticks, kn.future_arg())
        ps = jnp.where(pv > 0, ps, -1)
        for f in range(pv.shape[1]):
            cand_v, cand_s = pv[:, f], ps[:, f]
            cand_v = sticky_adjust(cand_v, cv0,
                                   (cand_s == cs0) & (cand_v > cv0))
            wv, ws = self._lex_max(wv, ws, cand_v, cand_s)
        return wv, ws

    def _finalize_merge(self, state: CompressedState, sent, wv, ws):
        """Complete a pull-merge batch: reset transmit counts at changed
        lines, count live evictions — both against the PRE-round cache
        (``state`` still holds it)."""
        cv0, cs0 = state.cache_val, state.cache_slot
        changed = (wv != cv0) | (ws != cs0)
        sent = jnp.where(changed, jnp.int8(0), sent)
        evicted = (cs0 >= 0) & (ws != cs0)
        return dataclasses.replace(
            state, cache_slot=ws, cache_val=wv, cache_sent=sent,
            evictions=state.evictions
            + jnp.sum(evicted.astype(jnp.int32)))

    def _merge_pulled(self, state: CompressedState, sent, pv, ps, ok,
                      now, drop_key=None, stale_filtered=False,
                      kn=None):
        """Merge pre-gathered peer board rows ``pv``/``ps`` ([nl, F, K])
        into the cache.

        Merge semantics per candidate (vs the PRE-round line, one
        consistent batch resolution like ops/gossip.prepare_deliveries):
        staleness gate (skipped when the caller already filtered the
        board, ``stale_filtered``); dead sources/receivers
        contribute/accept nothing (the ``ok`` mask); ``drop_prob``
        models UDP loss; same-slot DRAINING stickiness rewrites an
        advancing ALIVE to DRAINING.  (Fold + finalize are split out so
        the sharded twins can fold candidate groups as they arrive —
        :meth:`_fold_pulled`.)"""
        kn = self._knobs if kn is None else kn
        keep = None
        if kn.needs_drop_draw:
            keep = jax.random.bernoulli(drop_key, kn.keep_prob,
                                        pv.shape)
        wv, ws = self._fold_pulled(
            state.cache_val, state.cache_slot, state.cache_val,
            state.cache_slot, pv, ps, ok, now, keep=keep,
            stale_filtered=stale_filtered, kn=kn)
        return self._finalize_merge(state, sent, wv, ws)

    def _insert_own_offers(self, cache_val, cache_slot, cache_sent,
                           offer_val, base_slot, reset_on_hold=False):
        """Insert owner offers into the cache: ``offer_val[r, c]`` is
        the value offered for slot ``base_slot[r] + c`` (each row is ONE
        owner's consecutive slot run — true at both call sites: a
        node's own slots in announce, a rolled partner's own slots in
        push-pull).  Under the owner-run line layout (hash_line) the
        run occupies S consecutive lines from the owner's hashed base,
        so placement needs no collision handling: one line receives at
        most one candidate (S ≤ K, enforced), and the [nl, K, S]
        broadcast-compare below reduces over a service axis where
        exactly one s matches per line.

        Three measured alternatives, all SLOWER in the full round
        (benchmarks/round_phases.py, 100k nodes):
        * pad-offers + per-row conditional-roll placement (log2 K
          passes): the announce phase alone measures ~5.2 vs ~5.8 ms,
          but the roll chain breaks XLA's fusion with the surrounding
          phases and the FULL round regresses ~29.5 → ~36.5 ms;
        * ``take_along_axis(offer, (k−base) mod K)`` within-row
          gather: minor-axis arbitrary gathers are scatter-class on
          TPU — ~300 ms/round;
        * a static [D, nl, K] inverse table: its build is a 1M-update
          scalar scatter XLA won't hoist out of the round scan —
          ~916 ms/round.

        One line receives at most one candidate (S ≤ K, enforced), so
        no intra-batch tie handling is needed; candidates are
        sticky-adjusted against the PRE-insert line.  With
        ``reset_on_hold`` (the OWNER's announce path only), a line that
        ends up holding the offered slot gets its transmit budget reset
        even if nothing changed — the recovery re-offer's whole point
        (services_state.go:538); third parties (the push-pull exchange)
        reset only on change, like any merge accept.  Returns the cache
        triple + evictions."""
        p = self.p
        s = p.services_per_node
        k = p.cache_lines
        cv0, cs0 = cache_val, cache_slot
        slots = base_slot[:, None] + jnp.arange(s, dtype=jnp.int32)
        lines = hash_line(slots, k, s)                        # [nl, S]
        k_idx = jnp.arange(k, dtype=jnp.int32)[None, :, None]
        at_line = lines[:, None, :] == k_idx                  # [nl, K, S]
        cand_vs = jnp.where(at_line, offer_val[:, None, :], 0)
        cand_ss = jnp.where(cand_vs > 0, slots[:, None, :], -1)
        cand_vs = sticky_adjust(
            cand_vs, cv0[:, :, None],
            (cand_ss == cs0[:, :, None]) & (cand_vs > cv0[:, :, None]))
        cand_v = jnp.max(cand_vs, axis=2)                     # [nl, K]
        cand_s = jnp.max(jnp.where((cand_vs == cand_v[:, :, None])
                                   & (cand_v[:, :, None] > 0),
                                   cand_ss, -1), axis=2)
        cache_val, cache_slot = self._lex_max(cv0, cs0, cand_v, cand_s)
        if reset_on_hold:
            # The line holds the offered slot (a weaker same-slot
            # re-offer of the line's standing content also counts).
            holds = jnp.any((cand_vs > 0)
                            & (cand_ss == cache_slot[:, :, None]), axis=2)
            cache_sent = jnp.where(holds, jnp.int8(0), cache_sent)
        changed = (cache_slot != cs0) | (cache_val != cv0)
        cache_sent = jnp.where(changed, jnp.int8(0), cache_sent)
        ev = jnp.sum(((cache_slot != cs0) & (cs0 >= 0)).astype(jnp.int32))
        return cache_val, cache_slot, cache_sent, ev

    @cost.phased("announce")
    def _announce(self, state: CompressedState, round_idx, now,
                  row_offset=0, kn=None):
        """Owner refresh + recovery — fully elementwise: owner slots are
        row-aligned with the floor (``floor.reshape(N, S)``), so the
        refresh fold needs no scatter, and cache inserts go through the
        broadcast-compare lex reduction (``_insert_own_offers``).

        Refresh (staggered per record, ops/gossip.refresh_due) mints a
        fresh version of every present, non-tombstone own record.  A
        refresh of a record the whole cluster already holds (own ==
        floor, status unchanged) folds STRAIGHT into the floor: in the
        reference, refresh delivery is guaranteed by the 20 s full-state
        anti-entropy (PushPullInterval ≪ the 80 s ALIVE_LIFESPAN,
        main.go:252-256) rather than by gossip luck, and the floor is
        precisely this model's compression of "state every node holds" —
        simulating N copies of a timestamp bump nothing can invalidate
        would be pure cache pressure with no information content (the
        whole catalog would wash through the bounded caches once per
        refresh interval and drown real churn).  Refreshes of records
        still in flight (own > floor) mint normally and re-earn
        convergence through the census.

        Recovery (staggered per node) re-seeds the cache line of own
        slots still above the floor without minting — restoring the
        transmit budget of a stalled/evicted record, which is what
        drains collision chains (the changed-service re-broadcast,
        services_state.go:538)."""
        own, floor, offer_val, base_slot = self._announce_offers(
            state.own, state.floor, state.node_alive, round_idx, now,
            row_offset=row_offset, kn=kn)
        cv, cs, se, ev = self._insert_own_offers(
            state.cache_val, state.cache_slot, state.cache_sent,
            offer_val, base_slot, reset_on_hold=True)
        return dataclasses.replace(
            state, own=own, floor=floor, cache_slot=cs, cache_val=cv,
            cache_sent=se, evictions=state.evictions + ev)

    @cost.phased("announce")
    def _announce_offers(self, own0, floor0, node_alive, round_idx, now,
                         row_offset=0, kn=None):
        """The BOARD-INDEPENDENT half of announce: the refresh/fold
        update of ``own``/``floor`` plus the offer values, none of which
        read the cache — so the sharded split-phase round runs this
        while exchanged board rows are still in flight and applies the
        cache insert (:meth:`_insert_own_offers`) only in the final
        phase.  Returns ``(own, floor, offer_val, base_slot)``."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        s = p.services_per_node
        n = own0.shape[0]             # local row count (= p.n single-chip)
        node = jnp.arange(n, dtype=jnp.int32)[:, None]          # [N, 1]
        gnode = node + row_offset                               # global ids
        slots = row_offset * s + \
            jnp.arange(n * s, dtype=jnp.int32).reshape(n, s)    # [N, S]
        floor_l = lax.dynamic_slice(
            floor0, (row_offset * s,), (n * s,)).reshape(n, s)

        st = unpack_status(own0)
        present = is_known(own0) & node_alive[:, None]

        refresh_due = gossip_ops.refresh_due(
            own0, slots, round_idx, refresh_rounds=kn.refresh_rounds,
            round_ticks=t.round_ticks, now=now) & present \
            & (st != TOMBSTONE)
        # Lifeguard self-refutation (ops/suspicion.py): a SUSPECT own
        # record refreshes a refuting ALIVE immediately (and, when it
        # equalled the floor's copy, folds the refutation straight into
        # the floor — anti-entropy-guaranteed delivery, the refresh-fold
        # contract below).  Compiles to nothing at window 0.
        refresh_due, st = suspicion_ops.announce_refute(
            refresh_due, st, present, kn.suspicion_enabled)
        new_val = pack(now, st)
        fold = refresh_due & (own0 == floor_l)
        own = jnp.where(refresh_due, new_val, own0)
        floor_l = jnp.where(fold, new_val, floor_l)
        floor = lax.dynamic_update_slice(
            floor0, floor_l.reshape(-1), (row_offset * s,))

        rphase = gnode % kn.recover_rounds
        recover_due = ((round_idx % kn.recover_rounds) == rphase) & present \
            & (own > floor_l)

        offer = (refresh_due & ~fold) | recover_due
        offer_val = jnp.where(offer, own, 0)
        return own, floor, offer_val, slots[:, 0]

    @cost.phased("exchange", tag="push_pull")
    def _push_pull_stride(self, state: CompressedState, key, now,
                          kn=None):
        """Anti-entropy: two-way exchange with the node ``stride``
        positions away — each side's full cache plus its own rows.
        Caches are line-aligned across nodes, so the cache half is
        ``jnp.roll`` + elementwise lex-merge (on the sharded twin the
        roll lowers to a collective-permute); own rows (their slot ids
        and floor rows roll along with them) go through the
        broadcast-compare insert (``_insert_own_offers``).  Split scenarios mask the exchange where the two sides
        differ (a partition severs TCP push-pull too)."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        s = p.services_per_node
        stride = jax.random.randint(key, (), 1, p.n, dtype=jnp.int32)
        alive = state.node_alive
        own_slots = jnp.arange(p.m, dtype=jnp.int32).reshape(p.n, s)
        floor_rs = state.floor.reshape(p.n, s)

        cv0, cs0 = state.cache_val, state.cache_slot
        wv, ws = cv0, cs0
        sent = state.cache_sent
        ev = state.evictions
        tb = kn.budget_arg()
        node_ids = jnp.arange(p.n, dtype=jnp.int32)
        for roll_amt in (-stride, stride):
            ok = alive & jnp.roll(alive, roll_amt)
            if self._side is not None:
                ok = ok & (self._side == jnp.roll(self._side, roll_amt))
            okc = ok[:, None]
            # Partner's cache lines, aligned with mine.
            p_slot = jnp.roll(cs0, roll_amt, 0)
            p_val = jnp.roll(cv0, roll_amt, 0)
            p_val = jnp.where(okc & (p_slot >= 0), p_val, 0)
            p_own = None
            if tb is not None:
                # Per-origin budget on the exchanged cache half: the
                # rolled row is the partner's packet, and records from
                # the partner's own slot run are exempt.  (The own-rows
                # half below is ENTIRELY partner-owned — the exemption
                # covers all of it, so no gate is compiled there.)
                p_own = ((p_slot // p.services_per_node)
                         == jnp.roll(node_ids, roll_amt)[:, None])
            p_val = admit_gate(p_val, now, kn.stale_ticks,
                               kn.future_arg(), tb, p_own)
            p_slot = jnp.where(p_val > 0, p_slot, -1)
            p_val = sticky_adjust(p_val, cv0,
                                  (p_slot == cs0) & (p_val > cv0))
            wv, ws = self._lex_max(wv, ws, p_val, p_slot)
            # Partner's own rows (their authoritative records), filtered
            # against the (rolled, row-aligned) floor like any owner
            # offer.
            t_slot = jnp.roll(own_slots, roll_amt, 0)
            t_val = jnp.where(okc, jnp.roll(state.own, roll_amt, 0), 0)
            t_floor = jnp.roll(floor_rs, roll_amt, 0)
            t_val = jnp.where(t_val > t_floor, t_val, 0)
            t_val = admit_gate(t_val, now, kn.stale_ticks,
                               kn.future_arg())
            wv, ws, sent, _ = self._insert_own_offers(
                wv, ws, sent, t_val, t_slot[:, 0])

        # One eviction count against the pre-exchange cache (the whole
        # exchange is one batch, like the delivery path).
        changed = (wv != cv0) | (ws != cs0)
        sent = jnp.where(changed, jnp.int8(0), sent)
        ev = ev + jnp.sum(((cs0 >= 0) & (ws != cs0)).astype(jnp.int32))
        return dataclasses.replace(
            state, cache_slot=ws, cache_val=wv, cache_sent=sent,
            evictions=ev)

    def _line_census(self, state: CompressedState):
        """Per-line winner and holder count across alive nodes — the
        O(N·K)-elementwise census (plus [K]-sized gathers) behind the
        floor fold.

        Because the line hash is global, every copy of a record sits at
        the same line position on every node, so "who holds slot s at
        version v" is a column question: the line's winner (ws, wv) is a
        lex-max reduction over the node axis, and its holder count is an
        equality-match sum down the same column.  The owner is counted
        through its authoritative ``own`` record (its cache copy of its
        own slot, if any, is excluded — same double-count guard as
        :func:`_census`).  For winner slots this computes EXACTLY the
        per-slot census hit count: a cache entry for slot s can only
        live at line hash(s), and only entries at the winning version
        match.  (The sharded twin inherits this at the jit level: the
        node-axis reductions become all-reduces under GSPMD.)"""
        p = self.p
        alive_c = state.node_alive[:, None]
        occupied = (state.cache_slot >= 0) & alive_c
        val = jnp.where(occupied, state.cache_val, 0)
        wv = jnp.max(val, axis=0)                               # [K]
        ws = jnp.max(jnp.where(occupied & (val == wv[None, :]),
                               state.cache_slot, -1), axis=0)   # [K]

        node = jnp.arange(p.n, dtype=jnp.int32)[:, None]
        holder = occupied & (state.cache_slot == ws[None, :]) & \
            (state.cache_val == wv[None, :])
        owner_of_ws = jnp.where(ws >= 0, ws // p.services_per_node, -1)
        holder = holder & (node != owner_of_ws[None, :])
        count = jnp.sum(holder.astype(jnp.int32), axis=0)       # [K]

        own_flat = state.own.reshape(p.m)
        owner_alive = state.node_alive[jnp.maximum(owner_of_ws, 0)]
        own_at = own_flat[jnp.maximum(ws, 0)]
        owner_holds = (ws >= 0) & owner_alive & (own_at >= wv)
        return ws, wv, count + owner_holds.astype(jnp.int32)

    @cost.phased("ttl_sweep")
    def _floor_advance_and_sweep(self, state: CompressedState, now,
                                 kn=None):
        """Per-line census → floor advance → line free → TTL sweep.

        The fold is per cache line: each line's winning (slot, version)
        folds into the floor when every alive node holds it (or the
        quorum + anti-entropy-age rule below fires), and the folded
        entries free elementwise in the same pass.  Folding is per-LINE
        rather than per-slot — a line's non-winning slots wait for the
        line to drain (winner folds → line frees → losers re-enter via
        the owners' recovery re-offer) instead of being quorum-folded
        mid-displacement; for winner slots the count is identical to the
        per-slot census (see :func:`_line_census`).  This keeps the
        whole fold path O(N·K): the old per-slot census's three
        ~N·K-index scatter/gathers against [M] measured ~680 ms at the
        100k-node north star (scatter cost model:
        benchmarks/scatter_costs.py) — charged every sweep — vs ~2 ms
        for the column reductions here.

        The only remaining M-scaled sweep op — the exact below-floor
        line free, whose job is clearing stale cache copies orphaned by
        REFRESH folds (fold-freed lines are already handled inline) —
        runs every ``deep_sweep_every``-th sweep."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        ws, wv, hits = self._line_census(state)
        n_alive = jnp.sum(state.node_alive.astype(jnp.int32))
        safe_ws = jnp.maximum(ws, 0)
        above = (ws >= 0) & (wv > state.floor[safe_ws])
        caught_up = above & (hits >= n_alive)
        if p.fold_quorum < 1.0 and self._cut is None:
            # Quorum folds are DISABLED while a partition is modeled
            # (cut_mask active): the anti-entropy guarantee below cannot
            # reach across a cut, and a minority side smaller than the
            # quorum complement would otherwise be "delivered" records
            # through the shared floor it could never have received.
            # Quorum fold — the straggler-tail model: once ≥ quorum of
            # the alive population holds a record AND a full push-pull
            # interval has elapsed since it was minted (every node has
            # had an anti-entropy exchange opportunity, and a random
            # partner holds it w.p. ≥ quorum), cluster-wide delivery is
            # guaranteed by the full-state TCP anti-entropy — the same
            # argument the reference leans on for refresh delivery
            # (PushPullInterval 20 s ≪ ALIVE_LIFESPAN 80 s,
            # main.go:252-256; memberlist push-pull exchanges complete
            # state, services_delegate.go:146-167).  The epidemic
            # simulation still has to carry every record to quorum; only
            # the last-straggler tail — which the wire protocol handles
            # out-of-band of gossip packets — is folded analytically.
            q_hits = jnp.ceil(
                jnp.float32(p.fold_quorum)
                * n_alive.astype(jnp.float32)).astype(jnp.int32)
            age_ok = now - unpack_ts(wv) >= \
                kn.push_pull_rounds * t.round_ticks
            caught_up = caught_up | (above & (hits >= q_hits) & age_ok)

        fold_idx = jnp.where(caught_up, safe_ws, p.m)
        fold_val = jnp.where(caught_up, wv, 0)
        floor = state.floor.at[fold_idx].max(fold_val, mode="drop")
        # Floor-mediated DRAINING stickiness (see the module docstring):
        # a fold that would flip a DRAINING floor slot to a newer ALIVE
        # keeps DRAINING at the new timestamp — the per-host catalog
        # stickiness (services_state.go:329-331) applied at the point
        # where this model materializes the catalog.
        floor = apply_stickiness(state.floor, floor)

        # Free folded lines elementwise: every copy of a just-folded
        # winner is at its line position at ≤ the folded version.  A
        # winner already at-or-below the floor frees the same way —
        # without it, a below-floor copy delivered in flight just before
        # a fold (the pull/push-pull merges don't floor-filter
        # candidates) could re-occupy an empty line permanently when the
        # deep sweep is off (deep_sweep_every=0).  A colliding
        # below-floor loser behind such a winner surfaces as the line's
        # winner at the next census and frees then.
        stale_win = (ws >= 0) & ~above         # winner at/below the floor
        below = (state.cache_slot == ws[None, :]) & \
            (caught_up | stale_win)[None, :] & \
            (state.cache_val <= wv[None, :])

        cache_slot = jnp.where(below, -1, state.cache_slot)
        cache_val = jnp.where(below, 0, state.cache_val)
        cache_sent = jnp.where(below, jnp.int8(0), state.cache_sent)

        kw = dict(alive_lifespan=kn.alive_lifespan,
                  draining_lifespan=kn.draining_lifespan,
                  tombstone_lifespan=kn.tombstone_lifespan,
                  one_second=t.one_second,
                  suspicion_window=kn.suspicion_window)
        own, _ = ttl_sweep(state.own, now, **kw)
        floor_swept, _ = ttl_sweep(floor, now, **kw)
        swept_val, _ = ttl_sweep(cache_val, now, **kw)
        cache_sent = jnp.where(swept_val != cache_val, jnp.int8(0),
                               cache_sent)

        # Exact below-floor free (the O(N·K) gather from floor[M]):
        # catches cache copies orphaned by floor advances that aren't
        # line folds — refresh folds (the periodic cadence below), and
        # TTL transitions of floor entries (tombstone bumps to ts+1 s
        # can leap over copies of a version minted within that second;
        # detected by comparing the floor across its sweep, so the
        # gather runs only on rounds where expiry actually moved it).
        # deep_sweep_every == 0 disables only the periodic cadence —
        # sound when refresh folds cannot occur (pinned refresh).
        deep_due = floor_swept != floor
        deep_due = jnp.any(deep_due)
        if p.deep_sweep_every > 0:
            round_idx = now // t.round_ticks
            deep_rounds = kn.sweep_rounds * p.deep_sweep_every
            deep_due = deep_due | (round_idx % deep_rounds == 0)

        def deep_free(args):
            cs, cv, se = args
            orphaned = (cs >= 0) & (
                cv <= floor_swept[jnp.maximum(cs, 0)])
            return (jnp.where(orphaned, -1, cs),
                    jnp.where(orphaned, 0, cv),
                    jnp.where(orphaned, jnp.int8(0), se))

        cache_slot, swept_val, cache_sent = lax.cond(
            deep_due, deep_free, lambda a: a,
            (cache_slot, swept_val, cache_sent))

        return dataclasses.replace(
            state, own=own, floor=floor_swept, cache_slot=cache_slot,
            cache_val=swept_val, cache_sent=cache_sent)

    def _round_gossip_announce(self, state: CompressedState, src, k_drop,
                               round_idx, now, force_xla=False,
                               ann=None, kn=None):
        """Phases 1 + 2 of the round — publish/pull/merge + announce —
        the DENSE form, extracted so the sparse step's overflow
        fallback (``_step_sparse``) is literally this function.
        ``force_xla`` pins the publish/gather to the XLA twin (the
        sparse program's fallback branch — bit-identical to the Pallas
        path by the kernel parity contract, and it keeps the Pallas
        interpreter out of a ``lax.cond`` branch that rarely runs).
        ``ann`` is the announce own/floor half when the caller already
        computed it (the sparse step needs it for the announcer
        frontier either way) — identical values, one O(N·S) pass
        instead of two on overflow rounds."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        limit = kn.limit

        # 1. publish the board (pre-round snapshot) + pull deliveries.
        if self._fused_gather and not force_xla:
            # Fused Pallas path: publish selection + staleness gate +
            # board row-gather in one kernel — the [N, K] board never
            # touches HBM (ops/kernels, bit-identical to the XLA path).
            with cost.phase("publish"):
                sent, pv, ps = kernel_ops.fused_publish_gather_pallas(
                    state.cache_val, state.cache_slot, state.cache_sent,
                    src, now, stale_ticks=kn.stale_ticks,
                    budget=min(p.budget, p.cache_lines), limit=limit,
                    fanout=p.fanout, cache_lines=p.cache_lines,
                    interpret=self._kernels_interpret)
            ft = kn.future_arg()
            if ft is not None:
                # The kernel only gates staleness; apply the future
                # bound on the gathered candidates ([N, F, K]) — the
                # candidates are board copies evaluated at the same
                # ``now``, so post-kernel gating is equivalent to the
                # XLA twin's pre-gather board gate.  Only compiled when
                # the bound is enabled, so the disabled program stays
                # bit-identical to the pre-bound kernel path.
                pv = jnp.where(future_mask(pv, now, ft), 0, pv)
            tb = kn.budget_arg()
            if tb is not None:
                # Per-origin budget, post-kernel like the future bound:
                # each gathered candidate row IS a copy of one origin's
                # board row, so the suspicious rank over its K axis
                # equals the XLA twin's pre-gather board rank (same
                # ``now``, same gate order: staleness → future →
                # budget).  Origin of candidate [r, f] is ``src[r, f]``.
                own3 = ((ps // p.services_per_node)
                        == src[:, :, None])
                pv = jnp.where(budget_mask(pv, now, tb, own3), 0, pv)
            ok = state.node_alive[src] & state.node_alive[:, None]
            state = self._merge_pulled(state, sent, pv, ps, ok, now,
                                       drop_key=k_drop,
                                       stale_filtered=True, kn=kn)
        else:
            bval, bslot, sent = self._publish(state, limit,
                                              force_xla=force_xla)
            state = self._pull_merge(state, sent, bval, bslot, src,
                                     state.node_alive, now,
                                     drop_key=k_drop, kn=kn)

        # 2. announce re-stamps + recovery offers (end of round, like the
        # exact model: broadcastable the following round).
        if ann is None:
            return self._announce(state, round_idx, now, kn=kn)
        own1, floor1, offer_val, base_slot = ann
        cv, cs, se, ev = self._insert_own_offers(
            state.cache_val, state.cache_slot, state.cache_sent,
            offer_val, base_slot, reset_on_hold=True)
        return dataclasses.replace(
            state, own=own1, floor=floor1, cache_slot=cs, cache_val=cv,
            cache_sent=se, evictions=state.evictions + ev)

    def _step(self, state: CompressedState, key: jax.Array,
              kn=None) -> CompressedState:
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)

        src = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=state.node_alive, cut_mask=self._cut,
            **self._gate_kw(round_idx, kn))
        state = self._round_gossip_announce(state, src, k_drop,
                                            round_idx, now, kn=kn)

        # 3. anti-entropy.
        state = lax.cond(
            round_idx % kn.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now, kn=kn),
            lambda st: st, state)

        # 4. floor advance + sweep.
        state = lax.cond(
            round_idx % kn.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now, kn=kn),
            lambda st: st, state)

        return dataclasses.replace(state, round_idx=round_idx)

    # -- the sparse-frontier round (docs/sparse.md) --------------------------

    def _sparse_frontiers(self, state: CompressedState, src, limit,
                          round_idx, now):
        """The three bounded frontiers of a round, plus the dense-cheap
        announce precompute shared by both branches:

        * **senders** — rows with any ELIGIBLE line (occupied AND
          transmits left).  TransmitLimited is what makes the tail
          sparse: an exhausted relay still HOLDS its copy but publishes
          nothing, so its board is empty and its ``sent`` never bumps.
        * **receivers** — alive rows that sampled ≥ 1 active sender;
          every other row's pull folds only empty boards (a provable
          no-op: ``wv == cv0`` ⇒ no change, no reset, no eviction).
        * **announcers** — rows with any refresh/recovery offer; the
          own/floor half of announce is elementwise O(N·S) and runs
          dense in both branches (``_announce_offers`` reads neither
          the cache nor the board)."""
        sender = jnp.any(kernel_ops.eligible_lines(
            state.cache_slot, state.cache_sent, limit), axis=1)
        recv = state.node_alive & jnp.any(sender[src], axis=1)
        own1, floor1, offer_val, base_slot = self._announce_offers(
            state.own, state.floor, state.node_alive, round_idx, now)
        announcer = jnp.any(offer_val > 0, axis=1)
        return sender, recv, announcer, (own1, floor1, offer_val,
                                         base_slot)

    def _round_gossip_announce_sparse(self, st: CompressedState, src,
                                      k_drop, now, sender, recv,
                                      announcer, ann):
        """Phases 1 + 2 on the COMPACTED frontier views — bit-identical
        to ``_round_gossip_announce`` when no frontier overflows (the
        caller guards that with the dense fallback).  All write-backs
        are gather+select (``compact[pos]`` under the frontier mask) —
        the round keeps the model's zero-per-round-scatter budget; the
        only scatters are the O(N) inverse-position builds in
        ``compact_rows``."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        n, k = p.n, p.cache_lines
        cs_cap, cr_cap, ca_cap = self._sparse_caps
        own1, floor1, offer_val, base_slot = ann

        # Senders: publish the compacted board (the XLA twin with
        # explicit global row ids — the dense tie rotation per row).
        idx_s, row_s, valid_s, pos_s = sparse_ops.compact_rows(
            sender, cs_cap)
        cv_s = jnp.where(valid_s[:, None], st.cache_val[row_s], 0)
        sl_s = jnp.where(valid_s[:, None], st.cache_slot[row_s], -1)
        bval_c, bslot_c, sent_c = kernel_ops.publish_board_xla(
            cv_s, sl_s, st.cache_sent[row_s],
            budget=min(p.budget, k), limit=limit, fanout=p.fanout,
            cache_lines=k, row_ids=idx_s)
        sent = jnp.where(sender[:, None], sent_c[pos_s], st.cache_sent)
        # Board staleness gate once, on the compacted board; the pad
        # row at index cs_cap is the "inactive sender" — an all-zero
        # board, the merge no-op every non-frontier row serves in the
        # dense round too.
        b_own_c = None
        if t.tomb_budget is not None:
            # Compacted twin of the dense board budget gate: the global
            # row id of compacted board row c is ``idx_s[c]``.
            b_own_c = ((bslot_c // p.services_per_node)
                       == idx_s[:, None])
        bval_c = admit_gate(bval_c, now, t.stale_ticks, t.future_ticks,
                            t.tomb_budget, b_own_c)
        bval_p = jnp.concatenate(
            [bval_c, jnp.zeros((1, k), jnp.int32)])
        bslot_p = jnp.concatenate(
            [bslot_c, jnp.full((1, k), -1, jnp.int32)])
        bpos = jnp.where(sender, pos_s, cs_cap)            # [N]

        # Receivers: pull the compacted boards and fold.
        idx_r, row_r, valid_r, pos_r = sparse_ops.compact_rows(
            recv, cr_cap)
        src_r = src[row_r]                                 # [Cr, F]
        pv = bval_p[bpos[src_r]]                           # [Cr, F, K]
        ps = bslot_p[bpos[src_r]]
        ok = st.node_alive[src_r] & \
            (st.node_alive[row_r] & valid_r)[:, None]
        keep_r = None
        if p.drop_prob > 0.0:
            # The dense draw, sliced: the loss stream is
            # mode-independent (ops/sparse.py module docstring).
            keep = jax.random.bernoulli(k_drop, 1.0 - p.drop_prob,
                                        (n, p.fanout, k))
            keep_r = keep[row_r]
        cv0_r, cs0_r = st.cache_val[row_r], st.cache_slot[row_r]
        wv, ws = self._fold_pulled(cv0_r, cs0_r, cv0_r, cs0_r, pv, ps,
                                   ok, now, keep=keep_r,
                                   stale_filtered=True)
        sent_r = sent[row_r]
        changed = (wv != cv0_r) | (ws != cs0_r)
        sent_r = jnp.where(changed, jnp.int8(0), sent_r)
        ev = jnp.sum(((cs0_r >= 0) & (ws != cs0_r)).astype(jnp.int32))

        recv_c = recv[:, None]
        cache_val = jnp.where(recv_c, wv[pos_r], st.cache_val)
        cache_slot = jnp.where(recv_c, ws[pos_r], st.cache_slot)
        cache_sent = jnp.where(recv_c, sent_r[pos_r], sent)

        # Announcers: the cache insert on the compacted rows (own/floor
        # already advanced dense in ``_sparse_frontiers``; the insert
        # reads the POST-merge cache, exactly the dense phase order).
        idx_a, row_a, valid_a, pos_a = sparse_ops.compact_rows(
            announcer, ca_cap)
        off_a = jnp.where(valid_a[:, None], offer_val[row_a], 0)
        cv2, cs2, se2, ev_a = self._insert_own_offers(
            cache_val[row_a], cache_slot[row_a], cache_sent[row_a],
            off_a, base_slot[row_a], reset_on_hold=True)
        ann_c = announcer[:, None]
        cache_val = jnp.where(ann_c, cv2[pos_a], cache_val)
        cache_slot = jnp.where(ann_c, cs2[pos_a], cache_slot)
        cache_sent = jnp.where(ann_c, se2[pos_a], cache_sent)

        return dataclasses.replace(
            st, own=own1, floor=floor1, cache_slot=cache_slot,
            cache_val=cache_val, cache_sent=cache_sent,
            evictions=st.evictions + ev + ev_a)

    def _step_sparse(self, state: CompressedState, key: jax.Array):
        """One round on the sparse path: compute the frontiers, run the
        compacted phases when they fit their caps, fall back to the
        dense round (same program, ``lax.cond``) when any overflows —
        bit-identical either way.  Returns ``(state, stats[3])`` with
        stats = (ran-sparse, overflowed, frontier size)."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        src = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=state.node_alive, cut_mask=self._cut,
            **self._gate_kw(round_idx))

        sender, recv, announcer, ann = self._sparse_frontiers(
            state, src, limit, round_idx, now)
        cs_cap, cr_cap, ca_cap = self._sparse_caps
        n_s = jnp.sum(sender.astype(jnp.int32))
        n_r = jnp.sum(recv.astype(jnp.int32))
        n_a = jnp.sum(announcer.astype(jnp.int32))
        overflow = (n_s > cs_cap) | (n_r > cr_cap) | (n_a > ca_cap)
        frontier = jnp.maximum(n_s, jnp.maximum(n_r, n_a))

        state = lax.cond(
            overflow,
            lambda st: self._round_gossip_announce(
                st, src, k_drop, round_idx, now, force_xla=True,
                ann=ann),
            lambda st: self._round_gossip_announce_sparse(
                st, src, k_drop, now, sender, recv, announcer, ann),
            state)

        # 3 + 4 — cadence-amortized, dense in both modes.
        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        ov = overflow.astype(jnp.int32)
        stats = jnp.stack([1 - ov, ov, frontier])
        return dataclasses.replace(state, round_idx=round_idx), stats

    # -- the software-pipelined round (docs/pipeline.md) ---------------------

    def _select_inflight(self, state, round_sel, k_round, kn=None):
        """Select round ``round_sel``'s publish from the CURRENT
        (pre-fold) cache: the raw board plus the pull sources, with the
        transmit-budget bump charged immediately (``_publish`` bumps
        ``cache_sent`` exactly as the lockstep round does; the fold's
        changed-line reset wins on overlap — the bump-then-reset order
        of the exact family).  Consumes the ``k_peers`` leg of
        ``round_sel``'s 4-way split, so every draw keeps its lockstep
        stream position.  The admission gates do NOT run here — the
        board is carried raw and gated at fold time against the fold
        tick's ``now``.  Returns ``((src, bval, bslot), cache_sent)``."""
        p = self.p
        kn = self._knobs if kn is None else kn
        _kp, k_peers, _kd, _kpp = jax.random.split(k_round, 4)
        src = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=state.node_alive, cut_mask=self._cut,
            **self._gate_kw(round_sel, kn))
        bval, bslot, sent = self._publish(
            state, kn.limit, force_xla=self._pipeline_force_xla)
        return (src, bval, bslot), sent

    def _step_pipelined(self, state, inflight, k_now, k_next, kn=None):
        """One software-pipelined round (docs/pipeline.md): fold the
        carried round-``r`` boards while round ``r+1``'s publish is
        selected from the PRE-fold cache — the honest one-round-stale
        schedule (a board reflects its publisher's belief before this
        tick's deliveries and announces landed).  The admission gates
        (staleness/future/budget) and the liveness mask run at FOLD
        time against this tick's ``now``/``node_alive`` — a board from
        a publisher that died in this tick's perturb folds nothing,
        exactly as in the lockstep round.  ``k_now`` is round ``r``'s
        folded key (perturb/drop/push-pull legs); ``k_next`` is round
        ``r+1``'s (its peers leg, consumed one tick early)."""
        p, t = self.p, self.t
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, _k_peers, k_drop, k_pp = jax.random.split(k_now, 4)

        if self.perturb is not None:
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)

        src, bval, bslot = inflight
        inflight, sent = self._select_inflight(state, round_idx + 1,
                                               k_next, kn=kn)
        state = self._pull_merge(state, sent, bval, bslot, src,
                                 state.node_alive, now, drop_key=k_drop,
                                 kn=kn)
        state = self._announce(state, round_idx, now, kn=kn)

        state = lax.cond(
            round_idx % kn.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now, kn=kn),
            lambda st: st, state)
        state = lax.cond(
            round_idx % kn.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now, kn=kn),
            lambda st: st, state)

        return dataclasses.replace(state, round_idx=round_idx), inflight

    # -- metrics ------------------------------------------------------------

    def convergence(self, state: CompressedState) -> jax.Array:
        """Fraction of (alive node, slot) beliefs agreeing with the
        freshest belief — the exact model's metric, computed from the
        compressed representation.

        Fast path (the common measurement regime — every node alive, no
        DRAINING records anywhere): circulating versions originate from
        their owners and only move forward, so the global truth is
        simply ``max(floor, own)`` elementwise and a slot is in flight
        iff its owner is ahead of the floor.  The per-slot behind count
        then collapses to one O(N·K) gather (cache entries at truth)
        plus elementwise passes — no scatters.  The invariant breaks
        only for DRAINING: a sticky-adjusted delivery re-packs an
        advancing ALIVE as DRAINING at the same tick, which outranks the
        owner's own copy (ops/status.py tie order), and a dead owner's
        cached copies outlive ``own``'s alive-mask — both cases (plus
        any dead node) fall back to the exact scatter census
        (:func:`_census`), which this fast path reproduces bit-for-bit
        otherwise (tests/test_compressed.py pins the equality).

        Cost: the exact census is three ~N·K-index scatter/gathers
        against [M] — ~680 ms at the 100k-node north star — vs ~230 ms
        for the fast path's single gather, which is why ``run`` samples
        the metric on the ``conv_every`` cadence rather than inline
        every round."""
        behind, denom = self._behind_and_denom(state)
        return 1.0 - behind / denom

    def behind(self, state: CompressedState) -> jax.Array:
        """The raw behind COUNT — #(alive node, slot) beliefs not at the
        freshest version, as a float32 count (same census as
        :meth:`convergence`, unnormalized).

        Exists because ``1 - behind/denom`` destroys resolution near
        convergence: at the north star denom = 10¹¹, so one float32 ulp
        below 1.0 (≈6e-8) already spans ~6,000 behind cells — an
        ε-threshold over a small unsettled set cannot be detected on
        the ratio.  The count itself is exact to ~1 part in 10⁶ (tree-
        reduced float32 sums of unit terms), so thresholds like
        "behind ≤ 10⁴" are sharp."""
        return self._behind_and_denom(state)[0]

    def _behind_and_denom(self, state: CompressedState):
        p = self.p

        def exact(st):
            truth, hits, n_alive = _census(st, p)
            behind = jnp.maximum(n_alive - hits, 0)
            # Denominator in float: n_alive·m overflows int32 at the
            # scales this model exists for (65,536 × 655,360 ≈ 4.3e10).
            denom = jnp.maximum(
                n_alive.astype(jnp.float32) * jnp.float32(p.m), 1.0)
            return jnp.sum(behind.astype(jnp.float32)), denom

        def fast(st):
            own_flat = st.own.reshape(p.m)
            truth = jnp.maximum(st.floor, own_flat)
            in_flight = truth > st.floor
            # Sentinel so folded slots can't collect hits through the
            # single gather (their behind is 0 by definition): packed
            # keys are < 2^31 - 1 (MAX_TICK), so nothing matches it.
            aux = jnp.where(in_flight, truth, jnp.int32(2**31 - 1))
            node = jnp.arange(p.n, dtype=jnp.int32)[:, None]
            occ = st.cache_slot >= 0
            not_own = jnp.where(
                occ, st.cache_slot // p.services_per_node, -1) != node
            at_truth = occ & not_own & (
                st.cache_val >= aux[jnp.maximum(st.cache_slot, 0)])
            n_inflight = jnp.sum(in_flight.astype(jnp.int32))
            # Owners of in-flight slots always hold truth (= their own
            # record); everyone else counts through the cache.
            sum_hits = jnp.sum(at_truth.astype(jnp.int32)) + n_inflight
            behind = jnp.float32(p.n) * n_inflight.astype(jnp.float32) \
                - sum_hits.astype(jnp.float32)
            denom = jnp.maximum(jnp.float32(p.n) * jnp.float32(p.m), 1.0)
            return behind, denom

        def fast_list(st):
            """The fastest census: when ≤ P slots are in flight (any
            churn burst; the floor folds the count monotonically down),
            enumerate them (static-size nonzero) and count holders down
            their line COLUMNS — a [P, N] contiguous row gather over the
            transposed cache instead of ``fast``'s [N, K]
            arbitrary-index gather from [M] (~230 ms/sample at the
            north star; this path measures a few ms).  Same counts as
            ``fast``, bit-for-bit (tests pin all three paths)."""
            cap = min(p.metric_inflight_cap, p.m)
            own_flat = st.own.reshape(p.m)
            truth = jnp.maximum(st.floor, own_flat)
            in_flight = truth > st.floor
            n_inflight = jnp.sum(in_flight.astype(jnp.int32))
            idx = jnp.nonzero(in_flight, size=cap, fill_value=p.m)[0]
            valid = idx < p.m
            slot = jnp.minimum(idx, p.m - 1)
            t_if = truth[slot]                              # [P]
            lines_if = hash_line(slot, p.cache_lines,
                                 p.services_per_node)
            held_s = st.cache_slot.T[lines_if]              # [P, N]
            held_v = st.cache_val.T[lines_if]
            owner = slot // p.services_per_node
            node = jnp.arange(p.n, dtype=jnp.int32)[None, :]
            match = (held_s == slot[:, None]) & \
                (held_v >= t_if[:, None]) & \
                (node != owner[:, None]) & valid[:, None]
            sum_hits = jnp.sum(match.astype(jnp.int32)) + n_inflight
            behind = jnp.float32(p.n) * n_inflight.astype(jnp.float32) \
                - sum_hits.astype(jnp.float32)
            denom = jnp.maximum(jnp.float32(p.n) * jnp.float32(p.m), 1.0)
            return behind, denom

        draining = is_known(state.own) & \
            (unpack_status(state.own) == DRAINING)
        draining_f = is_known(state.floor) & \
            (unpack_status(state.floor) == DRAINING)
        draining_c = (state.cache_slot >= 0) & \
            (unpack_status(state.cache_val) == DRAINING)
        fast_ok = jnp.all(state.node_alive) & ~jnp.any(draining) & \
            ~jnp.any(draining_f) & ~jnp.any(draining_c)
        # fast_list is compiled only on single-device sims: under the
        # sharded twin's GSPMD propagation the transpose-gather +
        # static-size nonzero combination intermittently SEGFAULTS the
        # XLA CPU compiler (jax 0.9.0; reproducible at
        # test_sharded_compressed::test_split_holds_then_heals in
        # full-suite context, crash inside backend_compile /
        # executable serialization).  The sharded twin samples its
        # metric through the gather path instead — bit-identical,
        # slower per sample; the single-chip bench is where the
        # sampling cost mattered (~9 ms/round at conv_every=25).
        if not self.metric_list_ok:
            return lax.cond(fast_ok, fast, exact, state)
        n_if = jnp.sum((jnp.maximum(state.floor,
                                    state.own.reshape(p.m))
                        > state.floor).astype(jnp.int32))
        small = n_if <= min(p.metric_inflight_cap, p.m)
        # One flat switch, not nested conds, keeps the program shallow.
        idx = jnp.where(fast_ok,
                        jnp.where(small, jnp.int32(2), jnp.int32(1)),
                        jnp.int32(0))
        return lax.switch(idx, (exact, fast, fast_list), state)

    # -- provenance hooks (ops/provenance.py, docs/telemetry.md) -------------

    def _prov_belief(self, state: CompressedState,
                     tracked: jax.Array) -> jax.Array:
        """Packed [N, T] belief matrix for the tracked slots — the
        column-wise restriction of ops/delta.compressed_belief:
        ``max(floor, cache hit, own row)``.  The version threshold in
        the ProvTrace (``ref``) is what makes this meaningful — the
        floor holds a stale copy of every converged slot."""
        p = self.p
        s = p.services_per_node
        lines = hash_line(tracked, p.cache_lines, s)
        hit = state.cache_slot[:, lines] == tracked[None, :]
        cached = jnp.where(hit, state.cache_val[:, lines], 0)
        owner = tracked // s
        col = tracked - owner * s
        own_b = jnp.where(
            owner[None, :] == jnp.arange(p.n, dtype=jnp.int32)[:, None],
            state.own[:, col], 0)
        return jnp.maximum(
            jnp.maximum(state.floor[tracked][None, :], cached), own_b)

    def _prov_sample_src(self, k_peers, node_alive):
        """The round's pull sources — overridden by the sharded twin,
        which replays its per-shard PRNG streams at the jit level."""
        p = self.p
        return gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=node_alive, cut_mask=self._cut)

    def _prov_channels(self, state: CompressedState, key: jax.Array,
                       kn=None):
        """Re-derive the round's sampled channels from ``key``: the
        board pulls ``src`` plus (on cadence) the stride push-pull's two
        legs.  All compressed exchanges are pull-shaped; the floor fold
        is not a peer channel, so floor-advance infections surface as
        ``PARENT_UNATTRIBUTED``."""
        p = self.p
        kn = self._knobs if kn is None else kn
        round_idx = state.round_idx + 1
        now = round_idx * self.t.round_ticks
        k_perturb, k_peers, _k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)
        alive = state.node_alive

        src = self._prov_sample_src(k_peers, alive)
        src = gossip_ops.stagger_gate(src, round_idx, self._stagger,
                                      self._stagger_period)
        if kn.cadence_enabled:
            src = gossip_ops.cadence_gate(src, round_idx, kn.tick_period,
                                          kn.tick_phase)
        pulls = [(src, None)]

        # The stride exchange (_push_pull_stride): node i merges the
        # cache+own rows of BOTH the node stride ahead and the node
        # stride behind — two pull legs with the same liveness/side
        # gating as the roll-based exchange.
        stride = jax.random.randint(k_pp, (), 1, p.n, dtype=jnp.int32)
        idx = jnp.arange(p.n, dtype=jnp.int32)
        pp_on = round_idx % kn.push_pull_rounds == 0
        for roll_amt, partner in ((-stride, (idx + stride) % p.n),
                                  (stride, (idx - stride) % p.n)):
            ok = alive & jnp.roll(alive, roll_amt)
            if self._side is not None:
                ok = ok & (self._side == jnp.roll(self._side, roll_amt))
            pulls.append((partner[:, None], (ok & pp_on)[:, None]))
        return [], pulls

    # -- drivers ------------------------------------------------------------
    # Donation: the _run*_jit entry points donate the input state so the
    # cache/floor tensors are rewritten in place across chunked
    # dispatches instead of double-buffered (see models/exact.py).
    # ``donate=False`` keeps the input alive at the cost of one copy.

    def _check_horizon(self, state, num_rounds, start_round=None):
        # ``start_round`` lets pipelined callers (bench.py, the bridge)
        # validate the horizon from their host-side round counter:
        # reading ``state.round_idx`` of an in-flight chunk's output
        # would block until that chunk finishes, serializing the
        # dispatch pipeline.
        if start_round is None:
            start_round = int(state.round_idx)
        self.t.validate_horizon(start_round + num_rounds)

    def _resolve_sparse_request(self, sparse):
        return sparse_ops.resolve_request(self._sparse_mode, sparse,
                                          self.supports_sparse)

    def _resolve_pipeline_request(self, pipeline):
        return pipeline_ops.resolve_request(self._pipeline_mode, pipeline,
                                            self.supports_pipeline)

    def _pipeline_dispatch(self, sparse):
        """Guard a pipelined dispatch: the carried board is dense, so
        the sparse-frontier round cannot compose with it."""
        if self._resolve_sparse_request(sparse):
            raise ValueError(
                "pipelined execution does not compose with the "
                "sparse-frontier round (the carried publish is dense); "
                "pass sparse='0' or pipeline=False")

    def step(self, state, key):
        self._check_horizon(state, 1)
        return self._step_jit(state, key)

    def step_sparse(self, state, key):
        """One sparse-path round; returns ``(state, stats[3])`` — the
        lockstep suites' probe (drivers report stats via
        ``last_sparse_stats`` instead, keeping their arity stable)."""
        self._resolve_sparse_request(True)
        self._check_horizon(state, 1)
        return self._step_sparse_jit(state, key)

    def run(self, state, key, num_rounds: int, conv_every: int = 1,
            donate: bool = True, start_round=None, sparse=None,
            pipeline=None):
        """Run ``num_rounds``, sampling the convergence metric every
        ``conv_every`` rounds (the returned curve has
        ``num_rounds // conv_every`` points, at rounds ``conv_every,
        2·conv_every, …``).  The census behind the metric costs ~3
        protocol rounds at 65k nodes on TPU v5e (scatter-bound), so
        large-N studies sample it on a cadence; tests and small N keep
        per-round resolution."""
        if num_rounds % conv_every:
            raise ValueError(
                f"num_rounds={num_rounds} not divisible by "
                f"conv_every={conv_every}")
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, conv, _inflight = self.run_pipelined(
                state, key, num_rounds, conv_every, donate=donate,
                start_round=start_round)
            return final, conv
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, conv, stats = self._run_sparse_jit(
                state, key, num_rounds, conv_every)
            self.last_sparse_stats = stats
            return final, conv
        self.last_sparse_stats = None
        return self._run_jit(state, key, num_rounds, conv_every)

    def run_behind(self, state, key, num_rounds: int, every: int = 1,
                   donate: bool = True, start_round=None, sparse=None):
        """Like :meth:`run` but sampling the raw behind COUNT
        (:meth:`behind`) instead of the normalized fraction — the
        bench's ε-crossing detector, immune to float32 resolution loss
        near 1.0."""
        if num_rounds % every:
            raise ValueError(
                f"num_rounds={num_rounds} not divisible by every={every}")
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, behind, stats = self._run_behind_sparse_jit(
                state, key, num_rounds, every)
            self.last_sparse_stats = stats
            return final, behind
        self.last_sparse_stats = None
        return self._run_behind_jit(state, key, num_rounds, every)

    def run_fast(self, state, key, num_rounds: int, donate: bool = True,
                 start_round=None, sparse=None, pipeline=None):
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, _inflight = self.run_fast_pipelined(
                state, key, num_rounds, donate=donate,
                start_round=start_round)
            return final
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, stats = self._run_fast_sparse_jit(state, key,
                                                     num_rounds)
            self.last_sparse_stats = stats
            return final
        self.last_sparse_stats = None
        return self._run_fast_jit(state, key, num_rounds)

    # -- pipelined drivers (docs/pipeline.md) --------------------------------
    # The explicit-arity twins of run/run_fast: they thread the
    # ``(state, inflight)`` scan carry so chunked dispatches resume the
    # software pipeline exactly where the previous chunk left it
    # (tests pin chunked == straight round for round).

    def run_pipelined(self, state, key, num_rounds: int,
                      conv_every: int = 1, *, inflight=None,
                      donate: bool = True, start_round=None):
        """Pipelined :meth:`run`: returns ``(final, conv, inflight)``.
        ``inflight=None`` primes the pipeline from the current cache
        (:meth:`prime_pipeline`); chunked callers pass the previous
        chunk's carry instead."""
        self._resolve_pipeline_request(True)
        if num_rounds % conv_every:
            raise ValueError(
                f"num_rounds={num_rounds} not divisible by "
                f"conv_every={conv_every}")
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if inflight is None:
            state, inflight = self._prime_jit(state, key)
        self.last_sparse_stats = None
        return self._run_pipelined_jit(state, key, num_rounds,
                                       conv_every, inflight)

    def run_fast_pipelined(self, state, key, num_rounds: int, *,
                           inflight=None, donate: bool = True,
                           start_round=None):
        """Pipelined :meth:`run_fast`: returns ``(final, inflight)``."""
        self._resolve_pipeline_request(True)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if inflight is None:
            state, inflight = self._prime_jit(state, key)
        self.last_sparse_stats = None
        return self._run_fast_pipelined_jit(state, key, num_rounds,
                                            inflight)

    def prime_pipeline(self, state, key):
        """Fill the software pipeline: select round
        ``state.round_idx + 1``'s publish from the current cache.
        Returns ``(state, inflight)`` — the pipelined scan carry."""
        return self._prime_jit(state, key)

    def step_pipelined(self, state, inflight, key):
        """One pipelined round from the BASE key (the drivers' key
        schedule) — the stepwise probe the lockstep suites compare
        against the scan drivers."""
        self._check_horizon(state, 1)
        return self._step_pipelined_jit(
            state, inflight,
            jax.random.fold_in(key, state.round_idx),
            jax.random.fold_in(key, state.round_idx + 1))

    def _trace_record(self, prev, nxt, stats):
        """One round's flight-recorder record (ops/trace.py) — the
        behind census goes through :meth:`behind`, so the sharded
        twin's census-path restrictions (``metric_list_ok``) apply
        unchanged."""
        p = self.p
        return trace_ops.compressed_record(
            prev, nxt, self.behind(nxt),
            budget=min(p.budget, p.cache_lines), fanout=p.fanout,
            limit=p.resolved_retransmit_limit(), stats=stats,
            tick_period=self._knobs.tick_period,
            tick_phase=self._knobs.tick_phase)

    def run_with_trace(self, state, key, num_rounds: int, cap: int = 0,
                       donate: bool = True, start_round=None,
                       sparse=None):
        """Scan with the per-round flight recorder (ops/trace.py):
        returns ``(final state, RoundTrace)``.  ``cap`` bounds the
        record buffer (0 = every round); rounds past it truncate with
        ``overflow`` set — the DeltaBatch contract.  Works unchanged on
        the sharded twin (records are computed at the jit level over
        the global tensors)."""
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, tr, stats = self._run_trace_sparse_jit(
                state, key, num_rounds, cap)
            self.last_sparse_stats = stats
            return final, tr
        self.last_sparse_stats = None
        return self._run_trace_jit(state, key, num_rounds, cap)

    def _digest_record(self, nxt, idents, buckets: int):
        """One round's coherence record (ops/digest.py) over the
        materialized belief view ``max(floor, cache hit, own)`` —
        computed at the jit level over the global tensors, so the
        sharded twin inherits this unchanged (GSPMD shards the gathers
        and the segment-sum)."""
        from sidecar_tpu.ops.delta import compressed_belief
        bel = compressed_belief(nxt.own, nxt.cache_slot, nxt.cache_val,
                                nxt.floor, self.p.services_per_node)
        return digest_ops.state_digest_record(
            nxt.round_idx, bel, nxt.node_alive, idents, buckets)

    def _resolve_digest_idents(self, idents):
        if idents is None:
            idents = digest_ops.default_idents(self.p.m)
        return jnp.asarray(idents, jnp.uint32)

    def run_with_digest(self, state, key, num_rounds: int, cap: int = 0,
                        buckets: int = digest_ops.DEFAULT_BUCKETS,
                        idents=None, donate: bool = True,
                        start_round=None, sparse=None):
        """Scan with the per-round coherence digest (ops/digest.py):
        returns ``(final state, DigestTrace)`` — the compressed
        drivers' no-conv arity, like :meth:`run_with_trace`.  Works
        unchanged on the sharded twin (the digest is computed at the
        jit level over the global tensors)."""
        cap = cap or num_rounds
        idents = self._resolve_digest_idents(idents)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, dt, stats = self._run_digest_sparse_jit(
                state, key, num_rounds, cap, idents, buckets)
            self.last_sparse_stats = stats
            return final, dt
        self.last_sparse_stats = None
        return self._run_digest_jit(state, key, num_rounds, cap, idents,
                                    buckets)

    def run_with_deltas(self, state, key, num_rounds: int, cap: int,
                        donate: bool = True, sparse=None):
        """Scan with per-round changed-belief extraction: returns
        ``(final state, DeltaBatch[num_rounds])``.  The belief view
        ``max(floor, cache hit, own)`` is materialized per round
        (ops/delta.compressed_belief — gathers + elementwise, no
        scatters) and diffed on device; this is O(N·M) per round, the
        bridge/test regime's tool — north-star-scale delta streaming
        stays on the exact model's shard sizes (see ops/delta.py)."""
        self._check_horizon(state, num_rounds)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, deltas, stats = self._run_deltas_sparse_jit(
                state, key, num_rounds, cap)
            self.last_sparse_stats = stats
            return final, deltas
        self.last_sparse_stats = None
        return self._run_deltas_jit(state, key, num_rounds, cap)

    def run_with_provenance(self, state, key, num_rounds: int, tracked,
                            cap: int = 0, prov=None, donate: bool = True,
                            start_round=None, sparse=None):
        """Scan with the record-level provenance tracer
        (ops/provenance.py): returns ``(final state, ProvTrace)`` —
        the compressed drivers' no-conv arity, like
        :meth:`run_with_trace`.  Chunked callers pass the previous
        chunk's ``ProvTrace`` as ``prov``."""
        tracked = tuple(int(s) for s in tracked)
        if not tracked:
            raise ValueError("provenance needs at least one tracked slot")
        for slot in tracked:
            if not 0 <= slot < self.p.m:
                raise ValueError(
                    f"tracked slot {slot} outside [0, {self.p.m})")
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if prov is None:
            prov = prov_ops.zero_prov(len(tracked), self.p.n, cap)
            prov = prov_ops.seed(
                prov,
                self._prov_belief(state, jnp.asarray(tracked, jnp.int32)),
                state.round_idx)
        if self._resolve_sparse_request(sparse):
            final, prov, stats = self._run_prov_sparse_jit(
                state, key, num_rounds, prov, tracked)
            self.last_sparse_stats = stats
            return final, prov
        self.last_sparse_stats = None
        return self._run_prov_jit(state, key, num_rounds, prov, tracked)

    # no-donate: single-round stepping is the oracle/replay path — those
    # callers diff pre- vs post-step states, so the input must survive.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_jit(self, state, key):
        return self._step(state, key)

    # no-donate: the sparse single-round probe serves the same
    # oracle/replay callers as _step_jit.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_sparse_jit(self, state, key):
        return self._step_sparse(state, key)

    # no-donate: the pipeline prologue's input state is the caller's —
    # only the scan drivers own their buffers.
    @functools.partial(jax.jit, static_argnums=0)
    def _prime_jit(self, state, key):
        inflight, sent = self._select_inflight(
            state, state.round_idx + 1,
            jax.random.fold_in(key, state.round_idx))
        return dataclasses.replace(state, cache_sent=sent), inflight

    # no-donate: the pipelined single-round probe serves the stepwise
    # lockstep suites.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_pipelined_jit(self, state, inflight, k_now, k_next):
        return self._step_pipelined(state, inflight, k_now, k_next)

    # Per-round keys fold the round index into the base key so chunked/
    # resumed runs replay identical randomness (see ExactSim).

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_jit(self, state, key, num_rounds, conv_every=1):
        def inner(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), \
                None
        def body(st, _):
            st, _ = lax.scan(inner, st, None, length=conv_every)
            return st, self.convergence(st)
        return lax.scan(body, state, None,
                        length=num_rounds // conv_every)

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_behind_jit(self, state, key, num_rounds, every):
        def inner(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), \
                None
        def body(st, _):
            st, _ = lax.scan(inner, st, None, length=every)
            return st, self.behind(st)
        return lax.scan(body, state, None, length=num_rounds // every)

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_jit(self, state, key, num_rounds):
        def body(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), None
        final, _ = lax.scan(body, state, None, length=num_rounds)
        return final

    # -- pipelined scan drivers (docs/pipeline.md) ---------------------------
    # Same donation and per-round key folding as the lockstep drivers;
    # the carry is ``(state, inflight)`` — round r+1's publish selected
    # inside the tick that folds round r.

    @functools.partial(jax.jit, static_argnums=(0, 3, 4),
                       donate_argnums=(1, 5))
    def _run_pipelined_jit(self, state, key, num_rounds, conv_every,
                           inflight):
        def inner(carry, _):
            st, infl = carry
            return self._step_pipelined(
                st, infl,
                jax.random.fold_in(key, st.round_idx),
                jax.random.fold_in(key, st.round_idx + 1)), None

        def body(carry, _):
            carry, _ = lax.scan(inner, carry, None, length=conv_every)
            return carry, self.convergence(carry[0])

        (final, inflight), conv = lax.scan(
            body, (state, inflight), None,
            length=num_rounds // conv_every)
        return final, conv, inflight

    @functools.partial(jax.jit, static_argnums=(0, 3),
                       donate_argnums=(1, 4))
    def _run_fast_pipelined_jit(self, state, key, num_rounds, inflight):
        def body(carry, _):
            st, infl = carry
            return self._step_pipelined(
                st, infl,
                jax.random.fold_in(key, st.round_idx),
                jax.random.fold_in(key, st.round_idx + 1)), None

        (final, inflight), _ = lax.scan(body, (state, inflight), None,
                                        length=num_rounds)
        return final, inflight

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_deltas_jit(self, state, key, num_rounds, cap):
        # Lazy import — ops/delta imports this module's hash_line.
        from sidecar_tpu.ops.delta import compressed_belief, extract_delta

        def belief(st):
            return compressed_belief(st.own, st.cache_slot, st.cache_val,
                                     st.floor, self.p.services_per_node)

        def body(carry, _):
            st, bel = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            bel2 = belief(st2)
            return (st2, bel2), extract_delta(bel, bel2, cap)

        (final, _), deltas = lax.scan(body, (state, belief(state)), None,
                                      length=num_rounds)
        return final, deltas

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_jit(self, state, key, num_rounds, cap):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, None))
            return (st2, buf), None

        (final, buf), _ = lax.scan(
            body, (state, trace_ops.zero_trace(cap)), None,
            length=num_rounds)
        return final, buf

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_jit(self, state, key, num_rounds, cap, idents,
                        buckets):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf), None

        (final, buf), _ = lax.scan(
            body, (state, digest_ops.zero_digest(cap)), None,
            length=num_rounds)
        return final, buf

    # Donates the ProvTrace too (argnum 4): it chains chunk-to-chunk the
    # way the state does.
    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_jit(self, state, key, num_rounds, prov, tracked):
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2 = self._step(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv), None

        (final, prov), _ = lax.scan(body, (state, prov), None,
                                    length=num_rounds)
        return final, prov

    # -- sparse-path scan drivers (docs/sparse.md) ---------------------------
    # Mirrors of the dense drivers above: same donation, same per-round
    # key folding (sparse chunks pipeline/resume interchangeably with
    # dense ones), plus an int32 [3] stats accumulator in the carry
    # (sparse rounds, overflow rounds, frontier high-water mark) that
    # the public wrappers surface through ``last_sparse_stats``.

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_sparse_jit(self, state, key, num_rounds, conv_every=1):
        def inner(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), None

        def body(carry, _):
            carry, _ = lax.scan(inner, carry, None, length=conv_every)
            return carry, self.convergence(carry[0])

        (final, stats), conv = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds // conv_every)
        return final, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_behind_sparse_jit(self, state, key, num_rounds, every):
        def inner(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), None

        def body(carry, _):
            carry, _ = lax.scan(inner, carry, None, length=every)
            return carry, self.behind(carry[0])

        (final, stats), behind = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds // every)
        return final, behind, stats

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_sparse_jit(self, state, key, num_rounds):
        def body(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), None

        (final, stats), _ = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_deltas_sparse_jit(self, state, key, num_rounds, cap):
        # Lazy import — ops/delta imports this module's hash_line.
        from sidecar_tpu.ops.delta import compressed_belief, extract_delta

        def belief(st):
            return compressed_belief(st.own, st.cache_slot, st.cache_val,
                                     st.floor, self.p.services_per_node)

        def body(carry, _):
            st, bel, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            bel2 = belief(st2)
            return (st2, bel2, sparse_ops.accumulate_stats(acc, s)), \
                extract_delta(bel, bel2, cap)

        (final, _, stats), deltas = lax.scan(
            body, (state, belief(state), sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, deltas, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_sparse_jit(self, state, key, num_rounds, cap):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, s))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), None

        (final, buf, stats), _ = lax.scan(
            body, (state, trace_ops.zero_trace(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_sparse_jit(self, state, key, num_rounds, cap,
                               idents, buckets):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), None

        (final, buf, stats), _ = lax.scan(
            body, (state, digest_ops.zero_digest(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_sparse_jit(self, state, key, num_rounds, prov,
                             tracked):
        # The sparse round consumes the same peer/push-pull draws as the
        # dense one (docs/sparse.md bit-identity), so the channel
        # re-derivation is shared.
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv, acc = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2, s = self._step_sparse(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv, sparse_ops.accumulate_stats(acc, s)), None

        (final, prov, stats), _ = lax.scan(
            body, (state, prov, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, prov, stats


# -- host-path kernels ------------------------------------------------------

def _line_compete(cache_slot, cache_val, cache_sent, rows, slots, vals,
                  cache_lines, services_per_node, floor):
    """Scatter-based line competition — retained ONLY for the host-side
    ``mint`` path (arbitrary slot lists, once per scenario event); the
    per-round paths are the scatter-free board/announce kernels above.

    Resolves a batch of (node-row, slot, val) cache insertions: the
    largest val wins each line (value ties broken by larger slot id),
    existing content included.  Entries with val ≤ 0 or slot < 0 are
    no-ops; floor-dead entries are filtered.  Returns
    (slot, val, sent, evicted-live-count)."""
    n = cache_slot.shape[0]
    valid = (vals > 0) & (slots >= 0)
    valid = valid & (vals > floor[jnp.where(valid, slots, 0)])
    line = jnp.where(valid,
                     hash_line(jnp.maximum(slots, 0), cache_lines,
                               services_per_node),
                     cache_lines)
    rows = jnp.where(valid, rows, n)

    val1 = cache_val.at[rows, line].max(vals, mode="drop")
    got = val1[jnp.where(valid, rows, 0), jnp.where(valid, line, 0)]
    won = valid & (vals == got)
    cand_slot = jnp.where(won, slots, -1)
    slot1 = jnp.where(cache_val == val1, cache_slot, -1)
    slot1 = slot1.at[rows, line].max(cand_slot, mode="drop")

    changed = (val1 != cache_val) | (slot1 != cache_slot)
    sent1 = jnp.where(changed, jnp.int8(0), cache_sent)

    # Eviction accounting: a line whose slot changed while the OLD entry
    # was still above the floor lost live information.
    old_live = (cache_slot >= 0) & \
        (cache_val > floor[jnp.maximum(cache_slot, 0)])
    evicted = old_live & (slot1 != cache_slot)
    return slot1, val1, sent1, jnp.sum(evicted.astype(jnp.int32))


def _census(state: CompressedState, p: CompressedParams):
    """Per-slot truth (freshest belief among alive nodes) and hit count
    (#alive nodes whose belief is at truth).  O(N·K + M)."""
    s, m = p.services_per_node, p.m
    alive = state.node_alive
    n_alive = jnp.sum(alive.astype(jnp.int32))

    own_flat = state.own.reshape(m)
    owner_alive = jnp.repeat(alive, s)
    own_val = jnp.where(owner_alive, own_flat, 0)

    # Truth: floor ∨ owners ∨ every live cache entry of an alive node.
    truth = jnp.maximum(state.floor, own_val)
    cslot = state.cache_slot.reshape(-1)
    cval = state.cache_val.reshape(-1)
    centry_alive = jnp.repeat(alive, p.cache_lines)
    cval = jnp.where((cslot >= 0) & centry_alive, cval, 0)
    cidx = jnp.where(cslot >= 0, cslot, m)
    truth = truth.at[cidx].max(cval, mode="drop")

    # Hits: nodes whose belief ≥ truth.  floor ≥ truth ⇒ everyone.
    all_know = state.floor >= truth
    # Cache hits — own slots excluded (owners are counted via ``own`` so
    # a cached copy of one's own record can't double-count).
    node_of_entry = jnp.repeat(jnp.arange(p.n, dtype=jnp.int32),
                               p.cache_lines)
    entry_owner = jnp.where(cslot >= 0, cslot // s, -1)
    counts = (cval >= truth[jnp.maximum(cslot, 0)]) & (cslot >= 0) \
        & centry_alive & (entry_owner != node_of_entry)
    hits = jnp.zeros((m,), jnp.int32).at[cidx].add(
        counts.astype(jnp.int32), mode="drop")
    hits = hits + (owner_alive & (own_flat >= truth)).astype(jnp.int32)
    hits = jnp.where(all_know, n_alive, hits)
    return truth, hits, n_alive
