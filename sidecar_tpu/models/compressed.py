"""The compressed large-cluster gossip model — bounded memory per node.

The exact model's ``known[N, N·spn]`` belief matrix is O(N²·spn): at the
north-star scale (100k nodes × 1M services, BASELINE.md) that is 4×10¹¹
cells — physically impossible on any chip.  This model replaces it with
three structures totalling O(N·K + M) (SURVEY.md §7 "Sparsity +
raggedness" names this the hard part):

* ``own[N, S]`` — owner-authoritative records for each node's own
  service slots (the reference keeps local services authoritative in the
  same state map, catalog/services_state.go:70-80).
* ``cache_{slot,val,sent}[N, K]`` — each node's bounded **in-flight
  belief cache**: a direct-mapped table of the records the node has
  recently learned and is still relaying.  This mirrors reality better
  than the dense matrix does: memberlist's TransmitLimited broadcast
  queue is itself bounded (the native engine caps it at 4096), and a
  real node's "interesting" state at any moment is the small delta
  against the converged catalog.  The line index is a global
  multiplicative hash of the slot id, so one slot occupies the SAME
  line on every node — deliberately: the floor census folds "every
  line's unanimously-held winner" per sweep, and winners are only
  unanimous because freshness order and line assignment are both
  global (see :func:`hash_line` for why the salted alternative was
  measured and rejected).  Colliding live slots drain newest-first,
  losers re-entering via the owners' recovery re-offer.
* ``floor[M]`` — the shared **converged baseline**: the record version
  every alive node is known to hold.  In the real cluster each of N
  hosts stores the full O(M) catalog; simulating N identical copies of
  the converged part is pure waste, so the model stores it once and
  advances it only when a per-slot census proves every alive node has
  caught up.  belief(i, m) = max(floor[m], cache hit, own if owner).

Line competition: the freshest record (largest packed key) wins a cache
line, ties broken by larger slot id; a line's value never regresses.
Evicting a still-live belief loses information — the model counts those
evictions (``state.evictions``) so an under-provisioned K is visible —
and liveness is restored by the owners' recovery re-offer plus the
anti-entropy cache/own exchange.

Scale regime: this model starts CONVERGED (floor = the boot catalog)
and measures how injected churn — the steady-state workload —
propagates back to full convergence.  Cold-start full-catalog sync is
the push-pull regime the exact model covers at small N; at 65k+ nodes
the physically meaningful question is delta propagation, which is what
bounded caches represent.

Round structure (mirrors models/exact.py):
1. select + deliver — top-``budget`` freshest eligible cache entries to
   ``fanout`` sampled peers; deliveries resolve through ONE
   line-competition scatter pass (two scatter-maxes: value, then
   winning slot on value ties) with merge semantics — staleness gate,
   acceptance against the pre-round belief, DRAINING stickiness —
   applied to the values first, exactly like ops/gossip.py.
2. announce — staggered owner re-stamps (the 1-minute refresh,
   services_state.go:547-549) minting a new version, plus **recovery**
   re-offers: own slots still above the floor re-enter the owner's
   cache with a fresh transmit budget WITHOUT a new version (the
   changed-service re-broadcast, services_state.go:538) — this is what
   makes convergence immune to cache evictions.
3. anti-entropy — every push-pull cadence, a two-way full-cache +
   own-rows exchange with the node ``stride`` positions away, routed
   through the same merge path.
4. floor advance + sweep — per-slot census (truth = freshest belief,
   hits = #alive nodes at truth); slots where every alive node agrees
   fold into the floor and their cache lines free; the TTL sweep
   (ops/ttl.py) runs over own + cache + floor — one shared floor sweep
   models every node's identical deterministic sweep.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.merge import staleness_mask, sticky_adjust
from sidecar_tpu.ops.status import ALIVE, TOMBSTONE, is_known, pack, unpack_status
from sidecar_tpu.ops.topology import Topology
from sidecar_tpu.ops.ttl import ttl_sweep

_K1 = np.uint32(2654435761)   # Knuth multiplicative
_K3 = np.uint32(0xC2B2AE35)   # murmur3 finalizer constant


def hash_line(slot, cache_lines: int):
    """Global multiplicative hash: slot id → cache line, the SAME line on
    every node.

    Cross-node alignment is load-bearing for the unanimity census: the
    fold throughput of the floor is "every line's current winner", and a
    winner can only be unanimously held if it wins its line on EVERY
    node — which the global hash guarantees (freshness order is global).
    A per-node-salted hash was measured and rejected: collisions become
    independent across nodes, so under capacity pressure only the
    globally-freshest few records are ever held by all nodes at once and
    fold throughput collapses (convergence wedged at ~0.4 on a 256-node
    default-refresh run).  With the global hash a line with several live
    slots drains newest-first, and evicted losers re-enter through the
    owners' recovery re-offer (``recover_rounds``) once the line frees."""
    u = jnp.asarray(slot).astype(jnp.uint32) * _K1
    u = (u ^ (u >> np.uint32(15))) * _K3
    shift = 32 - int(math.log2(cache_lines))
    return (u >> np.uint32(shift)).astype(jnp.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CompressedState:
    """Pytree carried through the round scan."""

    own: jax.Array         # int32 [N, S] owner-authoritative packed keys
    cache_slot: jax.Array  # int32 [N, K] slot id per line (-1 = empty)
    cache_val: jax.Array   # int32 [N, K] packed belief
    cache_sent: jax.Array  # int8 [N, K] transmit counts
    floor: jax.Array       # int32 [M] shared converged baseline
    node_alive: jax.Array  # bool [N]
    round_idx: jax.Array   # int32 scalar
    evictions: jax.Array   # int32 scalar — live beliefs lost to capacity


@dataclasses.dataclass(frozen=True)
class CompressedParams:
    n: int
    services_per_node: int = 10
    cache_lines: int = 256       # K — must be a power of two
    fanout: int = 3
    budget: int = 15
    drop_prob: float = 0.0
    retransmit_limit: int = 0    # 0 = auto (RetransmitMult semantics)
    recover_rounds: int = 10     # unconverged-own re-offer cadence — the
                                 # drain rate of collision chains (losers
                                 # of a shared line re-enter this often)

    def __post_init__(self):
        if self.cache_lines & (self.cache_lines - 1):
            raise ValueError("cache_lines must be a power of two")
        if self.budget > self.cache_lines:
            raise ValueError("budget cannot exceed cache_lines")

    @property
    def m(self) -> int:
        return self.n * self.services_per_node

    def resolved_retransmit_limit(self) -> int:
        if self.retransmit_limit > 0:
            return self.retransmit_limit
        return 4 * math.ceil(math.log10(self.n + 1))


PerturbFn = Callable[["CompressedState", jax.Array, jax.Array],
                     "CompressedState"]


class CompressedSim:
    """Single-chip compressed simulator (multi-chip:
    ``sidecar_tpu.parallel.sharded_compressed``)."""

    def __init__(self, params: CompressedParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 perturb: Optional[PerturbFn] = None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None):
        if topo.n != params.n:
            raise ValueError(f"topology has {topo.n} nodes, params say {params.n}")
        if cut_mask is not None and topo.nbrs is None:
            raise ValueError("cut_mask requires a neighbor-list topology")
        self.p = params
        self.t = timecfg
        self.topo = topo
        self.perturb = perturb
        self._nbrs = None if topo.nbrs is None else jnp.asarray(topo.nbrs)
        self._deg = None if topo.deg is None else jnp.asarray(topo.deg)
        self._cut = None if cut_mask is None else jnp.asarray(cut_mask)
        self._side = None if node_side is None else \
            jnp.asarray(node_side, jnp.int32)

    # -- state construction -------------------------------------------------

    def init_state(self) -> CompressedState:
        """Converged boot state: the whole catalog sits in the floor at
        tick 1, owners hold matching authoritative records, caches are
        empty.  Scenario perturbations (mint/churn) create the in-flight
        work this model measures."""
        p = self.p
        boot = jnp.full((p.n, p.services_per_node), pack(1, ALIVE),
                        dtype=jnp.int32)
        return CompressedState(
            own=boot,
            cache_slot=jnp.full((p.n, p.cache_lines), -1, jnp.int32),
            cache_val=jnp.zeros((p.n, p.cache_lines), jnp.int32),
            cache_sent=jnp.zeros((p.n, p.cache_lines), jnp.int8),
            floor=jnp.full((p.m,), pack(1, ALIVE), dtype=jnp.int32),
            node_alive=jnp.ones((p.n,), bool),
            round_idx=jnp.zeros((), jnp.int32),
            evictions=jnp.zeros((), jnp.int32),
        )

    # -- perturbation helper ------------------------------------------------

    def mint(self, state: CompressedState, slots, now_tick,
             status=ALIVE) -> CompressedState:
        """Inject new record versions at the given global slots: owners
        re-stamp their authoritative copy and seed their cache line (the
        changed-service broadcast, services_state.go:538-549).  The
        scenario-facing churn hook."""
        p = self.p
        slots = jnp.asarray(slots, jnp.int32)
        owner = slots // p.services_per_node
        col = slots % p.services_per_node
        val = jnp.broadcast_to(
            pack(jnp.asarray(now_tick, jnp.int32), status), slots.shape)
        val = jnp.where(state.node_alive[owner], val, 0)
        rows = jnp.where(val > 0, owner, p.n)
        own = state.own.at[rows, col].max(val, mode="drop")
        cs, cv, se, ev = _line_compete(
            state.cache_slot, state.cache_val, state.cache_sent,
            owner, slots, val, p.cache_lines, state.floor)
        return dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            evictions=state.evictions + ev)

    # -- kernels ------------------------------------------------------------

    def _select(self, state: CompressedState, limit: int):
        """Top-``budget`` freshest eligible cache entries per node.
        Eligible = transmits left AND still above the floor (entries the
        whole cluster already knows are dead weight)."""
        p = self.p
        slot, val = state.cache_slot, state.cache_val
        live = (slot >= 0) & (val > state.floor[jnp.maximum(slot, 0)])
        eligible = live & (state.cache_sent.astype(jnp.int32) < limit)
        priority = jnp.where(eligible, val, 0)
        msg, line_idx = lax.top_k(priority, min(p.budget, p.cache_lines))
        sel_slot = jnp.take_along_axis(slot, line_idx, axis=1)
        sel_slot = jnp.where(msg > 0, sel_slot, -1)
        # Padded lines index past K so scatters drop them (see
        # ops/gossip.select_messages for the aliasing hazard).
        line_idx = jnp.where(msg > 0, line_idx, p.cache_lines)
        return line_idx.astype(jnp.int32), sel_slot, msg

    def _apply(self, state: CompressedState, sent, rows, slots, vals,
               now):
        """Merge flat (node, slot, val) updates with full merge
        semantics: staleness gate, acceptance against the pre-batch
        belief, DRAINING stickiness.  Own-slot updates also land in
        ``own``; every accepted update enters the cache via line
        competition (an accepted record re-offers — the relay,
        services_state.go:377-392)."""
        p, t = self.p, self.t
        s = p.services_per_node
        safe_slots = jnp.maximum(slots, 0)
        owner_of = safe_slots // s
        col = safe_slots % s
        valid = (slots >= 0) & (vals > 0)
        is_own = (owner_of == rows) & valid

        vals = jnp.where(staleness_mask(vals, now, t.stale_ticks), 0, vals)

        # Pre-batch belief of (rows, slots).
        safe_rows = jnp.where(valid, rows, 0)
        line = hash_line(safe_slots, p.cache_lines)
        line_slot = state.cache_slot[safe_rows, line]
        line_val = state.cache_val[safe_rows, line]
        pre = jnp.where(valid, state.floor[safe_slots], 0)
        pre = jnp.maximum(pre, jnp.where(line_slot == slots, line_val, 0))
        own_pre = state.own[safe_rows, col]
        pre = jnp.maximum(pre, jnp.where(is_own, own_pre, 0))

        advanced = (vals > pre) & valid
        vals = sticky_adjust(vals, pre, advanced)
        vals = jnp.where(advanced, vals, 0)

        own_rows = jnp.where(is_own & advanced, rows, p.n)
        own = state.own.at[own_rows, col].max(vals, mode="drop")

        cs, cv, se, ev = _line_compete(
            state.cache_slot, state.cache_val, sent,
            rows, slots, vals, p.cache_lines, state.floor)
        return dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            evictions=state.evictions + ev)

    def _announce(self, state: CompressedState, round_idx, now):
        """Owner refresh + recovery.

        Refresh (staggered per record, ops/gossip.refresh_due) mints a
        fresh version of every present, non-tombstone own record.  A
        refresh of a record the whole cluster already holds (own ==
        floor, status unchanged) folds STRAIGHT into the floor: in the
        reference, refresh delivery is guaranteed by the 20 s full-state
        anti-entropy (PushPullInterval ≪ the 80 s ALIVE_LIFESPAN,
        main.go:252-256) rather than by gossip luck, and the floor is
        precisely this model's compression of "state every node holds" —
        simulating N copies of a timestamp bump nothing can invalidate
        would be pure cache pressure with no information content (the
        whole catalog would wash through the bounded caches once per
        refresh interval and drown real churn).  Refreshes of records
        still in flight (own > floor) mint normally and re-earn
        convergence through the census.

        Recovery (staggered per node) re-seeds the cache line of own
        slots still above the floor without minting — restoring the
        transmit budget of a stalled/evicted record, which is what
        drains collision chains (the changed-service re-broadcast,
        services_state.go:538)."""
        p, t = self.p, self.t
        n, s = p.n, p.services_per_node
        node = jnp.arange(n, dtype=jnp.int32)[:, None]          # [N, 1]
        slots = jnp.arange(p.m, dtype=jnp.int32).reshape(n, s)  # [N, S]

        st = unpack_status(state.own)
        present = is_known(state.own) & state.node_alive[:, None]

        refresh_due = gossip_ops.refresh_due(
            state.own, slots, round_idx, refresh_rounds=t.refresh_rounds,
            round_ticks=t.round_ticks, now=now) & present \
            & (st != TOMBSTONE)
        new_val = pack(now, st)
        fold = refresh_due & (state.own == state.floor[slots])
        own = jnp.where(refresh_due, new_val, state.own)
        floor = state.floor.at[jnp.where(fold, slots, p.m)].max(
            jnp.where(fold, new_val, 0), mode="drop")

        rphase = node % p.recover_rounds
        recover_due = ((round_idx % p.recover_rounds) == rphase) & present \
            & (own > floor[slots])

        offer = (refresh_due & ~fold) | recover_due
        vals = jnp.where(offer, own, 0).reshape(-1)
        nodes = jnp.broadcast_to(node, (n, s)).reshape(-1)
        flat_slots = jnp.where(offer, slots, -1).reshape(-1)

        # Owner-authoritative insert: straight line competition, then a
        # transmit-budget reset wherever the line now holds the offer.
        cs, cv, se, ev = _line_compete(
            state.cache_slot, state.cache_val, state.cache_sent,
            nodes, flat_slots, vals, p.cache_lines, floor)
        line = hash_line(jnp.maximum(flat_slots, 0), p.cache_lines)
        holds = (vals > 0) & \
            (cs[jnp.where(vals > 0, nodes, 0), line] == flat_slots)
        reset_rows = jnp.where(holds, nodes, n)
        se = se.at[reset_rows, line].set(jnp.int8(0), mode="drop")
        return dataclasses.replace(
            state, own=own, floor=floor, cache_slot=cs, cache_val=cv,
            cache_sent=se, evictions=state.evictions + ev)

    def _push_pull_stride(self, state: CompressedState, key, now):
        """Anti-entropy: two-way exchange with the node ``stride``
        positions away — each side's full cache plus its own rows, all
        routed through the standard merge path.  Split scenarios mask
        the exchange where the two sides differ (a partition severs TCP
        push-pull too)."""
        p = self.p
        stride = jax.random.randint(key, (), 1, p.n, dtype=jnp.int32)
        alive = state.node_alive
        my_node = jnp.arange(p.n, dtype=jnp.int32)
        own_slots = jnp.arange(p.m, dtype=jnp.int32).reshape(
            p.n, p.services_per_node)

        all_rows, all_slots, all_vals = [], [], []
        for roll_amt in (-stride, stride):
            ok = alive & jnp.roll(alive, roll_amt)
            if self._side is not None:
                ok = ok & (self._side == jnp.roll(self._side, roll_amt))
            okc = ok[:, None]
            # Partner's cache entries land on my aligned rows.
            p_slot = jnp.roll(state.cache_slot, roll_amt, 0)
            p_val = jnp.roll(state.cache_val, roll_amt, 0)
            p_val = jnp.where(okc & (p_slot >= 0), p_val, 0)
            all_rows.append(jnp.broadcast_to(
                my_node[:, None], p_slot.shape).reshape(-1))
            all_slots.append(jnp.where(p_val > 0, p_slot, -1).reshape(-1))
            all_vals.append(p_val.reshape(-1))
            # Partner's own rows (their authoritative records).
            t_slot = jnp.roll(own_slots, roll_amt, 0)
            t_val = jnp.where(okc, jnp.roll(state.own, roll_amt, 0), 0)
            all_rows.append(jnp.broadcast_to(
                my_node[:, None], t_slot.shape).reshape(-1))
            all_slots.append(jnp.where(t_val > 0, t_slot, -1).reshape(-1))
            all_vals.append(t_val.reshape(-1))

        return self._apply(
            state, state.cache_sent,
            jnp.concatenate(all_rows), jnp.concatenate(all_slots),
            jnp.concatenate(all_vals), now)

    def _floor_advance_and_sweep(self, state: CompressedState, now):
        """Census → floor advance → line free → TTL sweep."""
        p, t = self.p, self.t
        truth, hits, n_alive = _census(state, p)
        caught_up = hits >= n_alive
        floor = jnp.where(caught_up, jnp.maximum(state.floor, truth),
                          state.floor)

        below = (state.cache_slot >= 0) & (
            state.cache_val <= floor[jnp.maximum(state.cache_slot, 0)])
        cache_slot = jnp.where(below, -1, state.cache_slot)
        cache_val = jnp.where(below, 0, state.cache_val)
        cache_sent = jnp.where(below, jnp.int8(0), state.cache_sent)

        kw = dict(alive_lifespan=t.alive_lifespan,
                  draining_lifespan=t.draining_lifespan,
                  tombstone_lifespan=t.tombstone_lifespan,
                  one_second=t.one_second)
        own, _ = ttl_sweep(state.own, now, **kw)
        floor, _ = ttl_sweep(floor, now, **kw)
        swept_val, _ = ttl_sweep(cache_val, now, **kw)
        cache_sent = jnp.where(swept_val != cache_val, jnp.int8(0),
                               cache_sent)
        return dataclasses.replace(
            state, own=own, floor=floor, cache_slot=cache_slot,
            cache_val=swept_val, cache_sent=cache_sent)

    def _step(self, state: CompressedState,
              key: jax.Array) -> CompressedState:
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        # 1. select (pre-round snapshot) + gossip deliveries.
        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=state.node_alive, cut_mask=self._cut)
        line_idx, sel_slot, msg = self._select(state, limit)
        sent = _bump_transmits(state.cache_sent, line_idx, msg, p.fanout,
                               limit)

        n, fanout = dst.shape
        budget = msg.shape[1]
        v = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
        tgt = jnp.broadcast_to(dst[:, :, None], (n, fanout, budget))
        sl = jnp.broadcast_to(sel_slot[:, None, :], (n, fanout, budget))
        v = jnp.where(state.node_alive[:, None, None], v, 0)
        v = jnp.where(state.node_alive[tgt], v, 0)
        if p.drop_prob > 0.0:
            keep = jax.random.bernoulli(k_drop, 1.0 - p.drop_prob, v.shape)
            v = jnp.where(keep, v, 0)
        self_tgt = tgt == jnp.arange(n, dtype=jnp.int32)[:, None, None]
        v = jnp.where(self_tgt, 0, v)  # self-sends are merge no-ops

        state = self._apply(state, sent, tgt.reshape(-1), sl.reshape(-1),
                            v.reshape(-1), now)

        # 2. announce re-stamps + recovery offers (end of round, like the
        # exact model: broadcastable the following round).
        state = self._announce(state, round_idx, now)

        # 3. anti-entropy.
        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)

        # 4. floor advance + sweep.
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        return dataclasses.replace(state, round_idx=round_idx)

    # -- metrics ------------------------------------------------------------

    def convergence(self, state: CompressedState) -> jax.Array:
        """Fraction of (alive node, slot) beliefs agreeing with the
        freshest belief — the exact model's metric, computed from the
        compressed representation in O(N·K + M)."""
        truth, hits, n_alive = _census(state, self.p)
        behind = jnp.maximum(n_alive - hits, 0)
        # Denominator in float: n_alive·m overflows int32 at the scales
        # this model exists for (65,536 × 655,360 ≈ 4.3e10).
        denom = n_alive.astype(jnp.float32) * jnp.float32(self.p.m)
        frac_behind = jnp.sum(behind.astype(jnp.float32)) / \
            jnp.maximum(denom, 1.0)
        return 1.0 - frac_behind

    # -- drivers ------------------------------------------------------------

    def _check_horizon(self, state, num_rounds):
        self.t.validate_horizon(int(state.round_idx) + num_rounds)

    def step(self, state, key):
        self._check_horizon(state, 1)
        return self._step_jit(state, key)

    def run(self, state, key, num_rounds: int):
        self._check_horizon(state, num_rounds)
        return self._run_jit(state, key, num_rounds)

    def run_fast(self, state, key, num_rounds: int):
        self._check_horizon(state, num_rounds)
        return self._run_fast_jit(state, key, num_rounds)

    @functools.partial(jax.jit, static_argnums=0)
    def _step_jit(self, state, key):
        return self._step(state, key)

    # Per-round keys fold the round index into the base key so chunked/
    # resumed runs replay identical randomness (see ExactSim).

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _run_jit(self, state, key, num_rounds):
        def body(st, _):
            st = self._step(st, jax.random.fold_in(key, st.round_idx))
            return st, self.convergence(st)
        return lax.scan(body, state, None, length=num_rounds)

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _run_fast_jit(self, state, key, num_rounds):
        def body(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), None
        final, _ = lax.scan(body, state, None, length=num_rounds)
        return final


# -- shared kernels (also used by the sharded twin) -------------------------

def _line_compete(cache_slot, cache_val, cache_sent, rows, slots, vals,
                  cache_lines, floor):
    """Resolve a batch of (node-row, slot, val) cache insertions: the
    largest val wins each line (value ties broken by larger slot id),
    existing content included.  Entries with val ≤ 0 or slot < 0 are
    no-ops; floor-dead entries are filtered.  Returns
    (slot, val, sent, evicted-live-count)."""
    n = cache_slot.shape[0]
    valid = (vals > 0) & (slots >= 0)
    valid = valid & (vals > floor[jnp.where(valid, slots, 0)])
    line = jnp.where(valid, hash_line(jnp.maximum(slots, 0), cache_lines),
                     cache_lines)
    rows = jnp.where(valid, rows, n)

    val1 = cache_val.at[rows, line].max(vals, mode="drop")
    got = val1[jnp.where(valid, rows, 0), jnp.where(valid, line, 0)]
    won = valid & (vals == got)
    cand_slot = jnp.where(won, slots, -1)
    slot1 = jnp.where(cache_val == val1, cache_slot, -1)
    slot1 = slot1.at[rows, line].max(cand_slot, mode="drop")

    changed = (val1 != cache_val) | (slot1 != cache_slot)
    sent1 = jnp.where(changed, jnp.int8(0), cache_sent)

    # Eviction accounting: a line whose slot changed while the OLD entry
    # was still above the floor lost live information.
    old_live = (cache_slot >= 0) & \
        (cache_val > floor[jnp.maximum(cache_slot, 0)])
    evicted = old_live & (slot1 != cache_slot)
    return slot1, val1, sent1, jnp.sum(evicted.astype(jnp.int32))


def _bump_transmits(cache_sent, line_idx, msg, fanout, limit):
    n, k = cache_sent.shape
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    bump = jnp.where(msg > 0, fanout, 0).astype(jnp.int32)
    current = cache_sent[rows, jnp.minimum(line_idx, k - 1)]
    capped = jnp.minimum(current.astype(jnp.int32) + bump,
                         limit).astype(cache_sent.dtype)
    return cache_sent.at[rows, line_idx].set(capped, mode="drop")


def _census(state: CompressedState, p: CompressedParams):
    """Per-slot truth (freshest belief among alive nodes) and hit count
    (#alive nodes whose belief is at truth).  O(N·K + M)."""
    s, m = p.services_per_node, p.m
    alive = state.node_alive
    n_alive = jnp.sum(alive.astype(jnp.int32))

    own_flat = state.own.reshape(m)
    owner_alive = jnp.repeat(alive, s)
    own_val = jnp.where(owner_alive, own_flat, 0)

    # Truth: floor ∨ owners ∨ every live cache entry of an alive node.
    truth = jnp.maximum(state.floor, own_val)
    cslot = state.cache_slot.reshape(-1)
    cval = state.cache_val.reshape(-1)
    centry_alive = jnp.repeat(alive, p.cache_lines)
    cval = jnp.where((cslot >= 0) & centry_alive, cval, 0)
    cidx = jnp.where(cslot >= 0, cslot, m)
    truth = truth.at[cidx].max(cval, mode="drop")

    # Hits: nodes whose belief ≥ truth.  floor ≥ truth ⇒ everyone.
    all_know = state.floor >= truth
    # Cache hits — own slots excluded (owners are counted via ``own`` so
    # a cached copy of one's own record can't double-count).
    node_of_entry = jnp.repeat(jnp.arange(p.n, dtype=jnp.int32),
                               p.cache_lines)
    entry_owner = jnp.where(cslot >= 0, cslot // s, -1)
    counts = (cval >= truth[jnp.maximum(cslot, 0)]) & (cslot >= 0) \
        & centry_alive & (entry_owner != node_of_entry)
    hits = jnp.zeros((m,), jnp.int32).at[cidx].add(
        counts.astype(jnp.int32), mode="drop")
    hits = hits + (owner_alive & (own_flat >= truth)).astype(jnp.int32)
    hits = jnp.where(all_know, n_alive, hits)
    return truth, hits, n_alive
