"""Node-axis-sharded gossip simulator for multi-chip meshes.

Scaling design (the project's analog of context parallelism, SURVEY.md §5
"long-context"): the cluster-size axis N is sharded over the device mesh.
Each device owns a contiguous block of nodes — a node's entire row
(its full replicated catalog, the ``ServicesState`` of one host) stays
device-local, so the per-round compute (announce, top-k selection,
scatter-merge, TTL sweep) is embarrassingly parallel.

Cross-device traffic, by construction, is only:

* **Gossip messages** — each round's offers are budget-limited
  (``fanout × budget`` packed keys per node, the ~1398 B-packet analog,
  services_delegate.go:182-223), so an ``all_gather`` of the message
  tensors is tiny; every shard then scatter-merges the subset of
  deliveries targeting its own rows.  This mirrors reality: gossip
  *messages* cross the network, state stays put.
* **Anti-entropy** — instead of uniform-random partners (which would be a
  full-row all-to-all), the sharded simulator uses a **random-stride ring
  exchange**: each push-pull event draws one global stride s and every
  node i does a two-way full-state exchange with node (i+s) mod N.
  ``jnp.roll`` along the sharded axis lowers to an XLA collective-permute
  riding ICI.  Random strides give expander-like mixing across events;
  the divergence from memberlist's uniform partner choice
  (services_delegate.go:146-167) is a deliberate scalability trade and is
  visible only in the tail of convergence curves.

Like the single-chip model, the round is built around ONE scatter-max on
``known`` and ONE reset scatter on ``sent`` per shard per round (scatters
on the big tensors cost a full buffer rewrite each on TPU); announce
updates ride the same scatter, and the transmit-count bump is a small
extra scatter.

Partitions: pass ``node_side`` (int[N] side assignment) — gossip edges are
cut via ``cut_mask`` exactly as in the single-chip model, and the stride
exchange is masked where the two sides differ (a network split severs TCP
push-pull too).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from sidecar_tpu import metrics
from sidecar_tpu.models.exact import (
    SimParams,
    SimState,
    _resolve_cadence,
    clone_state,
)
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import pipeline as pipeline_ops
from sidecar_tpu.ops import provenance as prov_ops
from sidecar_tpu.ops import sparse as sparse_ops
from sidecar_tpu.ops import suspicion as suspicion_ops
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.merge import admit_gate, merge_packed, sticky_adjust
from sidecar_tpu.ops.status import (
    TOMBSTONE,
    is_known,
    pack,
    unpack_status,
)
from sidecar_tpu.ops.topology import Topology, zoned_exchange_plan
from sidecar_tpu.ops.ttl import ttl_sweep
from sidecar_tpu.telemetry import cost
from sidecar_tpu.parallel.mesh import (
    NODE_AXIS,
    make_mesh,
    resolve_board_exchange,
    shard_map,
)


class ShardedSim:
    """Multi-device exact simulator; protocol semantics match ExactSim
    except for the documented anti-entropy pairing (and independent PRNG
    streams per shard)."""

    # The sparse-frontier round is available on this twin
    # (docs/sparse.md); select-level compaction, per shard.
    supports_sparse = True

    # The software-pipelined round (docs/pipeline.md) is available via
    # TWIN DELEGATION: the pipelined program is the single-chip
    # ExactSim's, jitted over the GLOBAL row-sharded tensors — GSPMD
    # partitions the publish/fold, so pipelined-sharded is bit-identical
    # to pipelined-single-chip BY CONSTRUCTION (it IS the same program,
    # including the single-chip PRNG stream — the per-shard streams and
    # board-exchange modes are lockstep-path concepts).
    supports_pipeline = True

    def __init__(self, params: SimParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 mesh=None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None,
                 board_exchange: Optional[str] = None,
                 exchange_stub: bool = False,
                 sparse: Optional[str] = None,
                 digest_gate: Optional[bool] = None,
                 gate_buckets: int = 8,
                 pipeline: Optional[str] = None,
                 tick_period=None, tick_phase=None):
        if topo.n != params.n:
            raise ValueError(f"topology has {topo.n} nodes, params say {params.n}")
        if cut_mask is not None and topo.nbrs is None:
            raise ValueError("cut_mask requires a neighbor-list topology")
        self.p = params
        self.t = timecfg
        self.topo = topo
        self._sparse_mode = sparse_ops.resolve_sparse(sparse)
        self._pipeline_mode = pipeline_ops.resolve_pipeline(pipeline)
        self.last_sparse_stats = None
        # Per-node tick cadence (docs/pipeline.md), validated here and
        # normalized to full-[N] replicated vectors for the per-shard
        # ``[gi]`` slices; the raw arguments are kept for the pipelined
        # single-chip twin, which re-resolves them itself.
        self._cadence_args = (tick_period, tick_phase)
        tp, tph = _resolve_cadence(tick_period, tick_phase, params.n)
        self._cadence = None
        if not (isinstance(tp, int) and tp <= 1):
            self._cadence = tuple(
                jnp.broadcast_to(
                    jnp.asarray(v, jnp.int32).reshape(-1), (params.n,))
                for v in (tp, tph))
        self._pipe_twin = None
        # The dense twin exchanges bounded OFFER tensors, not boards:
        # all_gather replicates them, ring streams sender blocks hop by
        # hop, zoned ships only the row blocks the overlay can make
        # another shard sample (docs/topology.md).  all_to_all request
        # routing only exists on the compressed twin (its pulls have a
        # row-id request shape; dense offers are pushes) —
        # docs/sharding.md.
        if board_exchange == "zoned" and topo.nbrs is None:
            raise ValueError(
                "board_exchange='zoned' requires a neighbor-list "
                "topology: the complete graph reaches every shard "
                "(use all_gather there)")
        supported = ("all_gather", "ring")
        if topo.nbrs is not None:
            supported += ("zoned",)
        self.board_exchange = resolve_board_exchange(
            board_exchange, supported=supported)
        # Measurement-only (benchmarks/sharded_scaling.py): consume only
        # own-shard offers, skip the collectives — the exposed-comm
        # probe; the trajectory is wrong by construction.
        self._exchange_stub = exchange_stub
        self.mesh = mesh if mesh is not None else make_mesh()
        self.d = self.mesh.devices.size
        if params.n % self.d != 0:
            raise ValueError(f"n={params.n} must divide the {self.d}-device mesh")
        nl = params.n // self.d
        # Per-shard sparse sender cap: the global cap split over the
        # mesh with 2× imbalance slack (docs/sparse.md).
        cap = min(params.n,
                  params.sparse_cap
                  or sparse_ops.default_frontier_cap(params.n))
        self._sparse_cap_shard = min(nl, max(16, -(-cap // self.d) * 2))
        payload_ints = params.fanout + 2 * min(params.budget, params.m)
        # Zoned: static reachability plan (ops/topology.py) — which of
        # each shard's offer rows some other shard's overlay can sample.
        # Push direction: the dense twin ships offers toward targets.
        self._zoned_plan = None
        self._zoned_tabs = None
        if self.board_exchange == "zoned":
            self._zoned_plan = zoned_exchange_plan(topo, self.d,
                                                   direction="push")
            self._zoned_tabs = tuple(
                None if h is None
                else (jnp.asarray(h.rows), jnp.asarray(h.valid))
                for h in self._zoned_plan.hops)
            metrics.set_gauge("parallel.exchange.zoned_rows",
                              float(self._zoned_plan.total_rows))
        # Digest-gated exchange (the anti-entropy subsystem's kernel
        # leg, docs/antientropy.md): before the zoned hops, every shard
        # publishes a tiny per-row catalog digest (gate_buckets wide —
        # one all_gather of [d, gb, 2] uint32 per round) and each hop
        # whose sender and receiver blocks provably already agree is
        # skipped under a lax.cond.  The skip predicate is computed
        # from REPLICATED (all-gathered) data, so every shard takes the
        # same branch and the ppermute inside the cond stays a valid
        # collective; a skipped hop's offers could only re-deliver
        # values the receiver holds (equal digests ⇒ equal catalogs up
        # to hash collision), so the gated round is bit-identical in
        # the converged state (pinned in tests/test_antientropy.py).
        # Default off (None → SIDECAR_TPU_ANTIENTROPY_GATE env, "1" to
        # enable) — the ungated program compiles byte-for-byte as
        # before.
        if digest_gate is None:
            import os
            digest_gate = os.environ.get(
                "SIDECAR_TPU_ANTIENTROPY_GATE", "0") == "1"
        if digest_gate and self.board_exchange != "zoned":
            raise ValueError(
                "digest_gate composes with board_exchange='zoned' "
                f"only (got {self.board_exchange!r}): all_gather and "
                "ring ship whole blocks a digest cannot split")
        self.digest_gate = bool(digest_gate)
        self._gate_buckets = int(gate_buckets)
        self._gate_idents = None
        if self.digest_gate:
            digest_ops.bucket_ids_np(np.zeros(1, np.uint32),
                                     self._gate_buckets)  # validates
            self._gate_idents = jnp.asarray(
                digest_ops.default_idents(params.m))
        self.exchange_bytes_per_round = {
            "all_gather": (params.n - nl) * payload_ints * 4,
            "ring": (self.d - 1) * nl * payload_ints * 4,
            "zoned": (0 if self._zoned_plan is None
                      else self._zoned_plan.total_rows * payload_ints * 4),
        }[self.board_exchange]
        metrics.set_gauge("parallel.exchange.bytes",
                          float(self.exchange_bytes_per_round))

        shard = NamedSharding(self.mesh, P(NODE_AXIS))
        self._row_sharding = shard
        self._nbrs = (None if topo.nbrs is None
                      else jax.device_put(jnp.asarray(topo.nbrs), shard))
        self._deg = (None if topo.deg is None
                     else jax.device_put(jnp.asarray(topo.deg), shard))
        self._cut = (None if cut_mask is None
                     else jax.device_put(jnp.asarray(cut_mask), shard))
        self._side = (None if node_side is None
                      else jax.device_put(jnp.asarray(node_side, dtype=jnp.int32),
                                          NamedSharding(self.mesh, P())))
        # Round-stagger phase offsets (ops/topology.with_stagger,
        # docs/topology.md): replicated constant; None compiles the
        # unstaggered program bit for bit.
        self._stagger = (None if topo.stagger is None
                         or topo.stagger_period <= 1
                         else jnp.asarray(topo.stagger, jnp.int32))
        self._stagger_period = int(topo.stagger_period)

    # -- state -------------------------------------------------------------

    def init_state(self) -> SimState:
        p = self.p
        owner = np.arange(p.m, dtype=np.int64) // p.services_per_node
        known = np.zeros((p.n, p.m), dtype=np.int32)
        known[owner, np.arange(p.m)] = int(pack(1, 0))  # ALIVE @ tick 1
        shard = self._row_sharding
        repl = NamedSharding(self.mesh, P())
        return SimState(
            known=jax.device_put(jnp.asarray(known), shard),
            sent=jax.device_put(jnp.zeros((p.n, p.m), jnp.int8), shard),
            node_alive=jax.device_put(jnp.ones((p.n,), bool), repl),
            round_idx=jax.device_put(jnp.zeros((), jnp.int32), repl),
        )

    def gate_predicates(self, state: SimState) -> np.ndarray:
        """Host-side replica of the digest gate's per-hop skip
        predicate — bool [d-1], entry ``h-1`` True iff ring hop ``h``
        would be SKIPPED on the next round (all shards internally
        uniform and every receiver/sender pair digest-equal).  This is
        the same formula the compiled gate evaluates on-device
        (replicated, from the all-gathered [d, gb, 2] table), exposed
        on the host so tests and the bench can prove the gate actually
        engages in the converged state rather than inferring it from
        bit-identity alone."""
        if not self.digest_gate:
            raise ValueError("gate_predicates requires digest_gate=True")
        known = np.asarray(state.known)
        dig = digest_ops.node_digests_np(
            known, np.asarray(self._gate_idents), self._gate_buckets)
        nl = known.shape[0] // self.d
        uni = []
        first = []
        for i in range(self.d):
            blk = dig[i * nl:(i + 1) * nl]
            uni.append(bool((blk == blk[:1]).all()))
            first.append(blk[0])
        first_arr = np.stack(first)
        out = np.zeros(self.d - 1, bool)
        for h in range(1, self.d):
            out[h - 1] = all(uni) and bool(
                (first_arr == np.roll(first_arr, -h, axis=0)).all())
        return out

    # -- the per-shard gossip round (inside shard_map) ---------------------

    def _sample_dst_complete(self, k_peers, gi, alive, nl):
        """Complete-topology sampling (uniform over the whole cluster,
        self-excluded via the shift trick)."""
        p = self.p
        r = jax.random.randint(k_peers, (nl, p.fanout), 0, p.n - 1,
                               dtype=jnp.int32)
        dst = r + (r >= gi[:, None]).astype(jnp.int32)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    def _sample_dst_nbrs(self, k_peers, gi, alive, nl, nbrs_l, deg_l, cut_l):
        p = self.p
        slot = jax.random.randint(k_peers, (nl, p.fanout), 0,
                                  jnp.maximum(deg_l, 1)[:, None],
                                  dtype=jnp.int32)
        dst = jnp.take_along_axis(nbrs_l, slot, axis=1)
        if cut_l is not None:
            cut = jnp.take_along_axis(cut_l, slot, axis=1)
            dst = jnp.where(cut, gi[:, None], dst)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    def _stagger_gate(self, dst, gi, round_idx):
        """Round-stagger + tick-cadence gating (docs/topology.md,
        docs/pipeline.md), applied AFTER the sampling draw so the
        per-shard PRNG streams stay key-comparable with the ungated
        run; compiles away when neither is attached.  Gossip fan-out
        only — the stride push-pull is the catch-up channel and never
        gates."""
        if self._stagger is not None:
            off = ((round_idx + self._stagger[gi])
                   % self._stagger_period) != 0
            dst = jnp.where(off[:, None], gi[:, None], dst)
        if self._cadence is not None:
            per, pha = self._cadence
            dst = gossip_ops.cadence_gate(dst, round_idx, per[gi],
                                          pha[gi], self_idx=gi)
        return dst

    def _block_candidates(self, known0, dst_b, svc_b, msg_b, senders,
                          alive, r0, nl, now, keep_b):
        """Flat (rows, cols, vals, advanced) delivery candidates from
        one contiguous SENDER block, localized to this shard's rows and
        resolved against the pre-round local block ``known0`` — the
        round-5 candidate pipeline, applied per block so the split-phase
        round can evaluate own-shard offers while remote blocks are
        still in flight (every gate is elementwise and every candidate
        resolves against ``known0``, so block order is irrelevant; the
        combined scatter-max at the end commutes)."""
        t = self.t
        bn, fanout = dst_b.shape
        budget = svc_b.shape[1]
        val = jnp.broadcast_to(msg_b[:, None, :], (bn, fanout, budget))
        tgt = jnp.broadcast_to(dst_b[:, :, None], (bn, fanout, budget))
        svc = jnp.broadcast_to(svc_b[:, None, :], (bn, fanout, budget))

        b_own = None
        if t.tomb_budget is not None:
            # Per-origin budget (ops/merge.budget_mask): each
            # [fanout, budget] block is fanout copies of one sender's
            # packet — the suspicious rank per copy matches the dense
            # round's per-packet rank.  Sender-owned slots are exempt;
            # the no-offer sentinel ``svc = m`` maps to owner ``n``
            # (never a sender) with msg 0, so it is value-safe.
            b_own = ((svc // self.p.services_per_node)
                     == senders[:, None, None])
        val = admit_gate(val, now, t.stale_ticks, t.future_ticks,
                         t.tomb_budget, b_own)
        val = jnp.where(alive[senders][:, None, None], val, 0)
        val = jnp.where(alive[tgt], val, 0)
        if keep_b is not None:
            val = jnp.where(keep_b, val, 0)

        # Localize: rows outside [0, nl) belong to other shards — their
        # gathers clamp harmlessly and their scatters drop.
        tgt_local = (tgt - r0).reshape(-1)
        cols = svc.reshape(-1)
        val = val.reshape(-1)
        local = (tgt_local >= 0) & (tgt_local < nl)
        val = jnp.where(local, val, 0)

        pre_vals = known0[tgt_local, cols]
        advanced = (val > pre_vals) & local
        val = sticky_adjust(val, pre_vals, advanced)
        d_rows = jnp.where(local, tgt_local, nl)
        return d_rows, cols, val, advanced

    def _gossip_shard(self, known_l, sent_l, alive, key, round_idx,
                      nbrs_l=None, deg_l=None, cut_l=None,
                      use_sparse=False):
        """One shard's split-phase, comm-overlapped gossip round
        (docs/sharding.md): select local offers → issue the exchange →
        evaluate own-shard deliveries + the announce stamps (both
        board-independent, overlapping the in-flight offers) → consume
        remote blocks → ONE combined scatter → sweep.  Bit-identical to
        the pre-split round in both exchange modes (the lockstep suite
        is the oracle): every candidate resolves against the pre-round
        block and the combined scatter-max/reset commute."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        s = p.services_per_node
        nl = known_l.shape[0]
        d = self.d
        ax = lax.axis_index(NODE_AXIS)
        r0 = (ax * nl).astype(jnp.int32)
        now = round_idx * t.round_ticks
        gi = r0 + jnp.arange(nl, dtype=jnp.int32)      # my global node ids

        key_shard = jax.random.fold_in(key, ax)
        k_peers, k_drop = jax.random.split(key_shard)
        if nbrs_l is None:
            dst = self._sample_dst_complete(k_peers, gi, alive, nl)
        else:
            dst = self._sample_dst_nbrs(k_peers, gi, alive, nl,
                                        nbrs_l, deg_l, cut_l)
        dst = self._stagger_gate(dst, gi, round_idx)

        # Phase 1 — select offers from the local block + transmit
        # accounting.  row_offset ties the tie-break rotation to GLOBAL
        # node ids so the selection matches ExactSim bit-for-bit.
        #
        # Sparse mode (docs/sparse.md): the select/top-k — the phase
        # whose cost scales with the mostly-ineligible tail — runs on
        # the shard's compacted eligible-sender rows and the dense
        # offer tensors are reconstructed (a no-offer row is exactly
        # ``svc = m / msg = 0`` in the dense select too), so the
        # exchange and every downstream phase are untouched.  The cond
        # is per-shard divergent — legal, it contains no collectives —
        # with the dense select as the overflow fallback; bit-identical
        # either way.
        ovf = n_s = None
        if use_sparse:
            sender_l = jnp.any(
                gossip_ops.eligible_records(known_l, sent_l, limit),
                axis=1)
            n_s = jnp.sum(sender_l.astype(jnp.int32))
            ovf = n_s > self._sparse_cap_shard

            def dense_sel(_):
                svc, msg = gossip_ops.select_messages(
                    known_l, sent_l, p.budget, limit, row_offset=r0)
                se2 = gossip_ops.record_transmissions(
                    sent_l, svc, msg, p.fanout, limit)
                return svc, msg, se2

            def sparse_sel(_):
                idx_s, row_s, valid_s, pos_s = sparse_ops.compact_rows(
                    sender_l, self._sparse_cap_shard)
                kn_s = jnp.where(valid_s[:, None], known_l[row_s], 0)
                svc_c, msg_c = gossip_ops.select_messages(
                    kn_s, sent_l[row_s], p.budget, limit,
                    row_ids=idx_s + r0)
                se2 = gossip_ops.record_transmissions(
                    sent_l, svc_c, msg_c, p.fanout, limit,
                    row_ids=idx_s)
                snd = sender_l[:, None]
                svc = jnp.where(snd, svc_c[pos_s], p.m)
                msg = jnp.where(snd, msg_c[pos_s], 0)
                return svc, msg, se2

            svc_idx, msg, sent_l = lax.cond(ovf, dense_sel, sparse_sel,
                                            None)
        else:
            svc_idx, msg = gossip_ops.select_messages(
                known_l, sent_l, p.budget, limit, row_offset=r0)
            sent_l = gossip_ops.record_transmissions(
                sent_l, svc_idx, msg, p.fanout, limit)

        known0 = known_l               # pre-round snapshot: ALL candidate
        fanout = dst.shape[1]          # resolution happens against it
        budget = svc_idx.shape[1]
        keepmask = None
        if p.drop_prob > 0.0:
            # ONE draw over the full sender space (the pre-split shape),
            # sliced per block — splitting never changes the stream.
            keepmask = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob, (p.n, fanout, budget))

        def keep_slice(s0, bn):
            if keepmask is None:
                return None
            return lax.dynamic_slice(keepmask, (s0, 0, 0),
                                     (bn, fanout, budget))

        # Phase 2 — issue the exchange (mode-dependent; the only
        # cross-shard gossip traffic is the bounded offer tensors).
        if self.board_exchange == "all_gather" and not self._exchange_stub:
            with cost.phase("exchange"):
                dst_all = lax.all_gather(dst, NODE_AXIS, tiled=True)      # [N, F]
                svc_all = lax.all_gather(svc_idx, NODE_AXIS, tiled=True)  # [N, B]
                msg_all = lax.all_gather(msg, NODE_AXIS, tiled=True)      # [N, B]

        # Phase 3a — own-shard deliveries (no exchange needed).
        groups = [self._block_candidates(
            known0, dst, svc_idx, msg, gi, alive, r0, nl, now,
            keep_slice(r0, nl))]

        # Phase 3b — announce stamps (owners of my rows' slots are
        # exactly my rows; reads only the pre-round block, so it
        # overlaps the in-flight exchange).  Phase/guard arithmetic is
        # over GLOBAL slot ids, matching ExactSim._announce_updates
        # bit-for-bit.
        lr = jnp.arange(nl * s, dtype=jnp.int32) // s
        a_cols = r0 * s + jnp.arange(nl * s, dtype=jnp.int32)
        own = known0[lr, a_cols]
        st = unpack_status(own)
        present = is_known(own) & alive[r0 + lr]
        due = gossip_ops.refresh_due(
            own, a_cols, round_idx, refresh_rounds=t.refresh_rounds,
            round_ticks=t.round_ticks, now=now) & present \
            & (st != TOMBSTONE)
        # Lifeguard self-refutation, matching ExactSim._announce_updates
        # bit-for-bit (compiles to nothing at suspicion window 0).
        due, st = suspicion_ops.announce_refute(
            due, st, present, t.suspicion_window > 0)
        a_vals = jnp.where(due, pack(now, st), 0)
        a_rows = jnp.where(due, lr, nl)

        # Phase 4 — consume remote sender blocks.
        if self._exchange_stub:
            pass  # measurement-only exposed-comm probe: no collectives
        elif self.board_exchange == "all_gather":
            rem = p.n - nl
            if rem:
                # Rotate my own block out of the gathered tensors (it
                # was already consumed from the local arrays above):
                # the remaining N - nl senders, in ring order.
                shift = r0 + nl
                senders_r = (shift + jnp.arange(rem, dtype=jnp.int32)) \
                    % p.n
                keep_r = None
                if keepmask is not None:
                    keep_r = jnp.roll(keepmask, -shift, axis=0)[:rem]
                groups.append(self._block_candidates(
                    known0,
                    jnp.roll(dst_all, -shift, axis=0)[:rem],
                    jnp.roll(svc_all, -shift, axis=0)[:rem],
                    jnp.roll(msg_all, -shift, axis=0)[:rem],
                    senders_r, alive, r0, nl, now, keep_r))
        elif self.board_exchange == "zoned":
            # Zoned: per ring offset h, each shard ships ONLY the
            # statically-reachable offer rows of its block (plan built
            # at construction; docs/topology.md).  Pad rows ship msg=0
            # — provable scatter-max no-ops — so the consume is
            # bit-identical to all_gather for the same sampled peers.
            if d > 1:
                live = [h for h in range(1, d)
                        if self._zoned_tabs[h - 1] is not None]

                def zoned_send(h):
                    zrows, zvalid = self._zoned_tabs[h - 1]
                    rows_s = zrows[ax]                      # [R_h]
                    blocks = (dst[rows_s], svc_idx[rows_s],
                              jnp.where(zvalid[ax][:, None],
                                        msg[rows_s], 0))
                    perm = [(i, (i - h) % d) for i in range(d)]
                    with cost.phase("exchange"):
                        return tuple(lax.ppermute(b, NODE_AXIS, perm)
                                     for b in blocks)

                if live and self.digest_gate:
                    # Digest-gated hops: each hop runs under a
                    # lax.cond on a REPLICATED skip predicate — all
                    # shards uniform AND every (receiver, sender=-h)
                    # pair's digests equal — computed from one tiny
                    # all_gather, so every shard takes the same branch
                    # and the ppermute inside the cond is collective-
                    # safe.  The skip branch emits shape-matched
                    # no-op candidates (rows = nl drop in the combined
                    # scatter).  No double buffering here: a cond
                    # boundary would entangle adjacent hops' branches.
                    gb = self._gate_buckets
                    dig_l = digest_ops.node_digests(
                        known0, self._gate_idents, gb)       # [nl, gb, 2]
                    uni = jnp.all(dig_l == dig_l[:1])
                    with cost.phase("exchange"):
                        dig_all = lax.all_gather(dig_l[0], NODE_AXIS)
                        uni_all = lax.all_gather(uni, NODE_AXIS)
                    all_uni = jnp.all(uni_all)
                    for h in live:
                        agree_h = all_uni & jnp.all(
                            dig_all == jnp.roll(dig_all, -h, axis=0))
                        zrows, _zvalid = self._zoned_tabs[h - 1]
                        ss = (ax + h) % d                   # sender shard
                        senders_h = ss * nl + zrows[ss]
                        keep_b = (None if keepmask is None
                                  else keepmask[senders_h])
                        sz = zrows.shape[1] * fanout * budget

                        def live_fn(_, h=h, senders_h=senders_h,
                                    keep_b=keep_b):
                            cur = zoned_send(h)
                            return self._block_candidates(
                                known0, cur[0], cur[1], cur[2],
                                senders_h, alive, r0, nl, now, keep_b)

                        def skip_fn(_, sz=sz):
                            return (jnp.full((sz,), nl, jnp.int32),
                                    jnp.zeros((sz,), jnp.int32),
                                    jnp.zeros((sz,), jnp.int32),
                                    jnp.zeros((sz,), bool))

                        groups.append(lax.cond(~agree_h, live_fn,
                                               skip_fn, None))
                else:
                    cur = zoned_send(live[0]) if live else None
                    for j, h in enumerate(live):
                        if j + 1 < len(live):
                            # Double buffer: the next hop's (smaller)
                            # transfer is issued before this hop's
                            # block is consumed, same overlap shape as
                            # the ring leg.
                            nxt = zoned_send(live[j + 1])
                        zrows, _zvalid = self._zoned_tabs[h - 1]
                        ss = (ax + h) % d                   # sender shard
                        senders_h = ss * nl + zrows[ss]
                        keep_b = (None if keepmask is None
                                  else keepmask[senders_h])
                        groups.append(self._block_candidates(
                            known0, cur[0], cur[1], cur[2], senders_h,
                            alive, r0, nl, now, keep_b))
                        if j + 1 < len(live):
                            cur = nxt
        else:  # ring — stream offer blocks hop by hop over ppermute
            if d > 1:
                perm = [(i, (i - 1) % d) for i in range(d)]

                def hop(blocks):
                    with cost.phase("exchange"):
                        return tuple(lax.ppermute(b, NODE_AXIS, perm)
                                     for b in blocks)

                cur = hop((dst, svc_idx, msg))
                for h in range(1, d):
                    if h < d - 1:
                        # Double buffer: hop h+1's transfer is issued
                        # before hop h's block is consumed, so the next
                        # transfer overlaps this hop's gate/localize.
                        # Live footprint: two offer-block triples,
                        # O(N/d·(F+2B)).
                        nxt = hop(cur)
                    s0 = ((ax + h) % d) * nl
                    senders_h = s0 + jnp.arange(nl, dtype=jnp.int32)
                    groups.append(self._block_candidates(
                        known0, cur[0], cur[1], cur[2], senders_h,
                        alive, r0, nl, now, keep_slice(s0, nl)))
                    if h < d - 1:
                        cur = nxt

        # Final phase — ONE combined scatter for deliveries + announce
        # (scatters on the big tensors cost a full buffer rewrite each;
        # one per tensor per round stays the budget).
        rows = jnp.concatenate([g[0] for g in groups] + [a_rows])
        cols = jnp.concatenate([g[1] for g in groups] + [a_cols])
        vals = jnp.concatenate([g[2] for g in groups] + [a_vals])
        adv = jnp.concatenate([g[3] for g in groups] + [due])
        known_l, sent_l = gossip_ops.apply_updates(
            known_l, sent_l, rows, cols, vals, adv, num_rows=nl)

        # Lifespan sweep (local, amortized).
        def do_sweep(kn_se):
            kn, se = kn_se
            swept, _ = ttl_sweep(
                kn, now,
                alive_lifespan=t.alive_lifespan,
                draining_lifespan=t.draining_lifespan,
                tombstone_lifespan=t.tombstone_lifespan,
                one_second=t.one_second,
                suspicion_window=t.suspicion_window)
            se = jnp.where(swept != kn, jnp.int8(0), se)
            return swept, se

        known_l, sent_l = lax.cond(
            round_idx % t.sweep_rounds == 0,
            do_sweep, lambda kn_se: kn_se, (known_l, sent_l))
        if use_sparse:
            # Replicated stats outs: shards that overflowed this round
            # and the global eligible-sender count.
            return (known_l, sent_l, lax.psum(ovf.astype(jnp.int32),
                                              NODE_AXIS),
                    lax.psum(n_s, NODE_AXIS))
        return known_l, sent_l

    # -- anti-entropy stride exchange (jit level, sharding-propagated) -----

    @cost.phased("exchange", tag="push_pull")
    def _push_pull_stride(self, known, sent, alive, key, now, round_idx):
        """Two-way full-state exchange with the node `stride` positions
        away on the ring; jnp.roll on the sharded axis becomes an XLA
        collective-permute."""
        t = self.t
        stride = jax.random.randint(key, (), 1, self.p.n, dtype=jnp.int32)

        own_pull = own_push = None
        if t.tomb_budget is not None:
            # Per-origin budget on the full-row exchange (the packet is
            # the whole row — ops/gossip.push_pull's contract): the
            # pulled row's origin is the ``-stride`` partner; the
            # offered row's origin is the offering node itself.
            node_ids = jnp.arange(self.p.n, dtype=jnp.int32)
            slot_owner = (jnp.arange(self.p.m, dtype=jnp.int32)
                          // self.p.services_per_node)
            own_pull = (slot_owner[None, :]
                        == jnp.roll(node_ids, -stride)[:, None])
            own_push = slot_owner[None, :] == node_ids[:, None]
        ok = alive & jnp.roll(alive, -stride)
        if self._side is not None:
            ok &= self._side == jnp.roll(self._side, -stride)
        fwd = jnp.where(ok[:, None], jnp.roll(known, -stride, axis=0), 0)
        pulled = merge_packed(known, fwd, now, t.stale_ticks,
                              t.future_ticks, t.tomb_budget, own_pull)

        # Push = the reverse roll, stickiness vs the receiver's
        # pre-exchange row (same batch resolution as ops/gossip.push_pull).
        offered = admit_gate(known, now, t.stale_ticks, t.future_ticks,
                             t.tomb_budget, own_push)
        ok_back = alive & jnp.roll(alive, stride)
        if self._side is not None:
            ok_back &= self._side == jnp.roll(self._side, stride)
        back = jnp.where(ok_back[:, None], jnp.roll(offered, stride, axis=0), 0)
        back = sticky_adjust(back, known, back > known)
        merged = jnp.maximum(pulled, back)
        sent = jnp.where(merged != known, jnp.int8(0), sent)
        return merged, sent

    # -- provenance hooks (ops/provenance.py, docs/telemetry.md) -----------
    # Channel re-derivation replays the per-shard PRNG streams at the jit
    # level: the same fold_in(ax)/split draws _gossip_shard consumes,
    # stitched back into global [N, F] tensors.  Derivation only — the
    # step's own tensors are never touched, so provenance-enabled runs
    # stay bit-identical to untraced ones.

    def _prov_belief(self, state: SimState,
                     tracked: jax.Array) -> jax.Array:
        """Packed [N, T] belief matrix for the tracked slots."""
        return state.known[:, tracked]

    def _prov_channels(self, state: SimState, key: jax.Array):
        p, t = self.p, self.t
        round_idx = state.round_idx + 1
        alive = state.node_alive
        k_round, k_pp = jax.random.split(key)
        nl = p.n // self.d
        parts = []
        for ax in range(self.d):
            key_shard = jax.random.fold_in(k_round, ax)
            k_peers, _k_drop = jax.random.split(key_shard)
            gi = ax * nl + jnp.arange(nl, dtype=jnp.int32)
            if self._nbrs is None:
                parts.append(
                    self._sample_dst_complete(k_peers, gi, alive, nl))
            else:
                nbrs_l = self._nbrs[ax * nl:(ax + 1) * nl]
                deg_l = self._deg[ax * nl:(ax + 1) * nl]
                cut_l = (None if self._cut is None
                         else self._cut[ax * nl:(ax + 1) * nl])
                parts.append(self._sample_dst_nbrs(
                    k_peers, gi, alive, nl, nbrs_l, deg_l, cut_l))
        dst_all = gossip_ops.stagger_gate(
            jnp.concatenate(parts, axis=0), round_idx, self._stagger,
            self._stagger_period)
        if self._cadence is not None:
            per, pha = self._cadence
            dst_all = gossip_ops.cadence_gate(dst_all, round_idx, per,
                                              pha)
        pushes = [(dst_all, None)]

        # The stride exchange is two one-way pulls from the receiver's
        # point of view: i pulls the forward partner's full state and
        # receives the backward partner's push.
        stride = jax.random.randint(k_pp, (), 1, p.n, dtype=jnp.int32)
        idx = jnp.arange(p.n, dtype=jnp.int32)
        pp_on = round_idx % t.push_pull_rounds == 0
        pulls = []
        for roll_amt, partner in ((-stride, (idx + stride) % p.n),
                                  (stride, (idx - stride) % p.n)):
            ok = alive & jnp.roll(alive, roll_amt)
            if self._side is not None:
                ok = ok & (self._side == jnp.roll(self._side, roll_amt))
            pulls.append((partner[:, None], (ok & pp_on)[:, None]))
        return pushes, pulls

    # -- drivers -----------------------------------------------------------

    def _step_impl(self, state: SimState, key: jax.Array,
                   use_sparse: bool):
        p, t = self.p, self.t
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_round, k_pp = jax.random.split(key)

        spec_row = P(NODE_AXIS)
        spec_repl = P()
        out_specs = (spec_row, spec_row)
        if use_sparse:
            out_specs += (spec_repl, spec_repl)
        if self._nbrs is None:
            def wrapper_complete(kn, se, al, k, r):
                return self._gossip_shard(kn, se, al, k, r,
                                          use_sparse=use_sparse)
            fn = shard_map(
                wrapper_complete,
                mesh=self.mesh,
                in_specs=(spec_row, spec_row, spec_repl, spec_repl,
                          spec_repl),
                out_specs=out_specs,
                check_vma=False,
            )
            out = fn(state.known, state.sent, state.node_alive,
                     k_round, round_idx)
        elif self._cut is not None:
            def wrapper(kn, se, al, nb, dg, ct, k, r):
                return self._gossip_shard(kn, se, al, k, r, nbrs_l=nb,
                                          deg_l=dg, cut_l=ct,
                                          use_sparse=use_sparse)
            fn = shard_map(
                wrapper, mesh=self.mesh,
                in_specs=(spec_row,) * 2 + (spec_repl,) + (spec_row,) * 3
                         + (spec_repl, spec_repl),
                out_specs=out_specs, check_vma=False)
            out = fn(state.known, state.sent, state.node_alive,
                     self._nbrs, self._deg, self._cut, k_round,
                     round_idx)
        else:
            def wrapper_nocut(kn, se, al, nb, dg, k, r):
                return self._gossip_shard(kn, se, al, k, r, nbrs_l=nb,
                                          deg_l=dg, cut_l=None,
                                          use_sparse=use_sparse)
            fn = shard_map(
                wrapper_nocut, mesh=self.mesh,
                in_specs=(spec_row,) * 2 + (spec_repl,) + (spec_row,) * 2
                         + (spec_repl, spec_repl),
                out_specs=out_specs, check_vma=False)
            out = fn(state.known, state.sent, state.node_alive,
                     self._nbrs, self._deg, k_round, round_idx)
        if use_sparse:
            known, sent, ovf_shards, n_s = out
        else:
            known, sent = out

        known, sent = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda kn_se: self._push_pull_stride(
                kn_se[0], kn_se[1], state.node_alive, k_pp, now, round_idx),
            lambda kn_se: kn_se,
            (known, sent),
        )

        new = SimState(known=known, sent=sent,
                       node_alive=state.node_alive, round_idx=round_idx)
        if not use_sparse:
            return new
        # Stats: a round counts sparse when NO shard fell back; the
        # frontier gauge is the global eligible-sender count.
        ov = (ovf_shards > 0).astype(jnp.int32)
        return new, jnp.stack([1 - ov, ov, n_s])

    def _step(self, state: SimState, key: jax.Array) -> SimState:
        return self._step_impl(state, key, use_sparse=False)

    def _step_sparse(self, state: SimState, key: jax.Array):
        return self._step_impl(state, key, use_sparse=True)

    def convergence(self, state: SimState) -> jax.Array:
        alive = state.node_alive
        truth = jnp.max(jnp.where(alive[:, None], state.known, 0), axis=0)
        agree = state.known == truth[None, :]
        alive_f = alive.astype(jnp.float32)
        per_node = jnp.mean(agree.astype(jnp.float32), axis=1)
        return jnp.sum(per_node * alive_f) / jnp.maximum(jnp.sum(alive_f), 1.0)

    def _check_horizon(self, state, num_rounds, start_round=None):
        # ``start_round`` lets pipelined callers (the bridge, bench)
        # validate the horizon from their host-side round counter:
        # reading an in-flight chunk's ``round_idx`` would block until
        # that chunk finishes, serializing the dispatch pipeline.
        if start_round is None:
            start_round = int(state.round_idx)
        self.t.validate_horizon(start_round + num_rounds)

    def _resolve_sparse_request(self, sparse):
        return sparse_ops.resolve_request(self._sparse_mode, sparse,
                                          self.supports_sparse)

    def _resolve_pipeline_request(self, pipeline):
        return pipeline_ops.resolve_request(self._pipeline_mode, pipeline,
                                            self.supports_pipeline)

    def _pipeline_dispatch(self, sparse):
        """Guard a pipelined dispatch: the pipelined program is the
        single-chip ExactSim's (twin delegation), which composes with
        neither the sparse-frontier round nor the partition-side
        push-pull mask."""
        if self._resolve_sparse_request(sparse):
            raise ValueError(
                "pipelined execution does not compose with the "
                "sparse-frontier round (the carried publish is dense); "
                "pass sparse='0' or pipeline=False")
        if self._side is not None:
            raise ValueError(
                "pipelined execution does not support node_side: the "
                "single-chip pipelined program draws uniform push-pull "
                "partners, which have no side mask")

    def _pipeline_twin(self):
        """The lazily-built single-chip ExactSim whose pipelined jit
        program this twin dispatches on the row-sharded global state
        (GSPMD propagates the sharding through publish/fold).  Same
        params/topology/timecfg/cut/cadence; ``pipeline='1'`` so its
        drivers never silently fall back to lockstep."""
        if self._pipe_twin is None:
            from sidecar_tpu.models.exact import ExactSim
            tp, tph = self._cadence_args
            self._pipe_twin = ExactSim(
                self.p, self.topo, self.t,
                cut_mask=(None if self._cut is None
                          else np.asarray(self._cut)),
                pipeline="1", tick_period=tp, tick_phase=tph)
        return self._pipe_twin

    def run_pipelined(self, state: SimState, key: jax.Array,
                      num_rounds: int, *, inflight=None,
                      donate: bool = True, start_round=None):
        """Pipelined :meth:`run` → ``(final, conv, inflight)``: the
        single-chip pipelined program on the sharded state (see
        :meth:`_pipeline_twin`) — bit-identical to
        ``ExactSim.run_pipelined`` by construction."""
        self._resolve_pipeline_request(True)
        self._pipeline_dispatch(False)
        return self._pipeline_twin().run_pipelined(
            state, key, num_rounds, inflight=inflight, donate=donate,
            start_round=start_round)

    def run_fast_pipelined(self, state: SimState, key: jax.Array,
                           num_rounds: int, *, inflight=None,
                           donate: bool = True, start_round=None):
        """Pipelined :meth:`run_fast` → ``(final, inflight)``."""
        self._resolve_pipeline_request(True)
        self._pipeline_dispatch(False)
        return self._pipeline_twin().run_fast_pipelined(
            state, key, num_rounds, inflight=inflight, donate=donate,
            start_round=start_round)

    def prime_pipeline(self, state: SimState, key: jax.Array):
        """Fill the software pipeline (the twin's prologue)."""
        self._resolve_pipeline_request(True)
        self._pipeline_dispatch(False)
        return self._pipeline_twin().prime_pipeline(state, key)

    def step_pipelined(self, state: SimState, inflight, key: jax.Array):
        """One pipelined round from the BASE key (the twin's probe)."""
        self._resolve_pipeline_request(True)
        self._pipeline_dispatch(False)
        return self._pipeline_twin().step_pipelined(state, inflight, key)

    def step(self, state: SimState, key: jax.Array) -> SimState:
        self._check_horizon(state, 1)
        return self._step_jit(state, key)

    def step_sparse(self, state: SimState, key: jax.Array):
        """One sparse-path round → ``(state, stats[3])``."""
        self._resolve_sparse_request(True)
        self._check_horizon(state, 1)
        return self._step_sparse_jit(state, key)

    def run(self, state: SimState, key: jax.Array, num_rounds: int,
            donate: bool = True, start_round=None, sparse=None,
            pipeline=None):
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, conv, _inflight = self.run_pipelined(
                state, key, num_rounds, donate=donate,
                start_round=start_round)
            return final, conv
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, conv, stats = self._run_sparse_jit(state, key,
                                                      num_rounds)
            self.last_sparse_stats = stats
            return final, conv
        self.last_sparse_stats = None
        return self._run_jit(state, key, num_rounds)

    def _trace_record(self, prev: SimState, nxt: SimState, stats):
        """One round's flight-recorder record (ops/trace.py): computed
        at the jit level over the GLOBAL tensors, so GSPMD shards the
        reductions — the stream is bit-identical to ExactSim's."""
        tp, tph = (self._cadence if self._cadence is not None
                   else (None, None))
        return trace_ops.exact_record(
            prev, nxt, budget=min(self.p.budget, self.p.m),
            fanout=self.p.fanout,
            limit=self.p.resolved_retransmit_limit(), stats=stats,
            tick_period=tp, tick_phase=tph)

    def run_with_trace(self, state: SimState, key: jax.Array,
                       num_rounds: int, cap: int = 0,
                       donate: bool = True, start_round=None,
                       sparse=None):
        """Scan with the per-round flight recorder — the ExactSim
        contract: ``(final, RoundTrace, conv[num_rounds])`` with the
        static-cap truncation rule (docs/telemetry.md)."""
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, tr, conv, stats = self._run_trace_sparse_jit(
                state, key, num_rounds, cap)
            self.last_sparse_stats = stats
            return final, tr, conv
        self.last_sparse_stats = None
        return self._run_trace_jit(state, key, num_rounds, cap)

    def _digest_record(self, nxt: SimState, idents, buckets: int):
        """One round's coherence record (ops/digest.py): computed at
        the jit level over the GLOBAL tensors, so GSPMD shards the
        hash and the segment-sum — the stream is bit-identical to
        ExactSim's."""
        return digest_ops.state_digest_record(
            nxt.round_idx, nxt.known, nxt.node_alive, idents, buckets)

    def _resolve_digest_idents(self, idents):
        if idents is None:
            idents = digest_ops.default_idents(self.p.m)
        return jnp.asarray(idents, jnp.uint32)

    def run_with_digest(self, state: SimState, key: jax.Array,
                        num_rounds: int, cap: int = 0,
                        buckets: int = digest_ops.DEFAULT_BUCKETS,
                        idents=None, donate: bool = True,
                        start_round=None, sparse=None):
        """Scan with the per-round coherence digest — the ExactSim
        contract: ``(final, DigestTrace, conv[num_rounds])`` with the
        static-cap truncation rule (docs/telemetry.md)."""
        cap = cap or num_rounds
        idents = self._resolve_digest_idents(idents)
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, dt, conv, stats = self._run_digest_sparse_jit(
                state, key, num_rounds, cap, idents, buckets)
            self.last_sparse_stats = stats
            return final, dt, conv
        self.last_sparse_stats = None
        return self._run_digest_jit(state, key, num_rounds, cap, idents,
                                    buckets)

    def run_with_provenance(self, state: SimState, key: jax.Array,
                            num_rounds: int, tracked, cap: int = 0,
                            prov=None, donate: bool = True,
                            start_round=None, sparse=None):
        """Scan with the record-level provenance tracer — the ExactSim
        contract: ``(final, ProvTrace, conv[num_rounds])``, chunkable by
        passing the previous chunk's ``ProvTrace`` as ``prov``."""
        tracked = tuple(int(s) for s in tracked)
        if not tracked:
            raise ValueError("provenance needs at least one tracked slot")
        for slot in tracked:
            if not 0 <= slot < self.p.m:
                raise ValueError(
                    f"tracked slot {slot} outside [0, {self.p.m})")
        cap = cap or num_rounds
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if prov is None:
            prov = prov_ops.zero_prov(len(tracked), self.p.n, cap)
            prov = prov_ops.seed(
                prov,
                self._prov_belief(state, jnp.asarray(tracked, jnp.int32)),
                state.round_idx)
        if self._resolve_sparse_request(sparse):
            final, prov, conv, stats = self._run_prov_sparse_jit(
                state, key, num_rounds, prov, tracked)
            self.last_sparse_stats = stats
            return final, prov, conv
        self.last_sparse_stats = None
        return self._run_prov_jit(state, key, num_rounds, prov, tracked)

    def run_fast(self, state: SimState, key: jax.Array, num_rounds: int,
                 donate: bool = True, start_round=None, sparse=None,
                 pipeline=None):
        if self._resolve_pipeline_request(pipeline):
            self._pipeline_dispatch(sparse)
            final, _inflight = self.run_fast_pipelined(
                state, key, num_rounds, donate=donate,
                start_round=start_round)
            return final
        self._check_horizon(state, num_rounds, start_round)
        if not donate:
            state = clone_state(state)
        if self._resolve_sparse_request(sparse):
            final, stats = self._run_fast_sparse_jit(state, key,
                                                     num_rounds)
            self.last_sparse_stats = stats
            return final
        self.last_sparse_stats = None
        return self._run_fast_jit(state, key, num_rounds)

    # no-donate: single-round stepping is the oracle/replay path — those
    # callers diff pre- vs post-step states, so the input must survive.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_jit(self, state, key):
        return self._step(state, key)

    # no-donate: the sparse single-round probe serves the same
    # oracle/replay callers as _step_jit.
    @functools.partial(jax.jit, static_argnums=0)
    def _step_sparse_jit(self, state, key):
        return self._step_sparse(state, key)

    # Per-round keys fold the round index into the base key so chunked/
    # resumed runs replay identical randomness (see ExactSim).  The scan
    # drivers donate their input like every other _run*_jit (the sharded
    # known/sent blocks are the largest buffers in the process).

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_jit(self, state, key, num_rounds):
        def body(st, _):
            st = self._step(st, jax.random.fold_in(key, st.round_idx))
            return st, self.convergence(st)
        return lax.scan(body, state, None, length=num_rounds)

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_jit(self, state, key, num_rounds):
        def body(st, _):
            return self._step(st, jax.random.fold_in(key, st.round_idx)), None
        final, _ = lax.scan(body, state, None, length=num_rounds)
        return final

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_jit(self, state, key, num_rounds, cap):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, None))
            return (st2, buf), self.convergence(st2)

        (final, buf), conv = lax.scan(
            body, (state, trace_ops.zero_trace(cap)), None,
            length=num_rounds)
        return final, buf, conv

    @functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=1)
    def _run_trace_sparse_jit(self, state, key, num_rounds, cap):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = trace_ops.append_record(
                buf, self._trace_record(st, st2, s))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, buf, stats), conv = lax.scan(
            body, (state, trace_ops.zero_trace(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_jit(self, state, key, num_rounds, cap, idents,
                        buckets):
        def body(carry, _):
            st, buf = carry
            st2 = self._step(st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf), self.convergence(st2)

        (final, buf), conv = lax.scan(
            body, (state, digest_ops.zero_digest(cap)), None,
            length=num_rounds)
        return final, buf, conv

    @functools.partial(jax.jit, static_argnums=(0, 3, 4, 6),
                       donate_argnums=1)
    def _run_digest_sparse_jit(self, state, key, num_rounds, cap,
                               idents, buckets):
        def body(carry, _):
            st, buf, acc = carry
            st2, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            buf = digest_ops.append_digest(
                buf, self._digest_record(st2, idents, buckets))
            return (st2, buf, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, buf, stats), conv = lax.scan(
            body, (state, digest_ops.zero_digest(cap),
                   sparse_ops.zero_stats()), None, length=num_rounds)
        return final, buf, conv, stats

    # Donates the ProvTrace too (argnum 4): it chains chunk-to-chunk the
    # way the state does.
    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_jit(self, state, key, num_rounds, prov, tracked):
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2 = self._step(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv), self.convergence(st2)

        (final, prov), conv = lax.scan(body, (state, prov), None,
                                       length=num_rounds)
        return final, prov, conv

    @functools.partial(jax.jit, static_argnums=(0, 3, 5),
                       donate_argnums=(1, 4))
    def _run_prov_sparse_jit(self, state, key, num_rounds, prov, tracked):
        tr = jnp.asarray(tracked, jnp.int32)

        def body(carry, _):
            st, pv, acc = carry
            k = jax.random.fold_in(key, st.round_idx)
            st2, s = self._step_sparse(st, k)
            pushes, pulls = self._prov_channels(st, k)
            pv = prov_ops.observe(
                pv,
                prov_ops.holders(pv, self._prov_belief(st, tr)),
                prov_ops.holders(pv, self._prov_belief(st2, tr)),
                st2.round_idx, pushes, pulls)
            return (st2, pv, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st2)

        (final, prov, stats), conv = lax.scan(
            body, (state, prov, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, prov, conv, stats

    # Sparse-path scan drivers (docs/sparse.md): same donation and key
    # folding as the dense drivers, plus the stats accumulator.

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_sparse_jit(self, state, key, num_rounds):
        def body(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), \
                self.convergence(st)

        (final, stats), conv = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, conv, stats

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
    def _run_fast_sparse_jit(self, state, key, num_rounds):
        def body(carry, _):
            st, acc = carry
            st, s = self._step_sparse(
                st, jax.random.fold_in(key, st.round_idx))
            return (st, sparse_ops.accumulate_stats(acc, s)), None

        (final, stats), _ = lax.scan(
            body, (state, sparse_ops.zero_stats()), None,
            length=num_rounds)
        return final, stats
