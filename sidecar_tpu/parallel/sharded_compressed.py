"""Node-axis-sharded compressed gossip simulator — the north-star-scale
model on a multi-chip mesh.

This is the sharded twin of :class:`sidecar_tpu.models.compressed.
CompressedSim` (promised there), combining the two scale mechanisms:

* **Bounded memory per node** (the compressed model): own[N, S] +
  direct-mapped cache[N, K] + one shared floor[M] — O(N·K + M) instead of
  the dense model's O(N²·S).
* **Node-axis sharding** (the ShardedSim design, parallel/sharded.py):
  each device owns a contiguous block of nodes; a node's own rows and
  cache lines stay device-local, so select / line-competition / announce
  are embarrassingly parallel.

Cross-device traffic per round — all riding ICI collectives:

* **The message board** — each shard publishes its rows' top-``budget``
  cache lines (the ~1398 B-packet analog) and the boards are
  ``all_gather``-ed; each shard then PULLS the board rows its own nodes
  sampled and lex-merges them elementwise (the line-aligned delivery,
  models/compressed.py).  Per-shard merge work is O(N/d · fanout · K);
  the gather traffic is O(N·K) int32 — ~100 MB at the 100k-node north
  star, a few ms on ICI.  Messages cross the interconnect, state stays
  put — exactly the real network's economics.
* **Floor maintenance** — the shared converged baseline is REPLICATED
  across devices.  Owner-refresh folds touch only shard-owned slots, so
  an ``lax.pmax`` after the announce phase re-merges the replicas; the
  unanimity census (every ``sweep_rounds``) runs as local truth/hit
  contributions combined with ``pmax``/``psum`` under GSPMD sharding
  propagation.  floor is O(M) int32 — 4 MB at the 1M-service north star,
  trivially replicable.
* **Anti-entropy** — the same random-stride ring exchange as the dense
  sharded model: ``jnp.roll`` along the sharded node axis lowers to an
  XLA collective-permute.

Protocol semantics are IDENTICAL to the single-chip ``CompressedSim`` —
the merge/announce/push-pull kernels are literally the same methods
(called per-shard with ``row_offset``), so a deterministic lockstep run
matches bit-for-bit including the stride push-pull (both models draw the
same stride from the same key); see tests/test_sharded_compressed.py.
The divergences are the PRNG streams drawn per shard (``fold_in(key,
shard)``, like ShardedSim): *random* peer sampling and the ``drop_prob``
loss mask — with a pinned peer rule and ``drop_prob=0`` nothing random
remains and the lockstep is exact.

Scaling note: every per-round phase is O(N/d) per device (publish,
pull-merge, announce).  Two board-exchange modes
(``board_exchange=``):

* ``"all_gather"`` — replicate the full O(N·K) board per device.
  Simple, zero per-message bookkeeping, but the transient bytes per
  device grow with N regardless of d (~1 GB at 1M nodes, K=256),
  bounding single-pod reach.
* ``"all_to_all"`` — gather ONLY the board rows each shard's nodes
  sampled, keyed by source shard: per destination shard, requests are
  bucketed by source shard (rank-compaction into fixed per-pair
  capacity ``C = a2a_slack · ceil(nl·F/d)``), row ids ride one
  ``all_to_all``, each shard serves its requested rows from the local
  board, and a second ``all_to_all`` returns them.  Per-device
  transient is O(a2a_slack · (N/d) · F · K) — it SHRINKS with d, so
  the mode wins whenever ``a2a_slack·F < d`` and removes the O(N·K)
  replication bound entirely.  A request landing beyond a bucket's
  capacity is a DROPPED pull (the peer's board simply isn't seen that
  round — bounded-capacity behavior the loss-tolerant protocol absorbs,
  identical in kind to ``drop_prob``); with random peer sampling the
  per-pair load is Binomial(nl·F, 1/d), so at the default slack of 2
  an overflow is a many-sigma tail event (Chernoff: P ≲ e^{-μ/3} per
  pair, μ = nl·F/d ≈ 4.7k at the north star) — and the deterministic
  lockstep suite pins the mode bit-exact against the single-chip model
  precisely because no drop ever fires there.

Reference scale envelope this design answers: one Go process holds the
whole O(M) catalog per host (catalog/services_state.go:70-80); at the
north star (100k nodes / 1M services < 10 s, BASELINE.md) simulating
that requires both compression and sharding at once.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from sidecar_tpu.models.compressed import (
    CompressedParams,
    CompressedSim,
    CompressedState,
)
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops.topology import Topology
from sidecar_tpu.parallel.mesh import NODE_AXIS, make_mesh, shard_map


class ShardedCompressedSim(CompressedSim):
    """Multi-device compressed simulator.  Drop-in for CompressedSim
    (same driver contract: init_state / step / run / run_fast / mint /
    convergence), state sharded along the node axis."""

    def __init__(self, params: CompressedParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 mesh=None,
                 perturb=None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None,
                 board_exchange: str = "all_gather",
                 a2a_slack: int = 2):
        super().__init__(params, topo, timecfg, perturb=perturb,
                         cut_mask=cut_mask, node_side=node_side)
        if board_exchange not in ("all_gather", "all_to_all"):
            raise ValueError(
                f"board_exchange must be 'all_gather' or 'all_to_all', "
                f"got {board_exchange!r}")
        if a2a_slack < 1:
            raise ValueError("a2a_slack must be >= 1")
        self.board_exchange = board_exchange
        self.a2a_slack = a2a_slack
        # The in-flight-list census path is excluded from sharded
        # compilation (XLA CPU GSPMD segfault — see
        # CompressedSim._behind_and_denom); the gather fast path is
        # bit-identical.
        self.metric_list_ok = False
        self.mesh = mesh if mesh is not None else make_mesh()
        self.d = self.mesh.devices.size
        if params.n % self.d != 0:
            raise ValueError(
                f"n={params.n} must divide the {self.d}-device mesh")
        # Fixed per-(src shard, dst shard) request capacity for the
        # all_to_all mode (see the module docstring); the floor keeps
        # tiny test meshes from starving deterministic ring-walk peers.
        nl = params.n // self.d
        self._a2a_cap = max(16, -(-nl * params.fanout // self.d)
                            * a2a_slack)

        row = NamedSharding(self.mesh, P(NODE_AXIS))
        repl = NamedSharding(self.mesh, P())
        self._row_sharding = row
        self._repl_sharding = repl
        if self._nbrs is not None:
            self._nbrs = jax.device_put(self._nbrs, row)
            self._deg = jax.device_put(self._deg, row)
        if self._cut is not None:
            self._cut = jax.device_put(self._cut, row)
        if self._side is not None:
            self._side = jax.device_put(self._side, repl)

    # -- state --------------------------------------------------------------

    def init_state(self) -> CompressedState:
        st = super().init_state()
        return self._constrain(st, place=True)

    def _constrain(self, st: CompressedState, place=False) -> CompressedState:
        """Pin the canonical layout: per-node arrays sharded on the node
        axis, floor/alive/scalars replicated.  ``place=True`` moves host
        arrays (init); inside jit the sharding-constraint form keeps the
        scan carry layout stable."""
        row, repl = self._row_sharding, self._repl_sharding
        put = jax.device_put if place else lax.with_sharding_constraint
        return CompressedState(
            own=put(st.own, row),
            cache_slot=put(st.cache_slot, row),
            cache_val=put(st.cache_val, row),
            cache_sent=put(st.cache_sent, row),
            floor=put(st.floor, repl),
            node_alive=put(st.node_alive, repl),
            round_idx=put(st.round_idx, repl),
            evictions=put(st.evictions, repl),
            dropped=put(st.dropped, repl),
        )

    # -- peer sampling (global ids; overridable for deterministic tests) ----

    def _sample_dst_complete(self, k_peers, gi, alive, nl):
        p = self.p
        r = jax.random.randint(k_peers, (nl, p.fanout), 0, p.n - 1,
                               dtype=jnp.int32)
        dst = r + (r >= gi[:, None]).astype(jnp.int32)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    def _sample_dst_nbrs(self, k_peers, gi, alive, nl, nbrs_l, deg_l, cut_l):
        p = self.p
        slot = jax.random.randint(k_peers, (nl, p.fanout), 0,
                                  jnp.maximum(deg_l, 1)[:, None],
                                  dtype=jnp.int32)
        dst = jnp.take_along_axis(nbrs_l, slot, axis=1)
        if cut_l is not None:
            cut = jnp.take_along_axis(cut_l, slot, axis=1)
            dst = jnp.where(cut, gi[:, None], dst)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    # -- the all_to_all board exchange (inside shard_map) -------------------

    def _a2a_exchange(self, bval_l, bslot_l, dst, ax, nl):
        """Fetch exactly the board rows this shard's nodes sampled
        (``dst``: [nl, F] global peer ids) from their home shards.

        Request routing: each sampled peer id splits into (source
        shard, source row); own-shard rows read the local board
        directly; cross-shard rows are rank-compacted into per-source-
        shard buckets of static capacity ``C``, the row ids cross in
        one ``all_to_all``, every shard serves its requested rows from
        its local board, and the rows come back in a second
        ``all_to_all``.  Requests past a bucket's capacity become empty
        pulls, COUNTED in the returned drop total (see the module
        docstring for why dropping is sound and why it never fires at
        the default slack; tests assert the count stays 0).  Returns
        (pv, ps, n_dropped): [nl, F, K] board rows identical to
        ``bval[dst]``/``bslot[dst]`` of the all_gather path whenever
        ``n_dropped == 0``."""
        d, C = self.d, self._a2a_cap
        flat = dst.reshape(-1)                       # [R], R = nl·F
        src_shard = flat // nl
        src_row = flat % nl
        is_local = src_shard == ax

        # Rank of each cross-shard request within its source-shard
        # bucket, via one stable sort — O(R log R), independent of d
        # (an earlier form used d sequential cumsum passes, which
        # re-serializes at exactly the large d this mode exists for).
        src_eff = jnp.where(is_local, d, src_shard)  # locals → bucket d
        order = jnp.argsort(src_eff, stable=True)    # [R]
        counts = jnp.zeros((d + 1,), jnp.int32).at[src_eff].add(1)
        starts = jnp.cumsum(counts) - counts         # exclusive prefix
        rank_sorted = jnp.arange(flat.shape[0], dtype=jnp.int32) \
            - starts[src_eff[order]]
        rank = jnp.zeros(flat.shape, jnp.int32).at[order].set(rank_sorted)
        valid = ~is_local & (rank < C)
        n_dropped = jnp.sum((~is_local & (rank >= C)).astype(jnp.int32))

        req = jnp.zeros((d, C), jnp.int32)
        req = req.at[jnp.where(valid, src_shard, d),
                     jnp.where(valid, rank, 0)].set(src_row, mode="drop")
        req_in = lax.all_to_all(req, NODE_AXIS, 0, 0)   # [d, C] rows
                                                        # to serve
        rows = jnp.clip(req_in, 0, nl - 1)
        resp_v = lax.all_to_all(bval_l[rows], NODE_AXIS, 0, 0)
        resp_s = lax.all_to_all(bslot_l[rows], NODE_AXIS, 0, 0)

        # Assemble [R, K]: local rows from the local board, served rows
        # from the responses, overflowed requests empty.
        safe_shard = jnp.where(valid, src_shard, 0)
        safe_rank = jnp.where(valid, rank, 0)
        cross_v = resp_v[safe_shard, safe_rank]
        cross_s = resp_s[safe_shard, safe_rank]
        local_v = bval_l[jnp.where(is_local, src_row, 0)]
        local_s = bslot_l[jnp.where(is_local, src_row, 0)]
        pv = jnp.where(is_local[:, None], local_v,
                       jnp.where(valid[:, None], cross_v, 0))
        ps = jnp.where(is_local[:, None], local_s,
                       jnp.where(valid[:, None], cross_s, -1))
        k = self.p.cache_lines
        return (pv.reshape(nl, self.p.fanout, k),
                ps.reshape(nl, self.p.fanout, k), n_dropped)

    # -- the per-shard gossip + announce phase (inside shard_map) -----------

    def _gossip_shard(self, own_l, cslot_l, cval_l, csent_l, floor, alive,
                      key, round_idx, nbrs_l=None, deg_l=None, cut_l=None):
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        nl = own_l.shape[0]
        ax = lax.axis_index(NODE_AXIS)
        r0 = (ax * nl).astype(jnp.int32)
        gi = r0 + jnp.arange(nl, dtype=jnp.int32)
        now = round_idx * t.round_ticks

        k_peers, k_drop = jax.random.split(jax.random.fold_in(key, ax))
        if nbrs_l is None:
            dst = self._sample_dst_complete(k_peers, gi, alive, nl)
        else:
            dst = self._sample_dst_nbrs(k_peers, gi, alive, nl,
                                        nbrs_l, deg_l, cut_l)

        # Local view of this shard: the inherited single-chip kernels run
        # on it unchanged (row_offset maps local rows to global identity),
        # which is what makes the twin bit-exact by construction.
        local = CompressedState(
            own=own_l, cache_slot=cslot_l, cache_val=cval_l,
            cache_sent=csent_l, floor=floor, node_alive=alive[gi],
            round_idx=round_idx, evictions=jnp.zeros((), jnp.int32),
            dropped=jnp.zeros((), jnp.int32))

        # 1. publish local board rows + transmit accounting (elementwise;
        # row_offset ties the tie rotation to global node ids).
        bval_l, bslot_l, sent = self._publish(local, limit, row_offset=r0)

        # The only cross-shard gossip traffic: the board (bounded offers,
        # line-aligned — each row is the ≤budget records its node would
        # pack into one ~1398 B datagram).
        if self.board_exchange == "all_gather":
            bval = lax.all_gather(bval_l, NODE_AXIS, tiled=True)  # [N, K]
            bslot = lax.all_gather(bslot_l, NODE_AXIS, tiled=True)
            # 2. pull-merge into my rows (src holds global peer ids).
            local = self._pull_merge(local, sent, bval, bslot, dst,
                                     alive, now, drop_key=k_drop)
        else:
            pv, ps, n_drop = self._a2a_exchange(bval_l, bslot_l, dst,
                                                ax, nl)
            ok = alive[dst] & alive[gi][:, None]
            local = self._merge_pulled(local, sent, pv, ps, ok, now,
                                       drop_key=k_drop)
            local = dataclasses.replace(
                local, dropped=local.dropped + n_drop)

        # 3. announce re-stamps + recovery offers (local rows own exactly
        # this shard's slot range; the refresh fold raises only shard-owned
        # floor entries, re-merged via pmax below).
        local = self._announce(local, round_idx, now, row_offset=r0)

        floor = lax.pmax(local.floor, NODE_AXIS)
        ev = lax.psum(local.evictions, NODE_AXIS)
        dr = lax.psum(local.dropped, NODE_AXIS)
        return (local.own, local.cache_slot, local.cache_val,
                local.cache_sent, floor, ev, dr)

    # -- the round ----------------------------------------------------------

    def _step(self, state: CompressedState,
              key: jax.Array) -> CompressedState:
        p, t = self.p, self.t
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        # Same split as CompressedSim._step: lockstep runs draw the same
        # push-pull stride.
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)
        del k_drop  # folded per-shard inside _gossip_shard

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        spec_row, spec_repl = P(NODE_AXIS), P()
        topo_args, topo_specs = (), ()
        if self._nbrs is not None:
            topo_args = (self._nbrs, self._deg)
            topo_specs = (spec_row, spec_row)
            if self._cut is not None:
                topo_args += (self._cut,)
                topo_specs += (spec_row,)

        def body(own, cs, cv, se, floor, alive, k, r, *topo):
            if not topo:
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r)
            if len(topo) == 2:
                nb, dg = topo
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r, nbrs_l=nb, deg_l=dg)
            nb, dg, ct = topo
            return self._gossip_shard(own, cs, cv, se, floor, alive, k, r,
                                      nbrs_l=nb, deg_l=dg, cut_l=ct)

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(spec_row,) * 4 + (spec_repl,) * 4 + topo_specs,
            out_specs=(spec_row,) * 4 + (spec_repl,) * 3,
            check_vma=False)
        own, cs, cv, se, floor, ev, dr = fn(
            state.own, state.cache_slot, state.cache_val, state.cache_sent,
            state.floor, state.node_alive, k_peers, round_idx, *topo_args)
        state = dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            floor=floor, evictions=state.evictions + ev,
            dropped=state.dropped + dr)

        # 3. anti-entropy — the inherited stride exchange; jnp.roll along
        # the sharded axis lowers to a collective-permute.
        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)

        # 4. floor advance + sweep — inherited; the census scatter-adds
        # run under GSPMD propagation (local contributions + all-reduce).
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        state = dataclasses.replace(state, round_idx=round_idx)
        return self._constrain(state)
