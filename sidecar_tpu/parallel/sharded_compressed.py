"""Node-axis-sharded compressed gossip simulator — the north-star-scale
model on a multi-chip mesh.

This is the sharded twin of :class:`sidecar_tpu.models.compressed.
CompressedSim` (promised there), combining the two scale mechanisms:

* **Bounded memory per node** (the compressed model): own[N, S] +
  direct-mapped cache[N, K] + one shared floor[M] — O(N·K + M) instead of
  the dense model's O(N²·S).
* **Node-axis sharding** (the ShardedSim design, parallel/sharded.py):
  each device owns a contiguous block of nodes; a node's own rows and
  cache lines stay device-local, so select / line-competition / announce
  are embarrassingly parallel.

Cross-device traffic per round — all riding ICI collectives:

* **The message board** — each shard publishes its rows' top-``budget``
  cache lines (the ~1398 B-packet analog) and the boards are
  ``all_gather``-ed; each shard then PULLS the board rows its own nodes
  sampled and lex-merges them elementwise (the line-aligned delivery,
  models/compressed.py).  Per-shard merge work is O(N/d · fanout · K);
  the gather traffic is O(N·K) int32 — ~100 MB at the 100k-node north
  star, a few ms on ICI.  Messages cross the interconnect, state stays
  put — exactly the real network's economics.
* **Floor maintenance** — the shared converged baseline is REPLICATED
  across devices.  Owner-refresh folds touch only shard-owned slots, so
  an ``lax.pmax`` after the announce phase re-merges the replicas; the
  unanimity census (every ``sweep_rounds``) runs as local truth/hit
  contributions combined with ``pmax``/``psum`` under GSPMD sharding
  propagation.  floor is O(M) int32 — 4 MB at the 1M-service north star,
  trivially replicable.
* **Anti-entropy** — the same random-stride ring exchange as the dense
  sharded model: ``jnp.roll`` along the sharded node axis lowers to an
  XLA collective-permute.

Protocol semantics are IDENTICAL to the single-chip ``CompressedSim`` —
the merge/announce/push-pull kernels are literally the same methods
(called per-shard with ``row_offset``), so a deterministic lockstep run
matches bit-for-bit including the stride push-pull (both models draw the
same stride from the same key); see tests/test_sharded_compressed.py.
The divergences are the PRNG streams drawn per shard (``fold_in(key,
shard)``, like ShardedSim): *random* peer sampling and the ``drop_prob``
loss mask — with a pinned peer rule and ``drop_prob=0`` nothing random
remains and the lockstep is exact.

Scaling note: every per-round phase is O(N/d) per device (publish,
pull-merge, announce).  Two board-exchange modes
(``board_exchange=``):

* ``"all_gather"`` — replicate the full O(N·K) board per device.
  Simple, zero per-message bookkeeping, but the transient bytes per
  device grow with N regardless of d (~1 GB at 1M nodes, K=256),
  bounding single-pod reach.
* ``"all_to_all"`` — gather ONLY the board rows each shard's nodes
  sampled, keyed by source shard: per destination shard, requests are
  bucketed by source shard (rank-compaction into fixed per-pair
  capacity ``C = a2a_slack · ceil(nl·F/d)``), row ids ride one
  ``all_to_all``, each shard serves its requested rows from the local
  board, and a second ``all_to_all`` returns them.  Per-device
  transient is O(a2a_slack · (N/d) · F · K) — it SHRINKS with d, so
  the mode wins whenever ``a2a_slack·F < d`` and removes the O(N·K)
  replication bound entirely.  A request landing beyond a bucket's
  capacity is a DROPPED pull (the peer's board simply isn't seen that
  round — bounded-capacity behavior the loss-tolerant protocol absorbs,
  identical in kind to ``drop_prob``); with random peer sampling the
  per-pair load is Binomial(nl·F, 1/d), so at the default slack of 2
  an overflow is a many-sigma tail event (Chernoff: P ≲ e^{-μ/3} per
  pair, μ = nl·F/d ≈ 4.7k at the north star) — and the deterministic
  lockstep suite pins the mode bit-exact against the single-chip model
  precisely because no drop ever fires there.

Reference scale envelope this design answers: one Go process holds the
whole O(M) catalog per host (catalog/services_state.go:70-80); at the
north star (100k nodes / 1M services < 10 s, BASELINE.md) simulating
that requires both compression and sharding at once.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from sidecar_tpu import metrics
from sidecar_tpu.models.compressed import (
    CompressedParams,
    CompressedSim,
    CompressedState,
)
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import kernels as kernel_ops
from sidecar_tpu.ops import sparse as sparse_ops
from sidecar_tpu.ops.merge import admit_gate
from sidecar_tpu.ops.topology import Topology, zoned_exchange_plan
from sidecar_tpu.telemetry import cost
from sidecar_tpu.parallel.mesh import (
    NODE_AXIS,
    make_mesh,
    resolve_board_exchange,
    shard_map,
)


class ShardedCompressedSim(CompressedSim):
    """Multi-device compressed simulator.  Drop-in for CompressedSim
    (same driver contract: init_state / step / run / run_fast / mint /
    convergence), state sharded along the node axis."""

    # The pipelined round runs at the GLOBAL-array jit level (GSPMD
    # partitions it — see the class docstring note below run_pipelined
    # in CompressedSim), where the Pallas publish cannot partition: pin
    # the pipelined select to the bit-identical XLA kernel twin.
    _pipeline_force_xla = True

    def __init__(self, params: CompressedParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 mesh=None,
                 perturb=None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None,
                 board_exchange: Optional[str] = None,
                 a2a_slack: int = 2,
                 exchange_stub: bool = False,
                 sparse: Optional[str] = None,
                 pipeline: Optional[str] = None,
                 tick_period=None, tick_phase=None):
        super().__init__(params, topo, timecfg, perturb=perturb,
                         cut_mask=cut_mask, node_side=node_side,
                         sparse=sparse, pipeline=pipeline,
                         tick_period=tick_period, tick_phase=tick_phase)
        # Per-node tick cadence, normalized to full-[N] replicated
        # vectors so the per-shard round bodies can take ``[gi]``
        # slices (mirrors the ``self._stagger[gi]`` idiom); None
        # compiles the pre-cadence program bit for bit.
        self._cadence = None
        if self._knobs.cadence_enabled:
            self._cadence = tuple(
                jnp.broadcast_to(
                    jnp.asarray(v, jnp.int32).reshape(-1), (params.n,))
                for v in (self._knobs.tick_period,
                          self._knobs.tick_phase))
        if a2a_slack < 1:
            raise ValueError("a2a_slack must be >= 1")
        # None → SIDECAR_TPU_BOARD_EXCHANGE, default all_gather
        # (docs/sharding.md); the resolution is recorded in the metrics
        # registry (parallel.exchange.mode.<mode>).  zoned ships only
        # the board row blocks the overlay can make another shard
        # sample (docs/topology.md), so it needs a neighbor-list
        # topology: explicit zoned on the complete graph is a hard
        # error, env-derived zoned falls back to all_gather.
        if board_exchange == "zoned" and topo.nbrs is None:
            raise ValueError(
                "board_exchange='zoned' requires a neighbor-list "
                "topology: the complete graph reaches every shard "
                "(use all_gather there)")
        supported = ("all_gather", "all_to_all", "ring")
        if topo.nbrs is not None:
            supported += ("zoned",)
        self.board_exchange = resolve_board_exchange(
            board_exchange, supported=supported)
        self.a2a_slack = a2a_slack
        # Measurement-only knob (benchmarks/sharded_scaling.py): skip
        # the cross-shard exchange and consume only own-shard rows.
        # The resulting trajectory is WRONG by construction — its only
        # use is differencing wall-clock against the full round to
        # measure exposed (non-overlapped) communication time.
        self._exchange_stub = exchange_stub
        # Sharded delivery gather kernel (board_row_gather): rides the
        # same SIDECAR_TPU_KERNELS resolution as the publish kernel and
        # the same SIDECAR_TPU_FUSED_GATHER degrade switch.
        self._sharded_gather = (self._kernels == "pallas"
                                and kernel_ops.fused_gather_enabled())
        # Host-side watermark for sync_exchange_metrics.
        self._overflow_synced = 0
        # The in-flight-list census path is excluded from sharded
        # compilation (XLA CPU GSPMD segfault — see
        # CompressedSim._behind_and_denom); the gather fast path is
        # bit-identical.
        self.metric_list_ok = False
        self.mesh = mesh if mesh is not None else make_mesh()
        self.d = self.mesh.devices.size
        if params.n % self.d != 0:
            raise ValueError(
                f"n={params.n} must divide the {self.d}-device mesh")
        # Fixed per-(src shard, dst shard) request capacity for the
        # all_to_all mode (see the module docstring); the floor keeps
        # tiny test meshes from starving deterministic ring-walk peers.
        nl = params.n // self.d
        self._a2a_cap = max(16, -(-nl * params.fanout // self.d)
                            * a2a_slack)
        # Per-shard sparse-frontier caps (docs/sparse.md): the global
        # caps split over the mesh with 2× slack for load imbalance —
        # one hot shard must not flip the whole round dense early.
        self._sparse_caps_shard = tuple(
            min(nl, max(16, -(-c // self.d) * 2))
            for c in self._sparse_caps)

        row = NamedSharding(self.mesh, P(NODE_AXIS))
        repl = NamedSharding(self.mesh, P())
        self._row_sharding = row
        self._repl_sharding = repl
        if self._nbrs is not None:
            self._nbrs = jax.device_put(self._nbrs, row)
            self._deg = jax.device_put(self._deg, row)
        if self._cut is not None:
            self._cut = jax.device_put(self._cut, row)
        if self._side is not None:
            self._side = jax.device_put(self._side, repl)

        # Zoned: static reachability plan (ops/topology.py).  Pull
        # direction — the compressed twin's samplers PULL board rows,
        # so shard s must ship row r wherever some node holds r in its
        # neighbor table.
        self._zoned_plan = None
        self._zoned_tabs = None
        if self.board_exchange == "zoned":
            self._zoned_plan = zoned_exchange_plan(topo, self.d,
                                                   direction="pull")
            self._zoned_tabs = tuple(
                None if h is None
                else (jnp.asarray(h.rows), jnp.asarray(h.valid),
                      jnp.asarray(h.pos))
                for h in self._zoned_plan.hops)
            metrics.set_gauge("parallel.exchange.zoned_rows",
                              float(self._zoned_plan.total_rows))

        # Analytic per-round per-device RECEIVE bytes of the board
        # exchange (docs/metrics.md: parallel.exchange.bytes) — the
        # int32 bval + bslot payloads each mode moves.
        k, d, cap = params.cache_lines, self.d, self._a2a_cap
        self.exchange_bytes_per_round = {
            # every other shard's [nl, K] block, twice (val + slot)
            "all_gather": (params.n - nl) * k * 4 * 2,
            # request row-ids + the two response legs
            "all_to_all": d * cap * 4 + 2 * d * cap * k * 4,
            # d-1 hops of one [nl, K] block pair
            "ring": (d - 1) * nl * k * 4 * 2,
            # the statically-reachable row blocks only, val + slot
            "zoned": (0 if self._zoned_plan is None
                      else self._zoned_plan.total_rows * k * 4 * 2),
        }[self.board_exchange]
        metrics.set_gauge("parallel.exchange.bytes",
                          float(self.exchange_bytes_per_round))

    def sync_exchange_metrics(self, state: CompressedState) -> int:
        """Publish the cumulative bounded-exchange overflow count
        (``state.dropped`` — all_to_all bucket overflows) into the
        metrics registry as ``parallel.exchange.overflow``.  Host-side:
        reads the device scalar, so call it AFTER a dispatch pipeline
        has drained, never between pipelined chunks.  The watermark is
        per-trajectory: a state whose counter reads BELOW the watermark
        (a fresh init_state on a reused sim) resets it, so drops on the
        new trajectory count from zero — sync each trajectory before
        starting the next.  Returns the state's cumulative count."""
        dropped = int(jax.device_get(state.dropped))
        if dropped < self._overflow_synced:
            self._overflow_synced = 0     # fresh/rewound trajectory
        delta = dropped - self._overflow_synced
        if delta > 0:
            metrics.incr("parallel.exchange.overflow", delta)
        self._overflow_synced = dropped
        return dropped

    # -- state --------------------------------------------------------------

    def init_state(self) -> CompressedState:
        st = super().init_state()
        return self._constrain(st, place=True)

    def _constrain(self, st: CompressedState, place=False) -> CompressedState:
        """Pin the canonical layout: per-node arrays sharded on the node
        axis, floor/alive/scalars replicated.  ``place=True`` moves host
        arrays (init); inside jit the sharding-constraint form keeps the
        scan carry layout stable."""
        row, repl = self._row_sharding, self._repl_sharding
        put = jax.device_put if place else lax.with_sharding_constraint
        return CompressedState(
            own=put(st.own, row),
            cache_slot=put(st.cache_slot, row),
            cache_val=put(st.cache_val, row),
            cache_sent=put(st.cache_sent, row),
            floor=put(st.floor, repl),
            node_alive=put(st.node_alive, repl),
            round_idx=put(st.round_idx, repl),
            evictions=put(st.evictions, repl),
            dropped=put(st.dropped, repl),
        )

    # -- peer sampling (global ids; overridable for deterministic tests) ----

    def _sample_dst_complete(self, k_peers, gi, alive, nl):
        p = self.p
        r = jax.random.randint(k_peers, (nl, p.fanout), 0, p.n - 1,
                               dtype=jnp.int32)
        dst = r + (r >= gi[:, None]).astype(jnp.int32)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    def _sample_dst_nbrs(self, k_peers, gi, alive, nl, nbrs_l, deg_l, cut_l):
        p = self.p
        slot = jax.random.randint(k_peers, (nl, p.fanout), 0,
                                  jnp.maximum(deg_l, 1)[:, None],
                                  dtype=jnp.int32)
        dst = jnp.take_along_axis(nbrs_l, slot, axis=1)
        if cut_l is not None:
            cut = jnp.take_along_axis(cut_l, slot, axis=1)
            dst = jnp.where(cut, gi[:, None], dst)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    # -- the all_to_all request routing (inside shard_map) ------------------

    def _a2a_route(self, dst, ax, nl):
        """Request routing for the all_to_all exchange — pure index math
        over the sampled peer ids (NO board data), so the split-phase
        round computes it and launches the request leg BEFORE the local
        board publish, overlapping the request flight with the publish
        kernel.

        Each sampled peer id splits into (source shard, source row);
        own-shard rows are served locally; cross-shard rows are
        rank-compacted into per-source-shard buckets of static capacity
        ``C``.  Requests past a bucket's capacity become empty pulls,
        COUNTED in ``n_dropped`` (surfaced as ``state.dropped`` and the
        ``parallel.exchange.overflow`` metric; the lockstep suites
        assert it stays 0 — see the module docstring for why dropping
        is sound and why it never fires at the default slack).

        The rank comes from one stable sort — O(R log R), independent
        of d (an earlier form used d sequential cumsum passes, which
        re-serializes at exactly the large d this mode exists for).
        Returns ``(req[d, C], src_shard, src_row, is_local, valid,
        rank, n_dropped)`` with the per-request arrays flat [nl·F]."""
        d, C = self.d, self._a2a_cap
        flat = dst.reshape(-1)                       # [R], R = nl·F
        src_shard = flat // nl
        src_row = flat % nl
        is_local = src_shard == ax

        src_eff = jnp.where(is_local, d, src_shard)  # locals → bucket d
        order = jnp.argsort(src_eff, stable=True)    # [R]
        counts = jnp.zeros((d + 1,), jnp.int32).at[src_eff].add(1)
        starts = jnp.cumsum(counts) - counts         # exclusive prefix
        rank_sorted = jnp.arange(flat.shape[0], dtype=jnp.int32) \
            - starts[src_eff[order]]
        rank = jnp.zeros(flat.shape, jnp.int32).at[order].set(rank_sorted)
        valid = ~is_local & (rank < C)
        n_dropped = jnp.sum((~is_local & (rank >= C)).astype(jnp.int32))

        req = jnp.zeros((d, C), jnp.int32)
        req = req.at[jnp.where(valid, src_shard, d),
                     jnp.where(valid, rank, 0)].set(src_row, mode="drop")
        return req, src_shard, src_row, is_local, valid, rank, n_dropped

    def _serve_local(self, bval_f, bslot_l, dst, base):
        """Board rows of the block for the sampled peers: [nl, F] global
        ids → [nl, F, K], out-of-block entries (0, -1) — the merge
        no-op, so folding them is free.  Pallas DMA kernel
        (``board_row_gather``) when the kernel path is active, its
        bit-identical XLA twin otherwise."""
        if self._sharded_gather:
            return kernel_ops.board_row_gather_pallas(
                bval_f, bslot_l, dst, base,
                interpret=self._kernels_interpret)
        return kernel_ops.board_row_gather_xla(bval_f, bslot_l, dst, base)

    # -- the per-shard gossip + announce phase (inside shard_map) -----------

    def _gossip_shard(self, own_l, cslot_l, cval_l, csent_l, floor, alive,
                      key, round_idx, nbrs_l=None, deg_l=None, cut_l=None):
        """One shard's split-phase, comm-overlapped round
        (docs/sharding.md):

        1. LOCAL BOARD — publish selection on this shard's rows (the
           Pallas/XLA kernel, tie rotation over global ids) + ONE
           staleness gate per shard (elementwise — commutes with every
           exchange, so rows travel pre-filtered).
        2. ISSUE the exchange (mode-dependent; the a2a request leg is
           issued even earlier, before the publish).
        3. BOARD-INDEPENDENT local work while rows are in flight: fold
           own-shard deliveries (every candidate resolves against the
           pre-round cache, and the lex-max fold is order-independent,
           so groups fold as they arrive), and the announce own/floor
           half (refresh fold + offer values — none of it reads the
           cache).
        4. CONSUME remote rows — fold them, then the single batch
           finalize (sent reset + eviction count vs the pre-round
           cache) and the announce cache insert, exactly the op
           sequence of the single-chip round.

        Bit-identical to the pre-split round in every mode: the
        lockstep suites (tests/test_sharded_compressed.py,
        tests/test_sharded_exchange.py) are the oracle."""
        nl = own_l.shape[0]
        ax = lax.axis_index(NODE_AXIS)
        gi = (ax * nl).astype(jnp.int32) + jnp.arange(nl, dtype=jnp.int32)
        k_peers, k_drop = jax.random.split(jax.random.fold_in(key, ax))
        if nbrs_l is None:
            dst = self._sample_dst_complete(k_peers, gi, alive, nl)
        else:
            dst = self._sample_dst_nbrs(k_peers, gi, alive, nl,
                                        nbrs_l, deg_l, cut_l)
        if self._stagger is not None:
            dst = gossip_ops.stagger_gate(
                dst, round_idx, self._stagger[gi], self._stagger_period,
                self_idx=gi)
        if self._cadence is not None:
            per, pha = self._cadence
            dst = gossip_ops.cadence_gate(dst, round_idx, per[gi],
                                          pha[gi], self_idx=gi)
        return self._gossip_shard_body(own_l, cslot_l, cval_l, csent_l,
                                       floor, alive, dst, k_drop,
                                       round_idx)

    def _gossip_shard_body(self, own_l, cslot_l, cval_l, csent_l, floor,
                           alive, dst, k_drop, round_idx,
                           ann_local=None):
        """The round body after peer sampling — split out so the sparse
        step can reuse it verbatim as its per-chunk overflow fallback
        with a jit-level-precomputed ``dst`` (docs/sparse.md).
        ``ann_local`` is the announce own/floor half when the caller
        already ran it at the jit level (the sparse step computes it
        for the announcer frontier either way): ``own_l``/``floor``
        then arrive advanced and ``(offer_val, base_slot)`` are this
        shard's slices — identical values, one O(N·S) pass per round
        instead of two on overflow rounds."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        nl = own_l.shape[0]
        d = self.d
        ax = lax.axis_index(NODE_AXIS)
        r0 = (ax * nl).astype(jnp.int32)
        gi = r0 + jnp.arange(nl, dtype=jnp.int32)
        now = round_idx * t.round_ticks
        mode = self.board_exchange

        # Local view of this shard: the inherited single-chip kernels run
        # on it unchanged (row_offset maps local rows to global identity),
        # which is what makes the twin bit-exact by construction.
        local = CompressedState(
            own=own_l, cache_slot=cslot_l, cache_val=cval_l,
            cache_sent=csent_l, floor=floor, node_alive=alive[gi],
            round_idx=round_idx, evictions=jnp.zeros((), jnp.int32),
            dropped=jnp.zeros((), jnp.int32))

        n_drop = jnp.zeros((), jnp.int32)
        # The a2a request leg is pure index math over dst — issue it
        # ahead of the publish so the row ids cross while the publish
        # kernel runs.
        if mode == "all_to_all" and not self._exchange_stub:
            (req, src_shard, src_row, is_local, valid, rank,
             n_drop) = self._a2a_route(dst, ax, nl)
            with cost.phase("exchange"):
                req_in = lax.all_to_all(req, NODE_AXIS, 0, 0)  # [d, C] rows
            is_local_f = is_local.reshape(nl, p.fanout)

        # Phase 1 — local board rows + transmit accounting, then the
        # board staleness gate once per shard (rows travel filtered).
        bval_l, bslot_l, sent = self._publish(local, limit, row_offset=r0)
        b_own = None
        if t.tomb_budget is not None:
            # Per-origin budget on the shard's board block: local row r
            # is published by global node ``gi[r]``; slot owners come
            # from the global owner-run layout.  Gated once before the
            # rows travel — every downstream fold consumes budget-
            # filtered copies, like the single-chip board gate.
            b_own = ((bslot_l // p.services_per_node) == gi[:, None])
        bval_f = admit_gate(bval_l, now, t.stale_ticks, t.future_ticks,
                            t.tomb_budget, b_own)

        ok = alive[dst] & alive[gi][:, None]             # [nl, F]
        keep = None
        if p.drop_prob > 0.0:
            # ONE keep mask for the whole candidate set: groups fold
            # separately but slice this same draw, so the split changes
            # nothing observable.
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob,
                (nl, p.fanout, p.cache_lines))

        cv0, cs0 = cval_l, cslot_l
        wv, ws = cv0, cs0

        # Phase 3a — own-shard deliveries fold immediately (no exchange
        # needed): the sharded gather kernel DMAs the block rows.  The
        # all_gather mode skips this — its remote buffer IS the full
        # board, so local rows ride the same single consume (an extra
        # early-fold group there would duplicate [nl, F, K] work for no
        # footprint win; ring/a2a serve local rows separately by
        # construction).
        if mode != "all_gather" or self._exchange_stub:
            pv0, ps0 = self._serve_local(bval_f, bslot_l, dst, r0)
            wv, ws = self._fold_pulled(cv0, cs0, wv, ws, pv0, ps0,
                                       ok & (dst // nl == ax), now,
                                       keep=keep, stale_filtered=True)

        # Phase 3b — the announce own/floor half (refresh fold + offer
        # values; reads own/floor only, never the cache) overlaps the
        # in-flight exchange; the cache insert waits for the final
        # phase.
        if ann_local is None:
            own_l, floor, offer_val, base_slot = self._announce_offers(
                own_l, floor, alive[gi], round_idx, now, row_offset=r0)
        else:
            offer_val, base_slot = ann_local

        # Phases 2 + 4 — issue the remote exchange and consume its rows.
        if self._exchange_stub:
            pass  # measurement-only: exposed-comm probe, no collectives
        elif mode == "all_gather":
            with cost.phase("exchange"):
                bval = lax.all_gather(bval_f, NODE_AXIS, tiled=True)  # [N, K]
                bslot = lax.all_gather(bslot_l, NODE_AXIS, tiled=True)
            pv, ps = self._serve_local(bval, bslot, dst, 0)
            wv, ws = self._fold_pulled(cv0, cs0, wv, ws, pv, ps, ok,
                                       now, keep=keep,
                                       stale_filtered=True)
        elif mode == "all_to_all":
            rows = jnp.clip(req_in, 0, nl - 1)
            with cost.phase("exchange"):
                resp_v = lax.all_to_all(bval_f[rows], NODE_AXIS, 0, 0)
                resp_s = lax.all_to_all(bslot_l[rows], NODE_AXIS, 0, 0)
            safe_shard = jnp.where(valid, src_shard, 0)
            safe_rank = jnp.where(valid, rank, 0)
            cross_v = jnp.where(valid[:, None],
                                resp_v[safe_shard, safe_rank], 0) \
                .reshape(nl, p.fanout, p.cache_lines)
            cross_s = jnp.where(valid[:, None],
                                resp_s[safe_shard, safe_rank], -1) \
                .reshape(nl, p.fanout, p.cache_lines)
            wv, ws = self._fold_pulled(cv0, cs0, wv, ws, cross_v, cross_s,
                                       ok & ~is_local_f, now,
                                       keep=keep, stale_filtered=True)
        elif mode == "zoned":
            # Zoned: per ring offset h, each shard ships ONLY the
            # statically-reachable board rows of its block (pull-plan
            # built at construction; docs/topology.md).  The receiver
            # looks sampled rows up through the hop's pos table; pad
            # rows carry (0, -1) — the merge no-op — so the fold is
            # bit-identical to all_gather for the same sampled peers.
            src_shard_r = dst // nl
            src_row_r = dst - src_shard_r * nl
            if d > 1:
                live = [h for h in range(1, d)
                        if self._zoned_tabs[h - 1] is not None]

                def zoned_send(h):
                    zrows, zvalid, _ = self._zoned_tabs[h - 1]
                    vmask = zvalid[ax][:, None]
                    blk_v = jnp.where(vmask, bval_f[zrows[ax]], 0)
                    blk_s = jnp.where(vmask, bslot_l[zrows[ax]], -1)
                    perm = [(i, (i - h) % d) for i in range(d)]
                    with cost.phase("exchange"):
                        return (lax.ppermute(blk_v, NODE_AXIS, perm),
                                lax.ppermute(blk_s, NODE_AXIS, perm))

                cur = zoned_send(live[0]) if live else None
                for j, h in enumerate(live):
                    if j + 1 < len(live):
                        # Double buffer, same overlap as the ring leg.
                        nxt = zoned_send(live[j + 1])
                    _, _, zpos = self._zoned_tabs[h - 1]
                    ss = (ax + h) % d
                    sel = src_shard_r == ss
                    posr = zpos[ss][jnp.where(sel, src_row_r, 0)]
                    # Append one (0, -1) pad row: pos is R for rows the
                    # plan never ships (only ever looked up when the
                    # fold is masked off anyway).
                    pad_v = jnp.concatenate(
                        [cur[0],
                         jnp.zeros((1, p.cache_lines), cur[0].dtype)])
                    pad_s = jnp.concatenate(
                        [cur[1],
                         jnp.full((1, p.cache_lines), -1, cur[1].dtype)])
                    wv, ws = self._fold_pulled(
                        cv0, cs0, wv, ws, pad_v[posr], pad_s[posr],
                        ok & sel, now, keep=keep, stale_filtered=True)
                    if j + 1 < len(live):
                        cur = nxt
        else:  # ring — lax.ppermute streams block pairs hop by hop
            src_shard_r = dst // nl
            src_row_r = dst - src_shard_r * nl
            if d > 1:
                perm = [(i, (i - 1) % d) for i in range(d)]
                with cost.phase("exchange"):
                    cur_v = lax.ppermute(bval_f, NODE_AXIS, perm)
                    cur_s = lax.ppermute(bslot_l, NODE_AXIS, perm)
                for h in range(1, d):
                    if h < d - 1:
                        # Double buffer: hop h+1's transfer is issued
                        # BEFORE hop h's rows are consumed, so the
                        # next transfer overlaps this hop's
                        # gate/fold.  Live footprint: two [nl, K]
                        # block pairs, O(N/d·K) — never the
                        # replicated O(N·K) board.
                        with cost.phase("exchange"):
                            nxt_v = lax.ppermute(cur_v, NODE_AXIS, perm)
                            nxt_s = lax.ppermute(cur_s, NODE_AXIS, perm)
                    sel = src_shard_r == (ax + h) % d
                    rows_h = jnp.where(sel, src_row_r, 0)
                    wv, ws = self._fold_pulled(
                        cv0, cs0, wv, ws, cur_v[rows_h], cur_s[rows_h],
                        ok & sel, now, keep=keep, stale_filtered=True)
                    if h < d - 1:
                        cur_v, cur_s = nxt_v, nxt_s

        # Final phase — one batch resolution vs the pre-round cache
        # (the _merge_pulled finalize), then the announce cache insert
        # on the merged lines: the single-chip op sequence exactly.
        changed = (wv != cv0) | (ws != cs0)
        sent = jnp.where(changed, jnp.int8(0), sent)
        ev_merge = jnp.sum(((cs0 >= 0) & (ws != cs0)).astype(jnp.int32))
        cv, cs, se, ev_ann = self._insert_own_offers(
            wv, ws, sent, offer_val, base_slot, reset_on_hold=True)

        if ann_local is None:
            # Per-shard announce wrote only this shard's floor slice;
            # re-merge the replicas (precomputed floors arrive merged).
            floor = lax.pmax(floor, NODE_AXIS)
        ev = lax.psum(ev_merge + ev_ann, NODE_AXIS)
        dr = lax.psum(n_drop, NODE_AXIS)
        return own_l, cs, cv, se, floor, ev, dr

    # -- the sparse-frontier shard round (docs/sparse.md) --------------------

    def _gossip_shard_body_sparse(self, own_l, cslot_l, cval_l, csent_l,
                                  floor, alive, dst, k_drop, round_idx,
                                  sender_l, recv_l, ann_l, offer_val,
                                  base_slot):
        """Per-shard compaction of the split-phase round: publish runs
        on the shard's compacted active-sender rows (the XLA kernel
        with explicit global ids) and is scattered back to the dense
        ``[nl, K]`` block — bit-identical to the dense block, since
        inactive rows publish ``(0, -1)`` boards — so EVERY board
        exchange mode (all_gather | all_to_all | ring) runs verbatim on
        it; the fold/finalize and the announce cache insert run on the
        compacted receiver/announcer rows.  Compute shrinks to the
        frontier, the exchange keeps its dense shape (its cost is the
        mode's documented envelope, docs/sharding.md).  The caller
        guarantees no per-shard frontier overflowed (the jit-level
        dense fallback) and hands in the announce own/floor half
        PRECOMPUTED at the jit level (``_step_sparse`` needs it for the
        announcer frontier anyway — the O(N·S) pass runs once per
        round): ``own_l``/``floor`` arrive already advanced,
        ``offer_val``/``base_slot`` are this shard's slices."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        nl = own_l.shape[0]
        d = self.d
        ax = lax.axis_index(NODE_AXIS)
        r0 = (ax * nl).astype(jnp.int32)
        gi = r0 + jnp.arange(nl, dtype=jnp.int32)
        now = round_idx * t.round_ticks
        mode = self.board_exchange
        k = p.cache_lines
        cs_cap, cr_cap, ca_cap = self._sparse_caps_shard

        n_drop = jnp.zeros((), jnp.int32)
        # The a2a request leg is unchanged — pure index math over the
        # full dst (requests to inactive senders return empty boards,
        # the merge no-op), so bucket ranks and the drop accounting
        # match the dense round exactly.
        if mode == "all_to_all" and not self._exchange_stub:
            (req, src_shard, src_row, is_local, valid, rank,
             n_drop) = self._a2a_route(dst, ax, nl)
            with cost.phase("exchange"):
                req_in = lax.all_to_all(req, NODE_AXIS, 0, 0)
            is_local_f = is_local.reshape(nl, p.fanout)

        # Phase 1 — compacted publish, reconstructed to the dense block.
        idx_s, row_s, valid_s, pos_s = sparse_ops.compact_rows(
            sender_l, cs_cap)
        cv_s = jnp.where(valid_s[:, None], cval_l[row_s], 0)
        sl_s = jnp.where(valid_s[:, None], cslot_l[row_s], -1)
        bval_c, bslot_c, sent_c = kernel_ops.publish_board_xla(
            cv_s, sl_s, csent_l[row_s], budget=min(p.budget, k),
            limit=limit, fanout=p.fanout, cache_lines=k,
            row_ids=idx_s + r0)
        sent = jnp.where(sender_l[:, None], sent_c[pos_s], csent_l)
        b_own_c = None
        if t.tomb_budget is not None:
            # Compacted twin of the dense shard board budget gate: the
            # global publisher of compacted row c is ``gi[idx_s[c]]``
            # (pad rows reconstruct to all-zero boards, the no-op).
            b_own_c = ((bslot_c // p.services_per_node)
                       == (idx_s + r0)[:, None])
        bval_c = admit_gate(bval_c, now, t.stale_ticks, t.future_ticks,
                            t.tomb_budget, b_own_c)
        snd_c = sender_l[:, None]
        bval_f = jnp.where(snd_c, bval_c[pos_s], 0)
        bslot_f = jnp.where(snd_c, bslot_c[pos_s], -1)

        # Receiver compaction (shared by every fold below).
        idx_r, row_r, valid_r, pos_r = sparse_ops.compact_rows(
            recv_l, cr_cap)
        dst_c = dst[row_r]                                   # [Cr, F]
        ok_c = alive[dst_c] & (alive[gi[row_r]] & valid_r)[:, None]
        keep_c = None
        if p.drop_prob > 0.0:
            # The dense per-shard draw, sliced (mode-independent loss).
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob, (nl, p.fanout, k))
            keep_c = keep[row_r]
        cv0_c, cs0_c = cval_l[row_r], cslot_l[row_r]
        wv, ws = cv0_c, cs0_c

        # Phase 3a — own-shard early fold (ring/a2a; XLA gather twin).
        if mode != "all_gather" or self._exchange_stub:
            pv0, ps0 = kernel_ops.board_row_gather_xla(
                bval_f, bslot_f, dst_c, r0)
            wv, ws = self._fold_pulled(cv0_c, cs0_c, wv, ws, pv0, ps0,
                                       ok_c & (dst_c // nl == ax), now,
                                       keep=keep_c, stale_filtered=True)

        # Phases 2 + 4 — the exchange runs on the reconstructed dense
        # block (identical bytes to the dense round's exchange).
        if self._exchange_stub:
            pass
        elif mode == "all_gather":
            with cost.phase("exchange"):
                bval = lax.all_gather(bval_f, NODE_AXIS, tiled=True)
                bslot = lax.all_gather(bslot_f, NODE_AXIS, tiled=True)
            pv, ps = kernel_ops.board_row_gather_xla(bval, bslot,
                                                     dst_c, 0)
            wv, ws = self._fold_pulled(cv0_c, cs0_c, wv, ws, pv, ps,
                                       ok_c, now, keep=keep_c,
                                       stale_filtered=True)
        elif mode == "all_to_all":
            rows = jnp.clip(req_in, 0, nl - 1)
            with cost.phase("exchange"):
                resp_v = lax.all_to_all(bval_f[rows], NODE_AXIS, 0, 0)
                resp_s = lax.all_to_all(bslot_f[rows], NODE_AXIS, 0, 0)
            valid_c = valid.reshape(nl, p.fanout)[row_r]
            shard_c = jnp.where(valid, src_shard, 0) \
                .reshape(nl, p.fanout)[row_r]
            rank_c = jnp.where(valid, rank, 0) \
                .reshape(nl, p.fanout)[row_r]
            cross_v = jnp.where(valid_c[:, :, None],
                                resp_v[shard_c, rank_c], 0)
            cross_s = jnp.where(valid_c[:, :, None],
                                resp_s[shard_c, rank_c], -1)
            wv, ws = self._fold_pulled(cv0_c, cs0_c, wv, ws, cross_v,
                                       cross_s,
                                       ok_c & ~is_local_f[row_r], now,
                                       keep=keep_c, stale_filtered=True)
        elif mode == "zoned":
            # The dense zoned leg verbatim on the compacted receiver
            # rows; the shipped blocks keep their dense shape (the
            # mode's documented byte envelope).
            src_shard_r = dst_c // nl
            src_row_r = dst_c - src_shard_r * nl
            if d > 1:
                live = [h for h in range(1, d)
                        if self._zoned_tabs[h - 1] is not None]

                def zoned_send(h):
                    zrows, zvalid, _ = self._zoned_tabs[h - 1]
                    vmask = zvalid[ax][:, None]
                    blk_v = jnp.where(vmask, bval_f[zrows[ax]], 0)
                    blk_s = jnp.where(vmask, bslot_f[zrows[ax]], -1)
                    perm = [(i, (i - h) % d) for i in range(d)]
                    with cost.phase("exchange"):
                        return (lax.ppermute(blk_v, NODE_AXIS, perm),
                                lax.ppermute(blk_s, NODE_AXIS, perm))

                cur = zoned_send(live[0]) if live else None
                for j, h in enumerate(live):
                    if j + 1 < len(live):
                        nxt = zoned_send(live[j + 1])
                    _, _, zpos = self._zoned_tabs[h - 1]
                    ss = (ax + h) % d
                    sel = src_shard_r == ss
                    posr = zpos[ss][jnp.where(sel, src_row_r, 0)]
                    pad_v = jnp.concatenate(
                        [cur[0], jnp.zeros((1, k), cur[0].dtype)])
                    pad_s = jnp.concatenate(
                        [cur[1], jnp.full((1, k), -1, cur[1].dtype)])
                    wv, ws = self._fold_pulled(
                        cv0_c, cs0_c, wv, ws, pad_v[posr], pad_s[posr],
                        ok_c & sel, now, keep=keep_c,
                        stale_filtered=True)
                    if j + 1 < len(live):
                        cur = nxt
        else:  # ring
            src_shard_r = dst_c // nl
            src_row_r = dst_c - src_shard_r * nl
            if d > 1:
                perm = [(i, (i - 1) % d) for i in range(d)]
                with cost.phase("exchange"):
                    cur_v = lax.ppermute(bval_f, NODE_AXIS, perm)
                    cur_s = lax.ppermute(bslot_f, NODE_AXIS, perm)
                for h in range(1, d):
                    if h < d - 1:
                        with cost.phase("exchange"):
                            nxt_v = lax.ppermute(cur_v, NODE_AXIS, perm)
                            nxt_s = lax.ppermute(cur_s, NODE_AXIS, perm)
                    sel = src_shard_r == (ax + h) % d
                    rows_h = jnp.where(sel, src_row_r, 0)
                    wv, ws = self._fold_pulled(
                        cv0_c, cs0_c, wv, ws, cur_v[rows_h],
                        cur_s[rows_h], ok_c & sel, now, keep=keep_c,
                        stale_filtered=True)
                    if h < d - 1:
                        cur_v, cur_s = nxt_v, nxt_s

        # Final phase — finalize on the compacted rows, gather-based
        # write-back (zero scatters on the [nl, K] block), then the
        # announce cache insert on the compacted announcer rows.
        changed = (wv != cv0_c) | (ws != cs0_c)
        sent_r = jnp.where(changed, jnp.int8(0), sent[row_r])
        ev_merge = jnp.sum(((cs0_c >= 0)
                            & (ws != cs0_c)).astype(jnp.int32))
        rc = recv_l[:, None]
        cv = jnp.where(rc, wv[pos_r], cval_l)
        cs = jnp.where(rc, ws[pos_r], cslot_l)
        se = jnp.where(rc, sent_r[pos_r], sent)

        idx_a, row_a, valid_a, pos_a = sparse_ops.compact_rows(
            ann_l, ca_cap)
        off_a = jnp.where(valid_a[:, None], offer_val[row_a], 0)
        cv2, cs2, se2, ev_ann = self._insert_own_offers(
            cv[row_a], cs[row_a], se[row_a], off_a, base_slot[row_a],
            reset_on_hold=True)
        ac = ann_l[:, None]
        cv = jnp.where(ac, cv2[pos_a], cv)
        cs = jnp.where(ac, cs2[pos_a], cs)
        se = jnp.where(ac, se2[pos_a], se)

        # floor arrived fully advanced and replicated (jit-level
        # announce) — no pmax re-merge needed on this path.
        ev = lax.psum(ev_merge + ev_ann, NODE_AXIS)
        dr = lax.psum(n_drop, NODE_AXIS)
        return own_l, cs, cv, se, floor, ev, dr

    def _sample_dst_jit(self, k_peers, alive):
        """Replay the per-shard sampling streams at the jit level —
        shard s draws ``split(fold_in(key, s))[0]`` over its rows,
        exactly what ``_gossip_shard`` derives inside ``shard_map`` —
        so the sparse step can compute its receiver frontier from the
        very ``dst`` the round will use."""
        p = self.p
        nl = p.n // self.d
        parts = []
        for s_ix in range(self.d):
            k_p, _ = jax.random.split(jax.random.fold_in(k_peers, s_ix))
            gi = s_ix * nl + jnp.arange(nl, dtype=jnp.int32)
            if self._nbrs is None:
                parts.append(self._sample_dst_complete(k_p, gi, alive,
                                                       nl))
            else:
                nbrs_l = lax.dynamic_slice_in_dim(self._nbrs,
                                                  s_ix * nl, nl)
                deg_l = lax.dynamic_slice_in_dim(self._deg, s_ix * nl,
                                                 nl)
                cut_l = None if self._cut is None else \
                    lax.dynamic_slice_in_dim(self._cut, s_ix * nl, nl)
                parts.append(self._sample_dst_nbrs(
                    k_p, gi, alive, nl, nbrs_l, deg_l, cut_l))
        return jnp.concatenate(parts)

    # Provenance hook (ops/provenance.py): the pull channels must replay
    # the per-shard sampling streams, not the single-chip stream — the
    # rest of the provenance plane is inherited from CompressedSim.
    def _prov_sample_src(self, k_peers, node_alive):
        return self._sample_dst_jit(k_peers, node_alive)

    def _step_sparse(self, state: CompressedState, key: jax.Array):
        """The sharded sparse round: frontiers and the overflow check
        run at the jit level (GSPMD elementwise over the sharded
        state), then ONE replicated predicate picks the sparse or the
        dense shard body for every device — the collectives inside
        either branch stay uniform across the mesh, the same shape as
        the cadence-gated push-pull cond below."""
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)
        del k_drop  # folded per-shard inside the shard bodies

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        dst = gossip_ops.stagger_gate(
            self._sample_dst_jit(k_peers, state.node_alive),
            round_idx, self._stagger, self._stagger_period)
        if self._cadence is not None:
            per, pha = self._cadence
            dst = gossip_ops.cadence_gate(dst, round_idx, per, pha)
        dst = lax.with_sharding_constraint(dst, self._row_sharding)

        sender = jnp.any(kernel_ops.eligible_lines(
            state.cache_slot, state.cache_sent, limit), axis=1)
        recv = state.node_alive & jnp.any(sender[dst], axis=1)
        # The announce own/floor half runs ONCE here (the announcer
        # frontier needs offer_val anyway) and its outputs feed the
        # sparse shard body directly — per-shard recompute would double
        # the O(N·S) pass (GSPMD slices these row-sharded).
        own1, floor1, offer_val, base_slot = self._announce_offers(
            state.own, state.floor, state.node_alive, round_idx, now)
        ann = jnp.any(offer_val > 0, axis=1)

        nl = p.n // self.d
        cs_cap, cr_cap, ca_cap = self._sparse_caps_shard

        def per_shard(m):
            return jnp.sum(m.reshape(self.d, nl).astype(jnp.int32),
                           axis=1)

        ns, nr, na = per_shard(sender), per_shard(recv), per_shard(ann)
        overflow = jnp.any((ns > cs_cap) | (nr > cr_cap)
                           | (na > ca_cap))
        frontier = jnp.maximum(jnp.sum(ns),
                               jnp.maximum(jnp.sum(nr), jnp.sum(na)))

        spec_row, spec_repl = P(NODE_AXIS), P()
        base_specs = (spec_row,) * 4 + (spec_repl,) * 4 + (spec_row,)
        out_specs = (spec_row,) * 4 + (spec_repl,) * 3

        def dense_branch(st):
            def body(own, cs, cv, se, floor, al, k, r, dstl, offv,
                     bsl):
                ax = lax.axis_index(NODE_AXIS)
                _, kd = jax.random.split(jax.random.fold_in(k, ax))
                return self._gossip_shard_body(own, cs, cv, se, floor,
                                               al, dstl, kd, r,
                                               ann_local=(offv, bsl))
            fn = shard_map(body, mesh=self.mesh,
                           in_specs=base_specs + (spec_row, spec_row),
                           out_specs=out_specs, check_vma=False)
            # own/floor enter already announce-advanced — the jit-level
            # pass feeds BOTH branches.
            return fn(own1, st.cache_slot, st.cache_val,
                      st.cache_sent, floor1, st.node_alive, k_peers,
                      round_idx, dst, offer_val, base_slot)

        def sparse_branch(st):
            def body(own, cs, cv, se, floor, al, k, r, dstl, snd, rcv,
                     an, offv, bsl):
                ax = lax.axis_index(NODE_AXIS)
                _, kd = jax.random.split(jax.random.fold_in(k, ax))
                return self._gossip_shard_body_sparse(
                    own, cs, cv, se, floor, al, dstl, kd, r, snd, rcv,
                    an, offv, bsl)
            fn = shard_map(body, mesh=self.mesh,
                           in_specs=base_specs + (spec_row,) * 5,
                           out_specs=out_specs, check_vma=False)
            # own/floor enter already announce-advanced (own1/floor1).
            return fn(own1, st.cache_slot, st.cache_val,
                      st.cache_sent, floor1, st.node_alive, k_peers,
                      round_idx, dst, sender, recv, ann, offer_val,
                      base_slot)

        own, cs, cv, se, floor, ev, dr = lax.cond(
            overflow, dense_branch, sparse_branch, state)
        state = dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            floor=floor, evictions=state.evictions + ev,
            dropped=state.dropped + dr)

        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        state = dataclasses.replace(state, round_idx=round_idx)
        ov = overflow.astype(jnp.int32)
        stats = jnp.stack([1 - ov, ov, frontier])
        return self._constrain(state), stats

    # -- the round ----------------------------------------------------------

    def _step(self, state: CompressedState,
              key: jax.Array) -> CompressedState:
        p, t = self.p, self.t
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        # Same split as CompressedSim._step: lockstep runs draw the same
        # push-pull stride.
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)
        del k_drop  # folded per-shard inside _gossip_shard

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        spec_row, spec_repl = P(NODE_AXIS), P()
        topo_args, topo_specs = (), ()
        if self._nbrs is not None:
            topo_args = (self._nbrs, self._deg)
            topo_specs = (spec_row, spec_row)
            if self._cut is not None:
                topo_args += (self._cut,)
                topo_specs += (spec_row,)

        def body(own, cs, cv, se, floor, alive, k, r, *topo):
            if not topo:
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r)
            if len(topo) == 2:
                nb, dg = topo
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r, nbrs_l=nb, deg_l=dg)
            nb, dg, ct = topo
            return self._gossip_shard(own, cs, cv, se, floor, alive, k, r,
                                      nbrs_l=nb, deg_l=dg, cut_l=ct)

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(spec_row,) * 4 + (spec_repl,) * 4 + topo_specs,
            out_specs=(spec_row,) * 4 + (spec_repl,) * 3,
            check_vma=False)
        own, cs, cv, se, floor, ev, dr = fn(
            state.own, state.cache_slot, state.cache_val, state.cache_sent,
            state.floor, state.node_alive, k_peers, round_idx, *topo_args)
        state = dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            floor=floor, evictions=state.evictions + ev,
            dropped=state.dropped + dr)

        # 3. anti-entropy — the inherited stride exchange; jnp.roll along
        # the sharded axis lowers to a collective-permute.
        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)

        # 4. floor advance + sweep — inherited; the census scatter-adds
        # run under GSPMD propagation (local contributions + all-reduce).
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        state = dataclasses.replace(state, round_idx=round_idx)
        return self._constrain(state)
