"""Node-axis-sharded compressed gossip simulator — the north-star-scale
model on a multi-chip mesh.

This is the sharded twin of :class:`sidecar_tpu.models.compressed.
CompressedSim` (promised there), combining the two scale mechanisms:

* **Bounded memory per node** (the compressed model): own[N, S] +
  direct-mapped cache[N, K] + one shared floor[M] — O(N·K + M) instead of
  the dense model's O(N²·S).
* **Node-axis sharding** (the ShardedSim design, parallel/sharded.py):
  each device owns a contiguous block of nodes; a node's own rows and
  cache lines stay device-local, so select / line-competition / announce
  are embarrassingly parallel.

Cross-device traffic per round — all riding ICI collectives:

* **The message board** — each shard publishes its rows' top-``budget``
  cache lines (the ~1398 B-packet analog) and the boards are
  ``all_gather``-ed; each shard then PULLS the board rows its own nodes
  sampled and lex-merges them elementwise (the line-aligned delivery,
  models/compressed.py).  Per-shard merge work is O(N/d · fanout · K);
  the gather traffic is O(N·K) int32 — ~100 MB at the 100k-node north
  star, a few ms on ICI.  Messages cross the interconnect, state stays
  put — exactly the real network's economics.
* **Floor maintenance** — the shared converged baseline is REPLICATED
  across devices.  Owner-refresh folds touch only shard-owned slots, so
  an ``lax.pmax`` after the announce phase re-merges the replicas; the
  unanimity census (every ``sweep_rounds``) runs as local truth/hit
  contributions combined with ``pmax``/``psum`` under GSPMD sharding
  propagation.  floor is O(M) int32 — 4 MB at the 1M-service north star,
  trivially replicable.
* **Anti-entropy** — the same random-stride ring exchange as the dense
  sharded model: ``jnp.roll`` along the sharded node axis lowers to an
  XLA collective-permute.

Protocol semantics are IDENTICAL to the single-chip ``CompressedSim`` —
the merge/announce/push-pull kernels are literally the same methods
(called per-shard with ``row_offset``), so a deterministic lockstep run
matches bit-for-bit including the stride push-pull (both models draw the
same stride from the same key); see tests/test_sharded_compressed.py.
The divergences are the PRNG streams drawn per shard (``fold_in(key,
shard)``, like ShardedSim): *random* peer sampling and the ``drop_prob``
loss mask — with a pinned peer rule and ``drop_prob=0`` nothing random
remains and the lockstep is exact.

Scaling note: every per-round phase is O(N/d) per device (publish,
pull-merge, announce); the board all_gather replicates O(N·K) transient
bytes per device, which bounds single-pod reach to a few hundred
thousand nodes at K=256.  Past that, the upgrade path is gathering only
the board rows each shard's nodes actually sampled (an ``all_to_all``
keyed by source shard) instead of the full board.

Reference scale envelope this design answers: one Go process holds the
whole O(M) catalog per host (catalog/services_state.go:70-80); at the
north star (100k nodes / 1M services < 10 s, BASELINE.md) simulating
that requires both compression and sharding at once.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from sidecar_tpu.models.compressed import (
    CompressedParams,
    CompressedSim,
    CompressedState,
)
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops.topology import Topology
from sidecar_tpu.parallel.mesh import NODE_AXIS, make_mesh


class ShardedCompressedSim(CompressedSim):
    """Multi-device compressed simulator.  Drop-in for CompressedSim
    (same driver contract: init_state / step / run / run_fast / mint /
    convergence), state sharded along the node axis."""

    def __init__(self, params: CompressedParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 mesh=None,
                 perturb=None,
                 cut_mask: Optional[np.ndarray] = None,
                 node_side: Optional[np.ndarray] = None):
        super().__init__(params, topo, timecfg, perturb=perturb,
                         cut_mask=cut_mask, node_side=node_side)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.d = self.mesh.devices.size
        if params.n % self.d != 0:
            raise ValueError(
                f"n={params.n} must divide the {self.d}-device mesh")

        row = NamedSharding(self.mesh, P(NODE_AXIS))
        repl = NamedSharding(self.mesh, P())
        self._row_sharding = row
        self._repl_sharding = repl
        if self._nbrs is not None:
            self._nbrs = jax.device_put(self._nbrs, row)
            self._deg = jax.device_put(self._deg, row)
        if self._cut is not None:
            self._cut = jax.device_put(self._cut, row)
        if self._side is not None:
            self._side = jax.device_put(self._side, repl)

    # -- state --------------------------------------------------------------

    def init_state(self) -> CompressedState:
        st = super().init_state()
        return self._constrain(st, place=True)

    def _constrain(self, st: CompressedState, place=False) -> CompressedState:
        """Pin the canonical layout: per-node arrays sharded on the node
        axis, floor/alive/scalars replicated.  ``place=True`` moves host
        arrays (init); inside jit the sharding-constraint form keeps the
        scan carry layout stable."""
        row, repl = self._row_sharding, self._repl_sharding
        put = jax.device_put if place else lax.with_sharding_constraint
        return CompressedState(
            own=put(st.own, row),
            cache_slot=put(st.cache_slot, row),
            cache_val=put(st.cache_val, row),
            cache_sent=put(st.cache_sent, row),
            floor=put(st.floor, repl),
            node_alive=put(st.node_alive, repl),
            round_idx=put(st.round_idx, repl),
            evictions=put(st.evictions, repl),
        )

    # -- peer sampling (global ids; overridable for deterministic tests) ----

    def _sample_dst_complete(self, k_peers, gi, alive, nl):
        p = self.p
        r = jax.random.randint(k_peers, (nl, p.fanout), 0, p.n - 1,
                               dtype=jnp.int32)
        dst = r + (r >= gi[:, None]).astype(jnp.int32)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    def _sample_dst_nbrs(self, k_peers, gi, alive, nl, nbrs_l, deg_l, cut_l):
        p = self.p
        slot = jax.random.randint(k_peers, (nl, p.fanout), 0,
                                  jnp.maximum(deg_l, 1)[:, None],
                                  dtype=jnp.int32)
        dst = jnp.take_along_axis(nbrs_l, slot, axis=1)
        if cut_l is not None:
            cut = jnp.take_along_axis(cut_l, slot, axis=1)
            dst = jnp.where(cut, gi[:, None], dst)
        return jnp.where(alive[gi][:, None], dst, gi[:, None])

    # -- the per-shard gossip + announce phase (inside shard_map) -----------

    def _gossip_shard(self, own_l, cslot_l, cval_l, csent_l, floor, alive,
                      key, round_idx, nbrs_l=None, deg_l=None, cut_l=None):
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        nl = own_l.shape[0]
        ax = lax.axis_index(NODE_AXIS)
        r0 = (ax * nl).astype(jnp.int32)
        gi = r0 + jnp.arange(nl, dtype=jnp.int32)
        now = round_idx * t.round_ticks

        k_peers, k_drop = jax.random.split(jax.random.fold_in(key, ax))
        if nbrs_l is None:
            dst = self._sample_dst_complete(k_peers, gi, alive, nl)
        else:
            dst = self._sample_dst_nbrs(k_peers, gi, alive, nl,
                                        nbrs_l, deg_l, cut_l)

        # Local view of this shard: the inherited single-chip kernels run
        # on it unchanged (row_offset maps local rows to global identity),
        # which is what makes the twin bit-exact by construction.
        local = CompressedState(
            own=own_l, cache_slot=cslot_l, cache_val=cval_l,
            cache_sent=csent_l, floor=floor, node_alive=alive[gi],
            round_idx=round_idx, evictions=jnp.zeros((), jnp.int32))

        # 1. publish local board rows + transmit accounting (elementwise;
        # row_offset ties the tie rotation to global node ids).
        bval_l, bslot_l, sent = self._publish(local, limit, row_offset=r0)

        # The only cross-shard gossip traffic: the board (bounded offers,
        # line-aligned — each row is the ≤budget records its node would
        # pack into one ~1398 B datagram).
        bval = lax.all_gather(bval_l, NODE_AXIS, tiled=True)   # [N, K]
        bslot = lax.all_gather(bslot_l, NODE_AXIS, tiled=True)  # [N, K]

        # 2. pull-merge into my rows (src holds global peer ids).
        local = self._pull_merge(local, sent, bval, bslot, dst, alive,
                                 now, drop_key=k_drop)

        # 3. announce re-stamps + recovery offers (local rows own exactly
        # this shard's slot range; the refresh fold raises only shard-owned
        # floor entries, re-merged via pmax below).
        local = self._announce(local, round_idx, now, row_offset=r0)

        floor = lax.pmax(local.floor, NODE_AXIS)
        ev = lax.psum(local.evictions, NODE_AXIS)
        return (local.own, local.cache_slot, local.cache_val,
                local.cache_sent, floor, ev)

    # -- the round ----------------------------------------------------------

    def _step(self, state: CompressedState,
              key: jax.Array) -> CompressedState:
        p, t = self.p, self.t
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        # Same split as CompressedSim._step: lockstep runs draw the same
        # push-pull stride.
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)
        del k_drop  # folded per-shard inside _gossip_shard

        if self.perturb is not None:
            state = self.perturb(state, k_perturb, now)

        spec_row, spec_repl = P(NODE_AXIS), P()
        topo_args, topo_specs = (), ()
        if self._nbrs is not None:
            topo_args = (self._nbrs, self._deg)
            topo_specs = (spec_row, spec_row)
            if self._cut is not None:
                topo_args += (self._cut,)
                topo_specs += (spec_row,)

        def body(own, cs, cv, se, floor, alive, k, r, *topo):
            if not topo:
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r)
            if len(topo) == 2:
                nb, dg = topo
                return self._gossip_shard(own, cs, cv, se, floor, alive,
                                          k, r, nbrs_l=nb, deg_l=dg)
            nb, dg, ct = topo
            return self._gossip_shard(own, cs, cv, se, floor, alive, k, r,
                                      nbrs_l=nb, deg_l=dg, cut_l=ct)

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(spec_row,) * 4 + (spec_repl,) * 4 + topo_specs,
            out_specs=(spec_row,) * 4 + (spec_repl, spec_repl),
            check_vma=False)
        own, cs, cv, se, floor, ev = fn(
            state.own, state.cache_slot, state.cache_val, state.cache_sent,
            state.floor, state.node_alive, k_peers, round_idx, *topo_args)
        state = dataclasses.replace(
            state, own=own, cache_slot=cs, cache_val=cv, cache_sent=se,
            floor=floor, evictions=state.evictions + ev)

        # 3. anti-entropy — the inherited stride exchange; jnp.roll along
        # the sharded axis lowers to a collective-permute.
        state = lax.cond(
            round_idx % t.push_pull_rounds == 0,
            lambda st: self._push_pull_stride(st, k_pp, now),
            lambda st: st, state)

        # 4. floor advance + sweep — inherited; the census scatter-adds
        # run under GSPMD propagation (local contributions + all-reduce).
        state = lax.cond(
            round_idx % t.sweep_rounds == 0,
            lambda st: self._floor_advance_and_sweep(st, now),
            lambda st: st, state)

        state = dataclasses.replace(state, round_idx=round_idx)
        return self._constrain(state)
