"""Mesh + sharding helpers.

One logical axis, ``nodes``: every state tensor (known[N, M], sent[N, M],
node_alive[N]) is sharded along its leading node dimension; the service
axis M is kept whole per shard so each node's row — its entire replicated
catalog — lives on one device, exactly the data locality the reference has
(one host's ``ServicesState`` on one machine).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the node axis (all visible devices by default)."""
    devices = list(devices if devices is not None else jax.devices())
    import numpy as np
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [N, ...] tensors: leading axis split over the mesh."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
