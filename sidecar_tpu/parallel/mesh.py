"""Mesh + sharding helpers.

One logical axis, ``nodes``: every state tensor (known[N, M], sent[N, M],
node_alive[N]) is sharded along its leading node dimension; the service
axis M is kept whole per shard so each node's row — its entire replicated
catalog — lives on one device, exactly the data locality the reference has
(one host's ``ServicesState`` on one machine).
"""

from __future__ import annotations

import inspect
import os
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sidecar_tpu import metrics

NODE_AXIS = "nodes"

# Board-exchange selection (docs/sharding.md): how the per-round
# cross-shard exchange is spelled.  Resolved at sim construction — like
# SIDECAR_TPU_KERNELS, the choice is baked into the jitted round, so
# toggling the env var affects sims built afterwards.
BOARD_EXCHANGE_ENV = "SIDECAR_TPU_BOARD_EXCHANGE"
BOARD_EXCHANGES = ("all_gather", "all_to_all", "ring", "zoned")


def resolve_board_exchange(explicit: Optional[str] = None, *,
                           supported: Sequence[str] = BOARD_EXCHANGES,
                           record: bool = True) -> str:
    """Resolve the active board-exchange mode: an explicit constructor
    argument wins, else ``SIDECAR_TPU_BOARD_EXCHANGE``, else
    ``all_gather``.

    An EXPLICIT mode a twin doesn't support raises (the caller asked
    for something impossible).  An env-derived mode that is globally
    valid but unsupported by this twin FALLS BACK to ``all_gather``
    instead — the env knob is process-wide (an operator sets
    ``all_to_all`` for the compressed bench), and it must not hard-fail
    the dense twin's read paths (the bridge's ``sharded=True``); the
    fallback is recorded as ``parallel.exchange.mode.fallback``.
    Every resolution is recorded in the metrics registry
    (``parallel.exchange.mode.<mode>``) so bench/ops reports can read
    back which exchange a run actually used."""
    from_env = explicit is None
    if from_env:
        mode = os.environ.get(BOARD_EXCHANGE_ENV, "all_gather") \
            .strip().lower() or "all_gather"
    else:
        mode = explicit
    if mode not in supported:
        if from_env and mode in BOARD_EXCHANGES:
            if record:
                metrics.incr("parallel.exchange.mode.fallback")
            mode = "all_gather"
        else:
            raise ValueError(
                f"board_exchange must be one of {tuple(supported)}, got "
                f"{mode!r} (explicit argument or {BOARD_EXCHANGE_ENV})")
    if record:
        metrics.incr(f"parallel.exchange.mode.{mode}")
    return mode

# jax moved shard_map out of experimental (and renamed check_rep →
# check_vma) across the versions this repo meets in the wild; resolve
# once here so both sharded twins import one spelling.
try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_CHECK_ARG = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable ``shard_map`` wrapper (see module note)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      **{_SHARD_MAP_CHECK_ARG: check_vma})


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the node axis (all visible devices by default)."""
    devices = list(devices if devices is not None else jax.devices())
    import numpy as np
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [N, ...] tensors: leading axis split over the mesh."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
