"""Mesh + sharding helpers.

One logical axis, ``nodes``: every state tensor (known[N, M], sent[N, M],
node_alive[N]) is sharded along its leading node dimension; the service
axis M is kept whole per shard so each node's row — its entire replicated
catalog — lives on one device, exactly the data locality the reference has
(one host's ``ServicesState`` on one machine).
"""

from __future__ import annotations

import inspect
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"

# jax moved shard_map out of experimental (and renamed check_rep →
# check_vma) across the versions this repo meets in the wild; resolve
# once here so both sharded twins import one spelling.
try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_CHECK_ARG = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable ``shard_map`` wrapper (see module note)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      **{_SHARD_MAP_CHECK_ARG: check_vma})


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the node axis (all visible devices by default)."""
    devices = list(devices if devices is not None else jax.devices())
    import numpy as np
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [N, ...] tensors: leading axis split over the mesh."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
