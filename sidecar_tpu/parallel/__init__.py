"""Multi-device execution: mesh construction + node-axis-sharded simulators.

The reference scales by adding hosts to the gossip cluster (memberlist over
UDP/TCP, SURVEY.md §2.3); the TPU build scales by sharding the *node axis*
of the state tensors over a ``jax.sharding.Mesh`` and letting XLA place the
cross-shard exchanges on ICI collectives — the scaling-book recipe: pick a
mesh, annotate shardings, let XLA insert collectives.
"""

from sidecar_tpu.parallel.mesh import make_mesh, node_sharding  # noqa: F401
from sidecar_tpu.parallel.sharded import ShardedSim  # noqa: F401
from sidecar_tpu.parallel.sharded_compressed import (  # noqa: F401
    ShardedCompressedSim,
)
