"""Checker implementations (reference: healthy/commands.go:19-64)."""

from __future__ import annotations

import logging
import shlex
import subprocess
import time
import urllib.error
import urllib.request

log = logging.getLogger(__name__)

# Check status codes (healthy/healthy.go:18-23).
HEALTHY = 0
SICKLY = 1
FAILED = 2
UNKNOWN = 3


class Checker:
    """healthy/healthy.go:76-78 — run(args) → (status, error|None)."""

    def run(self, args: str) -> tuple[int, Exception | None]:
        raise NotImplementedError


class HttpGetCmd(Checker):
    """2xx ⇒ HEALTHY, anything else SICKLY (commands.go:19-33)."""

    def __init__(self, timeout: float = 10.0) -> None:
        self.timeout = timeout

    def run(self, args: str) -> tuple[int, Exception | None]:
        try:
            with urllib.request.urlopen(args, timeout=self.timeout) as resp:
                if 200 <= resp.status < 300:
                    return HEALTHY, None
                return SICKLY, None
        except urllib.error.HTTPError as exc:
            return SICKLY, exc
        except (OSError, ValueError) as exc:
            return UNKNOWN, exc


class ExternalCmd(Checker):
    """Exit 0 ⇒ HEALTHY (commands.go:42-55).  Executed without a shell
    wrapper, like the reference; invoke a shell yourself if needed."""

    def __init__(self, timeout: float = 30.0) -> None:
        self.timeout = timeout

    def run(self, args: str) -> tuple[int, Exception | None]:
        argv = shlex.split(args)
        if not argv:
            return UNKNOWN, ValueError("empty check command")
        try:
            result = subprocess.run(
                argv, capture_output=True, timeout=self.timeout, check=False)
        except (OSError, subprocess.TimeoutExpired) as exc:
            return SICKLY, exc
        if result.returncode == 0:
            return HEALTHY, None
        log.error("Error running command: exit %d (%s)", result.returncode,
                  result.stdout + result.stderr)
        return SICKLY, RuntimeError(f"exit code {result.returncode}")


class AlwaysSuccessfulCmd(Checker):
    """commands.go:60-64."""

    def run(self, args: str) -> tuple[int, Exception | None]:
        return HEALTHY, None


class ChaosChecker(Checker):
    """Fault-injection wrapper: consults a chaos injector (an object
    with ``check_fault(check_id) -> (extra_latency_s, fail)``, see
    sidecar_tpu/chaos/live_inject.py) before delegating to the real
    checker.  Injected latency models a hung/trickling endpoint — the
    workload that starves an undersized check pool (health/monitor.py);
    ``fail`` models the endpoint being gone.  The Monitor wraps checks
    with this automatically when its ``fault_injector`` is set."""

    def __init__(self, inner: Checker, injector, check_id: str) -> None:
        self.inner = inner
        self.injector = injector
        self.check_id = check_id

    # The Monitor's tick-deadline clamp reaches through to the real
    # checker's IO timeout.
    @property
    def timeout(self):
        return getattr(self.inner, "timeout", None)

    @timeout.setter
    def timeout(self, value) -> None:
        if hasattr(self.inner, "timeout"):
            self.inner.timeout = value

    def run(self, args: str) -> tuple[int, Exception | None]:
        delay, fail = self.injector.check_fault(self.check_id)
        if delay > 0.0:
            time.sleep(delay)
        if fail:
            return UNKNOWN, TimeoutError(
                f"chaos: injected failure for {self.check_id}")
        return self.inner.run(args)
