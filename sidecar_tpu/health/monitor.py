"""The health Monitor: manages and runs Checks on a fixed interval
(reference: healthy/healthy.go:33-218, service_bridge.go:18-187).

``watch`` syncs the check set with discovery (new service ⇒ fetch its
check type/args from the Discoverer, or a default HttpGet on the first
TCP port); ``run`` executes all checks concurrently each tick with a
per-check timeout of interval−1 ms; ``services()`` returns discovery's
services re-marked with check status — this is the ``serviceFunc`` the
catalog broadcasts (main.go:351)."""

from __future__ import annotations

import concurrent.futures
import logging
import re
import threading
import time
from typing import Callable, Optional

from sidecar_tpu import metrics
from sidecar_tpu.discovery.base import Discoverer
from sidecar_tpu.health.checks import (
    AlwaysSuccessfulCmd,
    ChaosChecker,
    Checker,
    ExternalCmd,
    FAILED,
    HEALTHY,
    HttpGetCmd,
    SICKLY,
    UNKNOWN,
)
from sidecar_tpu.runtime.looper import Looper
from sidecar_tpu import service as svc_mod
from sidecar_tpu.service import Service

log = logging.getLogger(__name__)

WATCH_INTERVAL = 0.5     # healthy.go:27
HEALTH_INTERVAL = 3.0    # healthy.go:28
DEFAULT_STATUS_ENDPOINT = "/"  # service_bridge.go:15


class Check:
    """One service's health check (healthy.go:44-89)."""

    def __init__(self, check_id: str, type: str = "http",
                 args: str = "", command: Optional[Checker] = None,
                 max_count: int = 1, status: int = UNKNOWN) -> None:
        self.id = check_id
        self.status = status
        self.count = 0
        self.max_count = max_count
        self.type = type
        self.args = args
        self.command: Optional[Checker] = (
            command if command is not None else HttpGetCmd())
        self.last_error: Optional[Exception] = None

    def update_status(self, status: int,
                      err: Optional[Exception]) -> None:
        """State machine with MaxCount escalation (healthy.go:93-114)."""
        if err is not None:
            log.debug("Error executing check, status UNKNOWN: (id %s)",
                      self.id)
            self.status = UNKNOWN
            self.last_error = err
        else:
            self.status = status

        if status == HEALTHY:
            self.count = 0
            return
        self.count += 1
        if self.count >= self.max_count:
            self.status = FAILED

    def service_status(self) -> int:
        """Check status → service status (healthy.go:116-127)."""
        if self.status in (HEALTHY, SICKLY):
            return svc_mod.ALIVE
        if self.status == UNKNOWN:
            return svc_mod.UNKNOWN
        return svc_mod.UNHEALTHY


# The check-arg template subset the reference supports
# (service_bridge.go:105-127): {{ host }}, {{ container }},
# {{ tcp <port> }}, {{ udp <port> }}.
_TEMPLATE_RE = re.compile(
    r"\{\{\s*(host|container|tcp|udp)(?:\s+(\d+))?\s*\}\}")


class Monitor:
    """healthy.go:33-42, 130-216."""

    # Hard ceiling on check-pool workers — the "few execution threads"
    # budget still bounds the node; the floor keeps small clusters
    # concurrent.
    MIN_POOL_WORKERS = 4
    MAX_POOL_WORKERS = 64

    def __init__(self, default_check_host: str,
                 default_check_endpoint: str = "") -> None:
        self.checks: dict[str, Check] = {}
        self.check_interval = HEALTH_INTERVAL
        self.default_check_host = default_check_host
        self.default_check_endpoint = default_check_endpoint
        self.discovery_fn: Optional[Callable[[], list[Service]]] = None
        self._lock = threading.RLock()
        # Chaos injection hook (sidecar_tpu/chaos/live_inject.py): when
        # set, new checks are wrapped in checks.ChaosChecker so the plan
        # can inject slow/failing endpoints.
        self.fault_injector = None
        # One long-lived BOUNDED pool for the whole monitor, SIZED BY
        # CHECK COUNT (plus hung stragglers) at each tick rather than a
        # fixed 4: a Base Checker has no IO timeout of its own, so a
        # hung endpoint pins a worker past the tick — with a fixed tiny
        # pool, a handful of hung checks permanently starves every
        # healthy check and the whole catalog flaps to UNKNOWN
        # (ADVICE.md r5 medium).  The tick deadline is enforced at the
        # POOL level (the wait() below), never trusted to the checker.
        self._pool_workers = self.MIN_POOL_WORKERS
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._pool_workers,
            thread_name_prefix="health-check")
        # Futures from earlier ticks whose checker is STILL running (the
        # pool can't kill a thread): tracked so the check isn't
        # resubmitted on top of its pinned worker, and so pool sizing
        # accounts for the pinned capacity.
        self._inflight: dict[concurrent.futures.Future, str] = {}

    def _ensure_pool(self, needed: int) -> None:
        """Grow the pool to ``needed`` workers (clamped to
        [MIN, MAX_POOL_WORKERS]).  Growth swaps in a fresh executor and
        abandons the old one without waiting — its pinned workers drain
        on their own; their late results are discarded exactly like the
        reference discards post-deadline check output
        (healthy.go:196-202)."""
        needed = min(self.MAX_POOL_WORKERS,
                     max(self.MIN_POOL_WORKERS, needed))
        if needed <= self._pool_workers:
            return
        old = self._pool
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=needed, thread_name_prefix="health-check")
        self._pool_workers = needed
        old.shutdown(wait=False)

    # -- check management --------------------------------------------------

    def add_check(self, check: Check) -> None:
        with self._lock:
            log.info("Adding health check: %s (ID: %s), Args: %s",
                     check.type, check.id, check.args)
            if self.fault_injector is not None and \
                    check.command is not None and \
                    not isinstance(check.command, ChaosChecker):
                check.command = ChaosChecker(check.command,
                                             self.fault_injector, check.id)
            self.checks[check.id] = check

    def mark_service(self, svc: Service) -> None:
        """healthy.go:149-163."""
        with self._lock:
            check = self.checks.get(svc.id)
            svc.status = (check.service_status() if check is not None
                          else svc_mod.UNKNOWN)

    def services(self) -> list[Service]:
        """Discovery output re-marked with check status — the catalog's
        broadcast source (service_bridge.go:18-37)."""
        if self.discovery_fn is None:
            log.error("Error: discovery_fn not defined!")
            return []
        out = []
        for svc in self.discovery_fn():
            if not svc.id:
                log.error("Error: monitor found empty service ID")
                continue
            self.mark_service(svc)
            out.append(svc)
        return out

    # -- check construction ------------------------------------------------

    def get_command_named(self, name: str) -> Checker:
        """service_bridge.go:72-83."""
        return {
            "HttpGet": HttpGetCmd,
            "External": ExternalCmd,
            "AlwaysSuccessful": AlwaysSuccessfulCmd,
        }.get(name, HttpGetCmd)()

    def default_check_for_service(self, svc: Service) -> Check:
        """HttpGet on the first TCP port at the default endpoint
        (service_bridge.go:48-69)."""
        port = next((p for p in svc.ports if p.type == "tcp"), None)
        if port is None:
            return Check(svc.id, command=AlwaysSuccessfulCmd())
        endpoint = self.default_check_endpoint or DEFAULT_STATUS_ENDPOINT
        url = f"http://{self.default_check_host}:{port.port}{endpoint}"
        return Check(svc.id, type="HttpGet", args=url, status=FAILED,
                     command=HttpGetCmd())

    def template_check_args(self, args: str, svc: Service) -> str:
        """Substitute service info into check args
        (service_bridge.go:105-127): ``{{ host }}``, ``{{ container }}``,
        ``{{ tcp N }}``/``{{ udp N }}`` (ServicePort → mapped port)."""
        def sub(match: re.Match) -> str:
            kind, port = match.group(1), match.group(2)
            if kind == "host":
                return self.default_check_host
            if kind == "container":
                return svc.hostname
            if port is None:
                return match.group(0)
            return str(svc.port_for_service_port(int(port), kind))

        return _TEMPLATE_RE.sub(sub, args)

    def check_for_service(self, svc: Service,
                          disco: Discoverer) -> Check:
        """service_bridge.go:131-141."""
        ctype, args = disco.health_check(svc)
        if not ctype:
            log.warning("Using default check for service %s (id: %s).",
                        svc.name, svc.id)
            check = self.default_check_for_service(svc)
        else:
            check = Check(svc.id, type=ctype, args=args, status=FAILED,
                          command=self.get_command_named(ctype))
        check.args = self.template_check_args(check.args, svc)
        return check

    # -- loops -------------------------------------------------------------

    def watch(self, disco: Discoverer, looper: Looper) -> None:
        """Sync the check set with discovery (service_bridge.go:146-187)."""
        looper.loop(self.watch_step(disco))

    def watch_step(self, disco: Discoverer) -> Callable[[], None]:
        """One tick of :meth:`watch` (scheduler form)."""
        self.discovery_fn = disco.services

        def one() -> None:
            services = disco.services()
            for svc in services:
                with self._lock:
                    have = svc.id in self.checks
                if not have:
                    check = self.check_for_service(svc, disco)
                    if check.command is None:
                        log.error("Attempted to add %s (id: %s) but no "
                                  "check configured!", svc.name, svc.id)
                    else:
                        self.add_check(check)
            live = {svc.id for svc in services}
            with self._lock:
                for cid in list(self.checks):
                    if cid not in live:
                        del self.checks[cid]

        return one

    def run(self, looper: Looper) -> None:
        """Run all checks concurrently each tick, per-check timeout
        interval−1 ms (healthy.go:166-213).

        The tick deadline is enforced at the POOL level: the wait()
        below moves on at the timeout regardless of any checker's own
        IO timeout (a Base Checker has none), scoring stragglers
        UNKNOWN/timeout exactly like the reference discarding late
        results (healthy.go:196-202).  A straggler whose thread is
        still pinned is remembered in ``_inflight``: it is NOT
        resubmitted while pinned (resubmitting a hung check every tick
        is how a fixed pool starves), and the pool is resized to
        runnable + pinned so hung endpoints can never crowd out healthy
        checks.  Checkers that do expose a timeout are additionally
        capped at the tick (same observable status, frees the worker
        sooner), and checks are submitted fastest-history-first."""
        def timed_run(c: Check):
            t0 = time.monotonic()
            try:
                return c.command.run(c.args)
            finally:
                c.last_duration = time.monotonic() - t0
                # Percentiles across ALL checks (the per-check
                # last_duration above only orders submission): a few
                # slow endpoints show up as a fat p99 even while p50
                # stays healthy (docs/metrics.md).
                metrics.histogram("health.check",
                                  c.last_duration * 1000.0)

        def one() -> None:
            with self._lock:
                checks = list(self.checks.values())
            if not checks:
                return
            timeout = max(self.check_interval - 0.001, 0.001)
            for c in checks:
                cmd_timeout = getattr(c.command, "timeout", None)
                if cmd_timeout is not None and cmd_timeout > timeout:
                    c.command.timeout = timeout
            # Reap stragglers that finished since last tick (their
            # results are discarded — they already scored
            # UNKNOWN/timeout the tick they overran).
            self._inflight = {f: cid for f, cid in self._inflight.items()
                              if not f.done()}
            pinned = set(self._inflight.values())
            runnable = [c for c in checks if c.id not in pinned]
            self._ensure_pool(len(runnable) + len(self._inflight))
            if not runnable:
                return
            runnable.sort(key=lambda c: getattr(c, "last_duration", 0.0))
            futures = {self._pool.submit(timed_run, c): c
                       for c in runnable}
            done, not_done = concurrent.futures.wait(
                futures, timeout=timeout)
            for fut in done:
                check = futures[fut]
                try:
                    status, err = fut.result()
                except Exception as exc:  # noqa: BLE001 — check errors are data
                    status, err = UNKNOWN, exc
                check.update_status(status, err)
            # Move on at the timeout like the reference; cancel() frees
            # queued-not-started entries, and entries that are genuinely
            # RUNNING go into _inflight so they aren't resubmitted onto
            # a second worker while the first is still pinned.
            for fut in not_done:
                check = futures[fut]
                log.error("Error, check %s timed out! (%s)", check.id,
                          check.args)
                check.update_status(UNKNOWN, TimeoutError("Timed out!"))
                if not fut.cancel():
                    self._inflight[fut] = check.id

        looper.loop(one)
