"""Health monitoring: checks services before announcing them
(reference: healthy/ package)."""

from sidecar_tpu.health.monitor import (
    Check,
    FAILED,
    HEALTH_INTERVAL,
    HEALTHY,
    Monitor,
    SICKLY,
    UNKNOWN,
    WATCH_INTERVAL,
)
from sidecar_tpu.health.checks import (
    AlwaysSuccessfulCmd,
    Checker,
    ExternalCmd,
    HttpGetCmd,
)

__all__ = [
    "Monitor", "Check", "Checker", "HttpGetCmd", "ExternalCmd",
    "AlwaysSuccessfulCmd", "HEALTHY", "SICKLY", "FAILED", "UNKNOWN",
    "HEALTH_INTERVAL", "WATCH_INTERVAL",
]
