"""Human-readable output helpers (reference: output/output.go:8-31)."""

from __future__ import annotations

from sidecar_tpu.service import NS_PER_SECOND


def time_ago(when_ns: int, ref_ns: int) -> str:
    """Humanized elapsed time, mirroring output.TimeAgo's buckets."""
    if when_ns == 0:
        return "never"
    diff = (ref_ns - when_ns) / NS_PER_SECOND
    if diff < 0:
        return "in the future"
    if diff < 1.5:
        return "1 sec ago"
    if diff < 60:
        return f"{int(diff)} secs ago"
    mins = diff / 60
    if mins < 1.5:
        return "1 min ago"
    if mins < 60:
        return f"{int(mins)} mins ago"
    hours = mins / 60
    if hours < 1.5:
        return "1 hour ago"
    if hours < 24:
        return f"{int(hours)} hours ago"
    days = hours / 24
    if days < 1.5:
        return "1 day ago"
    return f"{int(days)} days ago"
