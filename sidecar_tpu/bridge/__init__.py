"""The Delegate-shaped simulation bridge: a live node asks "simulate my
cluster forward N rounds" (BASELINE.json north star)."""

from sidecar_tpu.bridge.sim_bridge import SimBridge, serve_bridge

__all__ = ["SimBridge", "serve_bridge"]
