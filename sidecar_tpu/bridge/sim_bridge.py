"""Live-catalog ⇄ simulator bridge.

The BASELINE.json north star: expose the TPU gossip simulator behind the
existing Delegate-shaped state interface so a live node (or operator
tooling) can ask "simulate this cluster forward N rounds" — what-if
convergence forecasting the Go reference could never do.

Mapping:

* each catalog server becomes a simulator node; each (server, service)
  becomes a slot (slots padded to a uniform per-node width);
* wall-clock nanosecond ``Updated`` stamps are quantized onto the
  simulator's logical tick clock, preserving order;
* simulated results map back as per-node convergence plus a projected
  catalog view (which records every node would know after N rounds).

The bridge is pull-based (one RPC = one simulation run) so it never
blocks the live gossip path; state is snapshotted at request time.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax
import numpy as np

from sidecar_tpu import metrics
from sidecar_tpu import service as svc_mod
from sidecar_tpu.telemetry import profiling
from sidecar_tpu.catalog import ServicesState
from sidecar_tpu.models.exact import ExactSim, SimParams, SimState
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology as topo_mod
from sidecar_tpu.ops.status import pack, unpack_status, unpack_ts

log = logging.getLogger(__name__)

# Catalog Status values already match the simulator's 3-bit codes
# (service/service.go:17-23 ↔ ops/status.py), so statuses map through
# unchanged.


@dataclasses.dataclass
class BridgeMapping:
    """Index maps from a catalog snapshot."""

    hostnames: list[str]                   # node index → hostname
    slots: list[list[Optional[str]]]       # node index → slot → service id
    t0_ns: int                             # wall-clock origin
    tick_ns: int                           # ns per simulator tick


@dataclasses.dataclass
class SimulationReport:
    rounds: int
    seconds_simulated: float
    convergence: list[float]               # per-round cluster-wide fraction
    eps_round: Optional[int]               # first round ≥ 1-eps
    node_agreement: dict[str, float]       # hostname → final agreement
    projected: dict                        # hostname → {svc id → status str}
    # Per-round changed-belief stream (ops/delta.py), present when the
    # caller asked for it: one entry per round with the (hostname,
    # service id, status) triples that changed, or {"overflow": true}
    # when the round changed more cells than the cap (the consumer's
    # cue to resync from the projected snapshot).
    deltas: Optional[list] = None
    # Multi-chip runs (sharded=True): which board exchange the round
    # used (docs/sharding.md) and how many devices the mesh spanned.
    board_exchange: Optional[str] = None
    devices: Optional[int] = None
    # Sparse-frontier execution record (docs/sparse.md): the per-RUN
    # arbiter counters — mode, sparse/dense round split, overflow
    # fallbacks, switches, frontier high-water mark.  Reported per
    # request (a fresh arbiter per simulate call), so back-to-back
    # POST /simulate calls never bleed counters into each other.
    sparse: Optional[dict] = None
    # Flight-recorder round traces (ops/trace.py, docs/telemetry.md),
    # present when the caller asked for them: ``{"requested": N,
    # "rounds": [...]}`` with one record dict per traced round
    # (frontier/behind/admitted/exchange_bytes/mode/tombstones).
    trace: Optional[dict] = None
    # Suspicion/flap-damping what-if (ops/suspicion.py, docs/chaos.md),
    # present when the caller passed ``protocol``: the effective knob
    # bundle, plus — when damping is enabled — the services the damper
    # would suppress in THIS node's view over the simulated horizon and
    # their flap counts (the sim-side twin of catalog/damping.py,
    # cross-validated in tests/test_damping.py).
    robustness: Optional[dict] = None
    # Record-level provenance (ops/provenance.py, docs/telemetry.md),
    # present when the caller passed ``provenance``: per tracked record
    # the lag CDF / hop histogram / reach summary, the pooled lag
    # percentiles, and the exportable propagation tree — with ABSOLUTE
    # round numbers (chunked dispatches chain the carried trace).
    provenance: Optional[dict] = None
    # Coherence digest stream (ops/digest.py, docs/telemetry.md),
    # present when the caller passed ``digest`` > 0: per digested round
    # the alive/agree census and differing-bucket divergence lower
    # bounds vs the alive-max truth catalog, plus the final digest
    # summary (agreement fraction, per-node differing buckets, and the
    # quorum digest hex — the wire form ``GET /api/digest.json``
    # publishes on the live side).
    digest: Optional[dict] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class SimBridge:
    # Rounds per device dispatch.  Long requests are split into chunks
    # and PIPELINED: chunk i+1 is enqueued (the donated state carries
    # over zero-copy) before chunk i's results are pulled back, so the
    # host-side consumption — convergence bookkeeping and the
    # delta→(hostname, service) mapping — overlaps device compute
    # instead of serializing with it.  Chunking is bit-identical to one
    # long scan (per-round keys fold round_idx; the tested
    # checkpoint/resume contract), and bounded dispatches also keep a
    # tunneled TPU worker's watchdog happy (see sim/scenarios.py).
    CHUNK_ROUNDS = 150

    def __init__(self, state: ServicesState,
                 timecfg: TimeConfig = TimeConfig()) -> None:
        self.state = state
        self.t = timecfg

    # -- state mapping -----------------------------------------------------

    def snapshot(self, sharded: bool = False,
                 board_exchange: Optional[str] = None,
                 timecfg: Optional[TimeConfig] = None
                 ) -> tuple[SimState, SimParams, BridgeMapping, ExactSim]:
        """Freeze the live catalog into simulator tensors.

        Every node starts knowing the full snapshot (the live catalog IS
        the local node's view, already converged from its perspective);
        callers can blank rows to model cold joiners.

        ``sharded=True`` builds the node-axis-sharded twin over every
        attached device instead of the single-chip ExactSim (the
        catalog's node count must divide the mesh); ``board_exchange``
        picks its exchange mode (None → SIDECAR_TPU_BOARD_EXCHANGE,
        docs/sharding.md).  ``timecfg`` overrides the bridge's protocol
        clock for this snapshot — the per-request suspicion-window
        path (ops/suspicion.ProtocolParams)."""
        cfg = timecfg if timecfg is not None else self.t
        with self.state._lock:
            servers = {h: dict(server.services)
                       for h, server in self.state.servers.items()}
        if not servers:
            raise ValueError("empty catalog: nothing to simulate")

        hostnames = sorted(servers)
        spn = max(len(svcs) for svcs in servers.values())
        n = len(hostnames)

        all_updates = [svc.updated
                       for svcs in servers.values()
                       for svc in svcs.values()]
        t0 = min(all_updates)
        tick_ns = int(cfg.round_ticks / cfg.ticks_per_second * 1e9
                      / cfg.round_ticks)  # 1 tick in ns (1 ms default)

        slots: list[list[Optional[str]]] = []
        owned_vals = np.zeros((n, spn), dtype=np.int64)
        for ni, hostname in enumerate(hostnames):
            row: list[Optional[str]] = []
            for si, (sid, svc) in enumerate(sorted(servers[hostname]
                                                   .items())):
                # Ticks start at 1 (0 is the unknown sentinel).
                tick = max(1, (svc.updated - t0) // tick_ns + 1)
                owned_vals[ni, si] = int(pack(int(tick), svc.status))
                row.append(sid)
            row.extend([None] * (spn - len(row)))
            slots.append(row)

        params = SimParams(n=n, services_per_node=spn)
        if sharded:
            from sidecar_tpu.parallel.sharded import ShardedSim
            sim = ShardedSim(params, topo_mod.complete(n), cfg,
                             board_exchange=board_exchange)
        else:
            sim = ExactSim(params, topo_mod.complete(n), cfg)
        state = sim.init_state()
        # Overwrite the cold-start rows: every node knows the snapshot.
        known = np.tile(owned_vals.reshape(-1).astype(np.int32), (n, 1))
        state = dataclasses.replace(
            state, known=self._put_known(sim, known))
        mapping = BridgeMapping(hostnames=hostnames, slots=slots,
                                t0_ns=t0, tick_ns=tick_ns)
        return state, params, mapping, sim

    @staticmethod
    def _put_known(sim, known: np.ndarray):
        """Place a host-side belief matrix with the sim's canonical
        layout (row-sharded on the sharded twin, single-device
        otherwise)."""
        arr = jax.numpy.asarray(known.astype(np.int32))
        row_sharding = getattr(sim, "_row_sharding", None)
        if row_sharding is not None:
            arr = jax.device_put(arr, row_sharding)
        return arr

    # -- the RPC -----------------------------------------------------------

    def simulate(self, rounds: int, seed: int = 0,
                 cold_nodes: Optional[list[str]] = None,
                 eps: float = 0.01,
                 deltas_cap: int = 0,
                 sharded: bool = False,
                 board_exchange: Optional[str] = None,
                 sparse: Optional[bool] = None,
                 trace: int = 0,
                 digest: int = 0,
                 digest_buckets: int = 0,
                 protocol=None,
                 provenance: Optional[dict] = None) -> SimulationReport:
        """Run the catalog forward ``rounds`` gossip rounds.

        ``cold_nodes``: hostnames whose knowledge is blanked to their own
        records first — models fresh joiners (the join push-pull and
        epidemic spread then have to re-teach them).

        ``deltas_cap`` > 0 streams the per-round changed-belief sets out
        of the ``lax.scan`` (ExactSim.run_with_deltas → ops/delta.py)
        instead of reporting only the terminal projection: each round's
        changed cells are mapped back through the BridgeMapping to
        (hostname, service id, status) triples — the query plane's
        delta contract applied to simulated futures.

        ``sharded=True`` runs the multi-chip twin (node count must
        divide the device mesh); ``board_exchange`` selects its
        exchange mode (all_gather | ring; None → the
        SIDECAR_TPU_BOARD_EXCHANGE env contract, docs/sharding.md).
        Delta streaming stays single-chip: the two options are
        mutually exclusive.

        ``sparse`` selects the sparse-frontier round (docs/sparse.md):
        ``True``/``False`` force it per request; ``None`` follows the
        ``SIDECAR_TPU_SPARSE`` contract — under ``auto`` a per-request
        arbiter picks dense vs sparse at each ``CHUNK_ROUNDS`` boundary
        from the convergence census the pipeline already pulls, with
        hysteresis and the frontier-overflow→dense fallback.  The
        report's ``sparse`` block carries the per-RUN counters.

        ``trace`` > 0 records the flight-recorder stream for the first
        ``trace`` rounds (``run_with_trace`` → ops/trace.py): one
        record per round — frontier size, behind census, offers
        admitted, analytic exchange bytes, sparse/dense mode, overflow
        flag, tombstone count — in the report's ``trace`` block.
        Available on both the single-chip and sharded twins; mutually
        exclusive with ``deltas_cap`` (one scan streams one record
        kind).

        ``digest`` > 0 records the coherence-digest stream for the
        first ``digest`` rounds (``run_with_digest`` → ops/digest.py):
        per round the alive/agree census and differing-bucket
        divergence lower bounds vs the alive-max truth catalog, under
        the ONE digest definition the live cluster maintains
        incrementally — slot identities come from the snapshot's
        (hostname, service id) mapping via ``ident_of``, so the
        report's digests are directly comparable with the live
        ``GET /api/digest.json``.  ``digest_buckets`` overrides the
        bucket count (0 → the shared default; must be a power of two).
        Available on both the single-chip and sharded twins; mutually
        exclusive with ``deltas_cap``, ``trace``, and ``provenance``
        (one scan streams/carries one record kind).

        ``protocol`` (an :class:`ops.suspicion.ProtocolParams` or its
        dict form — the ``POST /simulate`` surface) runs the request
        under those suspicion/damping knobs: the suspicion window is
        threaded into the jitted round via a per-request TimeConfig,
        and with ``damping_threshold > 0`` the report's ``robustness``
        block predicts which services THIS node's flap damper
        (catalog/damping.py) would suppress over the horizon — the sim
        side of the sim↔live damping cross-validation
        (tests/test_damping.py).  Damping prediction consumes the
        delta stream, so it is single-chip only (like ``deltas_cap``)
        and raises with ``sharded=True``.

        ``provenance`` turns on the record-level tracer
        (ops/provenance.py, docs/telemetry.md): ``{"count": T}``
        spreads T tracers evenly over the catalog's real records, or
        ``{"services": [{"node": host, "service": id}, ...]}`` names
        them; optional ``"cap"`` bounds the per-round coverage window
        (default: ``rounds``).  The report's ``provenance`` block
        carries per-record lag CDFs, hop histograms, the pooled lag
        percentiles, and the propagation tree — round numbers are
        ABSOLUTE across the chunked dispatch (the carried ProvTrace
        chains chunk to chunk).  Works on both the single-chip and
        sharded twins and under forced/auto sparse; mutually
        exclusive with ``deltas_cap``, ``trace``, and damping
        prediction (one scan carries one extra stream)."""
        from sidecar_tpu.ops.suspicion import ProtocolParams

        if protocol is not None and not isinstance(protocol,
                                                   ProtocolParams):
            protocol = ProtocolParams.from_json(protocol)
        damping_on = protocol is not None and \
            protocol.damping_threshold > 0
        if sharded and damping_on:
            raise ValueError(
                "damping prediction consumes the delta stream and is "
                "single-chip only (like deltas_cap); drop sharded=True "
                "or the damping_threshold")
        if sharded and deltas_cap > 0:
            raise ValueError(
                "deltas_cap > 0 is not supported with sharded=True "
                "(delta extraction runs on the single-chip model)")
        if trace > 0 and deltas_cap > 0:
            raise ValueError(
                "trace and deltas_cap are mutually exclusive "
                "(one scan streams one record kind)")
        if trace > 0 and damping_on:
            raise ValueError(
                "trace and damping prediction are mutually exclusive "
                "(damping consumes the delta stream; one scan streams "
                "one record kind)")
        prov_on = provenance is not None
        if prov_on and not isinstance(provenance, dict):
            raise ValueError(
                "'provenance' must be an object: {\"count\": T} or "
                "{\"services\": [{\"node\": ..., \"service\": ...}]}, "
                "optional \"cap\"")
        if prov_on and deltas_cap > 0:
            raise ValueError(
                "provenance and deltas_cap are mutually exclusive "
                "(one scan carries one extra stream)")
        if prov_on and trace > 0:
            raise ValueError(
                "provenance and trace are mutually exclusive "
                "(one scan carries one extra stream)")
        if prov_on and damping_on:
            raise ValueError(
                "provenance and damping prediction are mutually "
                "exclusive (damping consumes the delta stream; one "
                "scan carries one extra stream)")
        from sidecar_tpu.ops import digest as digest_ops
        if digest > 0:
            # Fail fast on a bad bucket count (power-of-two contract).
            digest_buckets = digest_buckets or digest_ops.DEFAULT_BUCKETS
            digest_ops.bucket_ids_np(np.zeros(1, np.uint32),
                                     digest_buckets)
        if digest > 0 and deltas_cap > 0:
            raise ValueError(
                "digest and deltas_cap are mutually exclusive "
                "(one scan streams one record kind)")
        if digest > 0 and trace > 0:
            raise ValueError(
                "digest and trace are mutually exclusive "
                "(one scan streams one record kind)")
        if digest > 0 and prov_on:
            raise ValueError(
                "digest and provenance are mutually exclusive "
                "(one scan carries one extra stream)")
        if digest > 0 and damping_on:
            raise ValueError(
                "digest and damping prediction are mutually exclusive "
                "(damping consumes the delta stream; one scan streams "
                "one record kind)")
        # Damping prediction needs the per-round change stream even when
        # the caller didn't ask for deltas in the report.
        report_deltas = deltas_cap > 0
        if damping_on and deltas_cap == 0:
            deltas_cap = 4096
        t_req = time.perf_counter()
        state, params, mapping, sim = self.snapshot(
            sharded=sharded, board_exchange=board_exchange,
            timecfg=(protocol.timecfg(self.t)
                     if protocol is not None else None))

        if cold_nodes:
            known = np.asarray(state.known).copy()
            spn = params.services_per_node
            for hostname in cold_nodes:
                if hostname not in mapping.hostnames:
                    raise KeyError(hostname)
                ni = mapping.hostnames.index(hostname)
                own = known[ni, ni * spn:(ni + 1) * spn].copy()
                known[ni, :] = 0
                known[ni, ni * spn:(ni + 1) * spn] = own
            state = dataclasses.replace(state,
                                        known=self._put_known(sim, known))

        tracked: tuple = ()
        prov_cap = 0
        if prov_on:
            tracked, prov_cap = self._resolve_tracked(
                provenance, params, mapping, rounds)

        # Digest identities from the snapshot's canonical (hostname,
        # service id) mapping — the live path's ident_of, so sim and
        # live digests bucket the same records identically.  Padding
        # slots get synthetic names; their cells stay unknown (packed
        # 0) and never contribute.
        dig_idents = None
        if digest > 0:
            dig_idents = digest_ops.catalog_idents(
                (hostname, sid if sid is not None else f"\x00pad{si}")
                for ni, hostname in enumerate(mapping.hostnames)
                for si, sid in enumerate(mapping.slots[ni]))

        key = jax.random.PRNGKey(seed)
        sizes = []
        left = rounds
        while left > 0:
            sizes.append(min(self.CHUNK_ROUNDS, left))
            left -= sizes[-1]

        # Per-request sparse arbiter (docs/sparse.md): counters are
        # per-RUN by construction — a fresh arbiter per simulate call
        # (the watermark-reset contract of sync_exchange_metrics,
        # applied from the start).  Census: the chunk's terminal
        # convergence mapped back to a behind estimate.
        from sidecar_tpu.ops import sparse as sparse_ops
        if sparse is None:
            sparse_mode = sparse_ops.resolve_sparse(record=False)
        else:
            sparse_mode = "1" if sparse else "0"
        arbiter = sparse_ops.SparseArbiter.for_census(
            sparse_mode, params.n)
        nm = float(params.n) * float(params.m)

        def dispatch(st, n_rounds, start):
            # start_round: the host-side round counter — reading the
            # in-flight state's round_idx would block the pipeline.
            # The mode is passed EXPLICITLY both ways (an omitted
            # sparse= would resolve the sim's env default and defeat
            # the per-request {"sparse": false} forcing contract).
            use_sparse = arbiter.sparse
            kw = arbiter.dispatch_kwargs()
            # Rounds of THIS chunk inside the trace/digest budget:
            # chunks past it dispatch the plain program.
            traced_n = max(0, min(trace - start, n_rounds)) \
                if trace > 0 else 0
            digested_n = max(0, min(digest - start, n_rounds)) \
                if digest > 0 else 0
            with profiling.annotate("sidecar.bridge.dispatch"):
                if prov_on:
                    # The carried ProvTrace chains chunk→chunk through
                    # the mutable box: run_with_provenance donates the
                    # previous chunk's buffers and the returned trace
                    # is an async future, so dispatch stays pipelined.
                    out = sim.run_with_provenance(
                        st, key, n_rounds, tracked, cap=prov_cap,
                        prov=prov_box[0], start_round=start, **kw)
                    prov_box[0] = out[1]
                elif deltas_cap > 0:
                    out = sim.run_with_deltas(
                        st, key, n_rounds, deltas_cap,
                        start_round=start, **kw)
                elif traced_n > 0:
                    out = sim.run_with_trace(
                        st, key, n_rounds, cap=traced_n,
                        start_round=start, **kw)
                elif digested_n > 0:
                    out = sim.run_with_digest(
                        st, key, n_rounds, cap=digested_n,
                        buckets=digest_buckets, idents=dig_idents,
                        start_round=start, **kw)
                else:
                    out = sim.run(st, key, n_rounds, start_round=start,
                                  **kw)
            return out + ((sim.last_sparse_stats if use_sparse
                           else None),), (traced_n > 0, digested_n > 0)

        delta_stream = [] if deltas_cap > 0 else None
        trace_rounds = [] if trace > 0 else None
        digest_rounds = [] if digest > 0 else None
        prov_box = [None]
        conv_parts = []

        def consume(out, start, n_rounds, flags):
            from sidecar_tpu.ops import trace as trace_ops

            traced, digested = flags
            t0 = time.perf_counter()
            stats = out[-1]
            out = out[:-1]
            if deltas_cap > 0:
                final, batches, conv = out
                delta_stream.extend(self._map_deltas(
                    batches, mapping, params, len(conv),
                    start_round=start))
            elif traced:
                final, tr, conv = out
                trace_rounds.extend(trace_ops.trace_to_dicts(tr))
            elif digested:
                final, dtr, conv = out
                digest_rounds.extend(digest_ops.digest_to_dicts(dtr))
            elif prov_on:
                # The cumulative trace lives in prov_box (the chained
                # carry); each chunk only contributes its conv slice.
                final, _pv, conv = out
            else:
                final, conv = out
            conv_h = np.asarray(jax.device_get(conv))
            conv_parts.append(conv_h)
            arbiter.record_chunk(
                n_rounds, None if stats is None
                else np.asarray(jax.device_get(stats)))
            arbiter.update_census((1.0 - float(conv_h[-1])) * nm)
            # Chunk wall time measured at consumption (the device_get
            # above drains this chunk's compute) — docs/metrics.md.
            metrics.histogram_since("bridge.chunk", t0)
            return final

        # Each pending chunk carries its own start round — no reliance
        # on uniform chunk sizes.
        (pend, pend_tr), pend_start, pend_n = \
            dispatch(state, sizes[0], 0), 0, sizes[0]
        done = sizes[0]
        for n_rounds in sizes[1:]:
            (nxt, nxt_tr), nxt_start = dispatch(pend[0], n_rounds,
                                                done), done
            done += n_rounds
            consume(pend, pend_start, pend_n, pend_tr)
            pend, pend_tr, pend_start, pend_n = nxt, nxt_tr, \
                nxt_start, n_rounds
        final = consume(pend, pend_start, pend_n, pend_tr)
        conv = np.concatenate(conv_parts)
        known = np.asarray(final.known)

        truth = known.max(axis=0)
        agree = (known == truth[None, :]).mean(axis=1)
        node_agreement = {h: float(agree[i])
                          for i, h in enumerate(mapping.hostnames)}

        projected: dict = {}
        spn = params.services_per_node
        for ni, hostname in enumerate(mapping.hostnames):
            view = {}
            for oi, owner_host in enumerate(mapping.hostnames):
                for si, sid in enumerate(mapping.slots[oi]):
                    if sid is None:
                        continue
                    cell = int(known[ni, oi * spn + si])
                    if unpack_ts(np.int32(cell)) > 0:
                        view[sid] = svc_mod.status_string(
                            int(unpack_status(np.int32(cell))))
            projected[hostname] = view

        robustness = None
        if protocol is not None:
            robustness = {"protocol": protocol.to_json()}
            if damping_on:
                robustness.update(self._predict_damping(
                    protocol, delta_stream, mapping))

        prov_doc = None
        if prov_on:
            prov_doc = self._prov_report(prov_box[0], tracked, params,
                                         mapping)

        digest_doc = None
        if digest > 0:
            digest_doc = self._digest_report(
                digest, digest_buckets, digest_rounds, known,
                np.asarray(final.node_alive), dig_idents, mapping)

        hits = np.nonzero(conv >= 1.0 - eps)[0]
        metrics.histogram_since("bridge.simulate", t_req)
        return SimulationReport(
            rounds=rounds,
            seconds_simulated=rounds * sim.t.round_ticks
            / sim.t.ticks_per_second,
            convergence=[float(c) for c in conv],
            eps_round=int(hits[0]) + 1 if hits.size else None,
            node_agreement=node_agreement,
            projected=projected,
            deltas=delta_stream if report_deltas else None,
            board_exchange=sim.board_exchange if sharded else None,
            devices=sim.d if sharded else None,
            sparse={"mode": sparse_mode, **arbiter.snapshot()},
            trace=(None if trace_rounds is None
                   else {"requested": trace, "rounds": trace_rounds}),
            robustness=robustness,
            provenance=prov_doc,
            digest=digest_doc,
        )

    @staticmethod
    def _digest_report(requested: int, buckets: int, rounds_doc: list,
                       known: np.ndarray, alive: np.ndarray, idents,
                       mapping: BridgeMapping) -> dict:
        """The report's ``digest`` block: the per-round stream plus a
        final-state summary computed with the NumPy oracle (one O(N·M)
        pass on the already-fetched belief matrix) — agreement vs the
        alive-max truth catalog, per-node differing-bucket lower
        bounds, and the quorum digest in the live wire form."""
        from sidecar_tpu.ops import digest as digest_ops

        digs = digest_ops.node_digests_np(known, idents, buckets)
        truth = np.where(alive[:, None], known, 0).max(
            axis=0, keepdims=True)
        ref = digest_ops.node_digests_np(truth, idents, buckets)[0]
        diffs = digest_ops.diff_counts_np(digs, ref)
        alive_n = int(alive.sum())
        agree = int(((diffs == 0) & alive).sum())
        return {
            "requested": requested,
            "buckets": buckets,
            "rounds": rounds_doc,
            "final": {
                "agreement": (agree / alive_n) if alive_n else 1.0,
                "diff_total": int(diffs[alive].sum()),
                "diff_max": int(diffs[alive].max()) if alive_n else 0,
                "quorum_hex": digest_ops.digest_to_hex(ref),
                "node_diff_buckets": {
                    h: int(diffs[i])
                    for i, h in enumerate(mapping.hostnames)},
            },
        }

    @staticmethod
    def _resolve_tracked(req: dict, params: SimParams,
                         mapping: BridgeMapping,
                         rounds: int) -> tuple[tuple, int]:
        """Resolve a wire ``provenance`` object to (tracked slots,
        coverage cap).  ``{"count": T}`` spreads T tracers evenly over
        the REAL records (padded slots hold nothing and would only
        dilute the lag CDF); ``{"services": [...]}`` names records as
        (hostname, service id) pairs.  Unknown keys and unknown
        services are 400s at the HTTP surface."""
        from sidecar_tpu.ops import provenance as prov_ops

        unknown = set(req) - {"count", "services", "cap"}
        if unknown:
            raise ValueError(
                f"provenance: unknown key(s) {sorted(unknown)}; "
                "expected 'count' or 'services', optional 'cap'")
        cap = int(req.get("cap", 0))
        if cap < 0:
            raise ValueError(f"provenance.cap={cap} must be >= 0")
        cap = cap or rounds
        spn = params.services_per_node
        if "services" in req:
            ents = req["services"]
            if not isinstance(ents, list) or not ents:
                raise ValueError(
                    "provenance.services must be a non-empty list of "
                    "{\"node\": hostname, \"service\": id} objects")
            slots = set()
            for ent in ents:
                host, sid = ent["node"], ent["service"]
                if host not in mapping.hostnames:
                    raise KeyError(host)
                ni = mapping.hostnames.index(host)
                if sid not in mapping.slots[ni]:
                    raise KeyError(f"{host}/{sid}")
                slots.add(ni * spn + mapping.slots[ni].index(sid))
            return tuple(sorted(slots)), cap
        count = int(req.get("count", 8))
        if count < 1:
            raise ValueError(
                f"provenance.count={count} must be >= 1")
        real = [ni * spn + si
                for ni in range(len(mapping.hostnames))
                for si, sid in enumerate(mapping.slots[ni])
                if sid is not None]
        picks = prov_ops.default_tracked(len(real),
                                         min(count, len(real)))
        return tuple(sorted({real[p] for p in picks})), cap

    @staticmethod
    def _prov_report(prov, tracked: tuple, params: SimParams,
                     mapping: BridgeMapping) -> dict:
        """Reduce the finished ProvTrace into the report block:
        summarize + the exportable tree, with each tracked slot mapped
        back to its (hostname, service id) identity."""
        from sidecar_tpu.ops import provenance as prov_ops

        spn = params.services_per_node
        doc = prov_ops.summarize(prov, tracked, spn)
        for rec in doc["records"]:
            slot = rec["slot"]
            rec["node"] = mapping.hostnames[slot // spn]
            rec["service"] = mapping.slots[slot // spn][slot % spn]
        doc["tree"] = prov_ops.tree_to_dict(prov, tracked)
        return doc

    def _predict_damping(self, protocol, delta_stream,
                         mapping: BridgeMapping) -> dict:
        """Replay the simulated change stream through THE live damper
        implementation (catalog/damping.py) as observed from this
        node's own view — the sim-side twin of the catalog hook, on a
        logical clock derived from simulated ticks.

        Replay rules (SUSPECT quarantine invisible, discovery not a
        flap) live in ONE place — ``catalog.damping.TransitionReplay``
        — shared with the bench robustness harness and the
        cross-validation tests.  A delta round that overflowed its cap
        carries no change list; those rounds' flaps are unobservable
        and the count is REPORTED as ``delta_overflow_rounds`` (the
        DeltaBatch contract: truncation is surfaced, never silent)."""
        from sidecar_tpu.catalog.damping import FlapDamper, TransitionReplay

        observer = self.state.hostname \
            if self.state.hostname in mapping.hostnames \
            else mapping.hostnames[0]
        # Codes 0..5 have distinct names; higher codes alias to the
        # "Tombstone" fallback and must not clobber the real code 1.
        code_of = {svc_mod.status_string(c): c for c in range(6)}

        end_ns = mapping.t0_ns
        damper = FlapDamper.from_protocol(
            protocol, now_fn=lambda: end_ns)
        replay = TransitionReplay(damper)

        # Initial view + record ownership from the live catalog (the
        # snapshot the simulation started from).
        owner_of: dict[str, str] = {}
        with self.state._lock:
            for host, server in self.state.servers.items():
                for sid, svc in server.services.items():
                    replay.prime(sid, svc.status)
                    owner_of[sid] = host

        overflow_rounds = 0
        for round_doc in delta_stream or ():
            if round_doc.get("overflow"):
                overflow_rounds += 1
                continue
            for ch in round_doc.get("changes", ()):
                if ch["node"] != observer:
                    continue
                sid = ch["service"]
                st = code_of.get(ch["status"])
                if st is None:
                    continue
                now_ns = mapping.t0_ns + ch["tick"] * mapping.tick_ns
                end_ns = max(end_ns, now_ns)
                replay.see(owner_of.get(sid, observer), sid, st, now_ns)

        damped = sorted(f"{h}/{sid}" for h, sid in damper.damped(end_ns))
        return {"observer": observer, "damped": damped,
                "flaps": replay.flaps,
                "delta_overflow_rounds": overflow_rounds}

    # -- the capacity-planning sweep (docs/sweep.md) -----------------------

    def sweep(self, axes: dict, *, rounds: int = 200, eps: float = 0.01,
              n: Optional[int] = None, services_per_node: int = 4,
              fanout: int = 3, budget: int = 15, seed: int = 0,
              conv_every: int = 1, stop: bool = True,
              base: Optional[dict] = None,
              max_batch: Optional[int] = None,
              provenance: int = 8, slo=None) -> dict:
        """Evaluate a protocol-configuration grid in batched fleet
        dispatches (sidecar_tpu/fleet) and return the Pareto table.

        ``axes`` is the grid spec (axis name → value list,
        ``fleet/grid.KNOWN_AXES``); ``base`` fixes spec fields every
        point shares.  Scenarios are synthetic cold-start clusters of
        ``n`` nodes (default: the live catalog's node count, so the
        sweep plans capacity for THIS cluster's shape) on the exact
        model.  Compile-key axes (fanout, budget, topology — an
        ``ops/topology.from_name`` overlay) group into separate
        batches; data axes vary within one compiled scan.  Each row
        reports rounds/seconds-to-ε and the analytic exchange bytes
        spent getting there (early exit freezes both at the crossing);
        ``pareto_front`` lists the non-dominated configs on
        (rounds_to_eps, exchange_bytes).

        ``provenance`` tracers (default 8, 0 disables) ride every
        fleet dispatch (fleet/engine.py first_seen provenance,
        docs/telemetry.md), adding a per-scenario ``p99_lag_rounds``
        column to the table — the capacity-planning answer to "which
        config meets the lag SLO", not just "which converges".

        ``slo`` (optional list of ``telemetry/slo.py`` rule strings —
        "converge <= 5 s", "agreement >= 0.99", "p99 <= 12 rounds")
        adds a per-row ``slo`` verdict block via
        ``SloEvaluator.evaluate_row`` — the SAME evaluation contract
        the autopilot's objective minimizes (docs/autopilot.md).
        Malformed rules raise ``ValueError`` before any dispatch (a
        parseable 400 on the HTTP surface).

        Each phase of the dispatch path records a span
        (``bridge.sweep.expand`` → ``.build`` → ``.run`` →
        ``.pareto``) into the /api/trace ring, and the request's grid
        size lands in the ``bridge.sweep.points`` histogram."""
        from sidecar_tpu.fleet import FleetSim, expand_grid
        from sidecar_tpu.fleet.grid import pareto_front
        from sidecar_tpu.ops import provenance as prov_ops
        from sidecar_tpu.telemetry.span import span as _span

        if n is None:
            with self.state._lock:
                n = len(self.state.servers)
            n = max(n, 8)
        if rounds < 1:
            raise ValueError(f"rounds={rounds} must be >= 1")
        if conv_every < 1 or rounds % conv_every:
            raise ValueError(
                f"rounds={rounds} must be a positive multiple of "
                f"conv_every={conv_every}")
        base = dict(base or {})
        base.setdefault("seed", seed)
        # Process-wide default overlay for sweep points that don't name
        # one (docs/topology.md); an explicit base/axis value wins.
        env_topo = os.environ.get("SIDECAR_TPU_TOPOLOGY", "").strip()
        if env_topo and "topology" not in axes:
            base.setdefault("topology", env_topo)
        # Overlay names are validated BEFORE the grid expands — an
        # unknown/invalid name is a named 400 up front, not a compile
        # failure batches into the dispatch loop.
        tvals = axes.get("topology")
        tvals = list(tvals) if isinstance(tvals, (list, tuple)) else []
        if base.get("topology"):
            tvals.append(base["topology"])
        for t_name in dict.fromkeys(tvals):
            topo_mod.from_name(str(t_name), int(n))  # ValueError → 400
        # Cadence axes are validated BEFORE the grid expands, like the
        # overlay names above — a malformed tick_period/tick_phase is a
        # named 400 up front, not a spec error pt047 deep into the
        # expansion (docs/pipeline.md).
        for ax, floor in (("tick_period", 1), ("tick_phase", 0)):
            vals = axes.get(ax)
            vals = list(vals) if isinstance(vals, (list, tuple)) else []
            if base.get(ax) is not None:
                vals.append(base[ax])
            for v in vals:
                if isinstance(v, bool) or not isinstance(v, int) \
                        or v < floor:
                    raise ValueError(
                        f"{ax}={v!r} must be an int >= {floor} "
                        "(per-node gossip cadence in rounds, "
                        "docs/pipeline.md)")
        # Library-only axes get a NAMED rejection here rather than the
        # batch builder's family/plan error: the HTTP surface has no
        # way to supply a FaultPlan structure or select the compressed
        # family (docs/sweep.md).
        wire_only = {"fault_seed", "mint_frac"} & (set(axes) | set(base))
        if wire_only:
            raise ValueError(
                f"axis(es) {sorted(wire_only)} are library-only: "
                "fault_seed needs a shared FaultPlan structure and "
                "mint_frac the compressed family — build a "
                "ScenarioBatch directly (sidecar_tpu/fleet, "
                "docs/sweep.md); POST /sweep runs the plain exact "
                "family")
        if provenance < 0:
            raise ValueError(
                f"provenance={provenance} must be >= 0 (tracer count; "
                "0 disables the lag column)")
        # SLO rules parse BEFORE any dispatch: a malformed rule is a
        # named 400 up front, not a failure after the grid ran.
        evaluator = None
        if slo is not None:
            from sidecar_tpu.telemetry.slo import SloEvaluator
            if not isinstance(slo, (list, tuple)) or not slo or \
                    not all(isinstance(r, str) for r in slo):
                raise ValueError(
                    "'slo' must be a non-empty list of rule strings "
                    "(telemetry/slo.py grammar, e.g. "
                    "'converge <= 5 s', 'agreement >= 0.99')")
            evaluator = SloEvaluator(slo)   # ValueError → 400
        t_req = time.perf_counter()
        with _span("bridge.sweep.expand"):
            specs = expand_grid(axes, base)
        params = SimParams(n=int(n),
                           services_per_node=int(services_per_node),
                           fanout=int(fanout), budget=int(budget))
        tracked = prov_ops.default_tracked(
            params.m, int(provenance)) if provenance else ()
        # Cold-start study clock: refresh pinned out so rounds-to-ε
        # measures pure epidemic spread (the sim/scenarios convention).
        cfg = dataclasses.replace(self.t, refresh_interval_s=10_000.0)
        # Grid size per request — the capacity signal for sizing
        # max_batch and the fleet (docs/metrics.md: a count histogram,
        # not a latency).
        metrics.histogram("bridge.sweep.points", float(len(specs)))

        table: list = [None] * len(specs)
        batches = 0
        with _span("bridge.sweep.build"):
            built = list(self._build_sweep_batches(
                specs, params, cfg, max_batch))
        for batch, idxs in built:
            fleet = FleetSim(batch)
            with _span("bridge.sweep.run"):
                run = fleet.run(fleet.init_states(), rounds,
                                conv_every=conv_every, eps=eps,
                                stop=stop, tracked=tracked)
                rows = run.table(cfg.round_ticks, cfg.ticks_per_second)
            for j, src_idx in enumerate(idxs):
                rows[j]["config"] = batch.specs[j].axes()
                if evaluator is not None:
                    rows[j]["slo"] = evaluator.evaluate_row(
                        rows[j], lag=run.lag_summary(j),
                        seconds_per_round=(cfg.round_ticks
                                           / cfg.ticks_per_second),
                        publish=False)
                table[src_idx] = rows[j]
            batches += 1
        with _span("bridge.sweep.pareto"):
            front = pareto_front(table)
        wall = time.perf_counter() - t_req
        metrics.histogram_since("bridge.sweep", t_req)
        return {
            "points": len(specs),
            "batches": batches,
            "provenance": int(provenance),
            "n": int(n),
            "services_per_node": int(services_per_node),
            "rounds": rounds,
            "eps": eps,
            "stop": bool(stop),
            "wall_seconds": round(wall, 3),
            "scenarios_per_sec": round(len(specs) / wall, 2)
            if wall > 0 else None,
            "table": table,
            "pareto_front": list(front),
            # Rows the front refused to consider (never reached ε
            # within the horizon) — counted, never silently dropped
            # (fleet/grid.ParetoFront.excluded).
            "pareto_excluded": {"count": len(front.excluded),
                                "indices": list(front.excluded)},
            **({"slo_rules": [r.text() for r in evaluator.rules]}
               if evaluator is not None else {}),
        }

    @staticmethod
    def _build_sweep_batches(specs, params, cfg, max_batch):
        from sidecar_tpu.fleet import build_batches
        return build_batches(specs, params, cfg, family="exact",
                             max_batch=max_batch)

    # -- the autopilot loop (docs/autopilot.md) ----------------------------

    def autopilot_recommend(self, req: dict) -> dict:
        """``POST /autopilot/recommend``: one pass of the digital-twin
        control loop (sidecar_tpu/autopilot) — fit current conditions
        (or take the request's ``estimate``), search the knob space
        against the request's ``rules``, replay-verify the winner, and
        recommend (apply only behind ``SIDECAR_TPU_AUTOPILOT_APPLY``).
        Malformed rules/axes/estimates raise ``ValueError`` — a
        parseable 400."""
        from sidecar_tpu.autopilot import AutopilotController

        allowed = {"rules", "axes", "estimate", "rounds", "eps", "n",
                   "services_per_node", "fanout", "budget", "seed",
                   "seed_grid", "generations", "population", "elites",
                   "apply", "provenance"}
        bad = set(req) - allowed
        if bad:
            raise ValueError(
                f"unknown autopilot field(s) {sorted(bad)}; expected "
                f"a subset of {sorted(allowed)}")
        n = req.get("n")
        rounds = req.get("rounds")
        generations = req.get("generations")
        population = req.get("population")
        ctl = AutopilotController(bridge=self)
        return ctl.recommend(
            rules=req.get("rules"),
            axes=req.get("axes"),
            estimate=req.get("estimate"),
            rounds=None if rounds is None else int(rounds),
            eps=float(req.get("eps", 0.01)),
            n=None if n is None else int(n),
            services_per_node=int(req.get("services_per_node", 4)),
            fanout=int(req.get("fanout", 3)),
            budget=int(req.get("budget", 15)),
            seed=int(req.get("seed", 0)),
            seed_grid=int(req.get("seed_grid", 2)),
            generations=None if generations is None
            else int(generations),
            population=None if population is None
            else int(population),
            elites=int(req.get("elites", 2)),
            apply=bool(req.get("apply", False)),
            provenance=int(req.get("provenance", 0)))

    @staticmethod
    def _map_deltas(batches, mapping: BridgeMapping, params: SimParams,
                    rounds: int, start_round: int = 0) -> list:
        """DeltaBatch stream [rounds, cap] → per-round (hostname,
        service id, status) change lists.  Padded slots in an owner's
        run have no service id and are dropped (they can only change
        through announce of real records, so in practice none appear).
        ``start_round`` offsets the reported round numbers for chunked
        callers."""
        spn = params.services_per_node
        count = np.asarray(jax.device_get(batches.count))
        node = np.asarray(jax.device_get(batches.node))
        slot = np.asarray(jax.device_get(batches.slot))
        val = np.asarray(jax.device_get(batches.val))
        overflow = np.asarray(jax.device_get(batches.overflow))
        out = []
        for r in range(rounds):
            if bool(overflow[r]):
                out.append({"round": start_round + r + 1, "overflow": True,
                            "count": int(count[r])})
                continue
            changes = []
            for ni, si, v in zip(node[r], slot[r], val[r]):
                if ni < 0:
                    continue
                sid = mapping.slots[si // spn][si % spn]
                if sid is None:
                    continue
                changes.append({
                    "node": mapping.hostnames[ni],
                    "service": sid,
                    "status": svc_mod.status_string(
                        int(unpack_status(np.int32(v)))),
                    "tick": int(unpack_ts(np.int32(v))),
                })
            out.append({"round": start_round + r + 1, "overflow": False,
                        "count": int(count[r]), "changes": changes})
        return out


def serve_bridge(bridge: SimBridge, bind: str = "127.0.0.1",
                 port: int = 7778,
                 background: bool = True) -> ThreadingHTTPServer:
    """POST /simulate {"rounds": N, "seed": S, "cold_nodes": [...],
    "sharded": bool, "board_exchange": "all_gather"|"ring",
    "sparse": bool|null (null → SIDECAR_TPU_SPARSE / arbiter),
    "trace": N (flight-recorder records for the first N rounds —
    docs/telemetry.md),
    "provenance": {"count": T} | {"services": [{"node": host,
    "service": id}, ...]} with optional "cap" (record-level
    propagation tracing — per-record lag CDFs, hop histograms, and
    the propagation tree in the report's ``provenance`` block;
    mutually exclusive with deltas_cap/trace/damping —
    docs/telemetry.md),
    "protocol": {"suspicion_window_s": S, "damping_half_life_s": H,
    "damping_threshold": T, "future_fudge_s": F, ...} — the
    suspicion/flap-damping/clock-bound knob bundle
    (ops/suspicion.ProtocolParams; ``future_fudge_s`` < 0 disables the
    future-admission gate — docs/chaos.md); the report's
    ``robustness`` block carries the damping prediction}.

    POST /sweep {"axes": {axis: [values...]}, "rounds": N, "eps": E,
    "n": nodes, "services_per_node": S, "fanout": F, "budget": B,
    "base": {fixed spec fields}, "conv_every": K, "stop": bool,
    "seed": S, "provenance": T (lag tracers per scenario, default 8;
    adds the per-scenario ``p99_lag_rounds`` column)} — the batched
    capacity-planning sweep
    (sidecar_tpu/fleet, docs/sweep.md): the grid is expanded, chunked
    into vmapped fleet dispatches, and answered with a per-config
    Pareto table (rounds/seconds-to-ε, analytic exchange bytes,
    ``pareto_front`` indices, plus the counted ``pareto_excluded``
    never-converged rows).  An optional ``"slo": [rule, ...]`` list
    (telemetry/slo.py grammar) adds per-row verdict blocks.  Malformed
    grids or rules (unknown axis names, out-of-range knobs, duplicate
    names, bad rule syntax) return 400 with a parseable
    ``{"message": ...}`` body.

    POST /autopilot/recommend {"rules": [slo rule, ...], "axes":
    [{"name": knob, "lo": L, "hi": H, "log": bool, "integer": bool,
    "base": status-quo}, ...], "estimate": {"loss_rate": f,
    "churn_rate": f, "paused_frac": f}, "rounds": N, "n": nodes,
    "generations": G, "population": P, "apply": bool} — one pass of
    the digital-twin autopilot (sidecar_tpu/autopilot,
    docs/autopilot.md): fit → search → replay-verify → recommend;
    ``apply`` rewrites the bridge clock only behind the
    ``SIDECAR_TPU_AUTOPILOT_APPLY=1`` gate."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            log.debug("bridge: " + a[0], *a[1:])

        def _reply(self, status: int, doc: dict) -> None:
            body = json.dumps(doc).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _do_simulate(self, req: dict) -> dict:
            sparse_req = req.get("sparse")
            report = bridge.simulate(
                rounds=int(req.get("rounds", 50)),
                seed=int(req.get("seed", 0)),
                cold_nodes=req.get("cold_nodes"),
                eps=float(req.get("eps", 0.01)),
                deltas_cap=int(req.get("deltas_cap", 0)),
                sharded=bool(req.get("sharded", False)),
                board_exchange=req.get("board_exchange"),
                sparse=(None if sparse_req is None
                        else bool(sparse_req)),
                trace=int(req.get("trace", 0)),
                digest=int(req.get("digest", 0)),
                digest_buckets=int(req.get("digest_buckets", 0)),
                protocol=req.get("protocol"),
                provenance=req.get("provenance"))
            return report.to_json()

        def _do_sweep(self, req: dict) -> dict:
            axes = req.get("axes")
            if not isinstance(axes, dict) or not axes:
                raise ValueError(
                    "sweep request needs a non-empty 'axes' object "
                    "(axis name -> list of values)")
            base = req.get("base")
            if base is not None and not isinstance(base, dict):
                raise ValueError("'base' must be an object")
            n = req.get("n")
            return bridge.sweep(
                axes,
                rounds=int(req.get("rounds", 200)),
                eps=float(req.get("eps", 0.01)),
                n=None if n is None else int(n),
                services_per_node=int(req.get("services_per_node", 4)),
                fanout=int(req.get("fanout", 3)),
                budget=int(req.get("budget", 15)),
                seed=int(req.get("seed", 0)),
                conv_every=int(req.get("conv_every", 1)),
                stop=bool(req.get("stop", True)),
                base=base,
                provenance=int(req.get("provenance", 8)),
                slo=req.get("slo"))

        def _do_autopilot(self, req: dict) -> dict:
            return bridge.autopilot_recommend(req)

        def do_POST(self):
            route = self.path.split("?")[0]
            handlers = {"/simulate": self._do_simulate,
                        "/sweep": self._do_sweep,
                        "/autopilot/recommend": self._do_autopilot}
            if route not in handlers:
                self._reply(404, {"message": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("request body: not an object")
                doc = handlers[route](req)
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as exc:
                self._reply(400, {"message": str(exc)})
                return
            self._reply(200, doc)

    server = ThreadingHTTPServer((bind, port), Handler)
    if background:
        threading.Thread(target=server.serve_forever, name="sim-bridge",
                         daemon=True).start()
    else:
        server.serve_forever()
    return server
