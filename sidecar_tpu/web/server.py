"""Threading HTTP server mounting the Sidecar API, UI static files, and
the /watch versioned snapshot+delta stream (reference:
sidecarhttp/http.go:56-84; stream protocol: docs/query.md)."""

from __future__ import annotations

import json
import logging
import mimetypes
import pathlib
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from sidecar_tpu.telemetry.span import span as _span
from sidecar_tpu.web.api import SidecarApi

log = logging.getLogger(__name__)


def make_handler(api: SidecarApi, ui_dir: Optional[str],
                 static_dir: Optional[str]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging
            log.debug("http: " + fmt, *args)

        # -- plumbing ------------------------------------------------------

        def _send(self, status: int, content_type: str, body: bytes,
                  extra: Optional[dict] = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _serve_file(self, root: str, rel: str) -> None:
            base = pathlib.Path(root).resolve()
            target = (base / rel.lstrip("/")).resolve()
            if not target.is_relative_to(base):
                self._send(403, "text/plain", b"Forbidden")
                return
            if target.is_dir():
                target = target / "index.html"
            if not target.is_file():
                self._send(404, "text/plain", b"Not Found")
                return
            ctype = mimetypes.guess_type(str(target))[0] or \
                "application/octet-stream"
            self._send(200, ctype, target.read_bytes())

        def _watch(self, by_service: bool,
                   since: Optional[int] = None) -> None:
            """Versioned delta stream over the query hub
            (docs/query.md): a snapshot document establishes the
            client's version cursor, then one delta document per
            contiguous burst of changes; a client that passes
            ``?since=V`` at the current version skips the snapshot.  A
            subscriber that falls behind gets a fresh snapshot document
            (the hub's coalesce-to-snapshot rule) — version sequences
            are gap-free by construction."""
            sub = api.state.query_hub().subscribe(
                f"watch-{id(self)}-{threading.get_ident()}", prime=False)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def push(payload: bytes) -> None:
                    # The delivery hop of the live propagation path
                    # (docs/telemetry.md): write one /watch document to
                    # this subscriber.  ``payload`` is the hub's shared
                    # per-version buffer — the same object every other
                    # watcher of this version writes — so this hop does
                    # zero serialization; the memoryview keeps the
                    # chunked framing from copying the body.
                    with _span("watch.deliver"):
                        self.wfile.write(b"%x\r\n" % len(payload))
                        self.wfile.write(memoryview(payload))
                        self.wfile.write(b"\r\n")
                        self.wfile.flush()

                current = api.state.query_hub().current()
                if since is None or since != current.version:
                    push(api.watch_snapshot_bytes(by_service, current))
                cursor = current.version
                while True:
                    ev = sub.get(timeout=30.0)
                    if ev is None:
                        continue  # keep the connection; no change yet
                    events = [ev] + sub.drain()  # coalesce the burst
                    # A resync marker supersedes the deltas BEFORE it —
                    # but deltas published after the collapse can land
                    # behind it in the same batch (get() clears the
                    # marker, then the writer publishes into the freed
                    # deque before drain()); dropping those would be a
                    # permanent gap, so push the snapshot first and the
                    # newer deltas after it.
                    snaps = [e for e in events if e.kind == "snapshot"]
                    if snaps:
                        latest = snaps[-1].snapshot
                        if latest.version > cursor:
                            push(api.watch_snapshot_bytes(by_service,
                                                          latest))
                            cursor = latest.version
                    deltas = [e for e in events
                              if e.kind == "delta" and
                              e.version > cursor]
                    if deltas:
                        push(api.watch_delta_bytes(deltas))
                        cursor = deltas[-1].version
            except OSError:
                pass  # client went away
            finally:
                sub.close()

        # -- methods -------------------------------------------------------

        def do_GET(self) -> None:  # noqa: N802 — http.server API
            parsed = urllib.parse.urlparse(self.path)
            path = parsed.path
            query = urllib.parse.parse_qs(parsed.query)

            if path == "/":
                self.send_response(301)
                self.send_header("Location", "/ui/")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if path.startswith("/ui") and ui_dir:
                self._serve_file(ui_dir, path[len("/ui"):])
                return
            if path.startswith("/static") and static_dir:
                self._serve_file(static_dir, path[len("/static"):])
                return

            result = api.dispatch("GET", path, query,
                                  client=self.client_address[0])
            if isinstance(result, tuple) and result and result[0] == "watch":
                self._watch(result[1], result[2])
                return
            status, ctype, body, extra = result
            self._send(status, ctype, body, extra)

        def do_POST(self) -> None:  # noqa: N802
            parsed = urllib.parse.urlparse(self.path)
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                self.rfile.read(length)
            status, ctype, body, extra = api.dispatch(
                "POST", parsed.path, client=self.client_address[0])
            self._send(status, ctype, body, extra)

        def do_OPTIONS(self) -> None:  # noqa: N802
            status, ctype, body, extra = api.dispatch("OPTIONS", self.path)
            self._send(status, ctype, body, extra)

    return Handler


def serve_http(api: SidecarApi, bind: str = "0.0.0.0", port: int = 7777,
               ui_dir: Optional[str] = None,
               static_dir: Optional[str] = None,
               background: bool = True) -> ThreadingHTTPServer:
    """Start the API server (http.go:56-84; default port 7777)."""
    server = ThreadingHTTPServer(
        (bind, port), make_handler(api, ui_dir, static_dir))
    if background:
        threading.Thread(target=server.serve_forever, name="http-server",
                         daemon=True).start()
    else:
        server.serve_forever()
    return server
