"""HTTP API + UI server (reference: sidecarhttp/ package)."""

from sidecar_tpu.web.api import ApiServer, HttpListener, SidecarApi
from sidecar_tpu.web.server import serve_http

__all__ = ["SidecarApi", "ApiServer", "HttpListener", "serve_http"]
