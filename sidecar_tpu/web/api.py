"""The Sidecar HTTP API (reference: sidecarhttp/http_api.go:18-371,
http_listener.go:12-38).

Route logic is a transport-independent object returning
``(status, content_type, body)`` tuples so tests drive it directly
(the reference tests its handlers with httptest ResponseRecorders);
``sidecar_tpu.web.server`` mounts it on a threading HTTP server.

Routes (http.go:64-76, http_api.go:35-45):
  GET  /api/services.json           grouped-by-service + cluster members
  GET  /api/state.json              raw state dump
  GET  /api/services/{name}.json    one service's instances
  POST /api/services/{id}/drain     set local instance DRAINING
  GET  /api/watch (+ /watch)        versioned snapshot+delta stream
                                    (?since=V cursor; docs/query.md)
  GET  /servers                     human-readable state
  GET  /metrics (+ /api/metrics)    Prometheus text exposition of the
                                    registry (docs/telemetry.md)
  GET  /api/trace (+ /trace)        span-tracer ring buffer as JSON
                                    (?limit=N newest spans; ?since=S
                                    sequence cursor — docs/telemetry.md)
  GET  /api/propagation.json        per-origin propagation-lag
                                    percentiles + SLO verdicts
                                    (telemetry/propagation.py)
  GET  /api/propagation             human-readable lag table
  GET  /api/digest.json             local catalog coherence digest
                                    (ops/digest.py live twin; lock-free)
  GET  /api/coherence.json          cluster digest-agreement view + SLO
                                    verdicts (telemetry/coherence.py)
  GET  /api/coherence               human-readable coherence heat table
  GET  /api/debug/profile           live sampling CPU profile (pprof analog)
  GET  /api/haproxy/stats.csv       relay of the managed HAProxy's stats CSV
  GET  /api/damping.json            flap-damper penalties + suppressed set
                                    (catalog/damping.py; docs/chaos.md)
  OPTIONS                            CORS headers
Deprecated aliases /services.json and /state.json are also served.
"""

from __future__ import annotations

import json
import logging
import queue
import time
from typing import Callable, Optional

from sidecar_tpu import service as svc_mod
from sidecar_tpu.catalog.state import Listener, ServicesState
from sidecar_tpu.service import DRAINING, ns_to_rfc3339

log = logging.getLogger(__name__)


class _DropOldestQueue(queue.Queue):
    """Bounded queue whose non-blocking put evicts the OLDEST entry
    instead of failing: a slow /watch client keeps receiving the newest
    events (and a ``web.watch.dropped`` count says how many it lost)
    rather than silently freezing on a full buffer."""

    def put_nowait(self, item) -> None:
        from sidecar_tpu import metrics

        while True:
            try:
                super().put_nowait(item)
                return
            except queue.Full:
                try:
                    self.get_nowait()
                    metrics.incr("web.watch.dropped")
                except queue.Empty:
                    pass  # racing consumer freed space; retry


class HttpListener(Listener):
    """The queue-shaped catalog listener (http_listener.go:12-38):
    larger buffer for slow consumers, drop-oldest beyond it.  The
    /watch HTTP stream itself rides the query hub now; this class is
    the surface for in-process ``add_listener`` consumers (embedders,
    tools) that want a plain bounded queue of ChangeEvents — its
    ``web.watch.dropped`` counter reports THAT queue's evictions (hub
    subscribers report through ``query.hub.dropped`` instead)."""

    def __init__(self) -> None:
        self._name = f"httpListener-{time.time_ns()}"
        self._chan: "queue.Queue" = _DropOldestQueue(maxsize=50)

    def chan(self):
        return self._chan

    def name(self) -> str:
        return self._name

    def managed(self) -> bool:
        return False


class ApiServer:
    """Cluster-member info in /services.json (http_api.go:18-22)."""

    def __init__(self, name: str, last_updated: int,
                 service_count: int) -> None:
        self.name = name
        self.last_updated = last_updated
        self.service_count = service_count

    def to_json(self) -> dict:
        return {"Name": self.name,
                "LastUpdated": ns_to_rfc3339(self.last_updated),
                "ServiceCount": self.service_count}


CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET",
}


class SidecarApi:
    """http_api.go:30-32 — state + cluster membership view."""

    def __init__(self, state: ServicesState,
                 members_fn: Optional[Callable[[], list[str]]] = None,
                 cluster_name: str = "",
                 envoy_v1=None,
                 haproxy_stats_url: Optional[str] = None) -> None:
        import threading

        self.state = state
        self.members_fn = members_fn
        self.cluster_name = cluster_name
        self._profile_gate = threading.Semaphore(1)
        # When the node manages an HAProxy, the UI reads its stats CSV
        # THROUGH this API (GET /api/haproxy/stats.csv) instead of
        # hitting :3212 directly like the reference UI does
        # (ui/app/services/services.js:21-33) — same data, no
        # cross-origin fetch to a second port.  None = no HAProxy.
        self.haproxy_stats_url = haproxy_stats_url
        # The deprecated Envoy V1 REST API (an EnvoyApiV1) rides on the
        # main HTTP server, like the reference's sidecarhttp mux
        # (envoy_api.go:428-438 mounted in http.go:64-76).
        self.envoy_v1 = envoy_v1

    # -- route dispatch ----------------------------------------------------

    def dispatch(self, method: str, path: str,
                 query: Optional[dict] = None,
                 client: Optional[str] = None):
        """Returns (status, content_type, body_bytes) or a stream marker
        ("watch", by_service, since) for the stream route.  ``client`` is
        the peer IP when the call arrives over HTTP (None = a trusted
        in-process caller)."""
        query = query or {}
        parts = [p for p in path.split("/") if p]
        # Strip the /api prefix; deprecated unprefixed aliases hit the
        # same handlers (http.go:72-75).
        if parts and parts[0] == "api":
            parts = parts[1:]

        if method == "OPTIONS":
            return 200, "application/json", b"", CORS_HEADERS

        if parts == ["watch"] and method == "GET":
            by_service = query.get("by_service", ["true"])[0] != "false"
            since = None
            raw = query.get("since", [None])[0]
            if raw is not None:
                try:
                    since = int(raw)
                except ValueError:
                    return self._error(400, "since must be an integer "
                                            "version cursor")
            return ("watch", by_service, since)

        if method == "POST":
            if len(parts) == 3 and parts[0] == "services" \
                    and parts[2] == "drain":
                return self.drain_service(parts[1])
            return self._error(404, "Not Found")

        if parts == ["servers"]:
            return self.servers_page()

        # Envoy V1 REST: SDS /v1/registration/{service}, CDS
        # /v1/clusters[/{x}/{y}], LDS /v1/listeners[/{x}/{y}]
        # (envoy_api.go:428-438 — the trailing segments of the cluster/
        # listener routes are Envoy-supplied and unused).
        if self.envoy_v1 is not None and parts[:1] == ["v1"] \
                and method == "GET":
            if len(parts) == 3 and parts[1] == "registration":
                status, doc = self.envoy_v1.registration(parts[2])
                return self._json(status, doc)
            if parts[1] == "clusters" and len(parts) in (2, 4):
                status, doc = self.envoy_v1.clusters()
                return self._json(status, doc)
            if parts[1] == "listeners" and len(parts) in (2, 4):
                status, doc = self.envoy_v1.listeners()
                return self._json(status, doc)
            return self._error(404, "Not Found")

        # Observability surface — the go-metrics + net/http/pprof analog
        # (sidecarhttp/http.go:5, main.go:156-166): live hot-path
        # counters/timers/histograms, Prometheus exposition, the span
        # tracer, and thread stack dumps.
        if parts == ["metrics.json"]:
            return self.metrics_dump()
        if parts == ["metrics"]:
            return self.metrics_prometheus()
        if parts == ["trace"]:
            return self.trace_dump(query)
        if parts == ["cost.json"]:
            return self.cost_dump()
        if parts == ["propagation.json"]:
            return self.propagation_dump()
        if parts == ["propagation"]:
            return self.propagation_page()
        if parts == ["digest.json"]:
            return self.digest_dump()
        if parts == ["coherence.json"]:
            return self.coherence_dump()
        if parts == ["autopilot.json"]:
            return self.autopilot_dump()
        if parts == ["coherence"]:
            return self.coherence_page()
        if parts == ["damping.json"] or parts == ["damping"]:
            return self.damping_dump()
        if parts == ["debug", "stacks"]:
            return self.debug_stacks()
        if parts == ["debug", "profile"]:
            return self.debug_profile(query, client=client)
        if parts == ["haproxy", "stats.csv"]:
            return self.haproxy_stats()

        if len(parts) == 1 and parts[0].startswith("services."):
            return self.services(parts[0].rsplit(".", 1)[1])
        if len(parts) == 1 and parts[0].startswith("state."):
            return self.state_dump(parts[0].rsplit(".", 1)[1])
        if len(parts) == 2 and parts[0] == "services":
            name, _, ext = parts[1].rpartition(".")
            return self.one_service(name, ext)
        return self._error(404, "Not Found")

    # -- handlers ----------------------------------------------------------

    def _members(self) -> list[str]:
        return sorted(self.members_fn()) if self.members_fn else []

    def damping_dump(self):
        """Flap-damper state (``GET /api/damping.json`` —
        catalog/damping.py): per-instance penalties + the suppressed
        set, or ``{"enabled": false}`` when damping is off."""
        damper = getattr(self.state, "flap_damper", None)
        if damper is None:
            return self._json(200, {"enabled": False})
        return self._json(200, {"enabled": True, **damper.snapshot()})

    def services(self, extension: str):
        """Grouped-by-service + cluster members
        (http_api.go:202-268)."""
        if extension != "json":
            return self._error(
                404, "Not Found - Invalid content type extension")
        members = {}
        for name in self._members():
            server = self.state.servers.get(name)
            members[name] = ApiServer(
                name=name,
                last_updated=server.last_updated if server else 0,
                service_count=len(server.services) if server else 0,
            ).to_json()
        result = {
            "Services": {name: [svc.to_json() for svc in instances]
                         for name, instances
                         in self.state.by_service().items()},
            "ClusterName": self.cluster_name,
        }
        if members:
            result["ClusterMembers"] = members
        body = json.dumps(result, indent=2).encode()
        return 200, "application/json", body, CORS_HEADERS

    def state_dump(self, extension: str):
        """Raw state dump (http_api.go:272-291) — the bootstrap source
        for receivers (receiver.FetchInitialState)."""
        if extension != "json":
            return self._error(
                404, "Not Found - Invalid content type extension")
        return 200, "application/json", self.state.encode(), CORS_HEADERS

    def one_service(self, name: str, extension: str):
        """One service's instances (http_api.go:135-199)."""
        if extension != "json":
            return self._error(
                404, "Not Found - Invalid content type extension")
        if not name:
            return self._error(404, "Not Found - No service name provided")
        instances = []
        with self.state._lock:
            for _, _, svc in self.state.each_service():
                if svc.name == name:
                    instances.append(svc.to_json())
        if not instances:
            return self._error(404, f"no instances of {name} found")
        body = json.dumps({
            "Services": {name: instances},
            "ClusterName": self.cluster_name,
        }, indent=2).encode()
        return 200, "application/json", body, CORS_HEADERS

    def drain_service(self, service_id: str):
        """Set a local instance DRAINING (http_api.go:297-343); re-enters
        the merge path, where DRAINING is sticky
        (services_state.go:329-331)."""
        if not service_id:
            return self._error(404, "Not Found - No service ID provided")
        try:
            svc = self.state.get_local_service_by_id(service_id)
        except KeyError:
            return self._error(
                404, f'Not Found - Service ID "{service_id}" not found')
        svc.updated = svc_mod.now_ns()
        svc.status = DRAINING
        self.state.update_service(svc)
        body = json.dumps({
            "Message": f'Service "{svc.name}" instance "{svc.id}" set to '
                       "DRAINING"
        }, indent=2).encode()
        return 202, "application/json", body, {}

    def servers_page(self):
        """Auto-refreshing human-readable dump (http.go:28-45)."""
        body = ("\n \t\t\t<head>\n \t\t\t<meta http-equiv=\"refresh\" "
                "content=\"4\">\n \t\t\t</head>\n\t    \t<pre>"
                + self.state.format(self._members())
                + "</pre>").encode()
        return 200, "text/html", body, {}

    # -- watch plumbing ----------------------------------------------------

    def metrics_dump(self):
        """Hot-path counters/gauges/timers (the statsd registry's
        in-memory view) — the observability read the reference only had
        via an external statsd pipeline."""
        from sidecar_tpu import metrics

        body = json.dumps(metrics.snapshot(), indent=2).encode()
        return 200, "application/json", body, CORS_HEADERS

    def metrics_prometheus(self):
        """The registry in Prometheus text exposition format (``GET
        /metrics`` — the standard scrape path; counters, gauges, and
        the histogram instruments' quantiles, docs/telemetry.md)."""
        from sidecar_tpu.telemetry import render_prometheus

        body = render_prometheus().encode()
        return (200, "text/plain; version=0.0.4; charset=utf-8", body,
                CORS_HEADERS)

    def trace_dump(self, query: dict):
        """The span tracer's ring buffer as JSON (``GET /api/trace`` —
        end-to-end timing of the live propagation path, receive →
        merge → publish → watcher delivery; docs/telemetry.md).
        ``?limit=N`` returns only the newest N spans; ``?since=<seq>``
        is the incremental cursor — spans completed after that
        sequence number, oldest first, with ``next_since`` to resume
        from and ``dropped`` when the ring overwrote spans the cursor
        never read (with both, ``limit`` pages FORWARD from the
        cursor).  ``?format=chrome`` returns the same selection as
        Chrome trace-event JSON (Perfetto-loadable; the cursor keys
        ride along at the top level next to ``traceEvents``)."""
        from sidecar_tpu.telemetry import spans, spans_since
        from sidecar_tpu.telemetry.span import spans_to_chrome

        fmt = query.get("format", ["json"])[0]
        if fmt not in ("json", "chrome"):
            return self._error(400, "format must be json or chrome")
        limit = None
        raw = query.get("limit", [None])[0]
        if raw is not None:
            try:
                limit = int(raw)
            except ValueError:
                return self._error(400, "limit must be an integer")
        raw_since = query.get("since", [None])[0]
        if raw_since is not None:
            try:
                since = int(raw_since)
            except ValueError:
                return self._error(
                    400, "since must be an integer span cursor")
            doc = spans_since(since, limit)
        else:
            doc = {"spans": spans(limit)}
        if fmt == "chrome":
            chrome = {"traceEvents": spans_to_chrome(doc["spans"]),
                      "displayTimeUnit": "ms"}
            for key in ("next_since", "dropped"):
                if key in doc:
                    chrome[key] = doc[key]
            doc = chrome
        body = json.dumps(doc, indent=2).encode()
        return 200, "application/json", body, CORS_HEADERS

    def cost_dump(self):
        """Kernel-cost observatory registry (``GET /api/cost.json`` —
        telemetry/cost.py, docs/perf.md): every compiled-program cost
        report recorded in this process (compile/lower wall time,
        FLOP/byte estimates, HBM watermarks, collective payloads,
        per-phase byte attribution) plus the phase-scope state and
        ``compile.*`` counters."""
        from sidecar_tpu.telemetry import cost

        body = json.dumps(cost.snapshot(), indent=2).encode()
        return 200, "application/json", body, CORS_HEADERS

    def propagation_dump(self):
        """Live propagation-lag view (``GET /api/propagation.json`` —
        telemetry/propagation.py, the sim provenance plane's live
        twin): per observation site (catalog writer, query hub) the
        per-origin merge-lag percentiles, plus the convergence-SLO
        verdicts when an evaluator is attached (telemetry/slo.py)."""
        from sidecar_tpu.telemetry import propagation

        doc = propagation.snapshot()
        slo = getattr(self.state, "slo_evaluator", None)
        if slo is not None:
            doc["slo"] = slo.evaluate_live()
        return self._json(200, doc)

    def propagation_page(self):
        """Auto-refreshing human view of the propagation meter
        (``GET /api/propagation`` — the /servers convention): one row
        per (site, origin) with the lag percentiles."""
        from sidecar_tpu.telemetry import propagation

        doc = propagation.snapshot()
        rows = []
        for site, block in sorted(doc.get("sites", {}).items()):
            for origin, ent in sorted(block["origins"].items()):
                rows.append(
                    f"<tr><td>{site}</td><td>{origin}</td>"
                    f"<td>{ent['count']}</td>"
                    f"<td>{ent['p50_ms']}</td><td>{ent['p95_ms']}</td>"
                    f"<td>{ent['p99_ms']}</td><td>{ent['max_ms']}</td>"
                    f"</tr>")
            if block.get("overflow_origins"):
                rows.append(
                    f"<tr><td>{site}</td><td><i>(+"
                    f"{block['overflow_origins']} origins beyond cap)"
                    f"</i></td><td colspan=5></td></tr>")
        body = (
            "\n\t\t\t<head>\n\t\t\t<meta http-equiv=\"refresh\" "
            "content=\"4\">\n\t\t\t</head>\n\t\t\t"
            "<h3>Propagation lag (ms) — merge time − record stamp"
            "</h3>\n<table border=1 cellpadding=4>"
            "<tr><th>site</th><th>origin</th><th>count</th>"
            "<th>p50</th><th>p95</th><th>p99</th><th>max</th></tr>"
            + "".join(rows) + "</table>"
        ).encode()
        return 200, "text/html", body, CORS_HEADERS

    def digest_dump(self):
        """The local catalog's coherence digest
        (``GET /api/digest.json`` — ops/digest.py live twin): the same
        ``{"Buckets", "Records", "Hex"}`` document the push-pull
        annotation carries.  Lock-free: one immutable-snapshot read,
        the coherence plane's read-path contract."""
        doc_fn = getattr(self.state, "digest_doc", None)
        if doc_fn is None:
            return self._json(200, {"enabled": False})
        return self._json(200, doc_fn())

    def autopilot_dump(self):
        """The last autopilot recommendation report
        (``GET /api/autopilot.json`` — sidecar_tpu/autopilot,
        docs/autopilot.md): the fitted condition estimate, SLO rules,
        baseline-vs-recommended verdicts, search cost, the replay
        bit-identity check, and the apply-gate outcome.  ``{"enabled":
        false}`` until a recommendation has run (the digest_dump
        graceful-absence convention)."""
        report = getattr(self.state, "autopilot_report", None)
        if report is None:
            return self._json(200, {"enabled": False})
        return self._json(200, {"enabled": True, **report})

    def coherence_dump(self):
        """Cluster coherence view (``GET /api/coherence.json`` —
        telemetry/coherence.py): per-host digest agreement, the quorum
        digest, the pairwise differing-bucket matrix (each entry
        lower-bounds the records diverged between that host pair),
        the diverged-record estimate, and time-to-coherence — plus the
        coherence-SLO verdicts when an evaluator is attached
        (``state.slo_evaluator``, telemetry/slo.py)."""
        from sidecar_tpu.telemetry import coherence

        doc = coherence.snapshot()
        slo = getattr(self.state, "slo_evaluator", None)
        if slo is not None and doc.get("enabled"):
            doc["slo"] = slo.evaluate_coherence()
        return self._json(200, doc)

    def coherence_page(self):
        """Auto-refreshing human view of the coherence monitor
        (``GET /api/coherence`` — the /api/propagation convention):
        one summary row per host, then the pairwise differing-bucket
        matrix as a compact heat table (0 = the pair agrees; darker =
        more buckets — at least that many records — apart)."""
        from sidecar_tpu.telemetry import coherence

        doc = coherence.snapshot()
        if not doc.get("enabled"):
            return (200, "text/html",
                    b"<h3>Coherence monitor disabled "
                    b"(SIDECAR_TPU_COHERENCE=0)</h3>", CORS_HEADERS)
        rows = []
        for host, ent in sorted(doc.get("hosts", {}).items()):
            mark = " (local)" if ent["local"] else ""
            rows.append(
                f"<tr><td>{host}{mark}</td><td>{ent['records']}</td>"
                f"<td>{'yes' if ent['agree'] else 'no'}</td>"
                f"<td>{ent['diff_vs_quorum']}</td></tr>")
        quorum = doc.get("quorum") or {}
        matrix = doc.get("matrix") or {}
        hosts = matrix.get("hosts") or []
        heat = []
        if hosts:
            heat.append("<tr><th></th>" + "".join(
                f"<th>{h}</th>" for h in hosts) + "</tr>")
            buckets = max(1, doc.get("buckets") or 1)
            for a, row in zip(hosts, matrix.get("diff") or []):
                cells = []
                for d in row:
                    # Heat shading: white at 0 diverging to red as the
                    # differing-bucket count approaches the full width.
                    frac = min(1.0, d / buckets)
                    g = int(255 - 195 * frac)
                    cells.append(
                        f"<td style=\"background:rgb(255,{g},{g})\">"
                        f"{d}</td>")
                heat.append(f"<tr><th>{a}</th>" + "".join(cells)
                            + "</tr>")
        ttc = doc.get("ttc") or {}
        body = (
            "\n\t\t\t<head>\n\t\t\t<meta http-equiv=\"refresh\" "
            "content=\"4\">\n\t\t\t</head>\n\t\t\t"
            "<h3>Cluster coherence — catalog digest agreement</h3>"
            f"<p>agreement: <b>{quorum.get('agreement', 'n/a')}</b>"
            f" &nbsp; diverged-record estimate (lower bound): "
            f"<b>{doc.get('diverged_estimate', 'n/a')}</b>"
            f" &nbsp; time-to-coherence: last "
            f"{ttc.get('last_ms', 'n/a')} ms over {ttc.get('count', 0)}"
            " changes</p>"
            "\n<table border=1 cellpadding=4>"
            "<tr><th>host</th><th>records</th><th>quorum?</th>"
            "<th>diff buckets</th></tr>"
            + "".join(rows) + "</table>"
            "<h4>Pairwise differing buckets</h4>"
            "\n<table border=1 cellpadding=4>"
            + "".join(heat) + "</table>"
        ).encode()
        return 200, "text/html", body, CORS_HEADERS

    def debug_stacks(self):
        """Per-thread stack dump — the live-pprof analog the reference
        gets from net/http/pprof's side-effect import."""
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
            out.extend(line.rstrip()
                       for line in traceback.format_stack(frame))
        body = "\n".join(out).encode()
        return 200, "text/plain", body, CORS_HEADERS

    def haproxy_stats(self):
        """Relay the managed HAProxy's stats CSV (the reference UI's
        second data source, fetched straight off :3212 —
        ui/app/services/services.js:21-33).  404 when this node runs no
        HAProxy; 502 when HAProxy is expected but unreachable (the UI
        treats both as "no proxy data", like the reference's catch)."""
        import urllib.error
        import urllib.request

        if not self.haproxy_stats_url:
            return self._error(404, "this node manages no HAProxy")
        try:
            with urllib.request.urlopen(self.haproxy_stats_url,
                                        timeout=1.0) as resp:
                body = resp.read(4 << 20)
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            return self._error(502, f"HAProxy stats unreachable: {exc}")
        return 200, "text/plain", body, CORS_HEADERS

    def debug_profile(self, query: dict, client: Optional[str] = None):
        """On-demand CPU profile of the LIVE node —
        ``/api/debug/profile?seconds=N`` (the net/http/pprof CPU-profile
        analog, sidecarhttp/http.go:5; offline profiling stays behind
        ``--cpuprofile``).

        Loopback-only: the endpoint burns up to 60 s of CPU per request
        and the API is served with CORS ``*``, so an off-host (or
        cross-origin) caller could keep a node permanently profiling.
        net/http/pprof expects to live on a debug listener; the analog
        here is rejecting non-local peers outright.

        Like pprof's, this is a SAMPLING profile: every thread's stack
        is captured at ~100 Hz for N seconds and aggregated into
        flamegraph-collapsed lines (``frame;frame;frame count``) plus a
        self-time leaderboard.  cProfile is deliberately not used here —
        its tracer only hooks threads started after enabling, so it
        cannot see a running node's loops, and its per-call overhead
        would distort the hot paths it's meant to measure."""
        import math
        import sys
        import threading
        import time as time_mod

        if client is not None and client not in ("127.0.0.1", "::1",
                                                 "localhost") \
                and not client.startswith("127."):
            return self._error(
                403, "profiling is restricted to loopback clients")
        try:
            seconds = float(query.get("seconds", ["5"])[0])
        except ValueError:
            return self._error(400, "seconds must be a number")
        if not math.isfinite(seconds):
            return self._error(400, "seconds must be finite")
        seconds = min(max(seconds, 0.1), 60.0)
        interval = 0.01                       # 100 Hz, pprof's default
        # One profile at a time, like net/http/pprof: concurrent
        # samplers would multiply CPU burn and record each other.
        if not self._profile_gate.acquire(blocking=False):
            return self._error(409, "a CPU profile is already running")
        me = threading.get_ident()

        stacks: dict[tuple, int] = {}
        self_time: dict[str, int] = {}
        samples = 0
        try:
            deadline = time_mod.monotonic() + seconds
            while time_mod.monotonic() < deadline:
                for ident, frame in sys._current_frames().items():
                    if ident == me:
                        continue              # the sampler itself
                    stack = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        stack.append(
                            f"{code.co_name} "
                            f"({code.co_filename.rsplit('/', 1)[-1]}"
                            f":{f.f_lineno})")
                        f = f.f_back
                    stack.reverse()
                    stacks[tuple(stack)] = stacks.get(tuple(stack), 0) + 1
                    self_time[stack[-1]] = self_time.get(stack[-1], 0) + 1
                samples += 1
                time_mod.sleep(interval)
        finally:
            self._profile_gate.release()

        top = sorted(self_time.items(), key=lambda kv: -kv[1])[:25]
        lines = [f"# CPU profile: {samples} sampling passes over "
                 f"{seconds:g}s at ~{1 / interval:.0f} Hz "
                 f"(all threads; counts are samples observed)",
                 "", "# -- self time (leaf frame) --"]
        lines += [f"{count:8d}  {frame}" for frame, count in top]
        lines += ["", "# -- collapsed stacks (flamegraph format) --"]
        lines += [f"{';'.join(stack)} {count}"
                  for stack, count in
                  sorted(stacks.items(), key=lambda kv: -kv[1])]
        return 200, "text/plain", "\n".join(lines).encode(), CORS_HEADERS

    # -- watch plumbing ----------------------------------------------------

    def watch_snapshot_doc(self, by_service: bool, snapshot=None) -> dict:
        """The /watch snapshot document (docs/query.md): the catalog at
        one version, from the hub's immutable snapshot — no state lock,
        serialization cached per version."""
        if snapshot is None:
            snapshot = self.state.query_hub().current()
        body = (snapshot.by_service_json() if by_service
                else snapshot.to_json())
        return {"Version": snapshot.version, "Snapshot": body}

    def watch_delta_doc(self, events: list) -> dict:
        """One coalesced /watch delta document covering the contiguous
        version range [From, Version] — one ChangeEvent per version."""
        return {
            "From": events[0].version,
            "Version": events[-1].version,
            "Deltas": [ev.change.to_json() for ev in events],
        }

    # Zero-copy variants (docs/query.md): same document CONTENT as the
    # dict builders above, but served from the per-version buffers the
    # snapshot/event objects cache — every subscriber of a version
    # writes the SAME bytes object, so fan-out serialization is O(1)
    # per version instead of O(subscribers).

    def watch_snapshot_bytes(self, by_service: bool,
                             snapshot=None) -> bytes:
        if snapshot is None:
            snapshot = self.state.query_hub().current()
        return snapshot.watch_doc_bytes(by_service)

    def watch_delta_bytes(self, events: list) -> bytes:
        frags = [ev.change_frag() for ev in events]
        return (b'{"From":%d,"Version":%d,"Deltas":[%s]}'
                % (events[0].version, events[-1].version,
                   b",".join(frags)))


    def _json(self, status: int, doc: dict):
        body = json.dumps(doc, indent=2).encode()
        return status, "application/json", body, {}

    def _error(self, status: int, message: str):
        body = json.dumps({"status": "error", "message": message}).encode()
        return status, "application/json", body, {}
