"""The subscription hub: versioned snapshot publication + delta fan-out.

The hub sits on the catalog's writer path: ``ServicesState`` calls
:meth:`QueryHub.publish` for every change event (from inside
``notify_listeners``, i.e. under the writer's lock, so versions are
totally ordered by construction).  Each publish builds the successor
:class:`~sidecar_tpu.query.snapshot.CatalogSnapshot` by copy-on-write
and hands every subscriber a delta event on a bounded queue.

Backpressure semantics (docs/query.md): a subscriber whose queue is
full does NOT silently lose the event — its queued deltas are
discarded and replaced by a single *snapshot-at-latest-version* marker.
The subscriber's next reads then see one ``snapshot`` event carrying
the current version, from which delta flow resumes gap-free.  Both
sides of the collapse are counted (``query.hub.dropped`` — deltas
discarded, ``query.hub.coalesced`` — collapse occurrences) so a slow
consumer degrades observably instead of invisibly.

The hub never blocks the writer: publish is deque appends under
per-subscription mutexes, O(subscribers) with no serialization (the
snapshot's JSON forms are computed lazily by whichever reader first
needs them).
"""

from __future__ import annotations

import collections
import json
import logging
import math
import threading
import time
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu.telemetry.span import span as _span
from sidecar_tpu.telemetry import propagation as _propagation
from sidecar_tpu.query.snapshot import (
    CatalogSnapshot,
    ServerView,
    record_encode,
    snapshot_from_state,
)

log = logging.getLogger(__name__)

# Default per-subscriber queue bound.  Small enough that a stuck
# consumer collapses to a snapshot quickly instead of holding hundreds
# of delta events alive; large enough to ride normal bursts.
DEFAULT_SUBSCRIBER_BUFFER = 64

# Relay hubs ride a deeper queue than leaf subscribers: a relay that
# coalesces forces a resync on EVERY subscriber downstream of it, so
# the tier trades a little memory for far fewer collapses.
DEFAULT_RELAY_BUFFER = 256

# Fill lock for the per-event wire-encoding caches.  One lock for all
# events is fine: it is only ever taken by the FIRST consumer of each
# buffer (once per published version), never on the shared-buffer hot
# path.  Re-entrant: delta_doc_bytes fills change_frag under it.
_event_fill = threading.RLock()


class QueryEvent:
    """One item on a subscription queue.

    ``kind`` is ``"delta"`` (one catalog change; ``change`` holds the
    :class:`~sidecar_tpu.catalog.state.ChangeEvent`) or ``"snapshot"``
    (resync-at-latest: the subscriber fell behind, or this is the
    priming event of a fresh subscription).  ``version`` is the hub
    version AFTER applying the event; ``snapshot`` is the catalog at
    exactly that version.  ``published_ns`` stamps delta events at
    fan-out time so delivery can account publish-to-deliver lag
    (``query.hub.lag``); resync markers are built at delivery and
    carry 0.
    """

    __slots__ = ("kind", "version", "snapshot", "change", "published_ns",
                 "_frag", "_delta_doc")

    def __init__(self, kind: str, version: int,
                 snapshot: CatalogSnapshot, change=None,
                 published_ns: int = 0) -> None:
        self.kind = kind
        self.version = version
        self.snapshot = snapshot
        self.change = change
        self.published_ns = published_ns
        self._frag: Optional[bytes] = None
        self._delta_doc: Optional[bytes] = None

    # -- shared wire encodings (zero-copy fan-out, docs/query.md) ----------

    def change_frag(self) -> bytes:
        """Compact encoding of this delta's ChangeEvent — filled once
        per published version under the fill lock, then handed to every
        consumer as the same object: the /watch ``Deltas`` array and the
        UrlListener POST body are composed from this buffer instead of
        re-running ``json.dumps`` per subscriber."""
        frag = self._frag
        if frag is None:
            with _event_fill:
                if self._frag is None:
                    buf = json.dumps(self.change.to_json(),
                                     separators=(",", ":")).encode()
                    record_encode(len(buf))
                    self._frag = buf
                frag = self._frag
        return frag

    def delta_doc_bytes(self) -> bytes:
        """The UrlListener delta POST body
        (``{"Version": V, "ChangeEvent": {...}}``, byte-identical to
        ``delta_event_json``) as one cached buffer shared by every
        listener delivering this version."""
        doc = self._delta_doc
        if doc is None:
            with _event_fill:
                if self._delta_doc is None:
                    self._delta_doc = (b'{"Version":%d,"ChangeEvent":%s}'
                                       % (self.version, self.change_frag()))
                doc = self._delta_doc
        return doc

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"QueryEvent({self.kind}, v{self.version})"


class Subscription:
    """A bounded, coalescing delta queue for one consumer."""

    def __init__(self, hub: "QueryHub", name: str, buffer: int) -> None:
        if buffer < 1:
            raise ValueError("subscription buffer must be >= 1")
        self.name = name
        self._hub = hub
        self._buffer = buffer
        self._cond = threading.Condition()
        self._deque: "collections.deque[QueryEvent]" = collections.deque()
        self._pending_snapshot: Optional[CatalogSnapshot] = None
        self._closed = False
        # Per-subscriber delivery-lag instrumentation (docs/query.md):
        # how far behind the hub head this consumer's reads run, in
        # versions and in wall ms — updated at every delta delivery.
        self.delivered = 0
        self.last_lag_versions = 0
        self.last_lag_ms = 0.0

    # -- producer side (hub, under the writer path) ------------------------

    def _offer(self, event: QueryEvent) -> None:
        with self._cond:
            if self._closed:
                return
            if self._pending_snapshot is not None:
                # Already collapsed: the marker subsumes every delta up
                # to latest, just slide it forward.
                self._pending_snapshot = event.snapshot
                metrics.incr("query.hub.dropped")
            elif len(self._deque) >= self._buffer:
                dropped = len(self._deque)
                self._deque.clear()
                self._pending_snapshot = event.snapshot
                metrics.incr("query.hub.dropped", dropped + 1)
                metrics.incr("query.hub.coalesced")
                log.warning("query: subscriber %s fell behind; coalesced "
                            "%d deltas to snapshot v%d", self.name,
                            dropped + 1, event.version)
            else:
                self._deque.append(event)
            self._cond.notify_all()

    # -- consumer side -----------------------------------------------------

    def get(self, timeout: Optional[float] = None) -> Optional[QueryEvent]:
        """Next event, or None on timeout / after :meth:`close`.  A
        pending resync marker is delivered before any newer deltas (it
        is always the oldest information the subscriber is missing)."""
        event = None
        with self._cond:
            if not self._deque and self._pending_snapshot is None \
                    and not self._closed:
                self._cond.wait(timeout=timeout)
            if self._pending_snapshot is not None:
                snap = self._pending_snapshot
                self._pending_snapshot = None
                event = QueryEvent("snapshot", snap.version, snap)
            elif self._deque:
                event = self._deque.popleft()
        if event is not None:
            self._observe_delivery(event)
        return event

    def drain(self) -> list[QueryEvent]:
        """Every immediately-available event (burst coalescing for
        consumers that batch, e.g. the /watch chunk writer)."""
        out = []
        with self._cond:
            if self._pending_snapshot is not None:
                snap = self._pending_snapshot
                self._pending_snapshot = None
                out.append(QueryEvent("snapshot", snap.version, snap))
            while self._deque:
                out.append(self._deque.popleft())
        for event in out:
            self._observe_delivery(event)
        return out

    def _observe_delivery(self, event: QueryEvent) -> None:
        """Publish-to-deliver lag accounting, OUTSIDE the queue lock
        (metrics registry has its own).  Version gap = how far the hub
        head has moved past the event being handed over right now —
        the subscriber's staleness in catalog versions; ms = wall time
        the event sat queued.  Only delta events carry a publish stamp
        (resync markers are built at delivery — their lag is exactly
        the coalescing they represent, already counted in
        ``query.hub.dropped``)."""
        if not event.published_ns:
            return
        cur = self._hub._current
        gap = max(0, (cur.version if cur is not None
                      else event.version) - event.version)
        ms = max(0.0, (time.time_ns() - event.published_ns) / 1e6)
        self.delivered += 1
        self.last_lag_versions = gap
        self.last_lag_ms = ms
        metrics.histogram("query.hub.lag", ms)
        metrics.histogram("query.hub.lag.versions", gap)
        self._hub._observe_lag(gap)

    def pending(self) -> int:
        with self._cond:
            return len(self._deque) + (
                1 if self._pending_snapshot is not None else 0)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Detach from the hub; wakes any blocked :meth:`get`."""
        with self._cond:
            self._closed = True
            self._deque.clear()
            self._pending_snapshot = None
            self._cond.notify_all()
        self._hub._remove(self)


class QueryHub:
    """Snapshot publisher + subscriber registry for one catalog."""

    def __init__(self, state,
                 default_buffer: int = DEFAULT_SUBSCRIBER_BUFFER) -> None:
        self.state = state
        self.default_buffer = default_buffer
        self._lock = threading.Lock()      # subscriber set + version
        # Keyed by id(sub): O(1) unsubscribe at 100k-subscriber churn
        # (the old list scan made churn quadratic) while dict insertion
        # order keeps publish-order iteration stable.
        self._subs: dict[int, Subscription] = {}
        self._current: Optional[CatalogSnapshot] = None
        # High-water mark of the delivery version gap across ALL
        # subscribers — the query.hub.lag.max gauge (reset with the
        # metrics registry in tests).  Guarded by its own lock, NOT the
        # registry lock: every delivery calls _observe_lag, and an
        # unlocked read-modify-write here let concurrent deliveries
        # regress the high-water mark.
        self._max_lag_versions = 0
        self._lag_lock = threading.Lock()

    def _observe_lag(self, gap: int) -> None:
        with self._lag_lock:
            if gap > self._max_lag_versions:
                self._max_lag_versions = gap
            # Gauge write inside the lock so a stale value can never
            # overwrite a newer maximum.
            metrics.set_gauge("query.hub.lag.max", self._max_lag_versions)

    # -- lifecycle ---------------------------------------------------------

    def attach(self) -> CatalogSnapshot:
        """Build the version-1 snapshot from the live state.  Takes the
        state lock itself (re-entrant from ``ServicesState.query_hub``);
        lock order is always state → hub."""
        with self.state._lock:
            with self._lock:
                if self._current is None:
                    self._current = snapshot_from_state(self.state, 1)
                    metrics.set_gauge("query.snapshot.version", 1)
                return self._current

    def current(self) -> CatalogSnapshot:
        """The latest snapshot — one reference read, never a lock on
        the catalog."""
        snap = self._current
        if snap is None:
            return self.attach()
        return snap

    @property
    def damper(self):
        """The catalog's flap damper (catalog/damping.py), or None —
        snapshot-path consumers (the ADS server, HAProxy writer) gate
        proxy admission on it so a flapping service is withheld from
        routing without being dropped from the snapshots themselves
        (the catalog views stay complete; damping is a routing
        decision)."""
        return getattr(self.state, "flap_damper", None)

    # -- the writer-path publish -------------------------------------------

    def publish(self, event) -> CatalogSnapshot:
        """Build + publish the successor snapshot for one ChangeEvent.

        Runs on the catalog writer path, under ``state._lock`` (the
        re-entrant lock makes the state reads here free).  Copy-on-write
        scope: only the changed host's ``ServerView`` is rebuilt — from
        the previous snapshot's frozen services when the host's service
        set is unchanged (O(1) upsert of the event's own frozen copy),
        from the live state when services appeared/vanished (catches
        tombstone GC deletions, which emit no events)."""
        host = event.service.hostname
        with self._lock:
            prev = self._current
            if prev is None:
                # Publish before attach: the implicit v1 snapshot is
                # built from the (already mutated) state, so the v2
                # successor below is content-identical — harmless.
                prev = snapshot_from_state(self.state, 1)
            servers = dict(prev.servers)
            live = self.state.servers.get(host)
            if live is None:
                servers.pop(host, None)
            else:
                prev_view = prev.servers.get(host)
                if prev_view is not None and \
                        prev_view.services.keys() == live.services.keys() \
                        and event.service.id in live.services:
                    services = dict(prev_view.services)
                    services[event.service.id] = event.service
                else:
                    services = {sid: svc.copy()
                                for sid, svc in live.services.items()}
                servers[host] = ServerView(
                    name=live.name, services=services,
                    last_updated=live.last_updated,
                    last_changed=live.last_changed)
            snap = CatalogSnapshot(
                version=prev.version + 1,
                changed_ns=self.state.last_changed,
                cluster_name=self.state.cluster_name,
                hostname=self.state.hostname,
                servers=servers)
            self._current = snap
            subs = list(self._subs.values())
        metrics.incr("query.hub.published")
        metrics.set_gauge("query.snapshot.version", snap.version)
        qevent = QueryEvent("delta", snap.version, snap, change=event,
                            published_ns=time.time_ns())
        # The publish hop of the live propagation path: span for the
        # /api/trace causal chain, fan-out latency (all subscriber
        # offers for one version) into the query.hub.fanout histogram —
        # the p50/p95/p99 the 100k-watcher climb is measured by
        # (docs/telemetry.md, docs/metrics.md).
        with _span("query.publish"):
            t0 = time.perf_counter()
            for sub in subs:
                sub._offer(qevent)
            metrics.histogram_since("query.hub.fanout", t0)
        # End-to-end propagation lag at the query plane — the second
        # site of the live provenance twin (telemetry/propagation.py):
        # how far behind the origin's stamp this record was when it
        # became visible to /watch consumers.
        _propagation.observe("query", event.service.hostname,
                             (time.time_ns() - event.service.updated)
                             / 1e6)
        return snap

    # -- subscriptions -----------------------------------------------------

    def subscribe(self, name: str, buffer: Optional[int] = None,
                  prime: bool = True) -> Subscription:
        """Register a consumer.  With ``prime`` the first read returns a
        snapshot event at the current version, so every subscriber
        starts from a known version cursor."""
        sub = Subscription(self, name,
                           buffer if buffer is not None
                           else self.default_buffer)
        self.current()  # ensure attached (state→hub lock order)
        # Snapshot read + registration are ONE critical section: a
        # publish interleaved between them would be missed by both the
        # prime snapshot and the fan-out (it copies _subs before the
        # append) — the subscriber would hold a stale cursor with no
        # delta coming.
        with self._lock:
            self._subs[id(sub)] = sub
            if prime:
                # Inside the registration critical section: a publish
                # interleaved after registration could collapse the
                # queue to a NEWER pending snapshot, and an unlocked
                # prime assignment would overwrite it with the older
                # one (hub→sub lock order matches publish's fan-out;
                # close() releases the cond before taking the hub
                # lock, so no inversion).
                with sub._cond:
                    sub._pending_snapshot = self._current
                    sub._cond.notify_all()
            n_subs = len(self._subs)
        metrics.set_gauge("query.hub.subscribers", n_subs)
        return sub

    def _remove(self, sub: Subscription) -> None:
        with self._lock:
            if self._subs.pop(id(sub), None) is None:
                return
            metrics.set_gauge("query.hub.subscribers", len(self._subs))

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)


# -- tiered relay fan-out ---------------------------------------------------

_relay_count = 0
_relay_count_lock = threading.Lock()


def _relay_count_delta(d: int) -> None:
    global _relay_count
    with _relay_count_lock:
        _relay_count += d
        metrics.set_gauge("query.hub.tier.relays", _relay_count)


class RelayHub:
    """A coalescing fan-out tier between the root :class:`QueryHub` and
    its subscribers (docs/query.md).

    The relay holds ONE bounded subscription on its parent (the root
    hub or another relay) and re-fans every event to its own
    subscriptions from a dedicated delivery thread.  With W relays over
    N subscribers the writer-path publish touches W queues instead of
    N — O(relays) on the catalog writer — and the O(N) offer work
    happens on relay threads, off the writer.  Composing relays builds
    a tree (:func:`relay_tree`) whose per-hub fan-out stays bounded.

    Semantics are preserved end-to-end:

    * Events are re-fanned by reference — same ``QueryEvent``, same
      shared wire buffers, original ``published_ns`` — so a leaf
      subscriber's ``query.hub.lag`` measures true publish-to-deliver
      latency across every tier, and its version-gap is computed
      against the ROOT head.
    * A relay that falls behind collapses its parent queue to a
      snapshot marker exactly like any subscriber; re-fanning that
      marker resyncs everyone downstream (gap-free by construction).
    * Subscribing primes from the relay's *delivered horizon* (the last
      event it re-fanned), not the root head: the relay-local stream
      stays contiguous — prime at vK, next delta vK+1.
    """

    def __init__(self, parent, name: str = "relay",
                 buffer: int = DEFAULT_RELAY_BUFFER,
                 poll: float = 0.5) -> None:
        self.name = name
        self._parent = parent
        self._root = getattr(parent, "_root", parent)
        self._lock = threading.Lock()      # horizon + subscriber set
        self._subs: dict[int, Subscription] = {}
        self._closed = False
        self._poll = poll
        self.relayed = 0
        self._psub = parent.subscribe(f"relay:{name}", buffer=buffer,
                                      prime=False)
        # Horizon AFTER subscribing: events already queued are ≤ this
        # version and get skipped as catch-up duplicates; everything
        # newer flows through, so the horizon is never ahead of a
        # missed event.
        self._last: CatalogSnapshot = parent.current()
        _relay_count_delta(+1)
        self._thread = threading.Thread(
            target=self._pump, name=f"relay-{name}", daemon=True)
        self._thread.start()

    # -- QueryHub surface consumed by Subscription -------------------------

    @property
    def _current(self) -> Optional[CatalogSnapshot]:
        # Lag accounting measures staleness against the ROOT head.
        return self._root._current

    @property
    def default_buffer(self) -> int:
        return self._root.default_buffer

    @property
    def damper(self):
        return self._root.damper

    def current(self) -> CatalogSnapshot:
        return self._root.current()

    def _observe_lag(self, gap: int) -> None:
        self._root._observe_lag(gap)

    # -- delivery ----------------------------------------------------------

    def _pump(self) -> None:
        while True:
            ev = self._psub.get(timeout=self._poll)
            if self._closed or self._psub.closed:
                return
            if ev is None:
                continue
            with self._lock:
                # Horizon advance + fan-out list are ONE critical
                # section with subscribe()'s prime (the same discipline
                # as QueryHub.publish): a subscriber primed at _last
                # can never miss a later event.
                if ev.version <= self._last.version:
                    continue  # pre-subscription catch-up duplicate
                self._last = ev.snapshot
                subs = list(self._subs.values())
            t0 = time.perf_counter()
            for sub in subs:
                sub._offer(ev)
            self.relayed += 1
            metrics.incr("query.hub.tier.relayed")
            metrics.histogram_since("query.hub.tier.fanout", t0)

    # -- subscriptions (QueryHub parity) -----------------------------------

    def subscribe(self, name: str, buffer: Optional[int] = None,
                  prime: bool = True) -> Subscription:
        sub = Subscription(self, name,
                           buffer if buffer is not None
                           else self.default_buffer)
        with self._lock:
            if self._closed:
                raise RuntimeError(f"relay {self.name} is closed")
            self._subs[id(sub)] = sub
            if prime:
                with sub._cond:
                    sub._pending_snapshot = self._last
                    sub._cond.notify_all()
        return sub

    def _remove(self, sub: Subscription) -> None:
        with self._lock:
            self._subs.pop(id(sub), None)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def close(self) -> None:
        """Detach from the parent and close every downstream
        subscription (their blocked gets wake with None)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            subs = list(self._subs.values())
            self._subs.clear()
        self._psub.close()
        for sub in subs:
            sub.close()
        _relay_count_delta(-1)


def relay_tree(root: QueryHub, leaves: int, max_fanout: int = 16,
               buffer: int = DEFAULT_RELAY_BUFFER,
               name: str = "tier") -> tuple[list, list]:
    """Build a balanced relay tree under ``root`` with ``leaves`` leaf
    relays and at most ``max_fanout`` children per hub; returns
    ``(leaf_relays, all_relays)``.  Spread subscribers across the leaf
    relays: one root publish then costs ≤ ``max_fanout`` offers and
    every delivery thread re-fans a bounded set (100k subscribers at
    2048/leaf → 49 leaves, 4 mid relays, 2 tiers)."""
    if leaves < 1:
        raise ValueError("relay tree needs at least one leaf")
    sizes = [leaves]
    while sizes[0] > max_fanout:
        sizes.insert(0, math.ceil(sizes[0] / max_fanout))
    parents: list = [root]
    relays: list = []
    for tier, size in enumerate(sizes):
        level = [RelayHub(parents[i * len(parents) // size],
                          name=f"{name}{tier}.{i}", buffer=buffer)
                 for i in range(size)]
        relays.extend(level)
        parents = level
    return parents, relays
