"""Immutable versioned catalog snapshots — the copy-on-write read view.

A :class:`CatalogSnapshot` is a frozen view of the replicated catalog at
one hub version: readers hold a reference and walk it without any lock,
because nothing ever mutates a published snapshot.  The publisher
(:class:`sidecar_tpu.query.hub.QueryHub`) builds each successor by
structural sharing: only the server touched by a change event gets a
fresh service map; every other host's map is the same object as in the
predecessor.  Publishing is therefore O(services on the changed host),
not O(catalog) — and serialization (``to_json``/``encode``/
``by_service``) is computed lazily, at most once per version, shared by
every consumer of that version (the old read path re-serialized the
whole state per listener per event).

Versions are a dense monotonic int sequence starting at 1 (the attach
snapshot).  ``changed_ns`` carries the catalog's ``LastChanged``
nanosecond stamp at publish time, so the wire keeps the reference's
RFC3339 ``LastChanged`` field alongside the new version cursor.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterator, Mapping, Optional

from sidecar_tpu.service import Service, ns_to_rfc3339


@dataclasses.dataclass(frozen=True)
class ServerView:
    """Frozen per-host slice of a snapshot (the ``Server`` analog)."""

    name: str
    services: Mapping[str, Service]   # sid → frozen Service copy
    last_updated: int
    last_changed: int

    def to_json(self) -> dict:
        return {
            "Name": self.name,
            "Services": {sid: s.to_json()
                         for sid, s in self.services.items()},
            "LastUpdated": ns_to_rfc3339(self.last_updated),
            "LastChanged": ns_to_rfc3339(self.last_changed),
        }


class CatalogSnapshot:
    """One immutable, versioned view of the catalog.

    The lazy serialization caches are benign-race safe: concurrent
    first readers may compute the same value twice, but assignment is
    atomic and the inputs are frozen, so every reader sees a correct
    (and eventually the same) object.
    """

    __slots__ = ("version", "changed_ns", "cluster_name", "hostname",
                 "servers", "_json", "_encoded", "_by_service")

    def __init__(self, version: int, changed_ns: int, cluster_name: str,
                 hostname: str,
                 servers: Mapping[str, ServerView]) -> None:
        self.version = version
        self.changed_ns = changed_ns
        self.cluster_name = cluster_name
        self.hostname = hostname
        self.servers = servers
        self._json: Optional[dict] = None
        self._encoded: Optional[bytes] = None
        self._by_service: Optional[dict] = None

    # -- iteration (mirrors ServicesState's view methods) ------------------

    def each_service_sorted(self) -> Iterator[tuple[str, str, Service]]:
        """Deterministic (hostname, sid, service) walk — the same
        contract as ``ServicesState.each_service_sorted`` so consumers
        like the Envoy resource generator duck-type over either."""
        for hostname in sorted(self.servers):
            server = self.servers[hostname]
            for sid in sorted(server.services):
                yield hostname, sid, server.services[sid]

    def service_count(self) -> int:
        return sum(len(s.services) for s in self.servers.values())

    # -- cached serializations ---------------------------------------------

    def to_json(self) -> dict:
        """State-dump wire shape (``ServicesState.to_json`` parity) plus
        the version cursor."""
        if self._json is None:
            self._json = {
                "Servers": {h: s.to_json()
                            for h, s in self.servers.items()},
                "LastChanged": ns_to_rfc3339(self.changed_ns),
                "ClusterName": self.cluster_name,
                "Hostname": self.hostname,
                "Version": self.version,
            }
        return self._json

    def encode(self) -> bytes:
        if self._encoded is None:
            self._encoded = json.dumps(
                self.to_json(), separators=(",", ":")).encode()
        return self._encoded

    def by_service(self) -> dict[str, list[Service]]:
        """Instances grouped by service name (``ServicesState.by_service``
        parity, same deterministic order) — computed once per version."""
        if self._by_service is None:
            out: dict[str, list[Service]] = {}
            for _, _, svc in self.each_service_sorted():
                out.setdefault(svc.name, []).append(svc)
            self._by_service = out
        return self._by_service

    def by_service_json(self) -> dict:
        return {name: [svc.to_json() for svc in instances]
                for name, instances in self.by_service().items()}


def snapshot_from_state(state, version: int) -> CatalogSnapshot:
    """Full snapshot of a live ``ServicesState`` — the attach/resync
    builder.  Caller must hold (or be on the thread that holds)
    ``state._lock``; the hub's attach path does."""
    servers = {
        h: ServerView(
            name=server.name,
            services={sid: svc.copy()
                      for sid, svc in server.services.items()},
            last_updated=server.last_updated,
            last_changed=server.last_changed,
        )
        for h, server in state.servers.items()
    }
    return CatalogSnapshot(
        version=version, changed_ns=state.last_changed,
        cluster_name=state.cluster_name, hostname=state.hostname,
        servers=servers)
