"""Immutable versioned catalog snapshots — the copy-on-write read view.

A :class:`CatalogSnapshot` is a frozen view of the replicated catalog at
one hub version: readers hold a reference and walk it without any lock,
because nothing ever mutates a published snapshot.  The publisher
(:class:`sidecar_tpu.query.hub.QueryHub`) builds each successor by
structural sharing: only the server touched by a change event gets a
fresh service map; every other host's map is the same object as in the
predecessor.  Publishing is therefore O(services on the changed host),
not O(catalog) — and serialization (``to_json``/``encode``/
``by_service``) is computed lazily, at most once per version, shared by
every consumer of that version (the old read path re-serialized the
whole state per listener per event).

Versions are a dense monotonic int sequence starting at 1 (the attach
snapshot).  ``changed_ns`` carries the catalog's ``LastChanged``
nanosecond stamp at publish time, so the wire keeps the reference's
RFC3339 ``LastChanged`` field alongside the new version cursor.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Iterator, Mapping, Optional

from sidecar_tpu import metrics
from sidecar_tpu.service import Service, ns_to_rfc3339

_SEP = (",", ":")


def record_encode(nbytes: int) -> None:
    """Account one wire-encoding cache fill (``query.encode.*``).

    Counted ONLY on fills — never on cache hits — so the counters read
    as "serialization work actually performed": at N subscribers the
    zero-copy read path holds ``query.encode.bytes`` at O(1) per
    published version while the old path would have been O(N).  The
    bench's ``query_scale`` block derives its baseline-vs-zero-copy
    ratio from exactly these counters."""
    metrics.incr("query.encode.count")
    metrics.incr("query.encode.bytes", nbytes)


@dataclasses.dataclass(frozen=True)
class ServerView:
    """Frozen per-host slice of a snapshot (the ``Server`` analog)."""

    name: str
    services: Mapping[str, Service]   # sid → frozen Service copy
    last_updated: int
    last_changed: int

    def to_json(self) -> dict:
        return {
            "Name": self.name,
            "Services": {sid: s.to_json()
                         for sid, s in self.services.items()},
            "LastUpdated": ns_to_rfc3339(self.last_updated),
            "LastChanged": ns_to_rfc3339(self.last_changed),
        }


class CatalogSnapshot:
    """One immutable, versioned view of the catalog.

    Every serialization is computed at most once per version, under the
    snapshot's fill lock, and the SAME buffer object is handed to every
    consumer — the /watch chunk writer, UrlListener POST bodies, and the
    state-dump endpoints all share it (zero-copy fan-out: per version
    the cost is one ``json.dumps`` plus O(subscribers) pointer
    hand-offs).  The fast path is lock-free: a filled cache slot is read
    without taking the lock (attribute assignment is atomic), only the
    first reader of each form pays for the fill.
    """

    __slots__ = ("version", "changed_ns", "cluster_name", "hostname",
                 "servers", "_fill", "_json", "_encoded", "_by_service",
                 "_by_service_encoded", "_watch_raw", "_watch_by_service",
                 "_resync_doc")

    def __init__(self, version: int, changed_ns: int, cluster_name: str,
                 hostname: str,
                 servers: Mapping[str, ServerView]) -> None:
        self.version = version
        self.changed_ns = changed_ns
        self.cluster_name = cluster_name
        self.hostname = hostname
        self.servers = servers
        self._fill = threading.RLock()
        self._json: Optional[dict] = None
        self._encoded: Optional[bytes] = None
        self._by_service: Optional[dict] = None
        self._by_service_encoded: Optional[bytes] = None
        self._watch_raw: Optional[bytes] = None
        self._watch_by_service: Optional[bytes] = None
        self._resync_doc: Optional[bytes] = None

    # -- iteration (mirrors ServicesState's view methods) ------------------

    def each_service_sorted(self) -> Iterator[tuple[str, str, Service]]:
        """Deterministic (hostname, sid, service) walk — the same
        contract as ``ServicesState.each_service_sorted`` so consumers
        like the Envoy resource generator duck-type over either."""
        for hostname in sorted(self.servers):
            server = self.servers[hostname]
            for sid in sorted(server.services):
                yield hostname, sid, server.services[sid]

    def service_count(self) -> int:
        return sum(len(s.services) for s in self.servers.values())

    # -- cached serializations ---------------------------------------------

    def to_json(self) -> dict:
        """State-dump wire shape (``ServicesState.to_json`` parity) plus
        the version cursor."""
        doc = self._json
        if doc is None:
            with self._fill:
                if self._json is None:
                    self._json = {
                        "Servers": {h: s.to_json()
                                    for h, s in self.servers.items()},
                        "LastChanged": ns_to_rfc3339(self.changed_ns),
                        "ClusterName": self.cluster_name,
                        "Hostname": self.hostname,
                        "Version": self.version,
                    }
                doc = self._json
        return doc

    def encode(self) -> bytes:
        enc = self._encoded
        if enc is None:
            with self._fill:
                if self._encoded is None:
                    buf = json.dumps(self.to_json(),
                                     separators=_SEP).encode()
                    record_encode(len(buf))
                    self._encoded = buf
                enc = self._encoded
        return enc

    def by_service(self) -> dict[str, list[Service]]:
        """Instances grouped by service name (``ServicesState.by_service``
        parity, same deterministic order) — computed once per version."""
        grouped = self._by_service
        if grouped is None:
            with self._fill:
                if self._by_service is None:
                    out: dict[str, list[Service]] = {}
                    for _, _, svc in self.each_service_sorted():
                        out.setdefault(svc.name, []).append(svc)
                    self._by_service = out
                grouped = self._by_service
        return grouped

    def by_service_json(self) -> dict:
        return {name: [svc.to_json() for svc in instances]
                for name, instances in self.by_service().items()}

    def by_service_encoded(self) -> bytes:
        """Compact encoding of :meth:`by_service_json` — one fill per
        version, shared by every by-service /watch subscriber."""
        enc = self._by_service_encoded
        if enc is None:
            with self._fill:
                if self._by_service_encoded is None:
                    buf = json.dumps(self.by_service_json(),
                                     separators=_SEP).encode()
                    record_encode(len(buf))
                    self._by_service_encoded = buf
                enc = self._by_service_encoded
        return enc

    # -- shared wire documents (zero-copy fan-out) -------------------------

    def watch_doc_bytes(self, by_service: bool) -> bytes:
        """The full /watch snapshot document
        (``{"Version": V, "Snapshot": ...}``) as ONE cached buffer —
        every /watch subscriber of a version writes this same object to
        its socket (wrap in ``memoryview`` for partial writes)."""
        if by_service:
            doc = self._watch_by_service
        else:
            doc = self._watch_raw
        if doc is None:
            with self._fill:
                body = (self.by_service_encoded() if by_service
                        else self.encode())
                if by_service:
                    if self._watch_by_service is None:
                        self._watch_by_service = (
                            b'{"Version":%d,"Snapshot":%s}'
                            % (self.version, body))
                    doc = self._watch_by_service
                else:
                    if self._watch_raw is None:
                        self._watch_raw = (
                            b'{"Version":%d,"Snapshot":%s}'
                            % (self.version, body))
                    doc = self._watch_raw
        return doc

    def resync_doc_bytes(self) -> bytes:
        """The UrlListener resync POST body
        (``{"Version": V, "State": ...}``, docs/query.md) as one cached
        buffer shared by every listener that fell behind at this
        version."""
        doc = self._resync_doc
        if doc is None:
            with self._fill:
                if self._resync_doc is None:
                    self._resync_doc = (b'{"Version":%d,"State":%s}'
                                        % (self.version, self.encode()))
                doc = self._resync_doc
        return doc


def snapshot_from_state(state, version: int) -> CatalogSnapshot:
    """Full snapshot of a live ``ServicesState`` — the attach/resync
    builder.  Caller must hold (or be on the thread that holds)
    ``state._lock``; the hub's attach path does."""
    servers = {
        h: ServerView(
            name=server.name,
            services={sid: svc.copy()
                      for sid, svc in server.services.items()},
            last_updated=server.last_updated,
            last_changed=server.last_changed,
        )
        for h, server in state.servers.items()
    }
    return CatalogSnapshot(
        version=version, changed_ns=state.last_changed,
        cluster_name=state.cluster_name, hostname=state.hostname,
        servers=servers)
