"""The versioned snapshot + delta query plane — the read-path half of
the framework.

Every read-path consumer (web ``/watch``, ``UrlListener`` POSTs, the
Envoy ADS looper) historically re-serialized the whole ``ServicesState``
under its lock on every change, and ADS discovered changes by polling
``LastChanged`` once per second.  This package replaces all of that with
one subsystem:

* :mod:`sidecar_tpu.query.snapshot` — immutable, monotonically
  versioned, copy-on-write catalog snapshots published by the writer
  path, so readers never touch ``state._lock`` and serialization
  happens at most once per version (cached on the immutable object).
* :mod:`sidecar_tpu.query.hub` — the subscription hub: per-subscriber
  bounded queues, delta coalescing under backpressure (a subscriber
  that falls behind collapses to one snapshot-at-latest-version
  event), and ``query.*`` drop/coalesce counters.

The TPU side of the plane — per-round changed-cell extraction from the
simulators — lives in :mod:`sidecar_tpu.ops.delta` and streams out
through :mod:`sidecar_tpu.bridge.sim_bridge`.

Wire shapes and backpressure semantics: docs/query.md.
"""

from sidecar_tpu.query.snapshot import CatalogSnapshot, ServerView
from sidecar_tpu.query.hub import (
    QueryEvent,
    QueryHub,
    RelayHub,
    Subscription,
    relay_tree,
)

__all__ = [
    "CatalogSnapshot",
    "ServerView",
    "QueryEvent",
    "QueryHub",
    "RelayHub",
    "Subscription",
    "relay_tree",
]
