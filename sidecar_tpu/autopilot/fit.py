"""Condition fitting: telemetry → a simulable estimate (docs/autopilot.md).

The autopilot's first move is to answer "what is the cluster living
through RIGHT NOW?" in the simulator's vocabulary — a
:class:`ConditionEstimate` whose pieces map exactly onto the fleet
plane's inputs:

* ``loss_rate`` / ``churn_rate`` land on DATA axes
  (``ScenarioSpec.drop_prob`` / ``churn_prob`` — they vmap freely, so
  every search candidate carries the fitted environment at zero extra
  compile cost);
* ``paused_frac`` is STRUCTURE — it becomes a ``FaultPlan`` pause
  window (:meth:`ConditionEstimate.fault_plan`), shared by the whole
  batch the way the fleet shares compile keys.

Two adapters produce an estimate:

* :func:`fit_from_trace` — the rigorous path: flight-recorder round
  records (ops/trace.py, the same stream ``POST /simulate`` returns
  and tests replay through ``ChaosExactSim.run_with_trace``) plus the
  chaos injection counters.  The estimators invert the trace model:

  - **loss**: the chaos plane drops non-empty packets; the frontier
    census says how many non-empty packets were offered
    (``frontier × fanout`` per round), so
    ``loss = dropped / Σ frontier·fanout``.
  - **churn**: each ALIVE→TOMBSTONE restart of a live-owned slot
    spreads ≈ one false-positive tombstone ENTRY per cluster node
    (ops/trace.fp_tombstone_entries counts the transition at every
    believer), and restart churn tombstones half its flips, so
    ``churn ≈ 2 · fp_tombstones_total / (n · m · rounds)``.
  - **pause**: a node paused from the start of the horizon never
    learns the other ``m − spn`` slots and never teaches its own
    ``spn``, so once the up-cluster settles the behind census floor is
    ``spn · p · (2n − 1 − p)`` for ``p`` paused nodes — invert the
    quadratic on the min of the last few recorded rounds.  (A pause
    that starts AFTER convergence leaves no backlog floor and fits as
    ≈ 0 — the estimate reads standing degradation, not history.)

* :func:`fit_live` — the best-effort live path: the process metrics
  registry (engine UDP relay gauges, ``damping.flaps``,
  ``coherence.agreement``).  Live signals lack a round base, so churn
  needs an explicit observation ``window_rounds``; anything the
  registry can't support stays 0 and the raw inputs are preserved in
  ``signals`` — an unfittable parameter never silently pretends to be
  a fitted one.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

# Behind-census tail window: the pause estimator reads the MIN over the
# last few recorded rounds so a transient backlog (churn in flight, a
# late frontier) doesn't read as standing paused-node degradation.
TAIL_ROUNDS = 5


@dataclasses.dataclass(frozen=True)
class ConditionEstimate:
    """Current cluster conditions, in the simulator's vocabulary."""

    n: int                       # cluster size the estimate describes
    services_per_node: int
    loss_rate: float = 0.0       # fraction of non-empty packets lost
    churn_rate: float = 0.0      # per-round per-slot restart probability
    paused_frac: float = 0.0     # fraction of nodes stalled (state kept)
    seconds_per_round: Optional[float] = None   # the protocol clock
    source: str = "trace"        # "trace" | "live"
    signals: dict = dataclasses.field(default_factory=dict)

    @property
    def m(self) -> int:
        return self.n * self.services_per_node

    def base_fields(self) -> dict:
        """The estimate's DATA-axis half: ``ScenarioSpec`` base fields
        every search candidate inherits (negligible rates are omitted —
        a 1e-7 drop_prob would only perturb the PRNG stream)."""
        out: dict = {}
        if self.loss_rate > 1e-4:
            out["drop_prob"] = round(min(self.loss_rate, 0.9), 4)
        if self.churn_rate > 1e-6:
            out["churn_prob"] = round(min(self.churn_rate, 1.0), 6)
        return out

    def fault_plan(self, seed: int = 0, start_round: int = 1,
                   end_round: Optional[int] = None):
        """The estimate's STRUCTURAL half: a ``FaultPlan`` pausing
        ``round(paused_frac · n)`` nodes over the window, or None when
        no nodes appear stalled (an empty plan would still force the
        chaos scan path onto every candidate).  Which specific nodes
        stall is unobservable from pooled telemetry; the trailing run
        of node ids is chosen — symmetric under the complete overlay,
        deterministic for the fitted-then-swept contract."""
        count = int(round(self.paused_frac * self.n))
        if count < 1:
            return None
        from sidecar_tpu.chaos import FaultPlan, NodeFault
        from sidecar_tpu.chaos.plan import FOREVER
        nodes = tuple(range(self.n - count, self.n))
        return FaultPlan(seed=seed, nodes=(NodeFault(
            nodes=nodes, start_round=start_round,
            end_round=FOREVER if end_round is None else end_round,
            kind="pause"),))

    def to_json(self) -> dict:
        return {
            "n": self.n,
            "services_per_node": self.services_per_node,
            "loss_rate": round(self.loss_rate, 6),
            "churn_rate": round(self.churn_rate, 8),
            "paused_frac": round(self.paused_frac, 6),
            "seconds_per_round": self.seconds_per_round,
            "source": self.source,
            "signals": dict(self.signals),
        }


def _pause_from_behind(behind_tail: int, n: int, spn: int) -> float:
    """Invert the standing-backlog model ``behind = spn·p·(2n−1−p)``
    (p paused nodes: each is behind on ``m − spn`` cells and keeps the
    ``n − p`` up nodes behind on its own ``spn``) for the paused-node
    fraction."""
    if behind_tail <= 0 or n < 1 or spn < 1:
        return 0.0
    b = behind_tail / spn
    disc = (2 * n - 1) ** 2 - 4 * b
    p = (2 * n - 1 - math.sqrt(disc)) / 2 if disc >= 0 else n / 2
    return min(max(p / n, 0.0), 1.0)


def fit_from_trace(trace_rows, *, params, injections: Optional[dict] = None,
                   timecfg=None, source: str = "trace") -> ConditionEstimate:
    """Fit a :class:`ConditionEstimate` from flight-recorder rounds.

    ``trace_rows`` is the ``ops/trace.trace_to_dicts`` form (one dict
    per recorded round); ``injections`` the chaos counters
    (``ChaosExactSim.injection_counts``) when the trace came from the
    chaos family; ``params`` the SimParams of the traced run (the
    estimators need n/spn/fanout to invert the censuses); ``timecfg``
    supplies the protocol clock for ``seconds_per_round``."""
    rows = list(trace_rows)
    n, spn = int(params.n), int(params.services_per_node)
    m, fanout = n * spn, int(params.fanout)
    rounds = len(rows)

    offered = sum(int(r.get("frontier", 0)) for r in rows) * fanout
    dropped = int((injections or {}).get("dropped", 0))
    loss = dropped / offered if offered else 0.0

    fp_total = sum(int(r.get("fp_tombstones", 0)) for r in rows)
    churn = 2.0 * fp_total / (n * m * rounds) if rounds else 0.0

    tail = [int(r.get("behind", 0)) for r in rows[-TAIL_ROUNDS:]]
    behind_tail = min(tail) if tail else 0
    paused = _pause_from_behind(behind_tail, n, spn)

    spr = None
    if timecfg is not None:
        spr = timecfg.round_ticks / timecfg.ticks_per_second
    return ConditionEstimate(
        n=n, services_per_node=spn,
        loss_rate=min(max(loss, 0.0), 1.0),
        churn_rate=min(max(churn, 0.0), 1.0),
        paused_frac=paused, seconds_per_round=spr, source=source,
        signals={"rounds": rounds, "offered_packets": offered,
                 "dropped_packets": dropped, "fp_tombstones": fp_total,
                 "behind_tail": behind_tail})


def fit_live(snapshot: Optional[dict] = None, *, n: int,
             services_per_node: int,
             seconds_per_round: Optional[float] = None,
             window_rounds: Optional[int] = None) -> ConditionEstimate:
    """Best-effort estimate from the process metrics registry.

    * loss — the native transport relay's EAGAIN-dropped sends over
      packets out (``engine.udpSendDrops`` / ``engine.udpOut``);
    * churn — ``damping.flaps`` needs a round base: with
      ``window_rounds`` the flap count converts to a per-round
      per-slot rate, without one it stays 0 (reported raw in
      ``signals`` — never silently invented);
    * pause proxy — ``1 − coherence.agreement``: hosts off the quorum
      digest are standing divergence, the live shadow of a stalled
      node's backlog.
    """
    if snapshot is None:
        from sidecar_tpu import metrics
        snapshot = metrics.snapshot()
    gauges = snapshot.get("gauges", {})
    counters = snapshot.get("counters", {})
    m = n * services_per_node

    def signal(name):
        v = gauges.get(name)
        return v if v is not None else counters.get(name)

    out_pk = float(signal("engine.udpOut") or 0.0)
    drops = float(signal("engine.udpSendDrops") or 0.0)
    loss = drops / out_pk if out_pk > 0 else 0.0

    flaps = float(counters.get("damping.flaps") or 0.0)
    churn = flaps / (m * window_rounds) \
        if window_rounds and m else 0.0

    agreement = gauges.get("coherence.agreement")
    paused = max(0.0, 1.0 - float(agreement)) \
        if agreement is not None else 0.0

    return ConditionEstimate(
        n=n, services_per_node=services_per_node,
        loss_rate=min(max(loss, 0.0), 1.0),
        churn_rate=min(max(churn, 0.0), 1.0),
        paused_frac=min(paused, 1.0),
        seconds_per_round=seconds_per_round, source="live",
        signals={"udp_out": out_pk, "udp_send_drops": drops,
                 "flaps": flaps, "agreement": agreement,
                 "window_rounds": window_rounds})
